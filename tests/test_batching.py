"""Adaptive micro-batching semantics (ISSUE 1 tentpole).

The contract: with ``batch_max > 1`` a device stage drains already-queued
compatible buffers into ONE bucketed XLA dispatch, while every observable
single-buffer semantic — output values, strict ordering, pts/meta, EOS
flush — stays identical to the seed executor; with ``batch_max=1`` (the
default) the seed code path runs unchanged.
"""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import (Buffer, batch_signature, split_rows,
                                        stack_tensors)
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.pipeline.batching import BatchRunner, bucket_for

DESC = (
    "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
    "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
    "name=f ! tensor_sink name=out"
)


def _frames(n):
    return [np.full((16,), float(i), np.float32) for i in range(n)]


def _run(desc, frames, timeout=60, **kw):
    p = nt.Pipeline(desc, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
    return outs


# -- primitives ------------------------------------------------------------

def test_bucket_for_ladder():
    assert bucket_for(1) == 1
    assert bucket_for(3) == 4
    assert bucket_for(8) == 8
    assert bucket_for(9) == 16
    assert bucket_for(5, [2, 6]) == 6
    # above the ladder: LADDER-ROUNDED to a multiple of the top bucket —
    # an exact fallback minted one program per occupancy (the
    # recompile-unbounded regression, tests/test_adaptive_batching.py)
    assert bucket_for(7, [2, 6]) == 12
    assert bucket_for(300) == 512
    assert bucket_for(1000) == 1024


def test_stack_split_roundtrip(rng):
    rows = [tuple(rng.standard_normal((3, 4)).astype(np.float32)
                  for _ in range(2)) for _ in range(3)]
    stacked = stack_tensors(rows, pad_to=4)
    assert all(a.shape == (4, 3, 4) for a in stacked)
    # pad row repeats the last real row
    np.testing.assert_array_equal(np.asarray(stacked[0][3]), rows[2][0])
    back = split_rows(stacked, 3)
    for want, got in zip(rows, back):
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(g), w)


def test_batch_signature_gates_stacking():
    a = Buffer([np.zeros((2, 3), np.float32)])
    b = Buffer([np.ones((2, 3), np.float32)])
    c = Buffer([np.zeros((2, 3), np.float64)])
    d = Buffer([np.zeros((3, 2), np.float32)])
    assert batch_signature(a) == batch_signature(b)
    assert batch_signature(a) != batch_signature(c)
    assert batch_signature(a) != batch_signature(d)


def test_batch_runner_matches_per_row_fn(rng):
    fn = lambda arrays: (arrays[0] * 2.0 + 1.0,)  # noqa: E731
    br = BatchRunner(fn)
    rows = [(rng.standard_normal((8,)).astype(np.float32),)
            for _ in range(5)]  # 5 -> bucket 8: three pad rows dropped
    outs = br.run(rows)
    assert len(outs) == 5
    for (x,), (y,) in zip(rows, outs):
        np.testing.assert_allclose(np.asarray(y), x * 2.0 + 1.0, rtol=1e-6)


# -- pipeline semantics ----------------------------------------------------

def test_occupancy_above_one_under_backlog():
    """A backlogged queue must actually coalesce: with 24 buffers pushed
    before the first (compile-slowed) dispatch finishes, occupancy > 1."""
    metrics.reset()
    frames = _frames(24)
    outs = _run(DESC, frames, queue_capacity=32, batch_max=8)
    assert len(outs) == 24
    snap = metrics.snapshot()
    assert snap.get("f.batch_occupancy.n", 0) >= 1
    assert snap.get("f.batch_occupancy.p99", 0) > 1.0


def test_strict_output_ordering_and_pts():
    frames = _frames(32)
    outs = _run(DESC, frames, queue_capacity=32, batch_max=8)
    for i, (x, o) in enumerate(zip(frames, outs)):
        assert o.pts == i
        np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)


def test_bucket_padding_matches_unbatched_reference():
    """13 backlogged buffers hit partial buckets (padding); every output
    must match the batch_max=1 reference run value-for-value."""
    frames = _frames(13)
    batched = _run(DESC, frames, queue_capacity=16, batch_max=8)
    reference = _run(DESC, frames, queue_capacity=16, batch_max=1)
    for b, r in zip(batched, reference):
        np.testing.assert_allclose(
            np.asarray(b.tensors[0]), np.asarray(r.tensors[0]), rtol=1e-6)


def test_partial_batch_flushes_at_eos():
    """3 buffers with batch_max=8: nothing may wait for a full batch — all
    outputs delivered and EOS completes the pipeline."""
    frames = _frames(3)
    outs = _run(DESC, frames, queue_capacity=16, batch_max=8)
    assert len(outs) == 3
    for x, o in zip(frames, outs):
        np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)


def test_batch_max_1_is_bit_identical_to_default():
    """batch_max=1 must run the exact seed path: outputs byte-identical to
    the default pipeline's."""
    frames = _frames(6)
    explicit = _run(DESC, frames, batch_max=1)
    default = _run(DESC, frames)
    for a, b in zip(explicit, default):
        assert bytes(np.asarray(a.tensors[0])) == bytes(
            np.asarray(b.tensors[0]))
        assert a.pts == b.pts


def test_fused_stage_batches_and_matches():
    """A fused transform+filter chain is batchable as one stage; batched
    outputs match the unbatched fused run."""
    desc = (
        "appsrc name=src caps=other/tensors,dimensions=4:4,types=float32 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:2.0 ! "
        "tensor_filter framework=jax model=scaler custom=scale:4.0,dims:4:4 "
        "name=f ! tensor_sink name=out"
    )
    p = nt.Pipeline(desc, batch_max=4)
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused and fused[0].batchable
    frames = [np.full((4, 4), float(i + 1), np.float32) for i in range(9)]
    batched = _run(desc, frames, queue_capacity=16, batch_max=4)
    reference = _run(desc, frames, queue_capacity=16, batch_max=1)
    for b, r in zip(batched, reference):
        np.testing.assert_allclose(
            np.asarray(b.tensors[0]), np.asarray(r.tensors[0]), rtol=1e-6)


def test_host_stages_stay_unbatched():
    """Host-only elements are never planned batchable — their process()
    semantics are untouched by the batching layer."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
        "tensor_sink name=out", fuse=False, batch_max=8)
    by_name = {s.element.name: s.batchable for s in p.stages}
    assert not any(by_name.values())


def test_mixed_spec_buffers_split_batches():
    """Buffers whose tensor signatures differ must never stack; outputs
    still arrive in order with correct values (flexible appsrc caps)."""
    desc = ("appsrc name=src ! "
            "tensor_filter framework=custom-easy model=batch-double ! "
            "tensor_sink name=out")
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    register_custom_easy("batch-double", lambda ins: [ins[0] * 2],
                         jax_traceable=True)
    frames = [np.full((4 + (i % 2),), float(i), np.float32)
              for i in range(10)]
    outs = _run(desc, frames, queue_capacity=16, batch_max=8)
    for x, o in zip(frames, outs):
        np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)


def test_occupancy_visible_in_prometheus_text():
    from nnstreamer_tpu.utils.profiler import metrics_text

    metrics.reset()
    _run(DESC, _frames(16), queue_capacity=32, batch_max=8)
    text = metrics_text()
    assert "batch_occupancy" in text


def test_batch_linger_waits_for_stragglers():
    """batch_linger_ms > 0: the drain waits for late buffers instead of
    dispatching singles (explicit latency-for-occupancy trade)."""
    metrics.reset()
    p = nt.Pipeline(DESC, queue_capacity=32, batch_max=4,
                    batch_linger_ms=200.0)
    frames = _frames(8)
    outs = []
    with p:
        for i in range(0, 8, 2):  # trickle pairs with small gaps
            p.push("src", frames[i])
            p.push("src", frames[i + 1])
            time.sleep(0.01)
        for _ in frames:
            outs.append(p.pull("out", timeout=60))
        p.eos()
        p.wait(timeout=60)
    assert len(outs) == 8
    snap = metrics.snapshot()
    assert snap.get("f.batch_occupancy.p99", 0) > 1.0


# -- shutdown path (satellite: poison instead of 0.1 s polling) ------------

class TestStopLatency:
    def test_stop_wakes_blocked_stages_immediately(self):
        """An idle multi-stage pipeline must stop in far less than one
        seed-era 0.1 s poll interval per hop."""
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=4,types=float32 ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_sink name=out", fuse=False)
        p.start()
        time.sleep(0.05)  # let every stage block on its queue
        t0 = time.monotonic()
        p.stop()
        dt = time.monotonic() - t0
        assert dt < 0.5, f"stop took {dt:.3f}s"
        runners = {id(r): r for r in p._runners.values()}.values()
        assert not any(r.thread.is_alive() for r in runners)

    def test_stop_unblocks_backpressured_feeder(self):
        """A producer blocked on a FULL downstream queue must shed and exit
        promptly on stop()."""
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")

        def slow(ins):
            time.sleep(0.3)
            return [np.asarray(ins[0], np.float32)]

        register_custom_easy("stop-slow", slow, in_spec=spec, out_spec=spec)
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=4,types=float32 ! "
            "tensor_filter framework=custom-easy model=stop-slow ! "
            "tensor_sink name=out", queue_capacity=1)
        with p:
            for _ in range(4):  # floods the 1-deep filter queue
                p.push("src", np.ones((4,), np.float32))
            time.sleep(0.1)  # source thread now blocked in feed()
            t0 = time.monotonic()
        # context exit calls stop(): the blocked feed must shed, the slow
        # in-flight process() call (~0.3 s) bounds the join
        assert time.monotonic() - t0 < 2.0

    def test_clean_eos_still_drains_everything(self):
        frames = _frames(5)
        outs = _run(DESC, frames, batch_max=8)
        assert len(outs) == 5


# -- _StageQueue (satellite: single-notify, no thundering herd) ------------

class TestStageQueueStress:
    def test_many_producers_bounded_queue_no_lost_wakeups(self):
        """8 producers x 200 items through a 3-deep queue, one consumer:
        with per-item notify() (not notify_all) every item must still
        arrive — a lost wakeup deadlocks this test inside its timeout."""
        import threading

        from nnstreamer_tpu.pipeline.runtime import _POISON, _StageQueue

        q = _StageQueue(3)
        n_prod, per = 8, 200
        sent = []

        def producer(k):
            for i in range(per):
                assert q.put(("pad", (k, i)))
                sent.append(None)

        threads = [threading.Thread(target=producer, args=(k,), daemon=True)
                   for k in range(n_prod)]
        got = []
        for t in threads:
            t.start()
        while len(got) < n_prod * per:
            item = q.get(timeout=20.0)
            assert item is not None, (
                f"consumer starved after {len(got)} items (lost wakeup)")
            got.append(item[1])
        for t in threads:
            t.join(timeout=10.0)
            assert not t.is_alive(), "producer stuck (lost wakeup)"
        # per-producer FIFO survives the interleaving
        by_prod = {}
        for k, i in got:
            assert by_prod.get(k, -1) == i - 1
            by_prod[k] = i

    def test_close_wakes_every_blocked_producer(self):
        import threading

        from nnstreamer_tpu.pipeline.runtime import _StageQueue

        q = _StageQueue(1)
        assert q.put(("pad", 0))
        results = []
        threads = [
            threading.Thread(target=lambda: results.append(
                q.put(("pad", 1))), daemon=True)
            for _ in range(6)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)  # all six blocked on the full queue
        q.close()
        for t in threads:
            t.join(timeout=5.0)
            assert not t.is_alive()
        assert results == [False] * 6  # all shed, none stuck
