"""queue / videoconvert / videoscale compatibility elements (GStreamer base
elements every reference example pipeline assumes)."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.elements.video import VideoConvert, VideoScale


class TestVideoConvert:
    def _frame(self):
        rng = np.random.default_rng(0)
        return rng.integers(0, 256, (4, 6, 3), np.uint8)

    def test_rgb_to_bgr_roundtrip(self):
        f = self._frame()
        c = VideoConvert({"format": "BGR"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        np.testing.assert_array_equal(out, f[..., ::-1])
        back = VideoConvert({"format": "RGB"})
        back.configure({"sink": nt.Caps.new("video/x-raw", format="BGR")}, ["src"])
        np.testing.assert_array_equal(
            back.process("sink", Buffer([out]))[0][1].tensors[0], f)

    def test_rgb_to_rgba_alpha_opaque(self):
        f = self._frame()
        c = VideoConvert({"format": "RGBA"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (4, 6, 4)
        np.testing.assert_array_equal(out[..., :3], f)
        assert (out[..., 3] == 255).all()

    def test_gray8_bt601(self):
        f = np.zeros((2, 2, 3), np.uint8)
        f[0, 0] = [255, 0, 0]
        c = VideoConvert({"format": "GRAY8"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (2, 2, 1)
        assert out[0, 0, 0] == 76  # round(0.299*255)

    def test_passthrough_without_format(self):
        f = self._frame()
        c = VideoConvert({})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1]
        np.testing.assert_array_equal(out.tensors[0], f)

    def test_bad_format_rejected(self):
        with pytest.raises(Exception):
            VideoConvert({"format": "YUY2"})


class TestVideoScale:
    def test_nearest_downscale(self):
        f = np.arange(16, dtype=np.uint8).reshape(4, 4, 1)
        s = VideoScale({"width": 2, "height": 2})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY8",
                                         width=4, height=4)}, ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (2, 2, 1)
        np.testing.assert_array_equal(out[..., 0], [[0, 2], [8, 10]])

    def test_bilinear_constant_preserved(self):
        f = np.full((5, 7, 3), 111, np.uint8)
        s = VideoScale({"width": 13, "height": 9, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="RGB",
                                         width=7, height=5)}, ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (9, 13, 3)
        assert (out == 111).all()

    def test_caps_carry_new_size(self):
        s = VideoScale({"width": 8, "height": 6})
        caps = s.configure({"sink": nt.Caps.new("video/x-raw", format="RGB",
                                                width=4, height=4)}, ["src"])
        assert caps["src"].get("width") == 8
        assert caps["src"].get("height") == 6


def test_reference_style_pipeline_runs_verbatim():
    """The stock reference topology (videoconvert ! videoscale ! queue)
    runs as written, feeding the classification slice."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=12 height=10 pattern=random ! "
        "videoconvert format=RGB ! videoscale width=8 height=8 ! "
        "queue max-size-buffers=4 ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_sink name=out",
        fuse=True,
    )
    with p:
        bufs = [p.pull("out", timeout=15) for _ in range(2)]
        p.wait(timeout=15)
    for b in bufs:
        assert b.tensors[0].shape == (1, 8, 8, 3)
        assert b.tensors[0].dtype == np.float32


class TestReviewRegressions:
    def test_alpha_preserved_rgba_to_bgra(self):
        f = np.zeros((2, 2, 4), np.uint8)
        f[..., 0] = 10  # R
        f[..., 2] = 30  # B
        f[..., 3] = 128  # alpha must survive
        c = VideoConvert({"format": "BGRA"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGBA")},
                    ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert (out[..., 3] == 128).all()
        assert (out[..., 0] == 30).all() and (out[..., 2] == 10).all()

    def test_bilinear_2d_gray_frame(self):
        f = np.arange(12, dtype=np.uint8).reshape(3, 4)  # no channel dim
        s = VideoScale({"width": 8, "height": 6, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY8")},
                    ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (6, 8)  # stays 2-d

    def test_bilinear_16bit_range(self):
        f = np.full((2, 2, 1), 1000, np.uint16)
        s = VideoScale({"width": 4, "height": 4, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY16_LE")},
                    ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert (out == 1000).all()  # not clamped to 255
