"""queue / videoconvert / videoscale compatibility elements (GStreamer base
elements every reference example pipeline assumes)."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.elements.video import VideoConvert, VideoScale


class TestVideoConvert:
    def _frame(self):
        rng = np.random.default_rng(0)
        return rng.integers(0, 256, (4, 6, 3), np.uint8)

    def test_rgb_to_bgr_roundtrip(self):
        f = self._frame()
        c = VideoConvert({"format": "BGR"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        np.testing.assert_array_equal(out, f[..., ::-1])
        back = VideoConvert({"format": "RGB"})
        back.configure({"sink": nt.Caps.new("video/x-raw", format="BGR")}, ["src"])
        np.testing.assert_array_equal(
            back.process("sink", Buffer([out]))[0][1].tensors[0], f)

    def test_rgb_to_rgba_alpha_opaque(self):
        f = self._frame()
        c = VideoConvert({"format": "RGBA"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (4, 6, 4)
        np.testing.assert_array_equal(out[..., :3], f)
        assert (out[..., 3] == 255).all()

    def test_gray8_bt601(self):
        f = np.zeros((2, 2, 3), np.uint8)
        f[0, 0] = [255, 0, 0]
        c = VideoConvert({"format": "GRAY8"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (2, 2, 1)
        assert out[0, 0, 0] == 76  # round(0.299*255)

    def test_passthrough_without_format(self):
        f = self._frame()
        c = VideoConvert({})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGB")}, ["src"])
        out = c.process("sink", Buffer([f]))[0][1]
        np.testing.assert_array_equal(out.tensors[0], f)

    def test_bad_format_rejected(self):
        with pytest.raises(Exception):
            VideoConvert({"format": "YUY2"})


class TestVideoScale:
    def test_nearest_downscale(self):
        f = np.arange(16, dtype=np.uint8).reshape(4, 4, 1)
        s = VideoScale({"width": 2, "height": 2})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY8",
                                         width=4, height=4)}, ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (2, 2, 1)
        np.testing.assert_array_equal(out[..., 0], [[0, 2], [8, 10]])

    def test_bilinear_constant_preserved(self):
        f = np.full((5, 7, 3), 111, np.uint8)
        s = VideoScale({"width": 13, "height": 9, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="RGB",
                                         width=7, height=5)}, ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (9, 13, 3)
        assert (out == 111).all()

    def test_caps_carry_new_size(self):
        s = VideoScale({"width": 8, "height": 6})
        caps = s.configure({"sink": nt.Caps.new("video/x-raw", format="RGB",
                                                width=4, height=4)}, ["src"])
        assert caps["src"].get("width") == 8
        assert caps["src"].get("height") == 6


def test_reference_style_pipeline_runs_verbatim():
    """The stock reference topology (videoconvert ! videoscale ! queue)
    runs as written, feeding the classification slice."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=12 height=10 pattern=random ! "
        "videoconvert format=RGB ! videoscale width=8 height=8 ! "
        "queue max-size-buffers=4 ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_sink name=out",
        fuse=True,
    )
    with p:
        bufs = [p.pull("out", timeout=15) for _ in range(2)]
        p.wait(timeout=15)
    for b in bufs:
        assert b.tensors[0].shape == (1, 8, 8, 3)
        assert b.tensors[0].dtype == np.float32


class TestYUV:
    """I420/NV12 camera-native formats (VERDICT r2 missing #4): BT.601
    limited-range goldens and the verbatim upstream camera topology."""

    def _solid_i420(self, h, w, y, u, v):
        flat = np.concatenate([
            np.full(h * w, y, np.uint8),
            np.full(h * w // 4, u, np.uint8),
            np.full(h * w // 4, v, np.uint8)])
        return flat.reshape(h * 3 // 2, w)

    def test_i420_red_golden(self):
        # BT.601: pure red is (Y,U,V) = (82, 90, 240)
        from nnstreamer_tpu.elements.video import _yuv_to_rgb

        rgb = _yuv_to_rgb(self._solid_i420(4, 4, 82, 90, 240), 4, 4, "I420")
        r, g, b = (int(c) for c in rgb[0, 0])
        assert r == 255 and g <= 2 and b <= 2

    def test_rgb_i420_roundtrip(self):
        from nnstreamer_tpu.elements.video import _rgb_to_yuv, _yuv_to_rgb

        rng = np.random.default_rng(0)
        # block-uniform image: chroma subsampling is lossless on it
        small = rng.integers(0, 256, (4, 4, 3), np.uint8)
        rgb = np.repeat(np.repeat(small, 2, 0), 2, 1)
        back = _yuv_to_rgb(_rgb_to_yuv(rgb, "I420"), 8, 8, "I420")
        # limited-range quantization costs a few codes, not more
        assert np.abs(back.astype(int) - rgb.astype(int)).max() <= 6

    def test_nv12_matches_i420(self):
        from nnstreamer_tpu.elements.video import _rgb_to_yuv, _yuv_to_rgb

        rng = np.random.default_rng(1)
        rgb = rng.integers(0, 256, (8, 6, 3), np.uint8)
        a = _yuv_to_rgb(_rgb_to_yuv(rgb, "I420"), 8, 6, "I420")
        b = _yuv_to_rgb(_rgb_to_yuv(rgb, "NV12"), 8, 6, "NV12")
        np.testing.assert_array_equal(a, b)

    def test_camera_pipeline_verbatim_i420(self):
        """The stock upstream camera topology with I420 caps, as written:
        appsrc (I420) ! videoconvert ! tensor_converter ! ..."""
        p = nt.Pipeline(
            "appsrc name=cam caps=video/x-raw,format=I420,width=16,height=8 ! "
            "videoconvert format=RGB ! tensor_converter ! "
            "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
            "tensor_sink name=out")
        frame = self._solid_i420(8, 16, 82, 90, 240)  # pure red
        with p:
            p.push("cam", frame)
            b = p.pull("out", timeout=15)
            p.eos()
            p.wait(timeout=15)
        out = np.asarray(b.tensors[0])
        assert out.shape[-3:] == (8, 16, 3)
        assert out.reshape(-1, 3)[0, 0] == 1.0  # red channel saturated
        assert out.reshape(-1, 3)[0, 1] <= 0.01

    def test_convert_rgb_to_nv12_and_back_pipeline(self):
        p = nt.Pipeline(
            "appsrc name=src caps=video/x-raw,format=RGB,width=8,height=8 ! "
            "videoconvert format=NV12 ! tensor_sink name=out", fuse=False)
        rgb = np.repeat(np.repeat(
            np.random.default_rng(2).integers(0, 256, (4, 4, 3), np.uint8),
            2, 0), 2, 1)
        with p:
            p.push("src", rgb)
            b = p.pull("out", timeout=15)
            p.eos()
            p.wait(timeout=15)
        yuv = np.asarray(b.tensors[0])
        assert yuv.shape == (12, 8)  # H*3/2 x W byte layout

    def test_compositor_i420_base(self):
        desc = (
            "appsrc name=cam caps=video/x-raw,format=I420,width=8,height=8 ! comp. "
            "appsrc name=ov caps=video/x-raw,format=RGBA,width=8,height=8 ! comp. "
            "compositor name=comp ! tensor_sink name=out")
        p = nt.Pipeline(desc, fuse=False)
        base = self._solid_i420(8, 8, 16, 128, 128)  # black
        ov = np.zeros((8, 8, 4), np.uint8)
        ov[..., 1] = 200
        ov[..., 3] = 255  # opaque green overlay
        with p:
            p.push("cam", base)
            p.push("ov", ov)
            b = p.pull("out", timeout=15)
            p.eos("cam")
            p.eos("ov")
            p.wait(timeout=15)
        out = np.asarray(b.tensors[0])
        assert out.shape == (12, 8)  # output stays I420 like the base
        from nnstreamer_tpu.elements.video import _yuv_to_rgb

        rgb = _yuv_to_rgb(out, 8, 8, "I420")
        assert abs(int(rgb[0, 0, 1]) - 200) <= 4  # green survived the trip
        assert rgb[0, 0, 0] <= 6 and rgb[0, 0, 2] <= 6

    def test_videoscale_rejects_yuv(self):
        with pytest.raises(Exception, match="videoconvert"):
            p = nt.Pipeline(
                "appsrc name=src caps=video/x-raw,format=I420,width=8,height=8 ! "
                "videoscale width=4 height=4 ! tensor_sink name=out")
            p.start()

    def test_odd_dims_rejected(self):
        from nnstreamer_tpu.elements.video import _rgb_to_yuv

        with pytest.raises(Exception, match="even"):
            _rgb_to_yuv(np.zeros((5, 4, 3), np.uint8), "I420")


class TestReviewRegressions:
    def test_alpha_preserved_rgba_to_bgra(self):
        f = np.zeros((2, 2, 4), np.uint8)
        f[..., 0] = 10  # R
        f[..., 2] = 30  # B
        f[..., 3] = 128  # alpha must survive
        c = VideoConvert({"format": "BGRA"})
        c.configure({"sink": nt.Caps.new("video/x-raw", format="RGBA")},
                    ["src"])
        out = c.process("sink", Buffer([f]))[0][1].tensors[0]
        assert (out[..., 3] == 128).all()
        assert (out[..., 0] == 30).all() and (out[..., 2] == 10).all()

    def test_bilinear_2d_gray_frame(self):
        f = np.arange(12, dtype=np.uint8).reshape(3, 4)  # no channel dim
        s = VideoScale({"width": 8, "height": 6, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY8")},
                    ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert out.shape == (6, 8)  # stays 2-d

    def test_bilinear_16bit_range(self):
        f = np.full((2, 2, 1), 1000, np.uint16)
        s = VideoScale({"width": 4, "height": 4, "method": "bilinear"})
        s.configure({"sink": nt.Caps.new("video/x-raw", format="GRAY16_LE")},
                    ["src"])
        out = s.process("sink", Buffer([f]))[0][1].tensors[0]
        assert (out == 1000).all()  # not clamped to 255


class TestCompositor:
    def test_source_over_blend(self):
        from nnstreamer_tpu.elements.video import Compositor

        base = np.full((2, 2, 3), 100, np.uint8)
        ov = np.zeros((2, 2, 4), np.uint8)
        ov[0, 0] = [200, 0, 0, 255]   # opaque red: replaces
        ov[0, 1] = [200, 0, 0, 127]   # half: blends
        c = Compositor({})
        c.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()},
                    ["src"])
        out = c.process_group({
            "sink_0": Buffer([base], pts=5),
            "sink_1": Buffer([ov], pts=9),
        })[0][1]
        o = out.tensors[0]
        np.testing.assert_array_equal(o[0, 0], [200, 0, 0])
        assert abs(int(o[0, 1, 0]) - 150) <= 1  # 200*0.498 + 100*0.502
        np.testing.assert_array_equal(o[1, 1], [100, 100, 100])
        assert out.pts == 9

    def test_size_mismatch_rejected(self):
        from nnstreamer_tpu.elements.base import ElementError
        from nnstreamer_tpu.elements.video import Compositor

        c = Compositor({})
        c.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()},
                    ["src"])
        with pytest.raises(ElementError, match="videoscale"):
            c.process_group({
                "sink_0": Buffer([np.zeros((4, 4, 3), np.uint8)]),
                "sink_1": Buffer([np.zeros((2, 2, 4), np.uint8)]),
            })

    def test_stock_overlay_pipeline(self):
        """tee'd video + detection overlay composited — the stock example
        shape (camera branch + decoder branch reunited)."""
        desc = (
            "videotestsrc num-buffers=2 width=32 height=32 pattern=ball "
            "name=cam ! tee name=t "
            "t. ! queue ! comp.sink_0 "
            "t. ! queue ! tensor_converter ! "
            "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
            "tensor_filter framework=jax model=ssd_mobilenet "
            "custom=size:32,classes:4,batch:1 ! "
            "tensor_decoder mode=bounding_boxes option3=0.3 option4=32:32 ! "
            "comp.sink_1 "
            "compositor name=comp ! tensor_sink name=out"
        )
        p = nt.Pipeline(desc, fuse=False)
        with p:
            bufs = [p.pull("out", timeout=60) for _ in range(2)]
            p.wait(timeout=30)
        for b in bufs:
            assert b.tensors[0].shape == (32, 32, 3)
            assert "detections" in b.meta

    def test_bare_refs_and_pad_alpha_and_bgr_base(self):
        """GStreamer spellings work: bare `comp.` branch refs, per-pad
        sink_1::alpha, and a BGR base blends in its own channel order."""
        desc = (
            "videotestsrc num-buffers=1 width=8 height=8 pattern=black ! "
            "videoconvert format=BGR ! comp. "
            "appsrc name=ov ! comp. "
            "compositor name=comp sink_1::alpha=0.5 ! tensor_sink name=out"
        )
        p = nt.Pipeline(desc, fuse=False)
        ov = np.zeros((8, 8, 4), np.uint8)
        ov[..., 0] = 200  # pure RED overlay, fully opaque...
        ov[..., 3] = 255  # ...then scaled by pad alpha 0.5
        with p:
            p.push("ov", ov)
            b = p.pull("out", timeout=15)
            p.eos("ov")
            p.wait(timeout=15)
        out = b.tensors[0]
        # base black BGR; red at half alpha lands in the B-G-R layout's
        # channel 2 at ~100
        assert abs(int(out[0, 0, 2]) - 100) <= 1
        assert out[0, 0, 0] == 0  # blue channel untouched
