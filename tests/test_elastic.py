"""nns-elastic (ISSUE 11): drain/handover, orphan reaping, admission
robustness, the burn-rate autoscaler, and the recompile-on-reconfig
lint — docs/SERVING.md "Elastic serving".
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import Metrics, metrics
from nnstreamer_tpu.filters.llm import LLMFramework
from nnstreamer_tpu.trainer import checkpoint as ckpt
from nnstreamer_tpu.pipeline.runtime import PipelineError
from nnstreamer_tpu.utils import elastic, tracing

SERVE = ("max_new:10,serve:continuous,slots:2,stream_chunk:2,"
         "temperature:0.0,dtype:float32")


def make_fw(custom: str = SERVE, model: str = "llama_tiny"):
    fw = LLMFramework()
    fw.open({"model": model, "custom": custom})
    return fw


class Collector:
    """emit() target: records (token_id, meta) and flags completion."""

    def __init__(self):
        self.toks = []
        self.done = threading.Event()

    def __call__(self, tensors, meta):
        self.toks.append((int(tensors[0][0]) if len(tensors[0]) else -1,
                          dict(meta)))
        if meta.get("stream_last"):
            self.done.set()

    @property
    def ids(self):
        return [t for t, m in self.toks if t >= 0]

    @property
    def sid(self):
        return self.toks[0][1].get("stream_id") if self.toks else None


# ---------------------------------------------------------------------------
# drain / adopt
# ---------------------------------------------------------------------------

class TestDrainAdopt:
    def test_greedy_bit_identity_and_census(self):
        """A live greedy stream drained at step k and adopted on a fresh
        loop continues BIT-IDENTICALLY to an undrained run, with the
        3-program zero-recompile census intact on both loops, and both
        pools' free lists fully restored."""
        prompt = np.asarray([[3, 5, 7, 9]], np.int32)
        ref_c = Collector()
        fw_ref = make_fw()
        fw_ref.submit([prompt[0]], {}, ref_c)
        assert ref_c.done.wait(60)
        ref = ref_c.ids

        fw_a, fw_b = make_fw(), make_fw()
        got = Collector()
        seen3 = threading.Event()

        def emit_a(tensors, meta):
            got(tensors, meta)
            if len(got.toks) >= 3:
                seen3.set()

        fw_a.submit([prompt[0]], {}, emit_a)
        assert seen3.wait(60)
        snap = fw_a.drain_stream(got.sid, timeout=30)
        assert snap["kind"] == "live" and snap["greedy"] is True
        # the drained pipeline's pool is whole again
        assert fw_a._serve.pool_stats()["blocks_free"] == \
            fw_a._serve.pool_stats()["blocks_total"]
        # roundtrip through the checkpoint serialization substrate
        snap = ckpt.load_stream_snapshot(
            ckpt.save_stream_snapshot("/tmp/nns_elastic_snap.pkl", snap))

        cont = Collector()
        fw_b.adopt_stream(snap, cont)
        assert cont.done.wait(60)
        pre = got.ids[:snap["sidx"]]
        assert pre + cont.ids == ref, (pre, cont.ids, ref)
        # stream_index continues where the drained pipeline stopped
        assert [m["stream_index"] for _, m in cont.toks] == \
            list(range(snap["sidx"], len(ref)))
        assert cont.toks[-1][1].get("stream_last") is True
        # the 3-program zero-recompile pin holds on BOTH loops
        for fw in (fw_a, fw_b):
            loop = fw._serve
            assert (loop._decode._cache_size(),
                    loop._prefill._cache_size(),
                    loop._set_tok._cache_size()) == (1, 1, 1)
            stats = loop.pool_stats()
            assert stats["blocks_free"] == stats["blocks_total"]
        for fw in (fw_ref, fw_a, fw_b):
            fw.close()

    def test_drain_queued_stream_readmits(self):
        """A stream still WAITING for admission drains as a queued-kind
        snapshot (prompt + meta, no blocks) and completes after adopt."""
        fw_a, fw_b = make_fw(), make_fw()
        blocker, queued = Collector(), Collector()
        # slots:2 — fill both so the third submit stays queued
        fw_a.submit([np.asarray([1, 2, 3], np.int32)], {}, Collector())
        fw_a.submit([np.asarray([2, 3, 4], np.int32)], {}, blocker)
        sid = fw_a._serve.submit(np.asarray([[5, 6, 7]], np.int32),
                                 {}, queued)
        # it may admit once the first two finish — drain promptly; accept
        # either kind (queued before admission, live after)
        snap = fw_a.drain_stream(sid, timeout=30)
        assert snap["kind"] in ("queued", "live")
        cont = Collector()
        fw_b.adopt_stream(snap, cont)
        assert cont.done.wait(60)
        ref_c = Collector()
        ref_fw = make_fw()
        ref_fw.submit([np.asarray([5, 6, 7], np.int32)], {}, ref_c)
        assert ref_c.done.wait(60)
        pre = [] if snap["kind"] == "queued" else snap["sidx"]
        if snap["kind"] == "live":
            assert cont.ids == ref_c.ids[snap["sidx"]:]
        else:
            assert cont.ids == ref_c.ids
        for fw in (fw_a, fw_b, ref_fw):
            fw.close()

    def test_adopt_rejects_incompatible_snapshot(self):
        fw_a = make_fw()
        c = Collector()
        fw_a.submit([np.asarray([1, 2, 3, 4], np.int32)], {}, c)
        assert c.done.wait(60) or c.toks  # at least started
        while not c.done.wait(1):
            pass
        # finished stream: drain on a fresh one to get a snapshot
        got = Collector()
        seen = threading.Event()

        def emit(tensors, meta):
            got(tensors, meta)
            seen.set()

        fw_a.submit([np.asarray([9, 8, 7], np.int32)], {}, emit)
        assert seen.wait(60)
        snap = fw_a.drain_stream(got.sid, timeout=30)
        # different model geometry must be rejected with named problems
        fw_other = make_fw(SERVE + ",n_layers:1")
        from nnstreamer_tpu.filters.base import FrameworkError

        with pytest.raises(FrameworkError, match="geometry"):
            fw_other.adopt_stream(snap, Collector())
        # stale snapshot version
        bad = dict(snap, version=99)
        with pytest.raises(FrameworkError, match="version"):
            fw_a.adopt_stream(bad, Collector())
        fw_a.close()
        fw_other.close()

    def test_drain_under_sharing_materializes_private_blocks(self):
        """Drain a stream whose prefix blocks are SHARED (refcount>1)
        with a still-live peer: the v2 snapshot must carry private
        copies (never alias pool blocks), the surviving stream must
        finish bit-identically, and the adopted continuation must be
        bit-identical to an undrained run."""
        custom = ("max_new:24,serve:continuous,slots:2,stream_chunk:2,"
                  "temperature:0.0,dtype:float32,block_size:8,"
                  "prefill_chunk:8")
        rng = np.random.default_rng(42)
        pre = rng.integers(1, 500, (32,), np.int32)
        pa = np.concatenate([pre, rng.integers(1, 500, (3,), np.int32)])
        pb = np.concatenate([pre, rng.integers(1, 500, (5,), np.int32)])
        refs = []
        for p in (pa, pb):
            c = Collector()
            fw = make_fw(custom)
            fw.submit([p], {}, c)
            assert c.done.wait(120)
            refs.append(c.ids)
            fw.close()

        fw_a, fw_b = make_fw(custom), make_fw(custom)
        got_a, got_b = Collector(), Collector()
        seen_b = threading.Event()

        def emit_b(tensors, meta):
            got_b(tensors, meta)
            if len(got_b.toks) >= 3:
                seen_b.set()

        fw_a.submit([pa], {}, got_a)
        while not got_a.toks:
            time.sleep(0.005)
        fw_b_sid_holder = fw_a.submit([pb], {}, emit_b)
        del fw_b_sid_holder
        assert seen_b.wait(120)
        # B's prefix blocks are shared with the still-live A
        snap = fw_a.drain_stream(got_b.sid, timeout=30)
        assert snap["version"] == 2 and snap["kind"] == "live"
        assert snap["shared_blocks"] >= 4, snap["shared_blocks"]
        # the snapshot's cache rows are host copies, not pool views
        assert isinstance(snap["blocks_k"], np.ndarray)
        # survivor decodes to completion bit-identically — the drain
        # did not perturb (or free) the blocks it still references
        assert got_a.done.wait(120)
        assert got_a.ids == refs[0]
        cont = Collector()
        fw_b.adopt_stream(snap, cont)
        assert cont.done.wait(120)
        assert got_b.ids[:snap["sidx"]] + cont.ids == refs[1]
        # both pools whole again after everything retires
        for fw in (fw_a, fw_b):
            stats = fw._serve.pool_stats()
            assert stats["blocks_free"] == stats["blocks_total"]
            fw.close()

    def test_snapshot_file_version_gate(self, tmp_path):
        path = str(tmp_path / "snap.pkl")
        ckpt.save_stream_snapshot(path, {"kind": "queued", "version": 1})
        loaded = ckpt.load_stream_snapshot(path)
        assert loaded["kind"] == "queued"
        import pickle

        with open(path, "wb") as f:
            pickle.dump({"snapshot_version": 42}, f)
        with pytest.raises(ValueError, match="version"):
            ckpt.load_stream_snapshot(path)


# ---------------------------------------------------------------------------
# orphan reaping / cancellation
# ---------------------------------------------------------------------------

class TestCancelReap:
    def test_force_cancel_reaps_blocks_and_terminates(self):
        fw = make_fw(SERVE.replace("max_new:10", "max_new:200"))
        got = Collector()
        first = threading.Event()

        def emit(tensors, meta):
            got(tensors, meta)
            first.set()

        fw.submit([np.asarray([1, 2, 3], np.int32)], {}, emit)
        assert first.wait(60)
        sid = got.sid
        base = metrics.snapshot().get("llm.serve.reaped", 0.0)
        assert elastic.cancel_stream(sid, "test-reap", force=True)
        # terminator arrives and the pool is whole again
        assert got.done.wait(30)
        last = got.toks[-1][1]
        assert last.get("stream_aborted") is True
        assert last.get("abort_reason") == "test-reap"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            stats = fw._serve.pool_stats()
            if stats["blocks_free"] == stats["blocks_total"]:
                break
            time.sleep(0.05)
        stats = fw._serve.pool_stats()
        assert stats["blocks_free"] == stats["blocks_total"], stats
        assert metrics.snapshot().get("llm.serve.reaped", 0.0) == base + 1
        # registry entry cleaned up; cancel is now a no-op
        assert elastic.cancel_stream(sid) is False
        fw.close()

    def test_cancel_unknown_stream_is_noop(self):
        assert elastic.cancel_stream(999999999) is False
        assert elastic.cancel_stream(None) is False

    def test_slot_reusable_after_reap(self):
        fw = make_fw(SERVE.replace("slots:2", "slots:1")
                     .replace("max_new:10", "max_new:200"))
        got = Collector()
        first = threading.Event()

        def emit(tensors, meta):
            got(tensors, meta)
            first.set()

        fw.submit([np.asarray([1, 2, 3], np.int32)], {}, emit)
        assert first.wait(60)
        elastic.cancel_stream(got.sid, force=True)
        assert got.done.wait(30)
        # the only slot was reaped — a fresh stream must admit and finish
        nxt = Collector()
        fw.submit([np.asarray([4, 5, 6], np.int32)], {}, nxt)
        assert nxt.done.wait(60)
        assert not nxt.toks[-1][1].get("stream_aborted")
        fw.close()


# ---------------------------------------------------------------------------
# admission robustness (FIFO head-of-line + quotas)
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_admit_timeout_rejects_typed(self):
        """A waiting stream that cannot admit within admit_timeout is
        rejected with a typed abort instead of wedging every tenant
        queued behind it (the head-of-line fix)."""
        # slots:1 + a long-running occupant: the second prompt waits
        fw = make_fw("max_new:200,serve:continuous,slots:1,"
                     "stream_chunk:2,temperature:0.0,dtype:float32,"
                     "admit_timeout:0.3,n_layers:4")
        occupant, waiter = Collector(), Collector()
        first = threading.Event()

        def emit(tensors, meta):
            occupant(tensors, meta)
            first.set()

        fw.submit([np.asarray([1, 2, 3], np.int32)], {}, emit)
        assert first.wait(60)
        fw.submit([np.asarray([4, 5, 6], np.int32)], {}, waiter)
        assert waiter.done.wait(30)
        last = waiter.toks[-1][1]
        # either the occupant finished first (fast host) and the waiter
        # ran, or — the path under test — it timed out typed.  Force
        # determinism: the occupant decodes 200 tokens, far longer than
        # 0.3 s only on slow hosts, so accept the reject OR a full run
        # but require the typed reason when aborted.
        if last.get("stream_aborted"):
            assert last.get("abort_reason") == "admit-timeout"
            assert metrics.snapshot().get(
                "llm.serve.admit_timeouts", 0.0) >= 1
        fw.close()

    def test_impossible_reservation_rejected_typed(self):
        fw = make_fw()
        # max_seq-exceeding prompt: typed oversize rejection
        T = fw.cfg.max_seq + 4
        c = Collector()
        fw.submit([np.arange(1, T + 1, dtype=np.int32)], {}, c)
        assert c.done.wait(30)
        assert c.toks[-1][1].get("stream_aborted") is True
        assert c.toks[-1][1].get("abort_reason") == "prompt-oversize"
        fw.close()

    def test_tenant_quota_skips_not_blocks(self):
        """An over-quota tenant's prompt is SKIPPED (tenant-scoped
        deferral), not allowed to head-of-line-block other tenants."""
        fw = make_fw()
        loop_holder = {}
        capped, other = Collector(), Collector()
        # quota 0 blocks all reservations for tenant "capped"
        fw.submit([np.asarray([1, 2, 3], np.int32)],
                  {"_tenant": "capped"}, capped)
        loop_holder["loop"] = fw._serve
        fw._serve.set_tenant_quota("capped", 0)
        # wait out the first (pre-quota) stream, then submit both
        assert capped.done.wait(60)
        capped2 = Collector()
        fw.submit([np.asarray([1, 2, 3], np.int32)],
                  {"_tenant": "capped"}, capped2)
        fw.submit([np.asarray([4, 5, 6], np.int32)],
                  {"_tenant": "other"}, other)
        # "other" completes while "capped" defers behind its quota
        assert other.done.wait(60)
        assert not capped2.done.is_set()
        assert metrics.snapshot().get("llm.serve.quota_deferred",
                                      0.0) >= 1
        # lifting the quota admits the deferred stream
        fw._serve.set_tenant_quota("capped", None)
        assert capped2.done.wait(60)
        assert not capped2.toks[-1][1].get("stream_aborted")
        fw.close()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

class _StubCore:
    def __init__(self):
        self.tenant_admission = {}


class _StubLoop:
    def __init__(self):
        self.quotas = {}

    def set_tenant_quota(self, tenant, blocks):
        if blocks is None:
            self.quotas.pop(tenant, None)
        else:
            self.quotas[tenant] = blocks


class _StubFw:
    continuous = True

    def __init__(self):
        self._serve = _StubLoop()


class _StubEl:
    def __init__(self, core=None, fw=None):
        if core is not None:
            self._core = core
        if fw is not None:
            self.fw = fw


class _StubPipeline:
    def __init__(self, *els):
        self.elements = dict(enumerate(els))


class TestAutoscaler:
    def _mk(self, rules, core=None, loop_el=None):
        m = Metrics()
        rec = tracing.FlightRecorder("ring", 1024)
        els = [e for e in (
            _StubEl(core=core) if core is not None else None,
            loop_el) if e is not None]
        scaler = elastic.Autoscaler(
            _StubPipeline(*els), {"rules": rules}, metrics=m,
            recorder=rec)
        return scaler, m, rec

    def test_engage_relax_hysteresis_and_spans(self):
        core = _StubCore()
        scaler, m, rec = self._mk(
            [{"tenant": "*", "burn_above": 1.5, "burn_below": 0.5,
              "action": "admission:shed", "cooldown_s": 0.0}],
            core=core)
        m.gauge("slo.burn_rate", 3.0, tenant="acme")
        assert scaler.evaluate() == 1
        assert core.tenant_admission == {"acme": "shed"}
        # already engaged: in-band burn produces NO further edges
        m.gauge("slo.burn_rate", 2.5, tenant="acme")
        assert scaler.evaluate() == 0
        # inside the hysteresis band (0.5..1.5): still engaged
        m.gauge("slo.burn_rate", 1.0, tenant="acme")
        assert scaler.evaluate() == 0
        assert core.tenant_admission == {"acme": "shed"}
        # below the low band: relax
        m.gauge("slo.burn_rate", 0.2, tenant="acme")
        assert scaler.evaluate() == 1
        assert core.tenant_admission == {}
        kinds = [e.kind for e in rec.events()]
        assert kinds.count("elastic.scale") == 2
        edges = [e.args["edge"] for e in rec.events()
                 if e.kind == "elastic.scale"]
        assert edges == ["engage", "relax"]
        assert [a["edge"] for a in scaler.actions] == ["engage", "relax"]

    def test_cooldown_rate_limits(self):
        core = _StubCore()
        scaler, m, _ = self._mk(
            [{"tenant": "t", "burn_above": 1.0, "burn_below": 0.1,
              "action": "admission:shed", "cooldown_s": 60.0}],
            core=core)
        m.gauge("slo.burn_rate", 5.0, tenant="t")
        assert scaler.evaluate() == 1
        # burn drops under the low band immediately — but the cooldown
        # holds the relax edge back
        m.gauge("slo.burn_rate", 0.0, tenant="t")
        assert scaler.evaluate() == 0
        assert core.tenant_admission == {"t": "shed"}

    def test_kv_quota_action(self):
        el = _StubEl(fw=_StubFw())
        scaler, m, _ = self._mk(
            [{"tenant": "big", "burn_above": 1.0, "burn_below": 0.2,
              "action": "kv_quota:8", "cooldown_s": 0.0}],
            loop_el=el)
        m.gauge("slo.burn_rate", 2.0, tenant="big")
        assert scaler.evaluate() == 1
        assert el.fw._serve.quotas == {"big": 8}
        m.gauge("slo.burn_rate", 0.0, tenant="big")
        assert scaler.evaluate() == 1
        assert el.fw._serve.quotas == {}

    def test_policy_validation(self):
        problems = elastic.validate_autoscale_policy({"rules": [
            {"tenant": "x", "action": "explode"},
            {"burn_above": 1.0, "burn_below": 2.0},
            {"action": "kv_quota:-3"},
        ]})
        joined = "\n".join(problems)
        assert "explode" in joined
        assert "hysteresis" in joined
        assert "kv_quota" in joined
        with pytest.raises(ValueError, match="invalid autoscale"):
            elastic.load_autoscale_policy({"rules": [{"action": "nope"}]})
        assert elastic.load_autoscale_policy(None) == []

    def test_spill_action_drains_to_second_pipeline(self):
        """The spill action: a live stream of the burning tenant drains
        off the primary pipeline and is adopted by ``spill_to`` — real
        frameworks, stubbed only at the Pipeline wrapper level."""
        fw_a = make_fw(SERVE.replace("max_new:10", "max_new:200"))
        fw_b = make_fw(SERVE.replace("max_new:10", "max_new:200"))

        class _Pipe:
            def __init__(self, fw, sink):
                self.fw, self.sink = fw, sink
                self.elements = {}

            def serve_streams(self):
                return self.fw.serve_streams()

            def drain_stream(self, sid, timeout=10.0):
                return self.fw.drain_stream(sid, timeout)

            def adopt_stream(self, snap, timeout=10.0):
                return self.fw.adopt_stream(snap, self.sink)

        cont = Collector()
        prim, sec = _Pipe(fw_a, None), _Pipe(fw_b, cont)
        got = Collector()
        first = threading.Event()

        def emit(tensors, meta):
            got(tensors, meta)
            first.set()

        fw_a.submit([np.asarray([7, 8, 9], np.int32)],
                    {"_tenant": "noisy"}, emit)
        assert first.wait(60)
        m = Metrics()
        m.gauge("slo.burn_rate", 9.0, tenant="noisy")
        scaler = elastic.Autoscaler(
            prim, {"rules": [{"tenant": "noisy", "burn_above": 2.0,
                              "burn_below": 0.5, "action": "spill",
                              "cooldown_s": 60.0}]},
            spill_to=sec, metrics=m)
        assert scaler.evaluate() == 1
        assert cont.done.wait(60)
        # the spilled stream finished on the SECOND framework
        assert fw_b.serve_streams() == {}
        assert fw_a.serve_streams() == {}
        fw_a.close()
        fw_b.close()


# ---------------------------------------------------------------------------
# recompile-on-reconfig lint
# ---------------------------------------------------------------------------

class TestReconfigLint:
    DESC = ("appsrc name=src ! tensor_filter framework=llm "
            "model=llama_tiny custom=max_new:32,serve:continuous,slots:4 "
            "invoke-dynamic=true ! tensor_sink name=out")

    def test_signature_knobs_warn_value_knobs_pass(self):
        report = nt.analyze(self.DESC, deep=True,
                            reconfig={"slots": 8, "max_new": 64,
                                      "kv_blocks": 128})
        hits = [d for d in report
                if d.code == "recompile-on-reconfig"]
        msgs = "\n".join(d.message for d in hits)
        assert "slots" in msgs and "kv_blocks" in msgs
        assert "max_new" not in msgs  # host-value knob: silent
        assert "drain_stream" in msgs  # the remediation is named
        assert all(d.severity == "warning" for d in hits)

    def test_unchanged_knob_is_silent(self):
        report = nt.analyze(self.DESC, deep=True, reconfig={"slots": 4})
        assert not [d for d in report
                    if d.code == "recompile-on-reconfig"]

    def test_unset_knob_compares_against_default(self):
        # temperature is not in the custom= string; proposing its
        # compiled-in default (0.0) is a no-op, not a recompile
        report = nt.analyze(self.DESC, deep=True,
                            reconfig={"temperature": 0.0})
        assert not [d for d in report
                    if d.code == "recompile-on-reconfig"]
        report = nt.analyze(self.DESC, deep=True,
                            reconfig={"temperature": 0.7})
        assert [d for d in report if d.code == "recompile-on-reconfig"]

    def test_unknown_knob_flagged(self):
        report = nt.analyze(self.DESC, deep=True,
                            reconfig={"warp_factor": 9})
        hits = [d for d in report if d.code == "recompile-on-reconfig"]
        assert hits and "warp_factor" in hits[0].message

    def test_table_covers_documented_knobs(self):
        for knob in ("slots", "block_size", "kv_blocks", "prefill_chunk",
                     "stream_chunk", "max_new", "prefill_budget",
                     "admit_timeout", "stream_idle_timeout"):
            assert knob in elastic.SERVE_KNOB_SIGNATURE


# ---------------------------------------------------------------------------
# elastic stage restarts
# ---------------------------------------------------------------------------

class TestStageRestart:
    def _register(self, name, fail_times):
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")
        state = {"n": 0}

        def work(ins):
            state["n"] += 1
            if state["n"] <= fail_times:
                raise RuntimeError("injected stage fault")
            return [ins[0] * 2.0]

        register_custom_easy(name, work, in_spec=spec, out_spec=spec)

    @staticmethod
    def _force_restartable(p):
        """The injected fault lives in a HOST custom-easy fn (the only
        way to raise deterministically per-buffer), which the planner
        rightly does not mark pure — flip the marker to exercise the
        runner's restart machinery itself."""
        for r in {id(r): r for r in p._runners.values()}.values():
            if r.element.kind == "tensor_filter":
                r.stage.restartable = True

    def test_planner_marks_pure_stages_restartable(self):
        # the fused device chain (transform+filter) is pure → restartable;
        # source and sink stages stay fail-fast
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=4:4,"
            "types=float32 ! "
            "tensor_transform mode=arithmetic "
            "option=typecast:float32,div:2.0 ! "
            "tensor_filter framework=jax model=scaler "
            "custom=scale:4.0,dims:4:4 ! tensor_sink name=out")
        fused = [s for s in p.stages if len(s.node_ids) > 1]
        assert fused and all(s.restartable for s in fused)
        for s in p.stages:
            if s.element.kind in ("appsrc", "tensor_sink"):
                assert not s.restartable

    def test_restart_survives_bounded_faults(self):
        self._register("elastic-flaky", fail_times=1)
        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter name=flaky "
            "framework=custom-easy model=elastic-flaky ! "
            "tensor_sink name=out",
            fuse=False, max_stage_restarts=2)
        self._force_restartable(p)
        with p:
            for i in range(4):
                p.push("src", np.full((4,), float(i + 1), np.float32))
            outs = []
            while True:
                try:
                    outs.append(float(np.asarray(
                        p.pull("out", timeout=10).tensors[0])[0]))
                except TimeoutError:
                    break
            p.eos("src")
            p.wait(timeout=20)
        # buffer 1 was lost to the fault; 2..4 survived the restart
        assert outs == [4.0, 6.0, 8.0]
        assert metrics.snapshot().get("flaky.restarts", 0.0) == 1

    def test_restart_budget_exhausts_to_failure(self):
        self._register("elastic-dead", fail_times=10 ** 9)
        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter name=dead "
            "framework=custom-easy model=elastic-dead ! "
            "tensor_sink name=out",
            fuse=False, max_stage_restarts=1)
        self._force_restartable(p)
        with p:
            p.push("src", np.ones((4,), np.float32))
            p.push("src", np.ones((4,), np.float32))
            p.eos("src")
            with pytest.raises(PipelineError, match="injected"):
                p.wait(timeout=20)
        assert metrics.snapshot().get("dead.restarts", 0.0) == 1

    def test_default_is_fail_fast(self):
        self._register("elastic-once", fail_times=1)
        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter framework=custom-easy "
            "model=elastic-once ! tensor_sink name=out", fuse=False)
        with p:
            p.push("src", np.ones((4,), np.float32))
            p.eos("src")
            with pytest.raises(PipelineError):
                p.wait(timeout=20)
