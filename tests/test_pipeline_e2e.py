"""End-to-end pipeline tests on the CPU backend (reference analog: SSAT
integration suites driving gst-launch pipelines — SURVEY §4)."""

import time
import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.filters.custom_easy import register_custom_easy


@pytest.fixture(autouse=True)
def _register_models():
    spec = TensorsSpec.from_string("3:8:8:1", "float32")
    register_custom_easy(
        "e2e-double", lambda ins: [ins[0] * 2], in_spec=spec, out_spec=spec,
        jax_traceable=True,
    )
    yield


def test_videotestsrc_to_sink():
    p = nt.Pipeline(
        "videotestsrc num-buffers=4 width=8 height=8 pattern=random ! "
        "tensor_converter ! tensor_sink name=out"
    )
    with p:
        bufs = [p.pull("out", timeout=10) for _ in range(4)]
        p.wait(timeout=10)
    assert len(bufs) == 4
    assert bufs[0].tensors[0].shape == (1, 8, 8, 3)
    assert bufs[0].tensors[0].dtype == np.uint8
    # determinism: same pattern+index = same frame
    p2 = nt.Pipeline(
        "videotestsrc num-buffers=1 width=8 height=8 pattern=random ! "
        "tensor_converter ! tensor_sink name=out"
    )
    with p2:
        again = p2.pull("out", timeout=10)
    np.testing.assert_array_equal(bufs[0].tensors[0], again.tensors[0])


def test_appsrc_push_pull():
    p = nt.Pipeline("appsrc name=src ! tensor_sink name=out")
    with p:
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        p.push("src", x)
        out = p.pull("out", timeout=10)
        np.testing.assert_array_equal(out.tensors[0], x)
        p.eos("src")
        p.wait(timeout=10)


def test_full_slice_custom_easy():
    """src -> converter -> transform -> filter -> sink, unfused host path."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=3 width=8 height=8 pattern=random ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=custom-easy model=e2e-double ! "
        "tensor_sink name=out",
        fuse=False,
    )
    with p:
        outs = [p.pull("out", timeout=10) for _ in range(3)]
        p.wait(timeout=10)
    for buf in outs:
        a = buf.tensors[0]
        assert a.shape == (1, 8, 8, 3)
        assert a.dtype == np.float32
        assert a.max() <= 2.0 and a.min() >= 0.0


def test_fused_matches_unfused():
    desc = (
        "videotestsrc num-buffers=2 width=8 height=8 pattern=random ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=custom-easy model=e2e-double ! "
        "tensor_sink name=out"
    )
    results = {}
    for fuse in (False, True):
        p = nt.Pipeline(desc, fuse=fuse)
        with p:
            results[fuse] = [p.pull("out", timeout=15) for _ in range(2)]
            p.wait(timeout=15)
    for a, b in zip(results[False], results[True]):
        np.testing.assert_allclose(a.tensors[0], b.tensors[0], rtol=1e-6)


def test_fusion_actually_fuses():
    desc = (
        "videotestsrc num-buffers=1 width=8 height=8 ! tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=custom-easy model=e2e-double ! "
        "tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fuse=True)
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused, "transform+filter should fuse into one XLA stage"
    assert len(fused[0].node_ids) == 2


def test_jax_framework_scaler():
    p = nt.Pipeline(
        "appsrc name=src ! "
        "tensor_filter framework=jax model=scaler custom=scale:3.0,dims:4 ! "
        "tensor_sink name=out"
    )
    with p:
        p.push("src", np.array([1.0, 2.0, 3.0, 4.0], np.float32))
        out = p.pull("out", timeout=20)
        np.testing.assert_allclose(out.tensors[0], [3.0, 6.0, 9.0, 12.0])
        p.eos()
        p.wait(timeout=10)


def test_single_shot():
    s = nt.SingleShot(framework="jax", model="scaler", custom="scale:2.0,dims:3")
    out = s.invoke(np.array([1.0, 2.0, 3.0], np.float32))
    np.testing.assert_allclose(out[0], [2.0, 4.0, 6.0])
    s.close()


def test_framework_auto_priority():
    """framework=auto walks the priority list until a framework opens."""
    s = nt.SingleShot(framework="auto", model="scaler", custom="scale:2.0,dims:2")
    out = s.invoke(np.array([1.0, 2.0], np.float32))
    np.testing.assert_allclose(out[0], [2.0, 4.0])


def test_filter_latency_reported():
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=custom-easy model=e2e-double name=f ! "
        "tensor_sink name=out",
        fuse=False,
    )
    with p:
        p.pull("out", timeout=10)
        p.pull("out", timeout=10)
        p.wait(timeout=10)
    f = p.element("f")
    assert f.latency is not None and f.latency > 0
    assert f.throughput > 0


def test_error_propagates():
    register_custom_easy("boom", lambda ins: 1 / 0)
    p = nt.Pipeline(
        "appsrc name=src ! tensor_filter framework=custom-easy model=boom ! "
        "tensor_sink name=out",
        fuse=False,
    )
    with p:
        p.push("src", np.zeros(3, np.float32))
        with pytest.raises(Exception):
            for _ in range(100):
                p.pull("out", timeout=0.3)


def test_appsrc_caps_fuses_through_decoder():
    """appsrc caps carry the tensor spec, so transform+filter+decoder fuse
    into ONE XLA stage, with the label mapping deferred to the sink
    (host_post) — the headline bench topology."""
    desc = (
        "appsrc name=src caps=other/tensors,dimensions=4:4,types=float32 ! "
        "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:4:4 ! "
        "tensor_decoder mode=image_labeling option1=digits ! "
        "tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fuse=True)
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused and len(fused[0].node_ids) == 2

    x = np.zeros((4, 4), np.float32)
    x[np.arange(4), [2, 0, 3, 1]] = 5.0
    with p:
        p.push("src", x)
        buf = p.pull("out", timeout=15)
        p.eos()
        p.wait(timeout=15)
    assert list(buf.meta["label_index"]) == [2, 0, 3, 1]
    assert buf.meta["label"] == ["2", "0", "3", "1"]
    assert bytes(buf.tensors[0]).decode() == "2\n0\n3\n1"


def test_image_labeling_fused_matches_host():
    desc = (
        "appsrc name=src caps=other/tensors,dimensions=10:3,types=float32 ! "
        "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:10:3 ! "
        "tensor_decoder mode=image_labeling option1=digits ! "
        "tensor_sink name=out"
    )
    rng = np.random.default_rng(7)
    x = rng.standard_normal((3, 10)).astype(np.float32)
    outs = {}
    for fuse in (False, True):
        p = nt.Pipeline(desc, fuse=fuse)
        with p:
            p.push("src", x)
            outs[fuse] = p.pull("out", timeout=15)
            p.eos()
            p.wait(timeout=15)
    a, b = outs[False], outs[True]
    assert list(a.meta["label_index"]) == list(b.meta["label_index"])
    assert a.meta["label"] == b.meta["label"]
    np.testing.assert_allclose(a.meta["score"], b.meta["score"], rtol=1e-6)
    assert bytes(a.tensors[0]) == bytes(b.tensors[0])


def test_detection_decoder_fuses_and_defers():
    """Config #2 topology: transform+filter+bounding_boxes fuse into ONE XLA
    stage; NMS/overlay resolve lazily at the sink (host_post), one buffer
    per batch with per-frame detections in meta."""
    desc = (
        "videotestsrc device=true batch=2 num-buffers=4 width=64 height=64 "
        "pattern=ball name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=ssd_mobilenet "
        "custom=size:64,classes:5,batch:2 name=f ! "
        "tensor_decoder mode=bounding_boxes option3=0.3 option4=64:64 ! "
        "tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fuse=True)
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    # device source folds in too: src+transform+filter+decoder, one stage
    assert fused and len(fused[0].node_ids) == 4
    with p:
        bufs = [p.pull("out", timeout=120) for _ in range(2)]
        p.wait(timeout=60)
    for b in bufs:
        assert b.tensors[0].shape == (2, 64, 64, 4)
        assert len(b.meta["detections"]) == 2
        for frame_dets in b.meta["detections"]:
            for det in frame_dets:
                assert set(det) == {"box", "score", "class_index", "label"}


def test_audiotestsrc_device_matches_host_sine():
    """Device-generated windows must match the host sine path sample-for-
    sample (float32 tolerance)."""
    from nnstreamer_tpu.elements.source import AudioTestSrc

    host = AudioTestSrc({"format": "F32LE", "samplesperbuffer": 800,
                         "rate": 16000, "num_buffers": 4})
    host.configure({}, ["src"])
    host_windows = [b.tensors[0][:, 0] for b in host.generate()]

    dev = AudioTestSrc({"device": True, "batch": 2, "samplesperbuffer": 800,
                        "rate": 16000, "num_buffers": 4})
    dev.configure({}, ["src"])
    bufs = list(dev.generate())
    assert len(bufs) == 2  # 4 windows, batch=2
    got = np.concatenate([np.asarray(b.tensors[0]) for b in bufs], axis=0)
    want = np.stack(host_windows)
    # float32 sine vs the host's float64 path: ~1e-4 amplitude tolerance
    np.testing.assert_allclose(got, want, atol=2e-4)


def test_iio_device_backend_file_to_filter(tmp_path):
    """Deterministic synthetic sensor stream (interleaved s16le records in a
    file) through tensor_src_iio's buffered-scan backend into a filter
    (reference gsttensor_srciio.c semantics: scan decode, scale/offset,
    capacity batching; VERDICT r1 item #7)."""
    channels, capacity = 3, 8
    n_samples = capacity * 4 + 5  # tail of 5 must be dropped, not emitted
    rng = np.random.default_rng(2)
    raw = rng.integers(-1000, 1000, (n_samples, channels)).astype("<i2")
    dev = tmp_path / "iio_dev.bin"
    dev.write_bytes(raw.tobytes())

    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    spec = TensorsSpec.from_string(f"{channels}:{capacity}", "float32")
    register_custom_easy(
        "iio_mean", lambda ins: [np.mean(ins[0], axis=0)],
        in_spec=spec, out_spec=TensorsSpec.from_string(f"{channels}", "float32"))

    p = nt.Pipeline(
        f"tensor_src_iio device={dev} channels={channels} "
        f"buffer-capacity={capacity} scan-format=s16le scale=0.5 offset=2 "
        "num-buffers=-1 ! "
        "tensor_filter framework=custom-easy model=iio_mean ! "
        "tensor_sink name=out",
        fuse=False,
    )
    got = []
    with p:
        for _ in range(4):
            got.append(p.pull("out", timeout=15))
        p.wait(timeout=15)  # EOF after 4 full scans: clean EOS
    assert len(got) == 4
    for i, b in enumerate(got):
        window = raw[i * capacity:(i + 1) * capacity].astype(np.float32)
        want = np.mean((window + 2.0) * 0.5, axis=0)
        np.testing.assert_allclose(np.asarray(b.tensors[0]), want, rtol=1e-5)


def test_iio_tcp_backend():
    """Remote sensor stream over a socket (device=tcp://...)."""
    import socket
    import threading as th

    channels, capacity = 2, 4
    raw = np.arange(capacity * channels * 2, dtype="<i2")
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve():
        conn, _ = srv.accept()
        conn.sendall(raw.tobytes())
        conn.close()

    t = th.Thread(target=serve, daemon=True)
    t.start()
    p = nt.Pipeline(
        f"tensor_src_iio device=tcp://127.0.0.1:{port} channels={channels} "
        f"buffer-capacity={capacity} scan-format=s16le num-buffers=-1 ! "
        "tensor_sink name=out",
        fuse=False,
    )
    with p:
        b0 = p.pull("out", timeout=15)
        b1 = p.pull("out", timeout=15)
        p.wait(timeout=15)
    want = raw.astype(np.float32).reshape(-1, channels)
    np.testing.assert_allclose(np.asarray(b0.tensors[0]), want[:capacity])
    np.testing.assert_allclose(np.asarray(b1.tensors[0]), want[capacity:])
    srv.close()


def test_iio_fifo_backend_and_clean_shutdown(tmp_path):
    """FIFO sensor: reader must wait for the writer, deliver scans, and —
    critically — never hang pipeline shutdown when the writer stalls."""
    import os
    import threading as th
    import time as _t

    channels, capacity = 2, 4
    fifo = str(tmp_path / "sensor.fifo")
    os.mkfifo(fifo)
    raw = np.arange(capacity * channels, dtype="<i2")

    def write_one_then_stall():
        fd = os.open(fifo, os.O_WRONLY)
        os.write(fd, raw.tobytes())
        _t.sleep(30)  # stall: shutdown must not wait for us
        os.close(fd)

    t = th.Thread(target=write_one_then_stall, daemon=True)
    t.start()
    p = nt.Pipeline(
        f"tensor_src_iio device={fifo} channels={channels} "
        f"buffer-capacity={capacity} scan-format=s16le num-buffers=-1 ! "
        "tensor_sink name=out",
        fuse=False,
    )
    t0 = _t.monotonic()
    with p:
        b = p.pull("out", timeout=15)
        np.testing.assert_allclose(
            np.asarray(b.tensors[0]),
            raw.astype(np.float32).reshape(capacity, channels))
        # exit with the writer stalled mid-scan
    assert _t.monotonic() - t0 < 10, "shutdown hung on a stalled FIFO writer"


def test_unknown_property_rejected_at_startup():
    """gst_parse_launch behavior: a typo'd element property fails pipeline
    startup with the element and key named, instead of being silently
    ignored."""
    from nnstreamer_tpu.pipeline.runtime import PipelineError

    p = nt.Pipeline(
        "videotestsrc num-bufers=4 width=8 height=8 ! "  # typo'd num-buffers
        "tensor_converter ! tensor_sink name=out"
    )
    with pytest.raises(PipelineError, match="num_bufers"):
        p.start()

    # correct spelling still starts
    p2 = nt.Pipeline(
        "videotestsrc num-buffers=1 width=8 height=8 ! "
        "tensor_converter ! tensor_sink name=out"
    )
    with p2:
        p2.pull("out", timeout=10)
        p2.wait(timeout=10)


def test_device_source_folds_into_fused_stage():
    """VERDICT r2 weak #1 (host overhead): a device-resident source joins
    the fused stage — the pipeline front is ONE schedulable unit, and
    results still match the unfused run exactly."""
    desc = (
        "videotestsrc device=true batch=2 num-buffers=6 width=16 height=16 "
        "pattern=smpte name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=average custom=dims:3:16:16:2 ! "
        "tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fuse=True)
    from nnstreamer_tpu.pipeline.plan import FusedSourceElement

    srcs = [s for s in p.stages if isinstance(s.element, FusedSourceElement)]
    assert len(srcs) == 1 and len(srcs[0].node_ids) == 3
    assert len(p.stages) == 2  # fused front + sink
    fused_out = []
    with p:
        for _ in range(3):
            fused_out.append(np.asarray(p.pull("out", timeout=30).tensors[0]))
        p.wait(timeout=30)
    q = nt.Pipeline(desc, fuse=False)
    with q:
        for i in range(3):
            want = np.asarray(q.pull("out", timeout=30).tensors[0])
            np.testing.assert_allclose(fused_out[i], want, rtol=1e-6)
        q.wait(timeout=30)


def test_device_source_fold_truncates_tail_batch():
    # num-buffers=5 with batch=2: fused source must still emit 2+2+1 frames
    p = nt.Pipeline(
        "videotestsrc device=true batch=2 num-buffers=5 width=8 height=8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32 ! "
        "tensor_sink name=out")
    sizes = []
    with p:
        for _ in range(3):
            sizes.append(np.asarray(p.pull("out", timeout=30).tensors[0]).shape[0])
        p.wait(timeout=30)
    assert sizes == [2, 2, 1]


def test_sink_background_resolver_orders_and_labels():
    """host_post resolution happens off the pull thread but stays FIFO and
    produces identical labels/meta."""
    desc = (
        "videotestsrc device=true batch=2 num-buffers=12 width=16 height=16 "
        "pattern=ball name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=average custom=dims:3:16:16:2 ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fuse=True)
    metas = []
    with p:
        for _ in range(6):
            b = p.pull("out", timeout=30)
            assert "_host_post" not in b.meta  # resolved before delivery
            metas.append(list(b.meta["label_index"]))
        p.wait(timeout=30)
    q = nt.Pipeline(desc, fuse=False)
    with q:
        for i in range(6):
            want = q.pull("out", timeout=30).meta["label_index"]
            assert metas[i] == list(np.atleast_1d(want))
        q.wait(timeout=30)


def test_plan_construction_is_backend_free(monkeypatch):
    """Building a pipeline (including the donated folded-source path) must
    not initialize the jax backend: with a dead device tunnel that call
    blocks forever (the round-3 outage mode)."""
    import jax

    def boom():
        raise AssertionError("default_backend touched at plan time")

    monkeypatch.setattr(jax, "default_backend", boom)
    p = nt.Pipeline(
        "videotestsrc device=true batch=2 num-buffers=2 width=8 height=8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32 ! "
        "tensor_sink name=out")
    assert len(p.stages) == 2  # constructed and planned without backend


def test_donated_fused_program_compiles_and_matches(monkeypatch):
    """Force the donation gate ON (as on TPU) and run on CPU: the donated
    program must trace/compile/execute with identical results (CPU ignores
    donation), so the TPU-only branch is exercised before a chip round."""
    import jax

    desc = (
        "videotestsrc device=true batch=2 num-buffers=4 width=8 height=8 "
        "pattern=smpte ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_sink name=out")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    p = nt.Pipeline(desc)
    from nnstreamer_tpu.pipeline.plan import FusedSourceElement

    fs = next(s.element for s in p.stages
              if isinstance(s.element, FusedSourceElement))
    assert fs.fused._donate is True
    got = []
    with p:
        for _ in range(2):
            got.append(np.asarray(p.pull("out", timeout=30).tensors[0]))
        p.wait(timeout=30)
    monkeypatch.undo()
    q = nt.Pipeline(desc, fuse=False)
    with q:
        for i in range(2):
            want = np.asarray(q.pull("out", timeout=30).tensors[0])
            np.testing.assert_allclose(got[i], want, rtol=1e-6)
        q.wait(timeout=30)


class TestBoundedAdmission:
    """appsrc max-inflight=N: an END-TO-END admission bound (VERDICT r3
    Weak #2).  A credit frees at REAL delivery (pop / callback / drop),
    not at sink arrival — async dispatch reaches the sink as a future
    long before the batch's H2D/compute ran, so an arrival-time release
    would never bound the backlog.  Producers past the bound therefore
    block until a consumer pops — push and pull must run concurrently,
    like GStreamer appsrc with block=true."""

    def _slow_pipeline(self, inflight):
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")

        def slow(ins):
            time.sleep(0.15)
            return [np.asarray(ins[0], np.float32)]

        register_custom_easy("admission_slow", slow,
                             in_spec=spec, out_spec=spec)
        extra = f" max-inflight={inflight}" if inflight else ""
        return nt.Pipeline(
            f"appsrc name=src caps=other/tensors,dimensions=4,"
            f"types=float32{extra} ! "
            "tensor_filter framework=custom-easy model=admission_slow ! "
            "tensor_sink name=out")

    def test_push_blocks_until_a_pop_frees_a_credit(self):
        import threading as _t

        p = self._slow_pipeline(inflight=2)
        x = np.ones((4,), np.float32)
        done = {}
        with p:
            def pusher():
                t0 = time.monotonic()
                p.push("src", x)   # credit 1
                p.push("src", x)   # credit 2
                done["two"] = time.monotonic() - t0
                p.push("src", x)   # must WAIT for a pop
                done["three"] = time.monotonic() - t0

            th = _t.Thread(target=pusher, daemon=True)
            t0 = time.monotonic()
            th.start()
            first_pop = None
            for _ in range(3):
                p.pull("out", timeout=30)
                if first_pop is None:
                    first_pop = time.monotonic() - t0
            th.join(timeout=10)
            p.eos()
            p.wait(timeout=30)
        assert "three" in done, "third push never completed (credit leak?)"
        assert done["two"] < 0.12, f"first two pushes blocked ({done})"
        # the third push could only proceed after a credit freed, i.e.
        # not before the slow stage processed a buffer (no wall-clock
        # comparison with first_pop: the pusher can win that race by a
        # few ms once the semaphore releases inside pop)
        assert done["three"] >= 0.12, (done, first_pop)

    def test_e2e_latency_bounded_at_same_throughput(self):
        """6 pushes through a 150 ms stage: unbounded admission queues
        them all (last e2e ~6x stage time); max-inflight=2 holds every
        admission->delivery time near 2x stage time without losing
        throughput."""

        def run(inflight):
            p = self._slow_pipeline(inflight)
            x = np.ones((4,), np.float32)
            lat = []
            with p:
                import threading as _t
                push_ts = {}

                def pusher():
                    for i in range(6):
                        push_ts[i] = time.monotonic()
                        p.push("src", x)
                        push_ts[i] = time.monotonic()  # admission time

                th = _t.Thread(target=pusher, daemon=True)
                t0 = time.monotonic()
                th.start()
                for i in range(6):
                    p.pull("out", timeout=30)
                    lat.append(time.monotonic() - push_ts[i])
                wall = time.monotonic() - t0
                th.join()
                p.eos()
                p.wait(timeout=30)
            return max(lat), wall

        worst_bounded, wall_bounded = run(inflight=2)
        worst_free, wall_free = run(inflight=0)
        # same throughput (stage-bound): walls within 40%
        assert wall_bounded < wall_free * 1.4
        # bounded: every admitted request delivers within ~bound x stage;
        # unbounded: the last queued request waits ~6 stages
        assert worst_bounded < 0.15 * 3.5, f"{worst_bounded:.3f}s"
        assert worst_free > worst_bounded

    def test_credit_released_on_drop_path(self):
        """drop=true sinks discard buffers; discarded credits must free
        immediately (a leak deadlocks the pusher once N drops happen)."""
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")
        register_custom_easy("admission_fast",
                             lambda ins: [np.asarray(ins[0], np.float32)],
                             in_spec=spec, out_spec=spec)
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=4,"
            "types=float32 max-inflight=2 ! "
            "tensor_filter framework=custom-easy model=admission_fast ! "
            "tensor_sink name=out max-buffers=1 drop=true")
        x = np.ones((4,), np.float32)
        with p:
            # 8 pushes > 2 credits + 1 queue slot: only survives if
            # dropped buffers release their credits
            for _ in range(8):
                p.push("src", x)
            p.eos()
            p.wait(timeout=30)


class TestUnlinkedElementRejected:
    """A missing '!' between elements parses as a new gst-launch chain,
    leaving the second element with no input — the runtime must reject
    it at construction instead of hanging the first pull (this exact
    bug silently disconnected the bench's static llm sink for a round)."""

    def test_missing_bang_before_sink(self):
        from nnstreamer_tpu.pipeline.runtime import PipelineError

        with pytest.raises(PipelineError, match="no input link"):
            nt.Pipeline(
                "appsrc name=src ! "
                "tensor_transform mode=typecast option=float32 "
                "tensor_sink name=out")

    def test_multi_chain_mux_still_legal(self):
        # gst-launch juxtaposition with NAMED cross-links stays valid
        p = nt.Pipeline(
            "appsrc name=a caps=other/tensors,dimensions=4,types=float32 ! mux.sink_0 "
            "appsrc name=b caps=other/tensors,dimensions=4,types=float32 ! mux.sink_1 "
            "tensor_mux name=mux ! tensor_sink name=out")
        x = np.ones((4,), np.float32)
        with p:
            p.push("a", x)
            p.push("b", 2 * x)
            out = p.pull("out", timeout=15)
            p.eos()
            p.wait(timeout=15)
        assert len(out.tensors) == 2
