"""Deep analyzer tests: abstract shape execution + static HBM/recompile
budgeting (nns-lint --deep, docs/ANALYSIS.md "Deep pass").

Model-family stand-ins (mobilenet / ssd / posenet / llama-decode, in the
models/testmodels.py spirit) are registered as custom-easy and zoo models,
each with a seeded BAD twin whose traced output contradicts its declared
spec — the deep pass must catch every one statically, with element-path +
caret diagnostics and ZERO device dispatch (instrumented below).
"""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.analysis import PipelineLintError, analyze
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.filters.custom_easy import register_custom_easy
from nnstreamer_tpu.models.zoo import ModelBundle, register_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(dims, dtype="float32"):
    return TensorsSpec.from_string(dims, dtype)


def _ce(name, fn, in_dims, out_dims, in_dtype="float32",
        out_dtype="float32", n_out=1, param_bytes=0):
    outs = ",".join([out_dims] if isinstance(out_dims, str) else out_dims)
    types = ",".join([out_dtype] * (len(outs.split(","))
                                    if isinstance(out_dims, str) else n_out))
    register_custom_easy(
        name, fn,
        in_spec=TensorsSpec.from_string(in_dims, in_dtype),
        out_spec=TensorsSpec.from_string(outs, types),
        jax_traceable=True, param_bytes=param_bytes)


# -- model-family stand-ins (good) ------------------------------------------

_W_NET = np.zeros((32 * 32 * 3, 1001), np.float32)


def _mobilenet_like(ins):
    import jax.numpy as jnp

    x = ins[0].astype(jnp.float32)
    return [jnp.dot(x.reshape((1, -1)), _W_NET)]


def _ssd_like(ins):
    import jax.numpy as jnp

    x = ins[0].astype(jnp.float32)
    m = jnp.mean(x)
    return [jnp.zeros((1, 100, 4), jnp.float32) + m,
            jnp.zeros((1, 100), jnp.float32) + m]


def _posenet_like(ins):
    import jax.numpy as jnp

    return [jnp.zeros((1, 9, 9, 17), jnp.float32) + jnp.mean(ins[0])]


_W_VOCAB = np.zeros((256, 128), np.float32)


def _llama_decode_like(ins):
    import jax.numpy as jnp

    tok = ins[0].reshape((-1,))
    return [jnp.asarray(_W_VOCAB)[tok]]  # (1, 128) logits


_ce("deeptest_mobilenet", _mobilenet_like, "3:32:32:1", "1001:1",
    param_bytes=_W_NET.nbytes)
_ce("deeptest_ssd", _ssd_like, "3:32:32:1", "4:100:1,100:1")
_ce("deeptest_posenet", _posenet_like, "3:32:32:1", "17:9:9:1")
_ce("deeptest_llama", _llama_decode_like, "1:1", "128:1",
    in_dtype="int32", param_bytes=_W_VOCAB.nbytes)


# -- seeded bad twins: declared spec contradicts the traced output ----------

def _bad_shape(ins):  # declares 1001:1, traces (1, 3072)
    import jax.numpy as jnp

    return [ins[0].reshape((1, -1))]


def _bad_dtype(ins):  # declares float32, traces bool
    return [ins[0] > 0]


def _bad_arity(ins):  # declares ONE output, traces two
    return [ins[0], ins[0]]


def _bad_promote(ins):  # declares int32, + 0.5 silently promotes to float32
    return [ins[0] + 0.5]


def _bad_rank(ins):  # declares 3:32:32:1, mean drops the spatial rank
    import jax.numpy as jnp

    return [jnp.mean(ins[0], axis=(1, 2))]


def _bad_datadep(ins):  # data-dependent output shape: untraceable
    import jax.numpy as jnp

    return [jnp.nonzero(ins[0])[0]]


def _bad_hostsync(ins):  # float() on a traced value: ConcretizationTypeError
    return [ins[0] * float(ins[0].sum())]


_ce("deeptest_bad_shape", _bad_shape, "3:32:32:1", "1001:1")
_ce("deeptest_bad_dtype", _bad_dtype, "3:32:32:1", "3:32:32:1")
_ce("deeptest_bad_arity", _bad_arity, "3:32:32:1", "3:32:32:1")
_ce("deeptest_bad_promote", _bad_promote, "4:4", "4:4",
    in_dtype="int32", out_dtype="int32")
_ce("deeptest_bad_rank", _bad_rank, "3:32:32:1", "3:32:32:1")
_ce("deeptest_bad_datadep", _bad_datadep, "4:4", "16")
_ce("deeptest_bad_hostsync", _bad_hostsync, "4:4", "4:4")


@register_model("deeptest_zoo_net")
def _zoo_net(opts):
    w = np.zeros((32 * 32 * 3, 1001), np.float32)

    def apply_fn(params, x):
        import jax.numpy as jnp

        return jnp.dot(x.astype(jnp.float32).reshape((1, -1)), params["w"])

    return ModelBundle(apply_fn=apply_fn, params={"w": w},
                       in_spec=_spec("3:32:32:1"), out_spec=_spec("1001:1"),
                       name="deeptest_zoo_net")


@register_model("deeptest_zoo_badnet")
def _zoo_badnet(opts):
    w = np.zeros((32 * 32 * 3, 1000), np.float32)  # 1000 != declared 1001

    def apply_fn(params, x):
        import jax.numpy as jnp

        return jnp.dot(x.astype(jnp.float32).reshape((1, -1)), params["w"])

    return ModelBundle(apply_fn=apply_fn, params={"w": w},
                       in_spec=_spec("3:32:32:1"), out_spec=_spec("1001:1"),
                       name="deeptest_zoo_badnet")


def _pipe(model, dims="3:32:32:1", dtype="float32", fw="custom-easy",
          extra=""):
    return (f"appsrc caps=other/tensors,dimensions={dims},types={dtype} ! "
            f"tensor_filter framework={fw} model={model}{extra} ! "
            "tensor_sink")


def codes(report):
    return set(report.codes())


# ---------------------------------------------------------------------------
# golden bad pipelines: every seeded fixture caught, with path + caret
# ---------------------------------------------------------------------------

BAD_DEEP_PIPELINES = [
    (_pipe("deeptest_bad_shape"), "trace-shape-mismatch"),
    (_pipe("deeptest_bad_dtype"), "trace-shape-mismatch"),
    (_pipe("deeptest_bad_arity"), "trace-shape-mismatch"),
    (_pipe("deeptest_bad_promote", dims="4:4", dtype="int32"),
     "trace-shape-mismatch"),
    (_pipe("deeptest_bad_rank"), "trace-shape-mismatch"),
    (_pipe("deeptest_bad_datadep", dims="4:4"), "trace-error"),
    (_pipe("deeptest_bad_hostsync", dims="4:4"), "trace-error"),
    (_pipe("deeptest_zoo_badnet", fw="jax"), "trace-shape-mismatch"),
    # family twins wired through a WRONG declared filter output: the
    # element-level props override the registry spec, so the traced model
    # output contradicts what capsflow propagated downstream
    (_pipe("deeptest_ssd", extra=" output=4:100:1,10:1 "
           "outputtype=float32,float32"), "trace-shape-mismatch"),
    (_pipe("deeptest_posenet", extra=" output=17:17:9:1"),
     "trace-shape-mismatch"),
    (_pipe("deeptest_llama", dims="1:1", dtype="int32",
           extra=" output=64:1"), "trace-shape-mismatch"),
]


@pytest.mark.parametrize("desc,code", BAD_DEEP_PIPELINES,
                         ids=[f"{c}-{i}" for i, (_, c)
                              in enumerate(BAD_DEEP_PIPELINES)])
def test_seeded_fixture_caught_with_path_and_caret(desc, code):
    report = analyze(desc, deep=True)
    assert code in codes(report), report.render()
    diag = next(d for d in report if d.code == code)
    assert diag.severity == "error"
    assert diag.path, str(diag)
    assert diag.pos is not None, str(diag)
    assert "^" in report.render(), report.render()  # source caret


@pytest.mark.parametrize("model", [
    "deeptest_mobilenet", "deeptest_ssd", "deeptest_posenet",
])
def test_good_families_trace_clean(model):
    report = analyze(_pipe(model), deep=True)
    assert report.ok, report.render()
    assert report.resources is not None
    assert len(report.resources.stages) == 1


def test_llama_decode_standin_traces_clean():
    report = analyze(_pipe("deeptest_llama", dims="1:1", dtype="int32"),
                     deep=True)
    assert report.ok, report.render()


def test_zoo_jax_framework_traces_with_abstract_params():
    report = analyze(_pipe("deeptest_zoo_net", fw="jax"), deep=True)
    assert report.ok, report.render()
    # params are accounted (the jax fw sums its bundle leaves)
    st = report.resources.stages[0]
    assert st.param_bytes == 32 * 32 * 3 * 1001 * 4


def test_shallow_analyze_has_no_resources_and_misses_trace_bugs():
    """deep=False keeps the jax-free fast path: the same bad pipeline
    passes the syntactic passes (the declared specs are consistent)."""
    report = analyze(_pipe("deeptest_bad_shape"))
    assert report.resources is None
    assert "trace-shape-mismatch" not in codes(report)


# ---------------------------------------------------------------------------
# static resource report: HBM high-water + recompile census + budgets
# ---------------------------------------------------------------------------

def test_resource_report_multiplies_bucket_ladder():
    report = analyze(_pipe("deeptest_mobilenet"), deep=True,
                     batch_max=8, data_parallel=1, dispatch_depth=2)
    res = report.resources
    assert res.ladder == (1, 2, 4, 8)
    st = res.stages[0]
    assert st.batchable and not st.sharded
    assert st.variants == 4  # one compiled program per bucket
    assert st.rows_per_device == 8 * 2  # top bucket x dispatch window
    assert st.param_bytes == _W_NET.nbytes
    row = st.act_row_bytes
    assert row == (32 * 32 * 3) * 4 + 1001 * 4  # in + traced out, float32
    assert res.hbm_estimate == st.param_bytes + row * 16


def test_resource_report_sharded_rounds_buckets_to_replicas():
    report = analyze(_pipe("deeptest_mobilenet"), deep=True,
                     batch_max=8, data_parallel=4, dispatch_depth=1)
    st = report.resources.stages[0]
    assert st.sharded
    # ladder {1,2,4,8} rounds to replica multiples {4,8}: 2 programs,
    # top bucket 8 / 4 replicas = 2 rows resident per device
    assert st.variants == 2
    assert st.rows_per_device == 2


def test_unsorted_buckets_census_matches_runtime():
    """BatchRunner sorts its bucket ladder; the census must normalize the
    same way or an unsorted [8,2,4] collapses every entry to the first
    listed bucket >= n and under-counts compiled signatures."""
    want = analyze(_pipe("deeptest_mobilenet"), deep=True, batch_max=8,
                   batch_buckets=[2, 4, 8], data_parallel=4).resources
    got = analyze(_pipe("deeptest_mobilenet"), deep=True, batch_max=8,
                  batch_buckets=[8, 2, 4], data_parallel=4).resources
    assert got.ladder == want.ladder == (2, 4, 8)
    assert got.stages[0].variants == want.stages[0].variants
    assert got.stages[0].rows_per_device == want.stages[0].rows_per_device


def test_hbm_budget_warning_anchors_dominant_stage():
    report = analyze(_pipe("deeptest_mobilenet"), deep=True, batch_max=64,
                     data_parallel=1, hbm_budget_bytes=1 << 20)
    diag = next(d for d in report if d.code == "hbm-budget")
    assert diag.severity == "warning"
    assert diag.path and diag.pos is not None
    assert "^" in report.render()
    assert "budget" in diag.message and "MiB" in diag.message


def test_recompile_budget_warning():
    report = analyze(_pipe("deeptest_mobilenet"), deep=True, batch_max=256,
                     data_parallel=1, max_compiled_variants=3)
    diag = next(d for d in report if d.code == "recompile-budget")
    assert diag.severity == "warning"
    assert diag.path and diag.pos is not None


def test_budgets_off_by_default():
    report = analyze(_pipe("deeptest_mobilenet"), deep=True, batch_max=256)
    assert "hbm-budget" not in codes(report)
    assert "recompile-budget" not in codes(report)


def test_invoke_dynamic_flagged_recompile_unbounded():
    report = analyze(_pipe("deeptest_mobilenet",
                           extra=" invoke-dynamic=true"), deep=True)
    diag = next(d for d in report if d.code == "recompile-unbounded")
    assert diag.severity == "warning"
    assert diag.pos is not None


def test_example_pipeline_gets_resource_report():
    """The e2e-style image pipeline from the examples: the deep pass must
    produce a populated resource report (the acceptance bar)."""
    desc = ("videotestsrc num-buffers=8 width=224 height=224 device=true ! "
            "tensor_converter ! tensor_transform mode=arithmetic "
            "option=typecast:float32,div:127.5,add:-1.0 ! "
            "tensor_filter framework=jax model=mobilenet_v1 "
            "custom=dtype:float32 ! tensor_sink name=out")
    report = analyze(desc, deep=True, batch_max=4, data_parallel=1)
    assert report.ok, report.render()
    res = report.resources
    assert res is not None and len(res.stages) >= 1
    assert res.hbm_estimate > 0
    assert res.compiled_variants >= 1
    assert "deep resource report" in res.render()
    assert "est HBM high-water" in res.summary()


def test_fused_chain_merges_into_one_stage():
    desc = ("appsrc caps=other/tensors,dimensions=3:32:32:1,types=float32 ! "
            "tensor_transform mode=arithmetic option=div:2.0 ! "
            "tensor_filter framework=custom-easy model=deeptest_mobilenet ! "
            "tensor_sink")
    report = analyze(desc, deep=True, batch_max=4, data_parallel=1)
    assert report.ok, report.render()
    (st,) = report.resources.stages
    assert "+" in st.label  # transform + filter fused, ONE program set
    assert st.variants == 3  # ladder (1,2,4), not 2 stages x 3


# ---------------------------------------------------------------------------
# zero device dispatch (the acceptance bar: instrumented, not assumed)
# ---------------------------------------------------------------------------

def test_deep_pass_performs_zero_device_dispatch(monkeypatch):
    """Every jit-compiled call and device_put is trapped: the deep pass
    must complete (diagnostics, resource report, budgets) without ONE
    device dispatch — eval_shape traces, it never executes."""
    import jax

    real_jit = jax.jit

    def guarded_jit(*a, **k):
        real_jit(*a, **k)  # building the wrapper is legal (no dispatch)

        def trap(*aa, **kk):
            raise AssertionError("jit-compiled call during deep analysis")

        return trap

    def no_device_put(*a, **k):
        raise AssertionError("device_put during deep analysis")

    monkeypatch.setattr(jax, "jit", guarded_jit)
    monkeypatch.setattr(jax, "device_put", no_device_put)

    good = analyze(_pipe("deeptest_zoo_net", fw="jax"), deep=True,
                   batch_max=8, data_parallel=1, hbm_budget_bytes=1)
    assert "analyzer-error" not in codes(good), good.render()
    assert good.resources is not None
    assert "hbm-budget" in codes(good)
    bad = analyze(_pipe("deeptest_bad_shape"), deep=True)
    assert "trace-shape-mismatch" in codes(bad)

    from nnstreamer_tpu.analysis.tracecheck import trace_zoo_models

    diags, traced, _ = trace_zoo_models(
        names=("passthrough", "scaler", "average"))
    assert traced == 3
    assert [str(d) for d in diags] == []


def test_zoo_dogfood_families_trace_clean():
    """The CI deep-dogfood list: the real bundled model families
    (mobilenet/ssd/posenet at least) eval_shape-trace clean against their
    declared specs."""
    from nnstreamer_tpu.analysis.tracecheck import trace_zoo_models

    diags, traced, skipped = trace_zoo_models(
        names=("mobilenet_v1", "ssd_mobilenet", "posenet"))
    assert traced == 3 and skipped == 0
    assert [str(d) for d in diags] == []


# ---------------------------------------------------------------------------
# entry points: validate="deep", CLI --deep
# ---------------------------------------------------------------------------

def test_pipeline_validate_deep_raises_trace_errors():
    with pytest.raises(PipelineLintError) as ei:
        nt.Pipeline(_pipe("deeptest_bad_shape"), validate="deep")
    assert "trace-shape-mismatch" in ei.value.report.codes()


def test_pipeline_validate_deep_passes_clean_and_runs():
    p = nt.Pipeline(
        "appsrc name=src caps=other/tensors,dimensions=4:4,types=float32 ! "
        "tensor_filter framework=jax model=scaler "
        "custom=scale:2.0,dims:4:4 ! tensor_sink name=out",
        validate="deep")
    with p:
        p.push("src", [np.ones((4, 4), np.float32)])
        p.eos()
        buf = p.pull("out", timeout=10)
        p.wait(timeout=10)
    np.testing.assert_allclose(np.asarray(buf.tensors[0]),
                               np.full((4, 4), 2.0, np.float32))


def test_pipeline_validate_true_stays_shallow():
    # bool validate must not pay the deep pass (nor catch trace bugs):
    # exact PR2 semantics preserved
    nt.Pipeline(_pipe("deeptest_bad_shape"), validate=True)


def test_cli_deep_flag(capsys):
    from nnstreamer_tpu.tools.lint import main

    rc = main(["--deep", _pipe("deeptest_mobilenet")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "deep resource report" in out

    rc = main(["--deep", _pipe("deeptest_bad_shape")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "trace-shape-mismatch" in out


def test_cli_unresolved_calls_are_named_warnings(tmp_path, capsys):
    """A Pipeline(...) call the linter cannot resolve statically is a
    NAMED warning with a stable baseline key — strict mode fails on a new
    one instead of silently shrinking coverage."""
    f = tmp_path / "ex.py"
    f.write_text("import nnstreamer_tpu as nt\n"
                 "def go(d):\n"
                 "    return nt.Pipeline(d + ' ! tensor_sink')\n")
    from nnstreamer_tpu.tools.lint import main

    rc = main(["--files", str(f), "--strict"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "unresolvable-pipeline" in out and "ex.py:3" in out
    # non-strict: counted but not failing
    assert main(["--files", str(f)]) == 0


def test_unresolved_keys_stable_across_line_drift(tmp_path):
    from nnstreamer_tpu.tools.lint import (
        _unresolved_keys, extract_pipeline_strings)

    a = tmp_path / "a.py"
    a.write_text("import nnstreamer_tpu as nt\nnt.Pipeline(desc)\n")
    _, sk1 = extract_pipeline_strings(str(a))
    a.write_text("import nnstreamer_tpu as nt\n\n\n# moved\n"
                 "nt.Pipeline(desc)\n")
    _, sk2 = extract_pipeline_strings(str(a))
    assert sk1[0][0] != sk2[0][0]  # line moved...
    assert _unresolved_keys("a.py", sk1) == _unresolved_keys("a.py", sk2)


# ---------------------------------------------------------------------------
# helper units: bucket ladder + replication plan
# ---------------------------------------------------------------------------

def test_bucket_ladder():
    from nnstreamer_tpu.pipeline.batching import ladder

    assert ladder(1) == (1,)
    assert ladder(8) == (1, 2, 4, 8)
    assert ladder(6) == (1, 2, 4, 8)  # bucket_for(6) tops the ladder
    assert ladder(3, buckets=[2, 4]) == (2, 4)
    # above the top bucket the runtime LADDER-ROUNDS (multiples of the
    # top) instead of clamping the drain — the census models exactly the
    # rounded sizes the runner can now produce, still bounded
    assert ladder(500) == (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
    assert ladder(9, buckets=[2, 4]) == (2, 4, 8, 12)


def test_data_parallel_over_local_devices_is_an_error():
    """An explicit data_parallel the host cannot supply fails at start()
    with PipelineError — the deep pass surfaces it statically (the whole
    point of static analysis), anchored at the shard-eligible stage."""
    report = analyze(_pipe("deeptest_mobilenet"), deep=True,
                     batch_max=8, data_parallel=64)
    diag = next(d for d in report if d.code == "data-parallel-devices")
    assert diag.severity == "error"
    assert diag.path and diag.pos is not None
    # auto (0) can never over-ask; dp=1 never builds a mesh
    for dp in (0, 1):
        ok = analyze(_pipe("deeptest_mobilenet"), deep=True,
                     batch_max=8, data_parallel=dp)
        assert "data-parallel-devices" not in codes(ok)


def test_replication_plan_matches_runtime_semantics():
    from nnstreamer_tpu.pipeline.plan import replication_plan

    assert replication_plan(0, 1, 8) == 1      # batching off: no mesh
    assert replication_plan(1, 8, 8) == 1      # explicit single-device
    assert replication_plan(0, 8, 8) == 8      # auto: all local devices
    assert replication_plan(4, 8, 8) == 4      # exact
