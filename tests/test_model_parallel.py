"""2-D (data x model) placement semantics (ISSUE 9 tentpole).

The contract: ``Pipeline(model_parallel=M, data_parallel=N)`` builds ONE
``(data=N, model=M)`` mesh at start(); the sharded BatchRunner shards the
batch dim over ``data`` while placing each shardable stage's params per
its ``param_pspecs`` over ``model``; the llm filter's TP path (and its
paged KV block pool, sharded on the head dim) rides the SAME mesh — and
dp-only behavior (``model_parallel=1``) stays bit-identical to the
pre-2-D path, programs and metric names included.

Runs on the suite's virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``, set by conftest.py before
jax initializes).  ``tools/check_tier1.py`` additionally runs this file
as its own pytest process (the mesh gate) so the flag can never arrive
too late.
"""

import threading

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.models import llama
from nnstreamer_tpu.models.zoo import ModelBundle, register_model
from nnstreamer_tpu.pipeline.batching import BatchRunner
from nnstreamer_tpu.pipeline.plan import mesh_plan, replication_plan
from nnstreamer_tpu.parallel.mesh import (device_coords, make_mesh,
                                          mesh_axis_size)


def _mesh(data=1, model=1):
    import jax

    need = data * model
    if len(jax.devices()) < need:
        pytest.skip(f"needs {need} local devices")
    return make_mesh(data=data, model=model, devices=jax.devices()[:need])


# -- a tiny zoo model with REAL model-axis pspecs ---------------------------

_D, _H = 16, 8
_rng = np.random.default_rng(11)
_W1 = (_rng.standard_normal((_D, _H)).astype(np.float32)
       * (1.0 / np.sqrt(_D)))
_W2 = (_rng.standard_normal((_H, _D)).astype(np.float32)
       * (1.0 / np.sqrt(_H)))


@register_model("tp-test-mlp")
def _build_tp_mlp(opts):
    """Megatron-style 2-mat MLP: w1 splits its OUT dim over `model`, w2
    its IN dim — XLA all-reduces the block output once."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    params = {"w1": jnp.asarray(_W1), "w2": jnp.asarray(_W2)}

    def apply_fn(p, x):
        return jnp.tanh(x @ p["w1"]) @ p["w2"]

    spec = TensorsSpec.from_string(str(_D), "float32")
    return ModelBundle(apply_fn, params, spec, spec,
                       param_pspecs={"w1": P(None, "model"),
                                     "w2": P("model", None)})


DESC = (
    f"appsrc name=src caps=other/tensors,dimensions={_D},types=float32 ! "
    "tensor_filter framework=jax model=tp-test-mlp name=f ! "
    "tensor_sink name=out"
)


def _frames(n, dims=(_D,)):
    return [np.full(dims, float(i % 9) * 0.25, np.float32)
            for i in range(n)]


def _run(desc, frames, timeout=60, **kw):
    p = nt.Pipeline(desc, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
    return outs


def _assert_rows_bitwise(got, want):
    assert len(got) == len(want)
    for i, (a, b) in enumerate(zip(got, want)):
        assert a.pts == b.pts
        for x, y in zip(a.tensors, b.tensors):
            assert bytes(np.asarray(x)) == bytes(np.asarray(y)), f"row {i}"


# -- make_mesh validation (satellite: clear divisibility errors) -----------

def test_make_mesh_names_non_divisible_axis():
    import jax

    n = len(jax.devices())
    with pytest.raises(ValueError) as e:
        make_mesh(model=3)  # 3 does not divide 8
    msg = str(e.value)
    assert "'model'" in msg and "3" in msg and str(n) in msg


def test_make_mesh_rejects_zero_and_negative_axes():
    with pytest.raises(ValueError, match="'model' must be >= 1"):
        make_mesh(model=0)
    with pytest.raises(ValueError, match="'seq' must be >= 1"):
        make_mesh(seq=-2)


def test_make_mesh_explicit_plan_mismatch_names_axis():
    with pytest.raises(ValueError) as e:
        make_mesh(data=2, model=3)
    msg = str(e.value)
    assert "'model'" in msg and "needs 6" in msg


def test_make_mesh_data_none_still_auto_absorbs():
    import jax

    n = len(jax.devices())
    m = make_mesh(data=None, model=2)
    assert mesh_axis_size(m, "data") == n // 2


def test_make_mesh_degenerate_1x1():
    import jax

    m = make_mesh(data=1, model=1, devices=[jax.devices()[0]])
    assert mesh_axis_size(m, "data") == 1
    assert mesh_axis_size(m, "model") == 1


def test_make_mesh_model_only_and_auto_absorb():
    import jax

    n = len(jax.devices())
    m = make_mesh(model=n)  # model-only: data auto-absorbs to 1
    assert mesh_axis_size(m, "model") == n
    assert mesh_axis_size(m, "data") == 1
    m = make_mesh(model=2)  # auto-absorb: data takes the rest
    assert mesh_axis_size(m, "data") == n // 2
    assert mesh_axis_size(m, "model") == 2


def test_device_coords_covers_the_grid():
    m = _mesh(data=2, model=2)
    coords = device_coords(m)
    assert sorted(coords.values()) == [(0, 0), (0, 1), (1, 0), (1, 1)]


# -- mesh_plan resolution ---------------------------------------------------

def test_mesh_plan_semantics():
    # dp-only stays replication_plan exactly
    assert mesh_plan(0, 1, 8, 8) == (replication_plan(0, 8, 8), 1)
    assert mesh_plan(0, 1, 1, 8) == (1, 1)      # batching off, mp off
    assert mesh_plan(0, 1, 8, 8) == (8, 1)      # dp auto absorbs all
    assert mesh_plan(4, 1, 8, 8) == (4, 1)      # dp exact
    # model exact, data auto absorbs the remainder
    assert mesh_plan(0, 2, 8, 8) == (4, 2)
    # model exact, batching off: TP-only
    assert mesh_plan(0, 4, 1, 8) == (1, 4)
    # model auto absorbs what data leaves (explicit dp)
    assert mesh_plan(4, 0, 8, 8) == (4, 2)
    # model auto with batching off: all devices go to model
    assert mesh_plan(0, 0, 1, 8) == (1, 8)
    assert mesh_plan(1, 0, 8, 8) == (1, 8)      # dp explicitly off
    # both auto with batching on: data wins (dp-only compatibility)
    assert mesh_plan(0, 0, 8, 8) == (8, 1)
    # degenerate single device
    assert mesh_plan(0, 0, 8, 1) == (1, 1)


# -- 2-D sharded dispatch ---------------------------------------------------

def test_2d_runner_rows_bit_identical_every_occupancy(rng):
    """Every occupancy 1..9 (crossing a bucket boundary): rows through a
    (data=2, model=2) mesh are byte-equal to the plain BatchRunner's."""
    import jax.numpy as jnp

    fn = lambda arrays: (jnp.tanh(arrays[0] * 1.5 + 0.25),)  # noqa: E731
    single = BatchRunner(fn)
    sharded = BatchRunner(fn, mesh=_mesh(data=2, model=2))
    assert sharded.replicas == 2 and sharded.model_axis == 2
    for n in range(1, 10):
        rows = [(rng.standard_normal((24,)).astype(np.float32),)
                for _ in range(n)]
        a = single.run(list(rows))
        b = sharded.run(list(rows))
        assert len(a) == len(b) == n
        for (x,), (y,) in zip(a, b):
            assert bytes(np.asarray(x)) == bytes(np.asarray(y)), f"n={n}"


def test_model_only_mesh_engages_sharded_path():
    """A (data=1, model=2) mesh must still engage the sharded path — the
    point is placing params over `model` even without data parallelism —
    with rows byte-equal to the plain path."""
    br = BatchRunner(lambda arrays: (arrays[0] * 2.0,),
                     mesh=_mesh(data=1, model=2))
    assert br.mesh is not None
    assert br.replicas == 1 and br.model_axis == 2
    rows = [(np.full((8,), float(i), np.float32),) for i in range(3)]
    plain = BatchRunner(lambda arrays: (arrays[0] * 2.0,))
    for (x,), (y,) in zip(plain.run(list(rows)), br.run(list(rows))):
        assert bytes(np.asarray(x)) == bytes(np.asarray(y))


def test_pipeline_2d_bit_identical_vs_dp_only_every_occupancy():
    """The acceptance bit-identity: a (data=2, model=2) pipeline delivers
    byte-equal rows to the dp-only run at every backlog occupancy."""
    for n in (1, 3, 8, 13):
        frames = _frames(n)
        sharded = _run(DESC, frames, queue_capacity=16, batch_max=8,
                       data_parallel=2, model_parallel=2)
        reference = _run(DESC, frames, queue_capacity=16, batch_max=8,
                         data_parallel=1, model_parallel=1)
        _assert_rows_bitwise(sharded, reference)


def test_placement_counters_prove_model_axis_shards():
    """param_shards/param_replicas split the placement; shard-rows
    counters carry (data, model) coordinates covering the full grid."""
    metrics.reset()
    frames = _frames(32)
    _run(DESC, frames, queue_capacity=64, batch_max=8,
         data_parallel=2, model_parallel=2)
    snap = metrics.snapshot()
    assert snap.get("f.param_replications") == 1.0
    assert snap.get("f.param_shards") == 2.0  # w1 AND w2 carry 'model'
    assert snap.get("f.param_replicas") == 0.0
    rows = {k: v for k, v in snap.items() if k.startswith("f.shard_rows.")}
    if not rows:
        pytest.skip("backlog never coalesced (single-buffer dispatches)")
    # every chip named by its (data, model) coordinate, whole grid seen
    assert set(rows) == {f"f.shard_rows.d{d}m{m}"
                        for d in range(2) for m in range(2)}, rows
    assert all(v > 0 for v in rows.values())


def test_dp_only_keeps_legacy_counter_names():
    """model_parallel=1 must keep the exact pre-2-D path: legacy
    .d<device-id> counter names, no param_shards split."""
    metrics.reset()
    frames = _frames(24)
    _run(DESC, frames, queue_capacity=64, batch_max=8, data_parallel=4,
         model_parallel=1)
    snap = metrics.snapshot()
    assert "f.param_shards" not in snap
    rows = {k for k in snap if k.startswith("f.shard_rows.")}
    if rows:
        assert all("m" not in k.rsplit(".", 1)[1] for k in rows), rows


def test_mesh_shape_exposed_and_lazy():
    p = nt.Pipeline(DESC, batch_max=8, data_parallel=2, model_parallel=2)
    assert p.mesh is None  # lazily built at start(), not construction
    with p:
        assert p.mesh_shape == (2, 2)
        assert p.mesh is not None
        p.eos()
        p.wait(timeout=60)


def test_2d_over_ask_fails_start_cleanly():
    from nnstreamer_tpu.pipeline.runtime import PipelineError

    p = nt.Pipeline(DESC, batch_max=8, data_parallel=4, model_parallel=4)
    with pytest.raises(PipelineError, match="model_parallel"):
        p.start()
    runners = {id(r): r for r in p._runners.values()}.values()
    assert not any(r.thread.is_alive() for r in runners)


def test_replicate_params_alias_still_routes():
    """Back-compat: Element.replicate_params delegates to place_params."""
    from nnstreamer_tpu.elements.base import Element

    calls = []

    class El(Element):
        kind = "x"

        def place_params(self, mesh):
            calls.append(mesh)
            return True

    assert El({}, name="x").replicate_params("MESH") is True
    assert calls == ["MESH"]


# -- llm filter on the shared mesh ------------------------------------------

LLM_BASE = "max_new:5,temperature:0.0,dtype:float32"


def _llm_pipeline_ids(custom, **kw):
    desc = ("appsrc name=src ! "
            f"tensor_filter framework=llm model=llama_tiny custom={custom} "
            "invoke-dynamic=true name=f ! tensor_sink name=out")
    p = nt.Pipeline(desc, **kw)
    with p:
        p.push("src", "the quick brown fox")
        outs = [p.pull("out", timeout=180) for _ in range(5)]
        p.eos("src")
        p.wait(timeout=60)
    return p, [int(b.tensors[0][0]) for b in outs]


def test_llm_model_parallel_streams_identical_ids():
    _, ref = _llm_pipeline_ids(LLM_BASE, model_parallel=1)
    desc = ("appsrc name=src ! tensor_filter framework=llm "
            f"model=llama_tiny custom={LLM_BASE} invoke-dynamic=true "
            "name=f ! tensor_sink name=out")
    p2 = nt.Pipeline(desc, model_parallel=2)
    with p2:
        # the filter rode the PIPELINE's mesh, params sharded over model
        fw = p2.element("f").fw
        assert fw.mesh is p2.mesh
        spec = str(fw.bundle.params["layers"]["wq"].sharding.spec)
        assert "model" in spec
        p2.push("src", "the quick brown fox")
        tp2 = [int(p2.pull("out", timeout=180).tensors[0][0])
               for _ in range(5)]
        p2.eos("src")
        p2.wait(timeout=60)
    assert p2.mesh_shape == (1, 2)
    assert tp2 == ref


def test_llm_tp_alias_promoted_to_pipeline_mesh():
    """Deprecation shim: custom=tp:2 inside a pipeline lands on the
    pipeline-owned mesh (model_parallel promoted), identical ids."""
    _, ref = _llm_pipeline_ids(LLM_BASE)
    p, ids = _llm_pipeline_ids(LLM_BASE + ",tp:2")
    assert p.model_parallel == 2
    assert p.mesh_shape == (1, 2)
    assert ids == ref


def test_llm_explicit_model_parallel_wins_over_alias():
    p, ids = _llm_pipeline_ids(LLM_BASE + ",tp:4", model_parallel=2)
    assert p.mesh_shape == (1, 2)
    _, ref = _llm_pipeline_ids(LLM_BASE)
    assert ids == ref


def test_llm_int4_kernel_refcount_survives_tp_move():
    """The int4 disable_kernel refcount must be taken by the shared-mesh
    TP path and released at close — exactly the old private-mesh
    contract."""
    from nnstreamer_tpu.ops import int4_matmul as i4

    assert i4.kernel_enabled()
    desc = ("appsrc name=src ! tensor_filter framework=llm "
            f"model=llama_tiny custom={LLM_BASE},quant:int4 "
            "invoke-dynamic=true name=f ! tensor_sink name=out")
    p = nt.Pipeline(desc, model_parallel=2)
    with p:
        assert not i4.kernel_enabled()  # taken while the TP filter lives
        p.push("src", "hi")
        for _ in range(5):
            p.pull("out", timeout=180)
        p.eos("src")
        p.wait(timeout=60)
    assert i4.kernel_enabled()  # released at close


# -- TP continuous serving (paged pool sharded over model) ------------------

SERVE = (LLM_BASE + ",stream_chunk:2,serve:continuous,slots:3,"
         "block_size:8")


def _fw(custom, provider=None):
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    if provider is not None:
        fw._mesh_provider = provider
    fw.open({"model": "llama_tiny", "custom": custom})
    return fw


def _serve_tokens(fw, prompts, timeout=300.0):
    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
    assert fw.drain(timeout=timeout)
    return got


def test_tp_paged_decode_matches_dense_and_dp_only():
    """TP paged decode vs dense-cache identity: every stream's greedy ids
    under model_parallel=2 equal the per-request dense-cache path's AND
    the unsharded continuous loop's."""
    from nnstreamer_tpu.filters.llm import LLMFramework

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 500, (t,), dtype=np.int32)
               for t in (3, 7, 5)]
    # dense-cache per-request reference
    dense = []
    for prompt in prompts:
        fw = LLMFramework()
        fw.open({"model": "llama_tiny",
                 "custom": LLM_BASE + ",stream_chunk:2"})
        dense.append([int(ids[0]) for ids, *_ in fw.invoke_stream([prompt])])
        fw.close()

    fw1 = _fw(SERVE)
    ref = _serve_tokens(fw1, prompts)
    fw1.close()
    fw2 = _fw(SERVE, provider=lambda: _mesh(data=1, model=2))
    try:
        got = _serve_tokens(fw2, prompts)
        spec = fw2._serve._pool_sharding
        assert spec is not None and "model" in str(spec.spec)
    finally:
        fw2.close()
    for i in range(3):
        assert got[i] == ref[i] == dense[i], f"stream {i}"


def test_tp_zero_recompile_churn_pin():
    """The 3-program census must survive TP: join/leave/complete over a
    sharded pool changes VALUES only — zero recompiles once warm."""
    fw = _fw(SERVE + ",prefill_chunk:4",
             provider=lambda: _mesh(data=1, model=2))
    rng = np.random.default_rng(5)
    try:
        _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32)])
        serve = fw._serve
        warm = {
            "decode": serve._decode._cache_size(),
            "prefill": serve._prefill._cache_size(),
            "set_tok": serve._set_tok._cache_size(),
        }
        assert warm == {"decode": 1, "prefill": 1, "set_tok": 1}
        _serve_tokens(fw, [rng.integers(1, 500, (t,), np.int32)
                           for t in (1, 7, 13)])
        _serve_tokens(fw, [rng.integers(1, 500, (9,), np.int32)])
        after = {
            "decode": serve._decode._cache_size(),
            "prefill": serve._prefill._cache_size(),
            "set_tok": serve._set_tok._cache_size(),
        }
    finally:
        fw.close()
    assert after == warm, f"recompile on churn: {warm} -> {after}"


def test_tp_geometry_rejected_with_named_dims():
    """llama_tiny has n_kv_heads=2: model_parallel=4 must fail open()
    with the offending dims named, not a GSPMD reshape error."""
    from nnstreamer_tpu.filters.base import FrameworkError

    with pytest.raises(FrameworkError, match="n_kv_heads=2"):
        _fw(SERVE, provider=lambda: _mesh(data=1, model=4))


# -- deep lint: mesh plan + per-chip pricing + goldens ----------------------

LLM_SERVE_DESC = (
    "appsrc name=src ! tensor_filter framework=llm model=llama_small "
    "custom=max_new:16,serve:continuous,slots:4,block_size:16 "
    "invoke-dynamic=true ! tensor_sink name=out"
)


def test_deep_lint_prices_tp_params_and_pool_per_chip():
    r1 = nt.analyze(LLM_SERVE_DESC, deep=True, model_parallel=1)
    r4 = nt.analyze(LLM_SERVE_DESC, deep=True, model_parallel=4)
    assert not r1.errors and not r4.errors
    s1 = r1.resources.stages[0]
    s4 = r4.resources.stages[0]
    assert r4.resources.model_parallel == 4
    # pool shards the head dim: exactly 1/M per chip
    assert s4.pool_bytes * 4 == s1.pool_bytes
    # params: sheared leaves /M, embed+norms replicated — the exact split
    cfg = llama.PRESETS["llama_small"]
    shard, repl = llama.param_bytes_split(cfg)
    assert shard + repl == llama.param_bytes_estimate(cfg)
    assert s4.param_bytes == shard // 4 + repl
    assert s4.param_bytes < s1.param_bytes / 2
    # the census stays the closed 3 programs under TP
    assert s4.variants == 3
    assert "model_parallel=4" in r4.resources.render()


def test_deep_lint_model_divisibility_golden():
    bad = ("appsrc name=src ! tensor_filter framework=llm "
           "model=llama_tiny custom=max_new:4,serve:continuous,slots:2 "
           "invoke-dynamic=true ! tensor_sink name=out")
    r = nt.analyze(bad, deep=True, model_parallel=4)
    codes = [d.code for d in r.diagnostics]
    assert "model-divisibility" in codes
    msg = next(d.message for d in r.diagnostics
               if d.code == "model-divisibility")
    assert "n_kv_heads=2" in msg and "model_parallel=4" in msg


def test_deep_lint_tp_alias_priced_like_model_parallel():
    """custom=tp:4 with the pipeline knob off prices per-chip the same
    way (the deep pass honors the deprecated alias the runtime does)."""
    desc = LLM_SERVE_DESC.replace("slots:4", "slots:4,tp:4")
    r = nt.analyze(desc, deep=True, model_parallel=1)
    r4 = nt.analyze(LLM_SERVE_DESC, deep=True, model_parallel=4)
    assert r.resources.stages[0].param_bytes \
        == r4.resources.stages[0].param_bytes
    assert r.resources.stages[0].pool_bytes \
        == r4.resources.stages[0].pool_bytes


def test_deep_lint_mesh_axis_missing_golden():
    """A pspec naming an axis the 2-D pipeline mesh does not carry must
    be flagged statically."""
    from jax.sharding import PartitionSpec as P

    @register_model("tp-test-badaxis")
    def _build(opts):
        import jax.numpy as jnp

        params = {"w": jnp.asarray(_W1)}
        spec = TensorsSpec.from_string(str(_D), "float32")
        return ModelBundle(lambda p, x: x @ p["w"] @ p["w"].T, params,
                           spec, spec,
                           param_pspecs={"w": P("seq", None)})

    desc = (f"appsrc name=src caps=other/tensors,dimensions={_D},"
            "types=float32 ! "
            "tensor_filter framework=jax model=tp-test-badaxis name=f ! "
            "tensor_sink name=out")
    r = nt.analyze(desc, deep=True, batch_max=4, model_parallel=2)
    codes = [d.code for d in r.diagnostics]
    assert "mesh-axis-missing" in codes
    msg = next(d.message for d in r.diagnostics
               if d.code == "mesh-axis-missing")
    assert "seq" in msg
    # dp-only never places pspecs: the same pipeline is clean at mp=1
    r1 = nt.analyze(desc, deep=True, batch_max=4, model_parallel=1)
    assert "mesh-axis-missing" not in [d.code for d in r1.diagnostics]


def test_deep_lint_generic_stage_divisibility_golden():
    """A jax-framework stage whose model-sharded dim does not divide M is
    flagged with the leaf path and dim size."""
    from jax.sharding import PartitionSpec as P

    @register_model("tp-test-odd")
    def _build(opts):
        import jax.numpy as jnp

        w = np.ones((_D, 6), np.float32)  # 6 % 4 != 0
        params = {"w": jnp.asarray(w)}
        in_spec = TensorsSpec.from_string(str(_D), "float32")
        out_spec = TensorsSpec.from_string("6", "float32")
        return ModelBundle(lambda p, x: x @ p["w"], params, in_spec,
                           out_spec, param_pspecs={"w": P(None, "model")})

    desc = (f"appsrc name=src caps=other/tensors,dimensions={_D},"
            "types=float32 ! "
            "tensor_filter framework=jax model=tp-test-odd name=f ! "
            "tensor_sink name=out")
    r = nt.analyze(desc, deep=True, batch_max=4, model_parallel=4)
    hits = [d for d in r.diagnostics if d.code == "model-divisibility"]
    assert hits and "w[1]=6" in hits[0].message


def test_deep_lint_model_parallel_over_ask():
    r = nt.analyze(LLM_SERVE_DESC, deep=True, model_parallel=16)
    codes = [d.code for d in r.diagnostics]
    assert "data-parallel-devices" in codes


def test_deep_lint_combined_over_ask_without_shardable_stage():
    """An llm-only pipeline (no shard-eligible stage) with an explicit
    dp x mp plan the host cannot supply must lint dirty — the runtime
    builds the mesh whenever model_parallel is configured, so start()
    WILL fail; the lint has to predict it."""
    r = nt.analyze(LLM_SERVE_DESC, deep=True, batch_max=8,
                   data_parallel=8, model_parallel=2)
    hits = [d for d in r.diagnostics if d.code == "data-parallel-devices"]
    assert hits and "data_parallel=8 x model_parallel=2" in hits[0].message
    # the same knobs really do fail at runtime with the same arithmetic
    from nnstreamer_tpu.pipeline.runtime import PipelineError

    with pytest.raises(PipelineError, match="model_parallel=2"):
        nt.Pipeline(LLM_SERVE_DESC, batch_max=8, data_parallel=8,
                    model_parallel=2)
    # and with model_parallel left OFF the dp knob stays inert for an
    # llm-only pipeline, exactly the pre-2-D behavior: clean lint
    r1 = nt.analyze(LLM_SERVE_DESC, deep=True, batch_max=8,
                    data_parallel=8, model_parallel=1)
    assert "data-parallel-devices" not in [d.code for d in r1.diagnostics]
