"""ops: flash attention (Pallas) and NMS.

The Pallas kernel runs in interpreter mode on the CPU test backend —
bit-faithful to the TPU kernel's math, slow, hermetic (SURVEY §4
translation: hermetic unit tests against golden references).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.ops.attention import attention_reference, flash_attention
from nnstreamer_tpu.ops.nms import nms_jax, nms_numpy


@pytest.fixture
def qkv(rng):
    def make(b, sq, skv, h, d):
        q = jnp.asarray(rng.standard_normal((b, sq, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, skv, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, skv, h, d)), jnp.float32)
        return q, k, v

    return make


class TestFlashAttention:
    def test_matches_reference(self, qkv):
        q, k, v = qkv(2, 128, 128, 2, 64)
        ref = attention_reference(q, k, v)
        out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    def test_causal(self, qkv):
        q, k, v = qkv(1, 128, 128, 2, 64)
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)
        # causality: perturbing future kv must not change earlier rows
        k2 = k.at[:, 64:].set(0.0)
        v2 = v.at[:, 64:].set(0.0)
        a = flash_attention(q, k2, v2, causal=True, block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(
            np.asarray(a)[:, :64], np.asarray(out)[:, :64], atol=3e-5
        )

    def test_kv_longer_than_q(self, qkv):
        """Cached-prefix shape: q aligned to the back of kv."""
        q, k, v = qkv(1, 64, 192, 2, 64)
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    def test_non_tiling_falls_back(self, qkv):
        q, k, v = qkv(1, 100, 100, 2, 64)
        ref = attention_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_bf16_io(self, qkv):
        q, k, v = (t.astype(jnp.bfloat16) for t in qkv(1, 128, 128, 1, 64))
        out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_jittable(self, qkv):
        q, k, v = qkv(1, 128, 128, 2, 64)
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True))
        out = f(q, k, v)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


class TestNmsParity:
    def test_jax_matches_numpy(self, rng):
        n = 50
        centers = rng.uniform(0, 10, (n, 2))
        sizes = rng.uniform(0.5, 3, (n, 2))
        boxes = np.concatenate([centers - sizes / 2, centers + sizes / 2], 1).astype(np.float32)
        scores = rng.uniform(0, 1, n).astype(np.float32)
        ref = nms_numpy(boxes, scores, 0.5, 10)
        idx, valid = nms_jax(jnp.asarray(boxes), jnp.asarray(scores), 0.5, 10)
        got = np.asarray(idx)[np.asarray(valid)]
        assert got.tolist() == ref.tolist()
