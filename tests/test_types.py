"""Unit tests for the core tensor type system (reference analog:
tests/common/unittest_common.cc — tensor type/caps/dim parsing)."""

import numpy as np
import pytest

from nnstreamer_tpu.core.types import (
    TENSOR_RANK_LIMIT,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    dims_equal,
    dtype_from_name,
    dtype_name,
    parse_dims,
    parse_fraction,
)


class TestDims:
    def test_parse_basic(self):
        assert parse_dims("3:224:224:1") == (3, 224, 224, 1)

    def test_parse_single(self):
        assert parse_dims("10") == (10,)

    def test_parse_trailing_zero_dropped(self):
        assert parse_dims("3:224:224:0") == (3, 224, 224)

    def test_parse_inner_zero_rejected(self):
        with pytest.raises(ValueError):
            parse_dims("3:0:224")

    def test_parse_empty_rejected(self):
        with pytest.raises(ValueError):
            parse_dims("")

    def test_rank_limit(self):
        ok = ":".join(["2"] * TENSOR_RANK_LIMIT)
        assert len(parse_dims(ok)) == TENSOR_RANK_LIMIT
        with pytest.raises(ValueError):
            parse_dims(ok + ":2")

    def test_dims_equal_ignores_trailing_ones(self):
        assert dims_equal((3, 224, 224), (3, 224, 224, 1, 1))
        assert not dims_equal((3, 224), (3, 224, 2))


class TestDtypes:
    @pytest.mark.parametrize(
        "name,np_dtype",
        [
            ("uint8", np.uint8),
            ("int8", np.int8),
            ("uint16", np.uint16),
            ("int16", np.int16),
            ("uint32", np.uint32),
            ("int32", np.int32),
            ("uint64", np.uint64),
            ("int64", np.int64),
            ("float16", np.float16),
            ("float32", np.float32),
            ("float64", np.float64),
        ],
    )
    def test_roundtrip(self, name, np_dtype):
        dt = dtype_from_name(name)
        assert dt == np.dtype(np_dtype)
        assert dtype_name(dt) == name

    def test_bfloat16(self):
        dt = dtype_from_name("bfloat16")
        assert dt.itemsize == 2
        assert dtype_name(dt) == "bfloat16"

    def test_unknown(self):
        with pytest.raises(ValueError):
            dtype_from_name("no-such-type")


class TestTensorSpec:
    def test_shape_reversal(self):
        s = TensorSpec.from_string("3:224:224:1", "uint8")
        assert s.shape == (1, 224, 224, 3)  # NHWC
        assert s.rank == 4
        assert s.count == 3 * 224 * 224
        assert s.nbytes == 3 * 224 * 224

    def test_from_shape(self):
        s = TensorSpec.from_shape((1, 224, 224, 3), np.float32)
        assert s.dims == (3, 224, 224, 1)
        assert s.nbytes == 3 * 224 * 224 * 4

    def test_of_array(self):
        a = np.zeros((2, 5, 7), np.int16)
        s = TensorSpec.of(a)
        assert s.shape == a.shape
        assert s.dtype == a.dtype

    def test_compat(self):
        a = TensorSpec.from_string("3:4:5", "float32")
        b = TensorSpec.from_string("3:4:5:1:1", "float32")
        c = TensorSpec.from_string("3:4:5", "int32")
        assert a.is_compatible(b)
        assert not a.is_compatible(c)


class TestTensorsSpec:
    def test_from_string_multi(self):
        ts = TensorsSpec.from_string("3:224:224:1,1001:1", "uint8,float32")
        assert len(ts) == 2
        assert ts[0].dtype == np.uint8
        assert ts[1].dtype == np.float32
        assert ts[1].shape == (1, 1001)

    def test_default_type_uint8(self):
        ts = TensorsSpec.from_string("4:4")
        assert ts[0].dtype == np.uint8

    def test_formats(self):
        ts = TensorsSpec.from_string("2:2", format="flexible")
        assert ts.is_flexible and not ts.is_sparse
        assert TensorsSpec.from_string("2:2", format="sparse").is_sparse

    def test_compat_static(self):
        a = TensorsSpec.from_string("3:4", "float32")
        b = TensorsSpec.from_string("3:4:1", "float32")
        assert a.is_compatible(b)
        assert not a.is_compatible(TensorsSpec.from_string("3:5", "float32"))


def test_parse_fraction():
    assert parse_fraction("30/1") == (30, 1)
    assert parse_fraction("15") == (15, 1)
    assert parse_fraction((24, 2)) == (24, 2)


class TestTensorCapsString:
    """Caps strings carrying tensor specs (reference caps syntax:
    ``other/tensors,num_tensors=2,dimensions=3:4.5:6,types=uint8.float32``)."""

    def test_single_tensor_spec(self):
        from nnstreamer_tpu.core.caps import parse_caps_string

        caps = parse_caps_string(
            "other/tensors,dimensions=3:224:224:8,types=uint8"
        )
        spec = caps.spec
        assert spec is not None and len(spec) == 1
        assert spec[0].shape == (8, 224, 224, 3)
        assert spec[0].dtype == np.uint8

    def test_multi_tensor_dot_syntax(self):
        from nnstreamer_tpu.core.caps import parse_caps_string

        caps = parse_caps_string(
            "other/tensors,num_tensors=2,dimensions=3:4.5:6,types=uint8.float32"
        )
        spec = caps.spec
        assert len(spec) == 2
        assert spec[0].dims == (3, 4) and spec[0].dtype == np.uint8
        assert spec[1].dims == (5, 6) and spec[1].dtype == np.float32

    def test_flexible_media(self):
        from nnstreamer_tpu.core.caps import parse_caps_string
        from nnstreamer_tpu.core.types import TensorFormat

        caps = parse_caps_string(
            "other/tensors-flexible,dimensions=2:2,types=int32"
        )
        assert caps.spec.format == TensorFormat.FLEXIBLE

    def test_framerate_in_spec(self):
        from nnstreamer_tpu.core.caps import parse_caps_string

        caps = parse_caps_string(
            "other/tensors,dimensions=2:2,types=int32,framerate=30/1"
        )
        assert caps.spec.rate == (30, 1)
        caps = parse_caps_string(
            "other/tensors,dimensions=2:2,types=int32,framerate=15"
        )
        assert caps.spec.rate == (15, 1)
