"""HBM-residency planner + pipelined async fetch engine (ISSUE 7).

The contract under test (docs/FETCH.md):

* ``fetch_depth`` opens an async fetch window at sinks — up to that many
  buffers resolve D2H / deferred host_post concurrently — with emission
  order strictly FIFO whatever order resolutions finish;
* EOS and stage errors flush the window: everything admitted before the
  boundary is still delivered;
* host-fed ingress donation (``donate_ingress``) is bit-identical to the
  non-donated path and only planned where sole ownership is provable;
* device-resident intermediate edges NEVER cross to host (transfers
  trapped, the way deep-lint tests trap dispatch);
* the planner auto-selects a model's REDUCED output exactly when every
  downstream consumer admits it;
* the deep lint prices per-sink-edge fetch bytes against the calibrated
  link and flags ``fetch-bound`` pipelines with zero device dispatch.
"""

import random
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.analysis import analyze
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.pipeline.runtime import PipelineError
from nnstreamer_tpu.core.config import get_config
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.pipeline.residency import (HBM_GBPS, compute_floor_ms,
                                               fetch_ms)

DIMS = 16

DESC = (
    f"appsrc name=src caps=other/tensors,dimensions={DIMS},types=float32 ! "
    "tensor_transform mode=arithmetic option=typecast:float32,add:1.0 ! "
    f"tensor_filter framework=jax model=scaler custom=scale:2.0,dims:{DIMS} "
    "name=f ! tensor_sink name=out"
)

SEG = (
    "videotestsrc device=true batch=2 num-buffers=4 width=64 height=64 "
    "name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=deeplab_mobilenet "
    "custom=size:64,batch:2 name=f ! "
    "tensor_decoder mode=image_segment option1=classmap ! "
    "tensor_sink name=out"
)


def _frames(n):
    return [np.full((DIMS,), float(i), np.float32) for i in range(n)]


def codes(report):
    return [d.code for d in report.diagnostics]


# ---------------------------------------------------------------------------
# fetch window: in-order emission, flush, accounting
# ---------------------------------------------------------------------------

def test_fetch_window_in_order_with_random_delays(monkeypatch):
    """fetch_depth=2 resolves materializations concurrently; randomized
    per-buffer delays must not reorder what pop() returns."""
    real = Buffer.to_host
    rng = random.Random(7)

    def slow(self):
        time.sleep(rng.random() * 0.004)
        return real(self)

    monkeypatch.setattr(Buffer, "to_host", slow)
    n = 24
    p = nt.Pipeline(DESC, fetch_depth=2)
    outs = []
    with p:
        for i, x in enumerate(_frames(n)):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in range(n):
            outs.append(p.pull("out", timeout=60))
        p.eos()
        p.wait(timeout=60)
    assert [o.pts for o in outs] == list(range(n))
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            np.asarray(o.tensors[0]), (float(i) + 1.0) * 2.0)


def test_fetch_depth_resolution_prop_beats_pipeline_beats_config():
    from nnstreamer_tpu.elements.sink import TensorSink

    el = TensorSink({"fetch_depth": 5})
    assert el.fetch_depth == 5
    el2 = TensorSink({})
    el2._fetch_depth = 3  # what the runner attaches from the pipeline knob
    assert el2.fetch_depth == 3
    el3 = TensorSink({})
    assert el3.fetch_depth == max(1, get_config().fetch_depth)


def test_eos_flushes_fetch_window():
    """Buffers admitted before EOS are all delivered after wait() — the
    window's pending resolutions survive the pipeline winding down."""
    n = 12
    p = nt.Pipeline(DESC, fetch_depth=2)
    with p:
        for i, x in enumerate(_frames(n)):
            p.push("src", nt.Buffer([x], pts=i))
        p.eos()
        p.wait(timeout=60)
        outs = [p.pull("out", timeout=30) for _ in range(n)]
    assert [o.pts for o in outs] == list(range(n))


def test_stage_error_still_delivers_prior_window():
    """A stage failure mid-stream flushes, not drops, the buffers that
    were already past it (then check() reports the failure)."""
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    calls = {"n": 0}

    def boom(ins):
        calls["n"] += 1
        if calls["n"] > 4:
            raise RuntimeError("deliberate stage failure")
        return [np.asarray(ins[0]) * 2.0]

    spec = TensorsSpec.from_string(str(DIMS), "float32")
    register_custom_easy("fetch-boom", boom, in_spec=spec, out_spec=spec)
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={DIMS},"
        "types=float32 ! "
        "tensor_filter framework=custom-easy model=fetch-boom name=f ! "
        "tensor_sink name=out"
    )
    p = nt.Pipeline(desc, fetch_depth=2)
    outs = []
    with p:
        src = p.element("src")
        for i, x in enumerate(_frames(8)):
            # raw element push: Pipeline.push() re-checks for errors and
            # would raise mid-loop once the failure lands
            src.push(nt.Buffer([x], pts=i))
        for _ in range(4):
            outs.append(p.pull("out", timeout=30))
        time.sleep(0.3)  # let the failing buffer hit the stage
        with pytest.raises(PipelineError):
            p.check()
    assert [o.pts for o in outs] == [0, 1, 2, 3]


def test_materialization_timeout_carries_trace_id(monkeypatch, caplog):
    """A fetch-window timeout names the buffer's trace id and dumps the
    flight-recorder ring, like watchdog fires (satellite: host_post
    resolver errors are debuggable)."""
    import logging

    real = Buffer.to_host

    def very_slow(self):
        time.sleep(1.5)
        return real(self)

    monkeypatch.setattr(Buffer, "to_host", very_slow)
    p = nt.Pipeline(DESC, fetch_depth=2, trace_mode="ring")
    with caplog.at_level(logging.ERROR,
                         logger="nnstreamer_tpu.elements.sink"):
        with p:
            p.push("src", nt.Buffer([_frames(1)[0]], pts=0))
            # wait for the stage to SUBMIT the future (first-buffer jit
            # compile is load-dependent) so the short pull timeout below
            # bounds materialization, not arrival
            sink = p.element("out")
            deadline = time.monotonic() + 30.0
            while sink._q.empty() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not sink._q.empty(), "stage never delivered the future"
            with pytest.raises(TimeoutError) as ei:
                p.pull("out", timeout=0.25)
    assert "trace id" in str(ei.value)
    assert any("flight recorder" in r.message for r in caplog.records)


def test_wait_stall_accounting_split():
    """h2d (appsrc admission) and d2h (sink materialization) waits land in
    SEPARATE metric series — the satellite's rtt_stalls split."""
    metrics.reset()
    desc = DESC.replace("appsrc name=src", "appsrc name=src max-inflight=1")
    p = nt.Pipeline(desc, fetch_depth=1)
    with p:
        for i, x in enumerate(_frames(6)):
            p.push("src", nt.Buffer([x], pts=i))
            p.pull("out", timeout=30)
        p.eos()
        p.wait(timeout=30)
    snap = metrics.snapshot()
    assert "src.h2d_wait_ms" in snap
    assert "out.d2h_wait_ms" in snap


def test_fetch_window_span_and_gauge():
    """With tracing on, every window submit records a fetch.window span
    carrying the outstanding depth."""
    from nnstreamer_tpu.utils import tracing

    tracing.recorder.configure("ring")
    tracing.recorder.clear()
    p = nt.Pipeline(DESC, fetch_depth=2, trace_mode="ring")
    with p:
        for i, x in enumerate(_frames(8)):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in range(8):
            p.pull("out", timeout=30)
        p.eos()
        p.wait(timeout=30)
    spans = [e for e in tracing.recorder.events() if e.kind == "fetch.window"]
    assert spans, "no fetch.window spans recorded"
    assert all(e.args and e.args.get("depth", 0) >= 1 for e in spans)
    tracing.recorder.configure("off")


# ---------------------------------------------------------------------------
# ingress donation
# ---------------------------------------------------------------------------

def _fused_stages(p):
    return [s.element for s in p.stages if s.element.kind == "fused"]


def test_ingress_donation_planned_and_bit_identical():
    x = np.arange(DIMS, dtype=np.float32)
    outs = {}
    for flag in (True, False):
        p = nt.Pipeline(DESC, donate_ingress=flag)
        fused = _fused_stages(p)
        assert fused and fused[0]._ingress_put is flag
        with p:
            p.push("src", x)
            outs[flag] = np.asarray(p.pull("out", timeout=60).tensors[0])
            p.eos()
            p.wait(timeout=30)
    assert np.array_equal(outs[True], outs[False])


def test_donation_vetoed_without_sole_consumer():
    """A source feeding a tee is not sole-consumed by the fused chain —
    the planner must not donate."""
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={DIMS},"
        "types=float32 ! tee name=t "
        "t. ! tensor_transform mode=arithmetic option=typecast:float32,"
        "add:1.0 ! "
        f"tensor_filter framework=jax model=scaler custom=scale:2.0,"
        f"dims:{DIMS} name=f ! tensor_sink name=out "
        "t. ! fakesink name=devnull"
    )
    p = nt.Pipeline(desc, donate_ingress=True)
    for fe in _fused_stages(p):
        assert not fe._ingress_put


def test_device_source_fold_keeps_plain_donation():
    """The folded device-source path donates WITHOUT the ingress
    device_put (its arrays are already device-minted)."""
    p = nt.Pipeline(
        "videotestsrc device=true batch=2 num-buffers=4 width=16 "
        "height=16 name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,"
        "div:255.0 ! tensor_sink name=out", donate_ingress=True)
    folded = [s.element for s in p.stages
              if getattr(s.element, "fused", None) is not None]
    assert folded
    assert folded[0].fused._donate and not folded[0].fused._ingress_put


# ---------------------------------------------------------------------------
# device residency: zero D2H on intermediate edges
# ---------------------------------------------------------------------------

def test_device_resident_intermediate_edges_zero_d2h(monkeypatch):
    """Between the fused device stage and a to_host=false sink (through a
    tee), NOTHING may cross to host: the framework's fetch chokepoints
    (Buffer.to_host / Buffer.resolve) are trapped, the way deep-lint
    tests trap dispatch."""
    desc = (
        "videotestsrc device=true batch=2 num-buffers=6 width=16 "
        "height=16 name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,"
        "div:255.0 ! tee name=t "
        "t. ! tensor_sink name=a to_host=false "
        "t. ! tensor_sink name=b to_host=false"
    )
    p = nt.Pipeline(desc)

    def trap(self):
        raise AssertionError("D2H on a device-resident path")

    monkeypatch.setattr(Buffer, "to_host", trap)
    monkeypatch.setattr(Buffer, "resolve", trap)
    with p:
        for _ in range(3):
            a = p.pull("a", timeout=60)
            b = p.pull("b", timeout=60)
            assert a.on_device and b.on_device
        p.wait(timeout=60)


# ---------------------------------------------------------------------------
# reduced-output selection goldens
# ---------------------------------------------------------------------------

def test_reduced_output_selected_for_classmap():
    p = nt.Pipeline(SEG)
    assert p.residency.reduced_outputs == ["f"]
    [edge] = p.residency.fetch
    assert edge.reduced == "fused host_post"
    # native stride 64/16 = 4: the classmap payload is 2*4*4 u8
    assert edge.bytes_per_buffer == 2 * 4 * 4
    with p:
        out = p.pull("out", timeout=120)
        p.wait(timeout=120)
    assert np.asarray(out.tensors[0]).shape == (2, 4, 4)


def test_reduced_output_not_selected_for_overlay():
    p = nt.Pipeline(SEG.replace(" option1=classmap", ""))
    assert p.residency.reduced_outputs == []
    with p:
        out = p.pull("out", timeout=120)
        p.wait(timeout=120)
    assert np.asarray(out.tensors[0]).shape == (2, 64, 64, 4)


def test_reduced_output_not_selected_when_pinned():
    """An explicit upsample option pins the geometry: no offer, even with
    an admitting consumer chain."""
    p = nt.Pipeline(SEG.replace("custom=size:64,batch:2",
                                "custom=size:64,batch:2,upsample:1"))
    assert p.residency.reduced_outputs == []
    with p:
        out = p.pull("out", timeout=120)
        p.wait(timeout=120)
    assert np.asarray(out.tensors[0]).shape == (2, 64, 64)


def test_reduced_output_knob_opt_out():
    p = nt.Pipeline(SEG, reduce_outputs=False)
    assert p.residency.reduced_outputs == []


def test_reduced_output_matches_explicit_native_stride():
    """Planner-selected reduced output is bit-identical to the hand-tuned
    custom=upsample:0 row it replaces."""
    auto = nt.Pipeline(SEG)
    hand = nt.Pipeline(SEG.replace("custom=size:64,batch:2",
                                   "custom=size:64,batch:2,upsample:0"))
    outs = {}
    for tag, p in (("auto", auto), ("hand", hand)):
        with p:
            outs[tag] = np.asarray(p.pull("out", timeout=120).tensors[0])
            p.wait(timeout=120)
    assert np.array_equal(outs["auto"], outs["hand"])


# ---------------------------------------------------------------------------
# deep lint: fetch pricing + fetch-bound
# ---------------------------------------------------------------------------

FETCH_BOUND = (
    "videotestsrc device=true batch=8 num-buffers=32 width=224 height=224 "
    "name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=deeplab_mobilenet "
    "custom=size:224,batch:8 name=f ! "
    "tensor_decoder mode=image_segment ! tensor_sink name=out"
)


def test_fetch_pricing_units():
    assert fetch_ms(38_200_000, 38.2) == pytest.approx(1000.0)
    assert fetch_ms(0, 38.2, rtt_ms=88.0) == pytest.approx(88.0)
    assert fetch_ms(1 << 20, 0.0) == 0.0  # uncalibrated: never priced
    assert compute_floor_ms(int(HBM_GBPS * 1e9)) == pytest.approx(1e3)


@pytest.fixture
def calibrated_link():
    cfg = get_config()
    old = (cfg.link_d2h_mbps, cfg.link_fetch_rtt_ms)
    cfg.link_d2h_mbps, cfg.link_fetch_rtt_ms = 38.2, 88.0
    yield cfg
    cfg.link_d2h_mbps, cfg.link_fetch_rtt_ms = old


def test_deep_lint_flags_fetch_bound(calibrated_link):
    report = analyze(FETCH_BOUND, deep=True)
    assert "fetch-bound" in codes(report)
    edges = report.resources.fetch_edges
    assert len(edges) == 1
    # overlay host_post ships the full-res u8 class map: 8*224*224
    assert edges[0].bytes_per_buffer == 8 * 224 * 224
    assert edges[0].reduced == "fused host_post"
    assert edges[0].d2h_ms > edges[0].compute_floor_ms > 0


def test_deep_lint_fetch_ok_for_tiny_payload(calibrated_link):
    """Classification's fused argmax ships bytes, not frames: priced but
    never flagged."""
    desc = (
        "videotestsrc device=true batch=64 num-buffers=64 width=224 "
        "height=224 name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,"
        "add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=mobilenet_v1 "
        "custom=size:224,batch:64 name=f ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out"
    )
    report = analyze(desc, deep=True)
    assert "fetch-bound" not in codes(report)
    [edge] = report.resources.fetch_edges
    assert edge.bytes_per_buffer == 64 * (4 + 4)  # [B]i32 + [B]f32


def test_deep_lint_fetch_unpriced_without_calibration():
    report = analyze(FETCH_BOUND, deep=True)
    assert "fetch-bound" not in codes(report)
    [edge] = report.resources.fetch_edges
    assert edge.bytes_per_buffer == 8 * 224 * 224
    assert edge.d2h_ms == 0.0


def test_fetch_check_zero_device_dispatch(monkeypatch, calibrated_link):
    """The fetch pricing pass is pure arithmetic: the fetch-bound verdict
    lands with every jit call and device_put trapped."""
    import jax

    real_jit = jax.jit

    def guarded_jit(*a, **k):
        real_jit(*a, **k)

        def trap(*aa, **kk):
            raise AssertionError("jit-compiled call during deep analysis")

        return trap

    def no_device_put(*a, **k):
        raise AssertionError("device_put during deep analysis")

    monkeypatch.setattr(jax, "jit", guarded_jit)
    monkeypatch.setattr(jax, "device_put", no_device_put)
    report = analyze(FETCH_BOUND, deep=True)
    assert "analyzer-error" not in codes(report), report.render()
    assert "fetch-bound" in codes(report)


def test_resource_report_renders_fetch_edges(calibrated_link):
    text = analyze(FETCH_BOUND, deep=True).resources.render()
    assert "fetch out <- " in text
    assert "d2h" in text and "compute floor" in text
