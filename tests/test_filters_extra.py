"""Extra framework sub-plugins: torch, gated onnxruntime/tflite.

Reference analog: ``tests/nnstreamer_filter_extensions_common`` — one
conformance surface per framework, skipped gracefully when the runtime
isn't built (SURVEY §4).
"""

from __future__ import annotations

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.elements.base import ElementError

torch = pytest.importorskip("torch")


class TestTorchFramework:
    def test_registered_module_in_pipeline(self):
        from nnstreamer_tpu.filters.torch_fw import register_torch_module

        class Doubler(torch.nn.Module):
            def forward(self, x):
                return x * 2

        register_torch_module("doubler", Doubler())
        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter framework=torch model=doubler ! "
            "tensor_sink name=out"
        )
        with p:
            p.push("src", np.arange(6, dtype=np.float32).reshape(2, 3))
            out = p.pull("out", timeout=10)
            p.eos()
            p.wait(timeout=10)
        np.testing.assert_allclose(
            np.asarray(out.tensors[0]), np.arange(6, dtype=np.float32).reshape(2, 3) * 2
        )

    def test_torchscript_file(self, tmp_path):
        class AddOne(torch.nn.Module):
            def forward(self, x):
                return x + 1

        path = str(tmp_path / "addone.pt")
        torch.jit.script(AddOne()).save(path)
        s = nt.SingleShot(framework="torch", model=path)
        (out,) = s.invoke(np.zeros((2, 2), np.float32))
        np.testing.assert_allclose(out, np.ones((2, 2), np.float32))
        s.close()

    def test_multi_output(self):
        from nnstreamer_tpu.filters.torch_fw import register_torch_module

        class TwoHeads(torch.nn.Module):
            def forward(self, x):
                return x.sum(dim=1), x.max(dim=1).values

        register_torch_module("twoheads", TwoHeads())
        s = nt.SingleShot(framework="torch", model="twoheads")
        outs = s.invoke(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert len(outs) == 2
        np.testing.assert_allclose(outs[0], [3.0, 12.0])
        np.testing.assert_allclose(outs[1], [2.0, 5.0])
        s.close()

    def test_bad_model_falls_through_with_clear_error(self):
        with pytest.raises(ElementError, match="torch"):
            nt.SingleShot(framework="torch", model="nosuch_model_xyz")


class TestStateDictImport:
    def test_layout_conversion(self):
        from nnstreamer_tpu.filters.torch_fw import state_dict_to_tree

        sd = {
            "features.conv0.weight": torch.zeros(8, 3, 3, 3),  # OIHW
            "classifier.weight": torch.zeros(10, 32),  # [out, in]
            "classifier.bias": torch.zeros(10),
        }
        tree = state_dict_to_tree(sd)
        assert tree["features.conv0.weight"].shape == (3, 3, 3, 8)  # HWIO
        assert tree["classifier.weight"].shape == (32, 10)
        assert tree["classifier.bias"].shape == (10,)

    def test_torch_linear_matches_jax_matmul(self):
        from nnstreamer_tpu.filters.torch_fw import state_dict_to_tree

        lin = torch.nn.Linear(4, 3)
        x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
        with torch.no_grad():
            ref = lin(torch.from_numpy(x)).numpy()
        tree = state_dict_to_tree(lin.state_dict())
        got = x @ tree["weight"] + tree["bias"]
        np.testing.assert_allclose(got, ref, atol=1e-6)


class TestGatedFrameworks:
    def test_onnxruntime_gated_error(self):
        try:
            import onnxruntime  # noqa: F401

            pytest.skip("onnxruntime installed; gate not exercised")
        except ImportError:
            pass
        with pytest.raises(ElementError, match="onnxruntime"):
            nt.SingleShot(framework="onnxruntime", model="x.onnx")

    def test_tflite_gated_error(self):
        for mod in ("tflite_runtime", "tensorflow"):
            try:
                __import__(mod)
                pytest.skip(f"{mod} installed; gate not exercised")
            except ImportError:
                pass
        with pytest.raises(ElementError, match="TFLite"):
            nt.SingleShot(framework="tensorflow-lite", model="m.tflite")


class TestReloadAndCombinations:
    """tensor_filter model reload + input/output-combination remapping
    (reference: tensor_filter_common.c ReloadModel, input-combination /
    output-combination — VERDICT r1 item #6)."""

    def _register(self, name, scale):
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")
        register_custom_easy(
            name, lambda ins: [np.asarray(ins[0], np.float32) * scale],
            in_spec=spec, out_spec=spec)

    def test_reload_model_swaps_without_rebuild(self):
        from nnstreamer_tpu.elements.filter import TensorFilter

        self._register("reload_a", 2.0)
        self._register("reload_b", 10.0)
        f = TensorFilter({"framework": "custom-easy", "model": "reload_a"})
        f.configure({}, ["src"])
        from nnstreamer_tpu.core.buffer import Buffer

        x = np.ones((4,), np.float32)
        out = f.process("sink", Buffer([x]))[0][1]
        np.testing.assert_allclose(out.tensors[0], 2.0 * x)
        f.reload_model("reload_b")
        out = f.process("sink", Buffer([x]))[0][1]
        np.testing.assert_allclose(out.tensors[0], 10.0 * x)
        assert f.props["model"] == "reload_b"

    def test_reload_rejects_mismatched_spec(self):
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.elements.base import ElementError
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        self._register("reload_c", 2.0)
        register_custom_easy(
            "reload_wrong", lambda ins: [np.zeros((7,), np.float32)],
            in_spec=TensorsSpec.from_string("7", "float32"),
            out_spec=TensorsSpec.from_string("7", "float32"))
        f = TensorFilter({"framework": "custom-easy", "model": "reload_c"})
        f.configure({}, ["src"])
        with pytest.raises(ElementError, match="reload"):
            f.reload_model("reload_wrong")
        # old model still live after the failed reload
        from nnstreamer_tpu.core.buffer import Buffer

        out = f.process("sink", Buffer([np.ones((4,), np.float32)]))[0][1]
        np.testing.assert_allclose(out.tensors[0], 2.0)

    def test_input_output_combination(self):
        """Buffer [a, b]: model consumes tensor 1 only; output buffer is
        [input 0 pass-through, model output]."""
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.elements.filter import TensorFilter

        self._register("combo_scale", 3.0)
        f = TensorFilter({
            "framework": "custom-easy", "model": "combo_scale",
            "input_combination": "1", "output_combination": "i0,o0",
        })
        f.configure({}, ["src"])
        a = np.full((2,), 7.0, np.float32)
        b = np.arange(4, dtype=np.float32)
        out = f.process("sink", Buffer([a, b]))[0][1]
        assert len(out.tensors) == 2
        np.testing.assert_allclose(out.tensors[0], a)     # i0 passed through
        np.testing.assert_allclose(out.tensors[1], 3.0 * b)  # o0

    def test_combination_fused_pipeline(self):
        """Combinations survive fusion: jax filter inside a fused stage with
        input/output remapping."""
        desc = (
            "appsrc name=src caps=other/tensors,dimensions=4:4.4:4,types=float32.float32 ! "
            "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:4:4 "
            "input-combination=1 output-combination=o0,i0 ! "
            "tensor_sink name=out"
        )
        p = nt.Pipeline(desc, fuse=True)
        rng = np.random.default_rng(0)
        a = rng.standard_normal((4, 4)).astype(np.float32)
        b = rng.standard_normal((4, 4)).astype(np.float32)
        with p:
            p.push("src", [a, b])
            buf = p.pull("out", timeout=30)
            p.eos()
            p.wait(timeout=15)
        np.testing.assert_allclose(np.asarray(buf.tensors[0]), 2.0 * b, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(buf.tensors[1]), a, rtol=1e-6)


class _FakeTFLiteInterpreter:
    """Recorded-IO stand-in for the TFLite Interpreter: enough surface for
    the wrapper's marshalling layer (set/get tensor by index, details
    dicts), computing y = 2x so data flow is observable."""

    def __init__(self):
        self._tensors = {}
        self.allocated = False
        self.invoked = 0

    def allocate_tensors(self):
        self.allocated = True

    def get_input_details(self):
        return [{"index": 0, "shape": np.array([1, 4]),
                 "dtype": np.float32, "name": "in0"},
                {"index": 1, "shape": np.array([2, 3]),
                 "dtype": np.uint8, "name": "in1"}]

    def get_output_details(self):
        return [{"index": 10, "shape": np.array([1, 4]),
                 "dtype": np.float32, "name": "out0"}]

    def set_tensor(self, index, value):
        assert value.flags["C_CONTIGUOUS"]  # wrapper must marshal contiguous
        self._tensors[index] = value

    def get_tensor(self, index):
        return self._tensors[index]

    def invoke(self):
        self.invoked += 1
        self._tensors[10] = self._tensors[0] * 2


class _FakeOrtSession:
    class _Input:
        def __init__(self, name):
            self.name = name

    def __init__(self):
        self.feeds = []

    def get_inputs(self):
        return [self._Input("a"), self._Input("b")]

    def run(self, outputs, feed):
        assert outputs is None
        self.feeds.append(feed)
        return [feed["a"] + feed["b"]]


class TestGatedWrapperConformance:
    """Marshalling-layer conformance for the gated tflite/ort wrappers via
    fake runtime objects (VERDICT r1 item #8: evidence the wrappers are
    complete without the runtimes installed)."""

    def test_tflite_invoke_marshalling(self):
        from nnstreamer_tpu.filters.gated import TFLiteFramework

        fw = TFLiteFramework()
        fw._interp = _FakeTFLiteInterpreter()
        x = np.arange(4, dtype=np.float32)[None, :]
        # non-contiguous input must be made contiguous by the wrapper
        y = np.zeros((2, 6), np.uint8)[:, ::2]
        outs = fw.invoke([x, y])
        assert fw._interp.invoked == 1
        np.testing.assert_allclose(outs[0], 2 * x)

    def test_tflite_model_info_mapping(self):
        from nnstreamer_tpu.filters.gated import TFLiteFramework

        fw = TFLiteFramework()
        fw._interp = _FakeTFLiteInterpreter()
        in_spec, out_spec = fw.get_model_info()
        assert len(in_spec) == 2 and len(out_spec) == 1
        assert in_spec[0].shape == (1, 4)
        assert in_spec[0].dtype == np.float32
        assert in_spec[1].shape == (2, 3)
        assert in_spec[1].dtype == np.uint8
        assert out_spec[0].shape == (1, 4)

    def test_tflite_in_pipeline_with_fake(self):
        """The wrapper drives a real pipeline once an interpreter exists."""
        from nnstreamer_tpu.elements.filter import SingleShot
        from nnstreamer_tpu.filters.gated import TFLiteFramework

        fw = TFLiteFramework()
        fw._interp = _FakeTFLiteInterpreter()
        x = np.ones((1, 4), np.float32)
        out = fw.invoke([x, np.zeros((2, 3), np.uint8)])
        np.testing.assert_allclose(out[0], 2.0)

    def test_ort_feed_name_mapping(self):
        from nnstreamer_tpu.filters.gated import OnnxRuntimeFramework

        fw = OnnxRuntimeFramework()
        fw._sess = _FakeOrtSession()
        fw._in_names = [i.name for i in fw._sess.get_inputs()]
        a = np.full((3,), 1.5, np.float32)
        b = np.full((3,), 0.5, np.float32)
        outs = fw.invoke([a, b])
        np.testing.assert_allclose(outs[0], 2.0)
        assert list(fw._sess.feeds[0]) == ["a", "b"]  # positional -> named

    def test_open_without_runtime_raises_framework_error(self):
        from nnstreamer_tpu.filters.base import FrameworkError
        from nnstreamer_tpu.filters.gated import (OnnxRuntimeFramework,
                                                  TFLiteFramework)

        for cls in (OnnxRuntimeFramework, TFLiteFramework):
            with pytest.raises(FrameworkError, match="install|not installed"):
                cls().open({"model": "nonexistent.bin"})
