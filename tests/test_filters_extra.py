"""Extra framework sub-plugins: torch, gated onnxruntime/tflite.

Reference analog: ``tests/nnstreamer_filter_extensions_common`` — one
conformance surface per framework, skipped gracefully when the runtime
isn't built (SURVEY §4).
"""

from __future__ import annotations

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.elements.base import ElementError

torch = pytest.importorskip("torch")


class TestTorchFramework:
    def test_registered_module_in_pipeline(self):
        from nnstreamer_tpu.filters.torch_fw import register_torch_module

        class Doubler(torch.nn.Module):
            def forward(self, x):
                return x * 2

        register_torch_module("doubler", Doubler())
        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter framework=torch model=doubler ! "
            "tensor_sink name=out"
        )
        with p:
            p.push("src", np.arange(6, dtype=np.float32).reshape(2, 3))
            out = p.pull("out", timeout=10)
            p.eos()
            p.wait(timeout=10)
        np.testing.assert_allclose(
            np.asarray(out.tensors[0]), np.arange(6, dtype=np.float32).reshape(2, 3) * 2
        )

    def test_torchscript_file(self, tmp_path):
        class AddOne(torch.nn.Module):
            def forward(self, x):
                return x + 1

        path = str(tmp_path / "addone.pt")
        torch.jit.script(AddOne()).save(path)
        s = nt.SingleShot(framework="torch", model=path)
        (out,) = s.invoke(np.zeros((2, 2), np.float32))
        np.testing.assert_allclose(out, np.ones((2, 2), np.float32))
        s.close()

    def test_multi_output(self):
        from nnstreamer_tpu.filters.torch_fw import register_torch_module

        class TwoHeads(torch.nn.Module):
            def forward(self, x):
                return x.sum(dim=1), x.max(dim=1).values

        register_torch_module("twoheads", TwoHeads())
        s = nt.SingleShot(framework="torch", model="twoheads")
        outs = s.invoke(np.arange(6, dtype=np.float32).reshape(2, 3))
        assert len(outs) == 2
        np.testing.assert_allclose(outs[0], [3.0, 12.0])
        np.testing.assert_allclose(outs[1], [2.0, 5.0])
        s.close()

    def test_bad_model_falls_through_with_clear_error(self):
        with pytest.raises(ElementError, match="torch"):
            nt.SingleShot(framework="torch", model="nosuch_model_xyz")


class TestStateDictImport:
    def test_layout_conversion(self):
        from nnstreamer_tpu.filters.torch_fw import state_dict_to_tree

        sd = {
            "features.conv0.weight": torch.zeros(8, 3, 3, 3),  # OIHW
            "classifier.weight": torch.zeros(10, 32),  # [out, in]
            "classifier.bias": torch.zeros(10),
        }
        tree = state_dict_to_tree(sd)
        assert tree["features.conv0.weight"].shape == (3, 3, 3, 8)  # HWIO
        assert tree["classifier.weight"].shape == (32, 10)
        assert tree["classifier.bias"].shape == (10,)

    def test_torch_linear_matches_jax_matmul(self):
        from nnstreamer_tpu.filters.torch_fw import state_dict_to_tree

        lin = torch.nn.Linear(4, 3)
        x = np.random.default_rng(0).standard_normal((2, 4)).astype(np.float32)
        with torch.no_grad():
            ref = lin(torch.from_numpy(x)).numpy()
        tree = state_dict_to_tree(lin.state_dict())
        got = x @ tree["weight"] + tree["bias"]
        np.testing.assert_allclose(got, ref, atol=1e-6)


class TestGatedFrameworks:
    def test_onnxruntime_gated_error(self):
        try:
            import onnxruntime  # noqa: F401

            pytest.skip("onnxruntime installed; gate not exercised")
        except ImportError:
            pass
        with pytest.raises(ElementError, match="onnxruntime"):
            nt.SingleShot(framework="onnxruntime", model="x.onnx")

    def test_tflite_gated_error(self):
        for mod in ("tflite_runtime", "tensorflow"):
            try:
                __import__(mod)
                pytest.skip(f"{mod} installed; gate not exercised")
            except ImportError:
                pass
        with pytest.raises(ElementError, match="TFLite"):
            nt.SingleShot(framework="tensorflow-lite", model="m.tflite")
