"""Hardened wire ingestion (ISSUE 12, docs/ROBUSTNESS.md): typed
WireError rejects, configurable limits, declared-vs-actual
cross-checks, meta-drop accounting, and msg-id salvage."""

import socket
import struct
import threading

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.utils import wire
from nnstreamer_tpu.utils.wire import WireError, WireLimits


def _hdr(n=0, meta=b"", pts=-1, seqno=0, flags=0):
    return struct.pack("<IIIIqQI", wire.MAGIC, wire.VERSION, flags, n,
                       pts, seqno, len(meta)) + meta


class TestDecodeHardening:
    def test_roundtrip_still_works(self):
        buf = Buffer([np.arange(12, dtype=np.float32).reshape(3, 4),
                      np.array([1, 2], np.int64)],
                     meta={"_query_msg": 7, "_tenant": "a"})
        buf.pts = 123
        out, flags = wire.decode_buffer(wire.encode_buffer(buf, flags=3))
        assert flags == 3
        assert out.pts == 123
        assert out.meta["_query_msg"] == 7
        np.testing.assert_array_equal(out.tensors[0], buf.tensors[0])

    def test_truncated_header_is_typed(self):
        with pytest.raises(WireError):
            wire.decode_buffer(b"\x01\x02")

    def test_truncated_tensor_is_typed_not_struct_error(self):
        raw = wire.encode_buffer(Buffer([np.zeros((4,), np.float32)]))
        for cut in (len(raw) - 3, 40, 37):
            with pytest.raises(WireError):
                wire.decode_buffer(raw[:cut])

    def test_bad_magic_and_version(self):
        raw = wire.encode_buffer(Buffer([]))
        with pytest.raises(WireError, match="magic"):
            wire.decode_buffer(b"XXXX" + raw[4:])
        bad = bytearray(raw)
        bad[4:8] = struct.pack("<I", 99)
        with pytest.raises(WireError, match="version"):
            wire.decode_buffer(bytes(bad))

    def test_tensor_count_bomb(self):
        raw = bytearray(wire.encode_buffer(Buffer([])))
        raw[12:16] = struct.pack("<I", 0xFFFFFFFF)
        with pytest.raises(WireError, match="tensor count"):
            wire.decode_buffer(bytes(raw))

    def test_rank_bomb(self):
        with pytest.raises(WireError, match="rank"):
            wire.decode_buffer(_hdr(n=1) + struct.pack("<I", 1 << 30))

    def test_meta_bomb_rejected_before_parse(self):
        raw = bytearray(wire.encode_buffer(Buffer([])))
        raw[32:36] = struct.pack("<I", 0xFFFFFFFF)
        with pytest.raises(WireError, match="meta"):
            wire.decode_buffer(bytes(raw))

    def test_nbytes_dims_cross_check(self):
        # dims say 4 float32 (16 bytes), header claims 20
        raw = (_hdr(n=1) + struct.pack("<II", 1, 4)
               + struct.pack("<I", 7) + b"float32"
               + struct.pack("<Q", 20) + b"\x00" * 20)
        with pytest.raises(WireError, match="declares 20 bytes"):
            wire.decode_buffer(raw)

    def test_tensor_bytes_limit(self):
        lim = WireLimits(max_tensor_bytes=64)
        raw = wire.encode_buffer(Buffer([np.zeros((65,), np.uint8)]))
        with pytest.raises(WireError, match="limit 64"):
            wire.decode_buffer(raw, lim)
        # under the limit decodes fine
        ok = wire.encode_buffer(Buffer([np.zeros((64,), np.uint8)]))
        wire.decode_buffer(ok, lim)

    def test_dtype_whitelist(self):
        # "O8" (object) parses in numpy but must never cross the wire
        raw = (_hdr(n=1) + struct.pack("<II", 1, 1)
               + struct.pack("<I", 2) + b"O8"
               + struct.pack("<Q", 8) + b"\x00" * 8)
        with pytest.raises(WireError, match="whitelist"):
            wire.decode_buffer(raw)

    def test_meta_must_be_json_object(self):
        with pytest.raises(WireError, match="JSON object"):
            wire.decode_buffer(_hdr(meta=b"[1, 2]"))
        with pytest.raises(WireError, match="json"):
            wire.decode_buffer(_hdr(meta=b"{nope"))

    def test_trailing_garbage_rejected(self):
        raw = wire.encode_buffer(Buffer([np.ones((2,), np.int32)]))
        with pytest.raises(WireError, match="trailing"):
            wire.decode_buffer(raw + b"\xde\xad")

    def test_encode_rejects_non_whitelisted_dtype(self):
        # symmetric contract: encode must fail loudly rather than
        # produce bytes (a DLQ/journal record) decode can never read
        with pytest.raises(WireError, match="not wire-serializable"):
            wire.encode_buffer(Buffer([np.zeros((2,), np.complex64)]))

    def test_wire_error_is_value_error(self):
        # pre-armor handlers catch ValueError; the typed reject must
        # still land in them
        assert issubclass(WireError, ValueError)


class TestMetaDropAccounting:
    def test_non_json_meta_counted_and_logged_once(self, caplog):
        metrics.reset()
        wire._warned_meta_keys.clear()

        class Opaque:
            pass

        buf = Buffer([], meta={"good": 1, "bad": Opaque()})
        import logging

        with caplog.at_level(logging.DEBUG,
                             logger="nnstreamer_tpu.utils.wire"):
            wire.encode_buffer(buf)
            wire.encode_buffer(buf)  # second drop: counted, not logged
        out, _ = wire.decode_buffer(wire.encode_buffer(buf))
        assert out.meta == {"good": 1}
        assert metrics.snapshot().get("wire.meta_dropped") == 3.0
        drops = [r for r in caplog.records if "bad" in r.getMessage()]
        assert len(drops) == 1  # once per key


class TestSalvage:
    def test_salvage_recovers_msg_id_from_malformed_tensor_section(self):
        buf = Buffer([np.zeros((4,), np.float32)],
                     meta={"_query_msg": 42, "_tenant": "t1"})
        raw = bytearray(wire.encode_buffer(buf))
        raw[-8:] = b"\x00" * 8  # corrupt the tensor payload size field
        raw = bytes(raw[:-4])   # and truncate
        with pytest.raises(WireError):
            wire.decode_buffer(raw)
        meta = wire.salvage_meta(raw)
        assert meta["_query_msg"] == 42
        assert meta["_tenant"] == "t1"

    def test_salvage_never_raises(self):
        for garbage in (b"", b"\x00" * 40, b"NNST" + b"\xff" * 64):
            assert wire.salvage_meta(garbage) is None or \
                isinstance(wire.salvage_meta(garbage), dict)


class _SockPair:
    """Real socketpair so read_frame sees genuine socket semantics."""

    def __enter__(self):
        self.a, self.b = socket.socketpair()
        self.b.settimeout(2.0)
        return self

    def __exit__(self, *exc):
        for s in (self.a, self.b):
            try:
                s.close()
            except OSError:
                pass


class TestReadFrameHardening:
    def test_roundtrip(self):
        with _SockPair() as sp:
            payload = wire.encode_buffer(Buffer([np.ones((3,), np.int8)]))
            wire.write_frame(sp.a, payload)
            assert wire.read_frame(sp.b) == payload

    def test_length_bomb_rejected_before_body(self):
        with _SockPair() as sp:
            sp.a.sendall(struct.pack("<Q", 1 << 62) + b"junk")
            with pytest.raises(WireError, match="declares"):
                wire.read_frame(sp.b)

    def test_crc_mismatch_typed(self):
        from nnstreamer_tpu.native import wire_gather

        with _SockPair() as sp:
            frame = bytearray(wire_gather([b"hello world"]))
            frame[-1] ^= 0xFF
            sp.a.sendall(bytes(frame))
            with pytest.raises(WireError, match="crc"):
                wire.read_frame(sp.b)

    def test_oversize_vs_limits_arg(self):
        lim = WireLimits(max_frame_bytes=16)
        from nnstreamer_tpu.native import wire_gather

        with _SockPair() as sp:
            sp.a.sendall(bytes(wire_gather([b"x" * 64])))
            with pytest.raises(WireError, match="limit 16"):
                wire.read_frame(sp.b, lim)


class TestServerSurvivesGarbage:
    """The serversrc read loop: a malformed frame is rejected typed —
    counted per tenant, answered when the msg id salvages — and the
    connection keeps serving (the satellite fix: one bad frame used to
    tear down the whole connection)."""

    def _serve(self):
        import nnstreamer_tpu as nt
        from nnstreamer_tpu.filters.custom_easy import \
            register_custom_easy
        from nnstreamer_tpu.core.types import TensorsSpec

        spec = TensorsSpec.from_string("4", "float32")
        register_custom_easy("wire-echo", lambda ins: [ins[0] * 2.0],
                             in_spec=spec, out_spec=spec)
        return nt.Pipeline(
            "tensor_query_serversrc name=ssrc port=0 id=61 ! "
            "tensor_filter framework=custom-easy model=wire-echo ! "
            "tensor_query_serversink id=61")

    def test_garbage_interleaved_with_valid_requests(self):
        from nnstreamer_tpu.utils.net import client_handshake

        metrics.reset()
        srv = self._serve()
        with srv:
            port = srv.element("ssrc").bound_port
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=5.0)
            try:
                client_handshake(sock, "hello", caps="other/tensors",
                                 topic="", tenant="garbler")
                sock.settimeout(5.0)
                answered = {}
                mid = 0
                for round_ in range(6):
                    # one VALID request
                    buf = Buffer([np.full((4,), float(round_),
                                          np.float32)],
                                 meta={"_query_msg": mid,
                                       "_tenant": "garbler"})
                    wire.write_frame(sock, wire.encode_buffer(buf))
                    mid += 1
                    # one GARBAGE frame (valid framing+meta, forged
                    # tensor section -> typed reject, salvaged msg id)
                    bad = bytearray(wire.encode_buffer(
                        Buffer([np.zeros((4,), np.float32)],
                               meta={"_query_msg": mid,
                                     "_tenant": "garbler"})))
                    bad[-10:] = b"\xff" * 10
                    wire.write_frame(sock, bytes(bad[:-6]))
                    mid += 1
                    # and one pure-noise frame (meta unsalvageable)
                    wire.write_frame(sock, b"\x07garbage" * 5)
                    mid += 0  # no msg id was consumed by noise
                deadline = 12
                import time as _t

                t0 = _t.monotonic()
                while len(answered) < 12 and _t.monotonic() - t0 < deadline:
                    try:
                        raw = wire.read_frame(sock)
                    except socket.timeout:
                        continue
                    assert raw is not None, \
                        "server dropped the connection on garbage"
                    got, _ = wire.decode_buffer(raw)
                    answered[int(got.meta["_query_msg"])] = got
                # every valid request answered with real results
                for r in range(6):
                    got = answered[2 * r]
                    assert not got.meta.get("wire_reject")
                    np.testing.assert_allclose(
                        np.asarray(got.tensors[0]),
                        np.full((4,), 2.0 * r, np.float32))
                # every salvageable garbage frame answered TYPED
                for r in range(6):
                    got = answered[2 * r + 1]
                    assert got.meta.get("wire_reject") is True
                    assert got.meta.get("abort_reason") == "wire"
                    assert got.tensors == []
            finally:
                sock.close()
            # 6 salvageable + 6 noise frames rejected, per tenant.
            # Poll: the last NOISE frame is never answered, so its
            # reject may still be mid-count when the 12th answer lands
            # client-side.  Same for `out`: the sink counts AFTER
            # core.send() completes the socket write, so the client can
            # read answer 12 before the stage thread reaches the
            # counter — wait for both, don't assert a happens-before
            # the server never promised.
            import time as _t

            deadline = _t.monotonic() + 5.0
            while _t.monotonic() < deadline and (
                    metrics.snapshot().get(
                        "query_server.wire_rejects", 0.0) < 12.0
                    or metrics.snapshot().get(
                        "query_server.out", 0.0) < 6.0):
                _t.sleep(0.02)
            snap = metrics.snapshot()
            lab = metrics.labeled_counters()
            assert snap.get("query_server.wire_rejects") == 12.0
            assert lab.get(("query_server.wire_rejects",
                            "garbler")) == 12.0
            assert snap.get("query_server.out") == 6.0

    def test_framing_violation_drops_connection_but_server_survives(self):
        from nnstreamer_tpu.utils.net import client_handshake

        metrics.reset()
        srv = self._serve()
        with srv:
            port = srv.element("ssrc").bound_port
            # connection 1: length bomb -> dropped
            s1 = socket.create_connection(("127.0.0.1", port), timeout=5.0)
            try:
                client_handshake(s1, "hello", caps="other/tensors",
                                 topic="", tenant="bomber")
                s1.sendall(struct.pack("<Q", 1 << 62) + b"x" * 16)
                s1.settimeout(5.0)
                # server closes: read returns EOF eventually
                import time as _t

                t0 = _t.monotonic()
                closed = False
                while _t.monotonic() - t0 < 8:
                    try:
                        if s1.recv(4096) == b"":
                            closed = True
                            break
                    except socket.timeout:
                        continue
                    except OSError:
                        closed = True
                        break
                assert closed, "length-bomb connection was not dropped"
            finally:
                s1.close()
            # connection 2 on the SAME server still serves
            s2 = socket.create_connection(("127.0.0.1", port), timeout=5.0)
            try:
                client_handshake(s2, "hello", caps="other/tensors",
                                 topic="")
                buf = Buffer([np.ones((4,), np.float32)],
                             meta={"_query_msg": 0})
                wire.write_frame(s2, wire.encode_buffer(buf))
                s2.settimeout(5.0)
                while True:
                    try:
                        raw = wire.read_frame(s2)
                        break
                    except socket.timeout:
                        continue
                got, _ = wire.decode_buffer(raw)
                np.testing.assert_allclose(
                    np.asarray(got.tensors[0]),
                    np.full((4,), 2.0, np.float32))
            finally:
                s2.close()
            assert metrics.labeled_counters().get(
                ("query_server.wire_rejects", "bomber")) == 1.0
