"""Trainer-path tests: datareposrc/sink, tensor_trainer, checkpoint/resume.

Reference analog: tests/nnstreamer_datarepo/ + the trainer SSAT suites
(SURVEY §4) — dataset files driven through training pipelines, stats
checked at the sink, model file written at EOS.
"""

import json
import os

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.trainer.checkpoint import load_checkpoint, save_checkpoint
from nnstreamer_tpu.trainer.subplugin import JaxTrainer


def _write_dataset(tmp_path, n=24, in_dim=4, classes=3, seed=0):
    """Linearly-separable toy set: class = argmax of 3 fixed projections."""
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((in_dim, classes)).astype(np.float32)
    xs = rng.standard_normal((n, in_dim)).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)
    data = tmp_path / "data.bin"
    meta = tmp_path / "data.json"
    with open(data, "wb") as f:
        for i in range(n):
            f.write(xs[i].tobytes())
            f.write(ys[i : i + 1].tobytes())
    json.dump(
        {
            "dims": f"{in_dim},1",
            "types": "float32,int32",
            "total_samples": n,
            "sample_size": in_dim * 4 + 4,
        },
        open(meta, "w"),
    )
    return str(data), str(meta), xs, ys


def test_datareposrc_reads_samples(tmp_path):
    data, meta, xs, ys = _write_dataset(tmp_path, n=10)
    p = nt.Pipeline(
        f"datareposrc location={data} json={meta} ! tensor_sink name=out"
    )
    with p:
        bufs = [p.pull("out", timeout=10) for _ in range(10)]
        p.wait(timeout=10)
    assert len(bufs) == 10
    np.testing.assert_array_equal(bufs[0].tensors[0], xs[0])
    assert int(bufs[0].tensors[1][0]) == int(ys[0])


def test_datareposrc_index_window_and_epochs(tmp_path):
    data, meta, xs, ys = _write_dataset(tmp_path, n=10)
    p = nt.Pipeline(
        f"datareposrc location={data} json={meta} start-sample-index=2 "
        "stop-sample-index=4 epochs=3 ! tensor_sink name=out"
    )
    with p:
        bufs = [p.pull("out", timeout=10) for _ in range(9)]
        p.wait(timeout=10)
    assert len(bufs) == 9  # samples 2..4, three epochs
    np.testing.assert_array_equal(bufs[0].tensors[0], xs[2])
    np.testing.assert_array_equal(bufs[3].tensors[0], xs[2])


def test_datareposrc_shuffle_deterministic(tmp_path):
    data, meta, xs, _ = _write_dataset(tmp_path, n=8)
    desc = (
        f"datareposrc location={data} json={meta} is-shuffle=true "
        "! tensor_sink name=out"
    )
    orders = []
    for _ in range(2):
        p = nt.Pipeline(desc)
        with p:
            got = [p.pull("out", timeout=10) for _ in range(8)]
            p.wait(timeout=10)
        orders.append([b.meta["sample_index"] for b in got])
    assert orders[0] == orders[1]  # seeded by epoch => reproducible
    assert sorted(orders[0]) == list(range(8))


def test_datareposink_roundtrip(tmp_path):
    data, meta, xs, ys = _write_dataset(tmp_path, n=6)
    out_data = str(tmp_path / "out.bin")
    out_meta = str(tmp_path / "out.json")
    p = nt.Pipeline(
        f"datareposrc location={data} json={meta} ! "
        f"datareposink location={out_data} json={out_meta}"
    )
    with p:
        p.wait(timeout=10)
    m = json.load(open(out_meta))
    assert m["total_samples"] == 6
    assert m["dims"] == "4,1"
    assert open(out_data, "rb").read() == open(data, "rb").read()


def test_trainer_learns_and_saves(tmp_path):
    data, meta, xs, ys = _write_dataset(tmp_path, n=24)
    model_path = str(tmp_path / "model.ckpt")
    p = nt.Pipeline(
        f"datareposrc location={data} json={meta} epochs=30 ! "
        "tensor_trainer framework=jax model=mlp:4:16:3 optimizer=adam "
        "learning-rate=0.05 num-training-samples=20 num-validation-samples=4 "
        f"epochs=30 batch-size=10 model-save-path={model_path} ! "
        "tensor_sink name=stats"
    )
    with p:
        stats = [np.asarray(p.pull("stats", timeout=60).tensors[0]) for _ in range(30)]
        p.wait(timeout=30)
    assert len(stats) == 30
    first, last = stats[0], stats[-1]
    assert last[0] < first[0]  # training loss decreased
    assert last[1] > 0.8  # training accuracy on separable toy data
    assert np.isfinite(last[2])  # validation loss present
    assert os.path.exists(model_path)


def test_trainer_resume_from_checkpoint(tmp_path):
    data, meta, xs, ys = _write_dataset(tmp_path, n=20)
    ckpt = str(tmp_path / "resume.ckpt")

    tr = JaxTrainer()
    tr.open({"model": "mlp:4:8:3", "learning_rate": 0.05})
    for i in range(20):
        tr.push_data([xs[i]], [ys[i : i + 1]], is_validation=False)
    s1 = tr.train_epoch()
    tr.save(ckpt)

    tr2 = JaxTrainer()
    tr2.open({"model": "mlp:4:8:3", "model_load_path": ckpt, "learning_rate": 0.05})
    # resumed params match saved ones exactly
    flat1 = np.concatenate([np.asarray(l["w"]).ravel() for l in tr.params])
    flat2 = np.concatenate([np.asarray(l["w"]).ravel() for l in tr2.params])
    np.testing.assert_allclose(flat1, flat2, rtol=0, atol=0)
    assert tr2.step == tr.step


def test_checkpoint_roundtrip(tmp_path):
    params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3), "b": [np.ones(2)]}
    path = str(tmp_path / "ck")
    save_checkpoint(path, params, step=7)
    got, _, step = load_checkpoint(path)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(got["a"]), params["a"])


def test_trainer_data_parallel_mesh(tmp_path):
    """DP training over the 8-device virtual mesh (SURVEY §2.9 DP row)."""
    data, meta, xs, ys = _write_dataset(tmp_path, n=16)
    tr = JaxTrainer()
    tr.open({"model": "mlp:4:8:3", "mesh": "data:8", "batch_size": 16,
             "learning_rate": 0.05})
    for i in range(16):
        tr.push_data([xs[i]], [ys[i : i + 1]], is_validation=False)
    stats = tr.train_epoch()
    assert np.isfinite(stats["training_loss"])


def test_trainer_resume_restores_opt_state(tmp_path):
    """Adam moments survive the checkpoint (regression: resume silently
    re-initialized the optimizer)."""
    import jax

    data, meta, xs, ys = _write_dataset(tmp_path, n=8)
    ckpt = str(tmp_path / "opt.ckpt")
    tr = JaxTrainer()
    tr.open({"model": "mlp:4:8:3", "learning_rate": 0.05})
    for i in range(8):
        tr.push_data([xs[i]], [ys[i : i + 1]], is_validation=False)
    tr.train_epoch()
    tr.save(ckpt)

    tr2 = JaxTrainer()
    tr2.open({"model": "mlp:4:8:3", "model_load_path": ckpt,
              "learning_rate": 0.05})
    leaves1 = jax.tree_util.tree_leaves(tr.opt_state)
    leaves2 = jax.tree_util.tree_leaves(tr2.opt_state)
    assert len(leaves1) == len(leaves2)
    # Adam mu/nu are nonzero after a step and must round-trip exactly
    assert any(np.any(np.asarray(l) != 0) for l in leaves2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_datareposrc_zero_copy_and_truncation(tmp_path):
    """Samples are views into the file mapping (no per-sample copies), and
    a meta/file size mismatch errors instead of yielding garbage."""
    from nnstreamer_tpu.elements.datarepo import DataRepoSrc

    data = np.arange(4 * 5, dtype=np.float32)  # 5 samples of [4] f32
    loc = tmp_path / "d.bin"
    loc.write_bytes(data.tobytes())
    meta = tmp_path / "d.json"
    meta.write_text('{"dims": "4", "types": "float32", "total_samples": 5, '
                    '"sample_size": 16}')
    src = DataRepoSrc({"location": str(loc), "json": str(meta)})
    src.configure({}, ["src"])
    bufs = list(src.generate())
    assert len(bufs) == 5
    np.testing.assert_array_equal(bufs[2].tensors[0], data[8:12])
    assert not bufs[2].tensors[0].flags["OWNDATA"]  # view, not a copy

    bad = tmp_path / "bad.json"
    bad.write_text('{"dims": "4", "types": "float32", "total_samples": 50, '
                   '"sample_size": 16}')
    src2 = DataRepoSrc({"location": str(loc), "json": str(bad)})
    src2.configure({}, ["src"])
    with pytest.raises(Exception, match="holds"):
        list(src2.generate())
