"""nns-slo (ISSUE 8 tentpole): per-tenant labeled metrics, the SLO
engine, tenant identity threading, and the per-branch queue-stamp fix.

The contract: a ``tenant`` born at ingress (appsrc ``tenant=`` prop /
``Pipeline(tenant=...)`` default / the query wire meta) rides
``Buffer.meta`` beside the trace id; labeled twins of the latency
histograms / shed counters / queue-depth gauges split per tenant in
``metrics_text`` (same sanitize+sha1 rule as series names); the SLO
engine turns those series into per-tenant verdicts with error-budget
burn rates and dominant-span attribution from the flight-recorder ring;
and NONE of it touches the trace_mode=off hot path (no stamps).
"""

import json
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import Metrics, metrics
from nnstreamer_tpu.utils import tracing
from nnstreamer_tpu.utils.profiler import metrics_text
from nnstreamer_tpu.utils.slo import (SLOEngine, SLOPolicy, TenantSLO,
                                      dominant_span, load_policy,
                                      validate_policy)
from nnstreamer_tpu.utils.tracing import FlightRecorder, recorder

DESC = (
    "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
    "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
    "name=f ! tensor_sink name=out"
)


@pytest.fixture(autouse=True)
def _clean_state():
    metrics.reset()
    recorder.configure("off")
    recorder.clear()
    yield
    recorder.configure("off")
    recorder.clear()
    metrics.reset()


def _frames(n, dims=16):
    return [np.full((dims,), float(i), np.float32) for i in range(n)]


def _run(desc, frames, timeout=60, **kw):
    p = nt.Pipeline(desc, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
    return outs


# -- labeled metrics registry ----------------------------------------------

def test_labeled_series_update_base_and_twin():
    m = Metrics()
    m.observe_latency("s.e2e_latency", 0.002, tenant="a")
    m.observe_latency("s.e2e_latency", 0.004, tenant="b")
    m.observe_latency("s.e2e_latency", 0.008)  # untenanted
    hists = m.histograms()
    assert hists["s.e2e_latency"][2] == 3  # base aggregates everything
    lab = m.labeled_histograms()
    assert lab[("s.e2e_latency", "a")][2] == 1
    assert lab[("s.e2e_latency", "b")][2] == 1
    assert m.percentile("s.e2e_latency", 99, tenant="a") == 0.002
    assert m.tenants("s.e2e_latency") == ["a", "b"]
    m.count("q.shed", 2, tenant="a")
    assert m.snapshot()["q.shed"] == 2.0  # base counter aggregates
    assert m.labeled_counters()[("q.shed", "a")] == 2.0


def test_labeled_only_observe_skips_base():
    m = Metrics()
    m.observe_latency("s.proc", 0.001)  # the per-dispatch base sample
    m.observe_latency_labeled("s.proc", 0.0005, "a")
    m.observe_latency_labeled("s.proc", 0.0005, "b")
    assert m.histograms()["s.proc"][2] == 1  # no double count
    assert m.labeled_histograms()[("s.proc", "a")][2] == 1


def test_fraction_over():
    m = Metrics()
    for v in (0.001, 0.002, 0.040, 0.900):
        m.observe_latency("s.e2e_latency", v, tenant="a")
    frac, n = m.fraction_over("s.e2e_latency", 0.025, tenant="a")
    assert n == 4
    assert frac == pytest.approx(0.5)  # 0.040 and 0.900 are over
    assert m.fraction_over("s.e2e_latency", 0.025, tenant="ghost") == \
        (0.0, 0)


def test_labeled_gauges_do_not_clobber_base():
    m = Metrics()
    m.gauge("f.queue_depth", 5.0)
    m.gauge("f.queue_depth", 2.0, tenant="a")
    assert m.gauges()["f.queue_depth"] == 5.0
    assert m.labeled_gauges()[("f.queue_depth", "a")] == 2.0


# -- labeled exposition -----------------------------------------------------

def test_labeled_exposition_help_type_once_and_scrape_twice():
    """Satellite: labeled histogram series emit ONE correct
    ``# HELP``/``# TYPE`` header per family, tenant label values go
    through the sanitize+sha1 rule, and scraping twice is identical."""
    metrics.observe_latency("out.e2e_latency", 0.002)
    metrics.observe_latency("out.e2e_latency", 0.004, tenant="acme")
    # colliding tenant values: both sanitize to t_1
    metrics.observe_latency("out.e2e_latency", 0.006, tenant="t:1")
    metrics.observe_latency("out.e2e_latency", 0.008, tenant="t/1")
    metrics.count("query_server.shed", 3, tenant="acme")
    metrics.gauge("f.queue_depth", 2, tenant="acme")
    one = metrics_text()
    two = metrics_text()
    assert one == two
    # one header pair for the whole family, labeled rows included
    assert one.count("# TYPE nnstpu_out_e2e_latency histogram") == 1
    assert one.count("# HELP nnstpu_out_e2e_latency ") == 1
    assert 'nnstpu_out_e2e_latency_bucket{tenant="acme",le="0.005"} 1' \
        in one
    assert 'nnstpu_out_e2e_latency_count{tenant="acme"} 1' in one
    # colliding tenants disambiguated, not merged
    tenant_vals = {line.split('tenant="')[1].split('"')[0]
                   for line in one.splitlines() if 'tenant="' in line}
    t1s = {v for v in tenant_vals if v.startswith("t_1")}
    assert len(t1s) == 2 and "t_1" not in t1s
    # no duplicate sample lines (the scrape-reject failure mode)
    samples = [ln for ln in one.splitlines()
               if ln and not ln.startswith("#")]
    assert len(samples) == len(set(samples))
    assert 'nnstpu_query_server_shed{tenant="acme"} 3' in one
    assert 'nnstpu_f_queue_depth{tenant="acme"} 2' in one
    assert "# TYPE nnstpu_query_server_shed counter" in one
    assert "# TYPE nnstpu_f_queue_depth gauge" in one


# -- policy ----------------------------------------------------------------

def test_policy_validate_and_load(tmp_path):
    good = {"tenants": [{"tenant": "a", "p99_ms": 50, "min_fps": 5}]}
    assert validate_policy(good) == []
    pol = load_policy(good)
    assert pol.for_tenant("a").p99_ms == 50
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(good))
    assert load_policy(str(path)).for_tenant("a").min_fps == 5
    assert load_policy(None).tenants == []
    assert load_policy(pol) is pol


@pytest.mark.parametrize("bad,msg", [
    ({}, "tenants"),
    ({"tenants": [{"p99_ms": 5}]}, "'tenant'"),
    ({"tenants": [{"tenant": "a"}, {"tenant": "a"}]}, "duplicate"),
    ({"tenants": [{"tenant": "a", "p99_ms": -1}]}, "p99_ms"),
    ({"tenants": [{"tenant": "a", "error_budget": 2}]}, "error_budget"),
    ({"tenants": [{"tenant": "a", "p99ms": 5}]}, "unknown"),
    ({"tenants": [{"tenant": "a"}], "bogus": 1}, "unknown"),
])
def test_policy_validation_errors(bad, msg):
    problems = validate_policy(bad)
    assert problems and any(msg in p for p in problems)
    with pytest.raises(ValueError, match="invalid SLO policy"):
        load_policy(bad)


# -- engine ----------------------------------------------------------------

def _fed_metrics(tenant="a", sink="out", n_ok=8, n_bad=2, sheds=0):
    m = Metrics()
    for _ in range(n_ok):
        m.observe_latency(f"{sink}.e2e_latency", 0.002, tenant=tenant)
    for _ in range(n_bad):
        m.observe_latency(f"{sink}.e2e_latency", 0.8, tenant=tenant)
    if sheds:
        m.count("query_server.shed", sheds, tenant=tenant)
    return m


def test_engine_breach_and_burn_rate():
    m = _fed_metrics(n_ok=8, n_bad=2, sheds=10)
    pol = SLOPolicy(tenants=[TenantSLO("a", p99_ms=50.0,
                                       error_budget=0.1)])
    eng = SLOEngine(pol, sinks=["out"], metrics=m)
    rep = eng.evaluate()
    v = rep["tenants"]["a"]
    assert not rep["ok"] and rep["breaches"] == ["a"]
    assert v["requests"] == 10 and v["sheds"] == 10
    # bad = 2 latency violations + 10 sheds of 20 attempts; budget 0.1
    assert v["burn_rate"] == pytest.approx((12 / 20) / 0.1)
    assert any("p99" in viol for viol in v["violations"])
    # burn gauges published into the SAME registry
    lg = m.labeled_gauges()
    assert lg[("slo.breach", "a")] == 1.0
    assert lg[("slo.burn_rate", "a")] == pytest.approx(v["burn_rate"])


def test_engine_ok_tenant_and_unknown_tenant_informational():
    m = _fed_metrics(n_ok=10, n_bad=0)
    m.observe_latency("out.e2e_latency", 0.001, tenant="stranger")
    pol = SLOPolicy(tenants=[TenantSLO("a", p99_ms=500.0)])
    eng = SLOEngine(pol, sinks=["out"], metrics=m)
    rep = eng.evaluate()
    assert rep["ok"]
    assert rep["tenants"]["a"]["ok"]
    # observed-but-unconfigured tenants report measurements, never breach
    s = rep["tenants"]["stranger"]
    assert s["ok"] and s["objectives"] is None and s["requests"] == 1


def test_engine_min_fps_objective():
    m = _fed_metrics(n_ok=4, n_bad=0)
    pol = SLOPolicy(tenants=[TenantSLO("a", min_fps=1e9)])
    eng = SLOEngine(pol, sinks=["out"], metrics=m)
    rep = eng.evaluate()
    assert any("throughput" in viol
               for viol in rep["tenants"]["a"]["violations"])


def test_dominant_span_attribution():
    rec = FlightRecorder("ring", capacity=64)
    rec.record("queue", "f", 1, 0, int(5e6), tenant="a")
    rec.record("stage", "f", 1, int(5e6), int(30e6), tenant="a")
    rec.record("stage", "f", 2, 0, int(99e6), tenant="b")  # other tenant
    rec.record("e2e", "out", 1, 0, int(40e6), tenant="a")  # excluded
    kind, ms = dominant_span("a", rec)
    assert kind == "stage" and ms == pytest.approx(30.0)
    assert dominant_span("ghost", rec) is None


def test_dominant_span_credits_batched_row_share():
    """Batched stage spans carry a row-aligned ``tenants`` list; each
    tenant is credited its row share of the amortized duration — batch
    compute is never invisible to attribution."""
    rec = FlightRecorder("ring", capacity=64)
    rec.record("stage", "f", 1, 0, int(40e6),
               trace_ids=[1, 2, 3, 4], rows=4,
               tenants=["a", "a", "b", None])
    rec.record("queue", "f", 1, 0, int(5e6), tenant="a")
    kind, ms = dominant_span("a", rec)
    assert kind == "stage" and ms == pytest.approx(20.0)  # 2/4 of 40ms
    kind_b, ms_b = dominant_span("b", rec)
    assert kind_b == "stage" and ms_b == pytest.approx(10.0)


def test_engine_fps_window_never_near_zero():
    """An on-demand report milliseconds after a daemon tick must not
    compute throughput over the tiny inter-call gap (the spurious
    min_fps-breach failure mode) — the rate base is the newest snapshot
    at least MIN_RATE_WINDOW_S old."""
    m = _fed_metrics(n_ok=10, n_bad=0)
    pol = SLOPolicy(tenants=[TenantSLO("a", min_fps=0.1)])
    eng = SLOEngine(pol, sinks=["out"], metrics=m)
    eng._t0 = time.monotonic() - 10.0  # 10 s of "run" behind us
    first = eng.evaluate()
    second = eng.evaluate()  # immediately after — old code: ~0 s window
    assert second["window_s"] >= SLOEngine.MIN_RATE_WINDOW_S
    assert second["tenants"]["a"]["ok"], second["tenants"]["a"]
    assert first["tenants"]["a"]["fps"] == pytest.approx(1.0, rel=0.2)


# -- pipeline integration ---------------------------------------------------

def test_pipeline_tenant_splits_series_and_report_breaches():
    pol = {"tenants": [{"tenant": "acme", "p99_ms": 1e-6},
                       {"tenant": "idle", "p99_ms": 1e9}]}
    p = nt.Pipeline(DESC, trace_mode="ring", tenant="acme", slo=pol)
    with p:
        for i, x in enumerate(_frames(6)):
            p.push("src", nt.Buffer([x], pts=i))
        outs = [p.pull("out", timeout=60) for _ in range(6)]
        rep = p.slo_report()
        p.eos()
        p.wait(timeout=60)
    assert all(o.meta[tracing.META_TENANT] == "acme" for o in outs)
    assert metrics.labeled_histograms()[("out.e2e_latency", "acme")][2] \
        == 6
    assert "acme" in rep["breaches"] and "idle" not in rep["breaches"]
    v = rep["tenants"]["acme"]
    # the dominant offending span kind is attributed from the ring and
    # names a real attributable kind present in the dump
    assert v["dominant_span_kind"] in ("queue", "stage", "fetch",
                                       "batch", "inflight")
    assert any(e.kind == v["dominant_span_kind"]
               and (e.args or {}).get("tenant") == "acme"
               for e in recorder.events())
    # per-tenant tracks in the Chrome export: the tenant's spans live on
    # their own pid with a tenant:<name> process_name
    chrome = tracing.to_chrome(recorder.events())
    names = [e["args"]["name"] for e in chrome["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert "tenant:acme" in names


def test_appsrc_tenant_prop_is_data_not_a_trace_stamp():
    """An explicit appsrc tenant= prop stamps meta regardless of trace
    mode (it must ride the wire for server-side accounting)."""
    outs = _run(DESC.replace("appsrc name=src",
                             "appsrc name=src tenant=acme"), _frames(3))
    assert all(o.meta.get(tracing.META_TENANT) == "acme" for o in outs)
    # trace off: the sink's labeled frames counter is the only split
    assert metrics.labeled_counters()[("out.frames", "acme")] == 3.0


def test_pipeline_default_tenant_off_path_writes_no_stamp():
    """The acceptance pin: Pipeline(tenant=...) with trace_mode=off must
    not stamp — tenant threading is part of the traced path only."""
    outs = _run(DESC, _frames(3), tenant="acme")  # trace off (default)
    for o in outs:
        assert tracing.META_TENANT not in o.meta


def test_bad_slo_policy_rejected_at_construction():
    """A broken slo= config must fail while building the Pipeline (every
    schema problem named), never inside start() with threads running."""
    from nnstreamer_tpu.pipeline.runtime import PipelineError

    with pytest.raises(PipelineError, match="unknown keys"):
        nt.Pipeline(DESC, slo={"tenants": [{"tenant": "a", "p99ms": 5}]})


def test_slo_engine_runs_continuously_with_pipeline():
    pol = {"tenants": [{"tenant": "acme", "p99_ms": 1e9}]}
    p = nt.Pipeline(DESC, trace_mode="ring", tenant="acme", slo=pol)
    with p:
        for i, x in enumerate(_frames(4)):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in range(4):
            p.pull("out", timeout=60)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if ("slo.breach", "acme") in metrics.labeled_gauges():
                break
            time.sleep(0.05)
        p.eos()
        p.wait(timeout=60)
    # the continuous loop published breach/burn gauges on its own
    assert metrics.labeled_gauges()[("slo.breach", "acme")] == 0.0


def test_per_tenant_queue_depth_gauge_sampled():
    from nnstreamer_tpu.pipeline.runtime import _StageQueue

    q = _StageQueue(capacity=8)
    b1 = nt.Buffer([np.zeros(2, np.float32)])
    b1.meta[tracing.META_TENANT] = "a"
    b2 = nt.Buffer([np.zeros(2, np.float32)])
    b2.meta[tracing.META_TENANT] = "a"
    b3 = nt.Buffer([np.zeros(2, np.float32)])  # untenanted
    for b in (b1, b2, b3):
        q.put(("sink", b))
    assert q.tenant_depths() == {"a": 2}


# -- per-branch queue stamps (tee fan-out satellite) ------------------------

def test_tee_branches_each_get_exact_queue_spans():
    """The OBSERVABILITY.md caveat is gone: per-branch queue stamps are
    keyed by the CONSUMING stage, so BOTH tee branches record a queue
    span for every frame (the old shared-scalar stamp was popped by
    whichever branch consumed first — the other lost its span)."""
    n = 4
    p = nt.Pipeline(
        f"videotestsrc num-buffers={n} width=4 height=4 ! "
        "tensor_converter ! tee name=t "
        "t. ! tensor_sink name=a t. ! tensor_sink name=b",
        trace_mode="ring")
    with p:
        for _ in range(n):
            p.pull("a", timeout=15)
            p.pull("b", timeout=15)
        p.wait(timeout=15)
    spans = {}
    for e in recorder.events():
        if e.kind == "queue" and e.stage in ("a", "b"):
            spans.setdefault(e.stage, []).append(e)
    assert len(spans.get("a", [])) == n
    assert len(spans.get("b", [])) == n
    # exactness: each branch's span starts at ITS OWN feed time — the
    # same frame's two spans are distinct records with sane durations
    for e in spans["a"] + spans["b"]:
        assert e.dur >= 0


def test_cli_validate_and_report(tmp_path, capsys):
    from nnstreamer_tpu.tools import slo as cli

    pol = {"tenants": [{"tenant": "acme", "p99_ms": 3.0}]}
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(pol))
    assert cli.main(["validate", str(path)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"tenants": []}))
    assert cli.main(["validate", str(bad)]) == 1
    capsys.readouterr()
    # report over a saved exposition: acme's p99 lands in the 5ms bucket
    # -> estimated 5ms > 3ms objective -> breach, exit 1
    metrics.observe_latency("out.e2e_latency", 0.004, tenant="acme")
    scrape = tmp_path / "scrape.txt"
    scrape.write_text(metrics_text())
    rc = cli.main(["report", str(path), "--text", str(scrape), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["breaches"] == ["acme"]
    assert out["tenants"]["acme"]["p99_ms"] == pytest.approx(5.0)
    assert out["tenants"]["acme"]["requests"] == 1
