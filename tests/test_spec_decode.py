"""Prefix-sharing copy-on-write paged KV cache + speculative decoding
(ISSUE 15, docs/SERVING.md §4b/§4c).

Covers the contracts the serve-a-million-tenants PR promises:

* ref-count/CoW allocator invariants: blocks free ONLY at refcount 0,
  fork-on-write isolation (a CoW fork never perturbs the sharing
  streams), recycled-slot identity under churn with a full free-list
  drain and cache eviction, and the stale-table sentinel still dropping
  multi-token (verify-shaped) writes to reclaimed blocks;
* prefix sharing: a cache-hit prompt's PHYSICAL admission reservation
  collapses to ~the non-shared suffix (two sharing streams fit a pool
  two cold ones cannot), bit-identity at every hit/miss/fork mix;
* per-tenant ``kv_blocks`` quotas charge LOGICAL blocks (per reference):
  a shared prefix never lets a tenant exceed quota for free;
* speculative decoding: greedy bit-identity of spec vs plain decode at
  accept rates 0, partial, and 1; the accepted/bonus ``spec_draft``
  meta flag and its pipeline-native routing homes (tensor_if
  META_VALUE, tensor_demux by-meta);
* the zero-recompile pin: the speculative loop compiles EXACTLY the 5
  programs ``serving_plan()`` predicts (target/draft prefill, propose,
  verify, slot-token setter — the plain decode chunk never compiles)
  and stream churn, cache hits, CoW forks, and accept/reject ratios
  change VALUES only.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.models import llama


def _metric(name):
    return metrics.snapshot().get(name, 0.0)


def _fw(custom, model="llama_tiny"):
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": model, "custom": custom})
    return fw


def _plain_tokens(prompt, custom, model="llama_tiny"):
    fw = _fw(custom, model)
    try:
        return [int(ids[0]) for ids, *_ in fw.invoke_stream([prompt])]
    finally:
        fw.close()


def _serve_tokens(fw, prompts, metas=None, timeout=300.0):
    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
                if metas is not None:
                    metas.setdefault(i, []).append(meta)
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
    assert fw.drain(timeout=timeout)
    return got


def _serve_staggered(fw, prompts, metas=None, timeout=300.0):
    """Submit one prompt at a time, waiting for each stream's FIRST
    token before submitting the next — guarantees the earlier prompt's
    prefill completed and its blocks are registered in the prefix
    index before the later one is admitted."""
    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
                if metas is not None:
                    metas.setdefault(i, []).append(meta)
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
        deadline = time.monotonic() + timeout
        while not got[i]:
            assert time.monotonic() < deadline, f"stream {i} first token"
            time.sleep(0.005)
    assert fw.drain(timeout=timeout)
    return got


BASE = "max_new:5,stream_chunk:2,temperature:0.0,dtype:float32"


def _shared_prompts(rng, prefix_len=24, suffixes=(3, 5)):
    pre = rng.integers(1, 500, (prefix_len,), dtype=np.int32)
    return [np.concatenate([pre, rng.integers(1, 500, (t,), np.int32)])
            for t in suffixes]


# ---------------------------------------------------------------------------
# allocator invariants: refcounts, CoW, eviction, sentinel
# ---------------------------------------------------------------------------

class TestRefcountAllocator:
    def test_free_only_at_refcount_zero(self):
        """Two staggered streams share the prefix blocks (refcount 2);
        the first retiring must NOT return shared blocks to the free
        list while the second still decodes; both retiring must."""
        rng = np.random.default_rng(10)
        pa, pb = _shared_prompts(rng, prefix_len=32, suffixes=(2, 3))
        short = BASE + ",serve:continuous,slots:2,block_size:8," \
            "prefill_chunk:8"
        # stream A short (retires first), stream B long
        fw = _fw("max_new:64,stream_chunk:2,temperature:0.0,"
                 "dtype:float32,serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:8")
        got = {0: [], 1: []}
        lock = threading.Lock()

        def em(i, n_stop=None):
            def e(t, m):
                with lock:
                    got[i].append(int(t[0][0]))
            return e

        fw.submit([pa], {}, em(0))
        while not got[0]:
            time.sleep(0.005)
        fw.submit([pb], {}, em(1))
        while not got[1]:
            time.sleep(0.005)
        serve = fw._serve
        stats = serve.pool_stats()
        assert stats["live_streams"] == 2
        assert stats["blocks_shared"] >= 4, stats  # 32-token prefix / 8
        shared_ids = [b for b in range(serve.n_blocks)
                      if serve._ref[b] > 1]
        assert fw.drain(180)
        # retired: every shared block released down to 0 and free again
        assert sorted(serve._free) == list(range(serve.n_blocks))
        assert (np.asarray(serve._ref) == 0).all()
        assert shared_ids, "expected shared blocks while both live"
        fw.close()
        del short

    def test_cow_fork_isolation(self):
        """Full-coverage hit (T a block multiple, whole prompt cached):
        the re-prefilled tail block is FORKED, the forking stream's
        writes never perturb the original — both emit reference ids,
        and the fork is counted."""
        rng = np.random.default_rng(11)
        p = rng.integers(1, 500, (24,), np.int32)  # 3 blocks of 8
        want = _plain_tokens(p, BASE)
        # prefill_chunk 4 < block_size 8: the recompute start (T-1)//4*4
        # = 20 straddles block 2 -> CoW fork
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:4")
        try:
            got = _serve_staggered(fw, [p])
            assert got[0] == want
            f0 = _metric("llm.serve.cow_forks")
            got = _serve_staggered(fw, [p, p])
            assert got[0] == want and got[1] == want
            assert _metric("llm.serve.cow_forks") - f0 >= 2
            # and the original prompt still replays bit-identically off
            # the (unperturbed) cached blocks
            got = _serve_tokens(fw, [p])
            assert got[0] == want
            assert sorted(fw._serve._free) == \
                list(range(fw._serve.n_blocks))
        finally:
            fw.close()

    def test_recycled_slots_and_eviction_under_churn(self):
        """slots:1 + a pool barely bigger than one stream: every
        admission recycles the predecessor's blocks, evicting its cache
        entries — every stream still emits reference ids and the free
        list fully drains back."""
        rng = np.random.default_rng(12)
        prompts = [rng.integers(1, 500, (t,), np.int32)
                   for t in (17, 19, 23, 18)]
        want = [_plain_tokens(p, BASE) for p in prompts]
        fw = _fw(BASE + ",serve:continuous,slots:1,block_size:8,"
                 "kv_blocks:4,prefill_chunk:8")
        try:
            e0 = _metric("llm.serve.prefix_evictions")
            got = _serve_tokens(fw, prompts)
            for i in range(len(prompts)):
                assert got[i] == want[i], f"stream {i} after recycle"
            serve = fw._serve
            assert sorted(serve._free) == list(range(serve.n_blocks))
            assert (np.asarray(serve._ref) == 0).all()
            assert _metric("llm.serve.prefix_evictions") > e0
            # index never points at an unindexed block and vice versa
            assert set(serve._prefix_index.values()) == \
                set(serve._block_hash.keys())
        finally:
            fw.close()

    def test_sentinel_drops_multitoken_writes(self):
        """The verify step's T=k+1 writes through a cleared (sentinel)
        table must DROP — a reclaimed shared block can never be written
        through a stale table, even by the new multi-token programs."""
        import jax.numpy as jnp

        cfg = llama.PRESETS["llama_tiny"]
        params = llama.init_params(cfg, seed=0)
        pool = llama.init_paged_cache(cfg, 4, 8, dtype="float32")
        n_blocks = 4
        tables = np.full((2, 6), n_blocks, np.int32)  # all sentinel
        park = np.full((2,), 6 * 8, np.int32)
        toks = np.asarray([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)
        _, pool2 = llama.forward_paged(
            params, jnp.asarray(toks), pool, jnp.asarray(tables),
            jnp.asarray(park), cfg, compute_dtype="float32")
        np.testing.assert_array_equal(np.asarray(pool2["k"]),
                                      np.zeros_like(pool2["k"]))
        np.testing.assert_array_equal(np.asarray(pool2["v"]),
                                      np.zeros_like(pool2["v"]))


# ---------------------------------------------------------------------------
# prefix sharing: admission, quota, bit-identity
# ---------------------------------------------------------------------------

class TestPrefixSharing:
    def test_hit_admits_where_cold_defers(self):
        """The reservation drop IS the tentpole: a 64-token shared
        prefix, 17-block pool.  Each stream's LOGICAL need is 12
        blocks — a cold second stream must wait for the first to
        finish; a SHARING second stream reserves only its ~4-block
        suffix and decodes CONCURRENTLY.  (max_new 24 keeps the
        first stream decoding long enough that the ordering assert
        is not load-sensitive.)"""
        rng = np.random.default_rng(13)
        pa, pb = _shared_prompts(rng, prefix_len=64, suffixes=(2, 3))
        custom = ("max_new:24,stream_chunk:2,temperature:0.0,"
                  "dtype:float32,serve:continuous,slots:2,block_size:8,"
                  "kv_blocks:17,prefill_chunk:8")

        def run(extra):
            fw = _fw(custom + extra)
            got = {0: [], 1: []}
            stamp = {0: [], 1: []}
            lock = threading.Lock()

            def em(i):
                def e(t, m):
                    with lock:
                        got[i].append(int(t[0][0]))
                        stamp[i].append(time.monotonic())
                return e

            try:
                fw.submit([pa], {}, em(0))
                while not got[0]:
                    time.sleep(0.005)
                fw.submit([pb], {}, em(1))
                assert fw.drain(180)
            finally:
                fw.close()
            assert len(got[0]) == 24 and len(got[1]) == 24
            # did B's first token land before A's last (concurrent) or
            # only after A fully retired (deferred)?
            return stamp[1][0] < stamp[0][-1]

        h0 = _metric("llm.serve.prefix_hits")
        assert run(",prefix_cache:0") is False, \
            "cold control: pool must defer the second stream"
        assert _metric("llm.serve.prefix_hits") == h0
        assert run("") is True, \
            "sharing must fit both streams concurrently"
        assert _metric("llm.serve.prefix_hits") > h0

    def test_resting_matched_blocks_not_double_counted_as_free(self):
        """Admission regression: a hit's matched blocks RESTING in the
        free list (refcount 0 after their writer retired) satisfy the
        mapping, not the reservation — the capacity check must demand
        ``phys`` blocks ON TOP of them.  Pool 12, cached prefix rests
        as 8 free blocks, a cold stream holds 4: the sharing stream
        (needs 8 resting + 2 fresh) must defer, then emit exactly the
        reference ids — the old check admitted it into a silently
        truncated table (bit-wrong output, no error)."""
        rng = np.random.default_rng(24)
        pre = rng.integers(1, 500, (64,), np.int32)
        pc = rng.integers(1, 500, (17,), np.int32)
        pb = np.concatenate([pre, rng.integers(1, 500, (2,), np.int32)])
        custom = ("max_new:8,stream_chunk:2,temperature:0.0,"
                  "dtype:float32,serve:continuous,slots:3,block_size:8,"
                  "kv_blocks:12,prefill_chunk:8")
        want_b = _plain_tokens(
            pb, "max_new:8,stream_chunk:2,temperature:0.0,dtype:float32")
        fw = _fw(custom)
        got = {0: [], 1: [], 2: []}
        lock = threading.Lock()

        def em(i):
            def e(t, m):
                with lock:
                    got[i].append(int(t[0][0]))
            return e

        try:
            # stream A caches the prefix, retires: 8 cached blocks rest
            # in the free list
            fw.submit([pre], {}, em(0))
            assert fw.drain(120)
            # cold C takes the uncached blocks and keeps decoding
            fw.submit([pc], {}, em(1))
            while not got[1]:
                time.sleep(0.002)
            h0 = _metric("llm.serve.prefix_hits")
            fw.submit([pb], {}, em(2))
            assert fw.drain(120)
            assert _metric("llm.serve.prefix_hits") > h0
            assert got[2] == want_b, (got[2], want_b)
            serve = fw._serve
            assert sorted(serve._free) == list(range(serve.n_blocks))
        finally:
            fw.close()

    def test_quota_charges_logical_blocks(self):
        """A tenant's kv_blocks quota charges per-REFERENCE: its second
        shared-prefix stream defers on quota even though its physical
        need is ~1 block — a shared prefix is not a quota discount."""
        rng = np.random.default_rng(14)
        pa, pb = _shared_prompts(rng, prefix_len=32, suffixes=(2, 3))
        fw = _fw("max_new:24,stream_chunk:2,temperature:0.0,"
                 "dtype:float32,serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:8")
        got = {0: [], 1: []}
        lock = threading.Lock()

        def em(i):
            def e(t, m):
                with lock:
                    got[i].append(int(t[0][0]))
            return e

        try:
            # logical need per stream = ceil((34|35 + 24)/8) = 8 blocks;
            # quota 9 < 16 -> the second stream must defer on QUOTA even
            # though sharing leaves plenty of physical blocks free
            fw.submit([pa], {"_tenant": "acme"}, em(0))
            while not got[0]:
                time.sleep(0.005)
            fw._serve.set_tenant_quota("acme", 9)
            q0 = _metric("llm.serve.quota_deferred")
            fw.submit([pb], {"_tenant": "acme"}, em(1))
            deadline = time.monotonic() + 30
            while _metric("llm.serve.quota_deferred") == q0:
                assert time.monotonic() < deadline, \
                    "expected quota deferral for the shared stream"
                time.sleep(0.01)
            assert fw._serve.pool_stats()["live_streams"] == 1
            # plenty of PHYSICAL space all along
            assert len(fw._serve._free) > 2
            # stream 1 admits after stream 0 retires
            assert fw.drain(180)
            assert len(got[1]) == 24
        finally:
            fw.close()

    def test_bit_identity_hit_miss_fork_mix(self):
        """Cache hits, partial hits, forks, and cold misses all emit
        exactly the dense-path reference ids."""
        rng = np.random.default_rng(15)
        pre = rng.integers(1, 500, (16,), np.int32)
        prompts = [
            np.concatenate([pre, rng.integers(1, 500, (5,), np.int32)]),
            np.concatenate([pre, rng.integers(1, 500, (9,), np.int32)]),
            pre.copy(),                       # full coverage -> fork
            rng.integers(1, 500, (11,), np.int32),  # cold miss
        ]
        want = [_plain_tokens(p, BASE) for p in prompts]
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:4")
        try:
            got = _serve_staggered(fw, prompts)
            for i in range(len(prompts)):
                assert got[i] == want[i], f"stream {i}"
        finally:
            fw.close()


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculativeDecoding:
    def test_accept_rate_one_bit_identity(self):
        """draft == target (same preset + seed): every proposal matches
        the target's argmax — k accepted + 1 bonus per round, outputs
        bit-identical to plain greedy decode."""
        rng = np.random.default_rng(16)
        prompts = [rng.integers(1, 500, (t,), np.int32) for t in (6, 11)]
        want = [_plain_tokens(p, BASE) for p in prompts]
        a0, r0 = (_metric("llm.serve.spec_accepted"),
                  _metric("llm.serve.spec_rejected"))
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "draft:llama_tiny,spec_k:3")
        metas = {}
        try:
            got = _serve_tokens(fw, prompts, metas=metas)
            for i, w in enumerate(want):
                assert got[i] == w, f"stream {i}"
        finally:
            fw.close()
        assert _metric("llm.serve.spec_accepted") > a0
        assert _metric("llm.serve.spec_rejected") == r0
        # the accept/reject flag rides every round token's meta: 1 for
        # accepted draft proposals, 0 for the target's bonus token
        flags = [m.get("spec_draft") for m in metas[0][1:]]
        assert set(flags) <= {0, 1} and 1 in flags

    def test_partial_accept_bit_identity(self):
        """A differently-seeded draft accepts a partial prefix some
        rounds — emitted ids must STILL be exactly the plain greedy
        stream (the target decides every token)."""
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, 500, (t,), np.int32) for t in (7, 13)]
        want = [_plain_tokens(p, BASE) for p in prompts]
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "draft:llama_tiny,spec_k:3,draft_seed:7")
        try:
            got = _serve_tokens(fw, prompts)
            for i, w in enumerate(want):
                assert got[i] == w, f"stream {i}"
        finally:
            fw.close()

    def test_accept_rate_zero_bit_identity(self):
        """Force every proposal off the target's argmax: each round
        emits ONLY the bonus token (the plain-decode degenerate case)
        — still bit-identical, with zero accepted proposals."""
        rng = np.random.default_rng(18)
        prompt = rng.integers(1, 450, (9,), np.int32)
        # enumerate the greedy continuation far past max_new, pick a
        # proposal id the target can never argmax inside this run
        cont = _plain_tokens(
            prompt, "max_new:32,stream_chunk:2,temperature:0.0,"
            "dtype:float32")
        dead = next(t for t in range(451, 512) if t not in cont)
        want = _plain_tokens(prompt, BASE)
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "draft:llama_tiny,spec_k:3")
        try:
            serve_loop = None
            got = {0: []}
            lock = threading.Lock()

            def emit(t, m):
                with lock:
                    got[0].append(int(t[0][0]))

            # wrap _propose AFTER the loop exists (first submit builds
            # it) — run one warm stream first, then patch
            fw.submit([prompt], {}, lambda t, m: None)
            assert fw.drain(120)
            a0 = _metric("llm.serve.spec_accepted")
            serve_loop = fw._serve
            real = serve_loop._propose

            def all_rejected(dp, tp, tk, pool, tables, pos, keys):
                props, dprobs, pool = real(dp, tp, tk, pool, tables,
                                           pos, keys)
                import jax.numpy as jnp

                return jnp.full_like(props, dead), dprobs, pool

            serve_loop._propose = all_rejected
            fw.submit([prompt], {}, emit)
            assert fw.drain(120)
            serve_loop._propose = real
        finally:
            fw.close()
        assert got[0] == want
        assert _metric("llm.serve.spec_accepted") == a0

    def test_bit_identity_at_max_seq_edge(self):
        """Final-round regression: the fixed [slots, k+1]-wide verify
        dispatches even when fewer tokens remain, so positions reach
        max_seq-1+k — the table must span them (serving_plan widens
        max_blocks by spec_k) or the stale-table clamp zeroes the live
        row's context and the LAST tokens go bit-wrong."""
        cfg16 = "max_new:16,stream_chunk:2,temperature:0.0,dtype:float32"
        rng = np.random.default_rng(25)
        # T=240 + max_new 16 == llama_tiny's max_seq 256 exactly; with
        # block_size 16 / prefill_chunk 32 the unwidened table would end
        # at position 256 and the verify at pos 252..255 would overrun
        prompt = rng.integers(1, 500, (240,), np.int32)
        want = _plain_tokens(prompt, cfg16)
        assert len(want) == 16
        fw = _fw(cfg16 + ",serve:continuous,slots:2,block_size:16,"
                 "prefill_chunk:32,draft:llama_tiny,spec_k:4,"
                 "draft_seed:7")
        try:
            got = _serve_tokens(fw, [prompt])
            assert got[0] == want, (got[0], want)
        finally:
            fw.close()

    def test_spec_with_prefix_sharing(self):
        """Speculation and sharing compose: the draft pool's blocks are
        shared/forked alongside the target's, greedy ids stay exact."""
        rng = np.random.default_rng(19)
        pa, pb = _shared_prompts(rng, prefix_len=16, suffixes=(3, 6))
        want = [_plain_tokens(p, BASE) for p in (pa, pb)]
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:8,draft:llama_tiny,spec_k:3,"
                 "draft_seed:7")
        h0 = _metric("llm.serve.prefix_hits")
        try:
            got = _serve_staggered(fw, [pa, pb])
            assert got[0] == want[0] and got[1] == want[1]
            assert _metric("llm.serve.prefix_hits") > h0
            assert sorted(fw._serve._free) == \
                list(range(fw._serve.n_blocks))
        finally:
            fw.close()

    def test_preset_only_and_continuous_only_are_rejected(self):
        """draft: still demands a preset zoo name + the continuous
        loop; temperature > 0 is NO LONGER rejected (speculative
        rejection sampling, docs/SERVING.md §4d) — pinned by the
        sampled-spec tests in tests/test_sampling.py."""
        from nnstreamer_tpu.filters.base import FrameworkError

        with pytest.raises(FrameworkError, match="preset"):
            _fw("serve:continuous,temperature:0.0,draft:/tmp/x.gguf")
        with pytest.raises(FrameworkError, match="serve:continuous"):
            _fw("temperature:0.0,draft:llama_tiny")
        # sampled + draft constructs (the old greedy-only guard is gone)
        fw = _fw("serve:continuous,temperature:0.8,draft:llama_tiny")
        fw.close()


# ---------------------------------------------------------------------------
# zero-recompile census
# ---------------------------------------------------------------------------

class TestSpecCensus:
    def test_five_program_pin_across_churn(self):
        """serving_plan() predicts 5 programs under speculation; churn
        with new lengths, cache hits, CoW forks, and every accept ratio
        must compile NOTHING new — and the plain decode chunk must
        never compile at all."""
        from nnstreamer_tpu.filters.llm import serving_plan

        cfg = llama.PRESETS["llama_tiny"]
        plan = serving_plan(cfg, slots=3, block_size=8, prefill_chunk=4,
                            draft_cfg=cfg, spec_k=3, dtype="float32")
        assert plan["programs"] == 5
        assert plan["draft_pool_bytes"] > 0
        rng = np.random.default_rng(20)
        fw = _fw(BASE + ",serve:continuous,slots:3,block_size:8,"
                 "prefill_chunk:4,draft:llama_tiny,spec_k:3,"
                 "draft_seed:7")
        try:
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32)])
            serve = fw._serve
            names = ("_prefill", "_set_tok", "_draft_prefill",
                     "_propose", "_verify")
            warm = {n: getattr(serve, n)._cache_size() for n in names}
            assert warm == {n: 1 for n in names}, warm
            assert serve._decode._cache_size() == 0
            p = rng.integers(1, 500, (24,), np.int32)
            _serve_tokens(fw, [p])
            _serve_tokens(fw, [p, p])  # hits + CoW forks
            _serve_tokens(fw, [rng.integers(1, 500, (t,), np.int32)
                               for t in (1, 7, 13)])
            after = {n: getattr(serve, n)._cache_size() for n in names}
            assert after == warm, f"recompile on churn: {warm}->{after}"
            assert serve._decode._cache_size() == 0
        finally:
            fw.close()

    def test_xray_census_drift_zero_with_spec_active(self):
        """nns-xray's live census: the enlarged 5-program budget is
        installed when speculation is on, and churn + cache hits + CoW
        forks + accept/reject keep measured drift at exactly 0."""
        from nnstreamer_tpu.utils.xray import ProgramRegistry

        reg = ProgramRegistry()
        rng = np.random.default_rng(23)
        pre = rng.integers(1, 500, (16,), np.int32)
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:4,draft:llama_tiny,spec_k:3,"
                 "draft_seed:7", model="llama_tiny")
        fw.attach_xray(reg, "llm")
        try:
            for wave in range(2):  # churn + hits + forks
                prompts = [
                    np.concatenate([pre, rng.integers(1, 500, (t,),
                                                      np.int32)])
                    for t in (2, 5)] + [pre.copy()]
                _serve_tokens(fw, prompts)
            census = reg.census()
            kinds = ("prefill", "set_tok", "draft_prefill", "propose",
                     "verify")
            for kind in kinds:
                e = census[f"llm.serve/{kind}"]
                assert e["predicted"] == 1
                assert e["live_compiles"] == 1, (kind, e)
                assert e["within"]
            assert len([k for k in census if k.startswith("llm.serve/")]) \
                == len(kinds)
            assert reg.drift_count() == 0
        finally:
            fw.close()

    def test_sharing_keeps_three_program_pin(self):
        """Without a draft the census stays 3 — prefix hits and forks
        are host values."""
        rng = np.random.default_rng(21)
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:4")
        try:
            p = rng.integers(1, 500, (24,), np.int32)
            _serve_tokens(fw, [p])
            serve = fw._serve
            warm = {n: getattr(serve, n)._cache_size()
                    for n in ("_decode", "_prefill", "_set_tok")}
            assert warm == {"_decode": 1, "_prefill": 1, "_set_tok": 1}
            _serve_tokens(fw, [p, p])  # hits + forks
            after = {n: getattr(serve, n)._cache_size()
                     for n in ("_decode", "_prefill", "_set_tok")}
            assert after == warm
        finally:
            fw.close()


# ---------------------------------------------------------------------------
# pipeline-native accept/reject routing
# ---------------------------------------------------------------------------

class TestSpecRouting:
    def test_tensor_if_meta_value_gates_spec_flag(self):
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.elements.cond import TensorIf

        el = TensorIf({"compared_value": "META_VALUE",
                       "compared_value_option": "spec_draft",
                       "operator": "GE", "supplied_value": "1"})
        el.configure({"sink": None}, ["src_0", "src_1"])
        acc = Buffer([np.asarray([3], np.int32)],
                     meta={"spec_draft": 1})
        bonus = Buffer([np.asarray([4], np.int32)],
                       meta={"spec_draft": 0})
        unstamped = Buffer([np.asarray([5], np.int32)])
        assert el.process("sink", acc) == [("src_0", acc)]
        assert el.process("sink", bonus) == [("src_1", bonus)]
        assert el.process("sink", unstamped) == [("src_1", unstamped)]

    def test_demux_by_meta_routes_whole_buffer(self):
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.core.caps import Caps
        from nnstreamer_tpu.elements.routing import TensorDemux

        el = TensorDemux({"by-meta": "spec_draft"})
        el.configure({"sink": Caps.any()}, ["src_0", "src_1"])
        acc = Buffer([np.asarray([3], np.int32),
                      np.asarray([9], np.uint8)],
                     meta={"spec_draft": 1})
        bonus = Buffer([np.asarray([4], np.int32)],
                       meta={"spec_draft": 0})
        out = el.process("sink", acc)
        assert out == [("src_1", acc)]  # whole buffer, both tensors
        assert len(out[0][1].tensors) == 2
        assert el.process("sink", bonus) == [("src_0", bonus)]
        # out-of-range / junk meta clamps to src_0, never raises
        junk = Buffer([np.asarray([1], np.int32)],
                      meta={"spec_draft": "nan?"})
        assert el.process("sink", junk)[0][0] == "src_0"

    def test_serve_loop_stamps_spec_draft(self):
        rng = np.random.default_rng(22)
        prompt = rng.integers(1, 500, (6,), np.int32)
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "draft:llama_tiny,spec_k:3")
        metas = {}
        try:
            _serve_tokens(fw, [prompt], metas=metas)
        finally:
            fw.close()
        # every round token carries the flag (the prefill-sampled first
        # token predates any proposal and is unstamped)
        assert all("spec_draft" in m for m in metas[0][1:])


# ---------------------------------------------------------------------------
# deep lint pricing
# ---------------------------------------------------------------------------

class TestSpecDeepLint:
    DESC = ("appsrc name=src ! tensor_filter framework=llm "
            "model=llama_small custom=max_new:16,serve:continuous,"
            "slots:4,block_size:16,kv_blocks:64,draft:llama_tiny,"
            "spec_k:4 invoke-dynamic=true ! tensor_sink name=out")

    def test_draft_params_pool_and_census_priced(self):
        import nnstreamer_tpu as nt

        rep = nt.analyze(self.DESC, deep=True)
        stage = rep.resources.stages[0]
        assert stage.variants == 5
        assert stage.draft_param_bytes > 0
        assert stage.draft_pool_bytes > 0
        # the draft rides the params/kv_pool ledger categories (what
        # nns-xray reconciles measured bytes against)
        tiny = llama.PRESETS["llama_tiny"]
        small = llama.PRESETS["llama_small"]
        dcfg = llama.resolve_config(
            "llama_tiny", {"vocab": small.vocab,
                           "max_seq": small.max_seq})
        assert stage.draft_param_bytes == llama.param_bytes_estimate(
            dcfg, param_dtype="float32")
        del tiny
        text = rep.resources.render()
        assert "draft params" in text and "draft pool" in text

    def test_unresolvable_draft_warns(self):
        import nnstreamer_tpu as nt

        rep = nt.analyze(self.DESC.replace("draft:llama_tiny",
                                           "draft:nope"), deep=True)
        assert any(d.code == "serving-unpriced"
                   and "draft" in d.message for d in rep.diagnostics)

    def test_reconfig_table_covers_spec_knobs(self):
        from nnstreamer_tpu.utils import elastic

        assert elastic.SERVE_KNOB_SIGNATURE["draft"] is True
        assert elastic.SERVE_KNOB_SIGNATURE["spec_k"] is True
        assert elastic.SERVE_KNOB_SIGNATURE["prefix_cache"] is False
