"""Adaptive bucket ladder (ISSUE 10 tentpole, docs/BATCHING.md "Adaptive
ladder").

The contract: with ``adaptive_buckets=True`` a batchable stage refines its
bucket ladder online from the drain occupancies it actually observes —
persistent skew mints an exact bucket instead of padding to the next power
of two — while every observable semantic (output values, ordering, pts)
stays bit-identical to the static ladder, the mint budget keeps the
deep-lint recompile census CLOSED, and a previous run's ladder snapshot
warm-starts the refined ladder at construction.
"""

import numpy as np

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.pipeline.batching import (
    AdaptiveLadder, BatchRunner, bucket_for, ladder, shard_bucket_for)
from nnstreamer_tpu.pipeline.plan import (ADAPTIVE_EXTRA_DEFAULT,
                                          adaptive_variant_budget)

DESC = (
    "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
    "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
    "name=f ! tensor_sink name=out"
)


def _frames(n):
    return [np.full((16,), float(i), np.float32) for i in range(n)]


def _run(frames, **kw):
    p = nt.Pipeline(DESC, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=60))
        p.eos()
        p.wait(timeout=60)
    return outs, p


# -- ladder primitives ------------------------------------------------------

def test_mint_after_persistent_skew():
    """An occupancy the ladder would pad, observed persistently, mints an
    exact bucket; one-off shapes never do."""
    lad = AdaptiveLadder((1, 2, 4, 8), budget=6, mint_after=4)
    lad.observe(3)  # transient: below mint_after
    assert lad.sizes() == (1, 2, 4, 8)
    for _ in range(4):
        lad.observe(6)
    assert lad.sizes() == (1, 2, 4, 6, 8)
    assert lad.bucket_for(5) == 6  # refined: no longer pads to 8
    assert lad.bucket_for(6) == 6


def test_exact_occupancies_never_mint():
    lad = AdaptiveLadder((1, 2, 4, 8), budget=8, mint_after=1)
    for n in (1, 2, 4, 8):
        lad.observe(n)
    assert lad.sizes() == (1, 2, 4, 8)


def test_budget_clamps_minting():
    """The ladder can NEVER grow past its budget — the census the deep
    pass priced is a hard ceiling, not advisory."""
    lad = AdaptiveLadder((1, 2, 4, 8), budget=5, mint_after=1)
    lad.observe(6)
    assert lad.sizes() == (1, 2, 4, 6, 8)
    lad.observe(5)
    lad.observe(3)
    assert lad.sizes() == (1, 2, 4, 6, 8)  # budget 5: no room left


def test_warm_start_pre_mints():
    lad = AdaptiveLadder((1, 2, 4, 8), budget=8, warm=[6, 3])
    assert lad.sizes() == (1, 2, 3, 4, 6, 8)
    assert lad.export() == [1, 2, 3, 4, 6, 8]


def test_sharded_rounding_still_applies():
    """Minted sizes are replica-aligned, so shard_bucket_for's rounding
    is a no-op on them — every replica still gets equal rows."""
    lad = AdaptiveLadder((1, 2, 4, 8), budget=8, align=4, mint_after=1)
    lad.observe(6)  # aligned up to 8: already a bucket, nothing minted
    assert lad.sizes() == (1, 2, 4, 8)
    lad = AdaptiveLadder((1, 2, 4, 16), budget=8, align=4, mint_after=1)
    lad.observe(6)
    assert 8 in lad.sizes()  # minted AS the aligned size
    assert shard_bucket_for(6, 4, lad.sizes()) == 8


def test_variant_budget_arithmetic():
    """plan.adaptive_variant_budget: the single home shared by runtime
    ladders and the deep census."""
    assert adaptive_variant_budget(9, 1, 0) == 9 + ADAPTIVE_EXTRA_DEFAULT
    assert adaptive_variant_budget(9, 2, 24) == 12
    # squeezed below the base ladder: refinement off, census intact
    assert adaptive_variant_budget(9, 4, 8) == 9


# -- ladder-rounded fallback (the recompile-unbounded regression) -----------

def test_bucket_for_above_top_is_ladder_rounded():
    """batch_max above the ladder top used to mint one program PER
    OCCUPANCY (the exact-size fallback); now sizes round to multiples of
    the top bucket and the census enumerates exactly them."""
    assert bucket_for(257) == 512
    assert bucket_for(513) == 768
    assert ladder(1000) == (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 768,
                            1024)


def test_runner_above_top_occupancies_share_rounded_programs():
    """Two different above-top occupancies land in the SAME rounded
    bucket -> one compiled program, not two."""
    br = BatchRunner(lambda arrays: (arrays[0] * 2.0,), buckets=[2, 4])
    rows5 = [(np.full((4,), float(i), np.float32),) for i in range(5)]
    rows7 = [(np.full((4,), float(i), np.float32),) for i in range(7)]
    out5 = br.run(rows5)
    out7 = br.run(rows7)
    assert len(out5) == 5 and len(out7) == 7
    assert set(br._progs) == {8}  # both ladder-rounded to 2*top


def test_deep_census_closed_above_ladder_top():
    """The recompile-unbounded regression: batch_max=1000 must price a
    FINITE census that exactly matches the runtime's rounded program set
    (no recompile-unbounded, no per-occupancy blowup)."""
    desc = ("appsrc name=src caps=other/tensors,dimensions=16,"
            "types=float32,format=static ! "
            "tensor_filter framework=jax model=scaler "
            "custom=scale:2.0,dims:16 name=f ! tensor_sink name=out")
    report = nt.analyze(desc, deep=True, batch_max=1000, data_parallel=1)
    assert not report.errors, report.render()
    assert not any(d.code == "recompile-unbounded" for d in report)
    [stage] = [s for s in report.resources.stages if s.batchable]
    assert stage.variants == len(ladder(1000))
    assert report.resources.ladder == ladder(1000)


# -- pipeline semantics -----------------------------------------------------

def _push_bursts(p, burst, bursts):
    """Drive a SKEWED steady state: bursts of ``burst`` same-spec buffers,
    each pulled to completion before the next, so every drain observes
    exactly ``burst`` rows (batch_linger collects the stragglers)."""
    outs = []
    k = 0
    for _ in range(bursts):
        for _ in range(burst):
            p.push("src", nt.Buffer([np.full((16,), float(k), np.float32)],
                                    pts=k))
            k += 1
        for _ in range(burst):
            outs.append(p.pull("out", timeout=60))
    return outs


def test_skewed_occupancy_refines_and_cuts_pad_waste():
    """A runner persistently draining 6 rows grows a 6-bucket: the ladder
    snapshot shows the mint and steady-state pad-waste stops growing."""
    metrics.reset()
    # data_parallel=1: the conftest's 8 virtual devices would otherwise
    # auto-shard the stage, and sharded minting aligns 6 up to the
    # replica count (see test_sharded_rounding_still_applies)
    p = nt.Pipeline(DESC, queue_capacity=32, batch_max=8,
                    batch_linger_ms=60.0, adaptive_buckets=True,
                    data_parallel=1)
    with p:
        _push_bursts(p, 6, 40)
        snap_mid = metrics.snapshot().get("f.batch_pad_waste", 0.0)
        assert 6 in p.element("f")._batch_ladder.sizes(), \
            p.element("f")._batch_ladder.sizes()
        _push_bursts(p, 6, 10)
        snap_end = metrics.snapshot().get("f.batch_pad_waste", 0.0)
        p.eos()
        p.wait(timeout=60)
    assert p.ladder_snapshot()["f"].count(6) == 1
    # refined steady state: 6-drains stopped padding entirely
    assert snap_end == snap_mid, (snap_mid, snap_end)
    assert metrics.snapshot().get("f.ladder_minted", 0) >= 1


def test_adaptive_bit_identical_to_static_ladder():
    """Refinement changes WHICH bucket a drain pads to, never the math:
    outputs byte-identical to the static ladder on identical input."""
    frames = _frames(36)
    a, _ = _run(frames, queue_capacity=48, batch_max=8,
                adaptive_buckets=True, batch_linger_ms=5.0)
    b, _ = _run(frames, queue_capacity=48, batch_max=8,
                adaptive_buckets=False, batch_linger_ms=5.0)
    for x, y in zip(a, b):
        assert bytes(np.asarray(x.tensors[0])) == bytes(
            np.asarray(y.tensors[0]))
        assert x.pts == y.pts


def test_warm_started_pipeline_compiles_refined_ladder():
    """A ladder snapshot fed back via bucket_ladders= pre-mints at
    construction — the first 6-drain already has its exact bucket (zero
    pad waste at that occupancy from buffer one)."""
    metrics.reset()
    p = nt.Pipeline(DESC, queue_capacity=32, batch_max=8,
                    batch_linger_ms=60.0, adaptive_buckets=True,
                    data_parallel=1,
                    bucket_ladders={"f": [1, 2, 4, 6, 8]})
    with p:
        assert p.element("f")._batch_ladder.sizes() == (1, 2, 4, 6, 8)
        _push_bursts(p, 6, 3)
        p.eos()
        p.wait(timeout=60)
    occ = metrics.snapshot().get("f.batch_occupancy.p99", 0)
    waste = metrics.snapshot().get("f.batch_pad_waste", 0.0)
    if occ >= 6.0:  # drains actually coalesced to the skewed size
        assert waste == 0.0, waste


def test_occupancy_histogram_in_prometheus_text():
    """The occupancy series renders as a REAL cumulative histogram
    (_bucket{le=}) in ladder-shaped buckets — the same exposition family
    as the PR 5 latency histograms, fed by the same stream the adaptive
    ladder refines from."""
    from nnstreamer_tpu.utils.profiler import metrics_text

    metrics.reset()
    frames = _frames(24)
    _run(frames, queue_capacity=32, batch_max=8)
    text = metrics_text()
    assert 'nnstpu_f_batch_occupancy_bucket{le="8"}' in text
    assert 'nnstpu_f_batch_occupancy_bucket{le="+Inf"}' in text
    assert "nnstpu_f_batch_occupancy_count" in text


def test_deep_census_prices_adaptive_budget():
    """With adaptive on, the deep pass prices every batchable stage at
    its full mint budget — the worst case the runtime can compile — and
    the report says so."""
    desc = ("appsrc name=src caps=other/tensors,dimensions=16,"
            "types=float32,format=static ! "
            "tensor_filter framework=jax model=scaler "
            "custom=scale:2.0,dims:16 name=f ! tensor_sink name=out")
    r = nt.analyze(desc, deep=True, batch_max=8, adaptive_buckets=True,
                   max_compiled_variants=10)
    assert not r.errors, r.render()
    [stage] = [s for s in r.resources.stages if s.batchable]
    base = len(ladder(8))
    assert stage.variants == adaptive_variant_budget(base, 1, 10)
    assert r.resources.compiled_variants <= 10
    assert r.resources.adaptive_buckets
    assert "adaptive" in r.resources.render()
    # and the budget is EXACTLY what the runtime would hand the stage
    from nnstreamer_tpu.core.config import get_config

    p = nt.Pipeline(desc, batch_max=8, adaptive_buckets=True)
    assert p._ladder_budget == adaptive_variant_budget(
        base, 1, get_config().max_compiled_variants)


def test_align_assignment_reruns_warm_mints():
    """Warm-start sizes are minted before the mesh exists (align=1); the
    runtime assigns the real data width at start() — assigning align must
    RE-ROUND already-minted sizes so a dp=1 snapshot warm-started into a
    sharded deployment never leaves an undispatchable entry burning a
    budget slot."""
    lad = AdaptiveLadder((1, 2, 4, 8, 16), budget=8, warm=[6, 10])
    assert lad.sizes() == (1, 2, 4, 6, 8, 10, 16)
    lad.align = 4
    # 6 -> 8 (dedups into base), 10 -> 12: the freed slot is mintable again
    assert lad.sizes() == (1, 2, 4, 8, 12, 16)
    for _ in range(AdaptiveLadder((1,), budget=0).mint_after):
        lad.observe(5)  # aligned -> 8: already a bucket, nothing minted
    assert lad.sizes() == (1, 2, 4, 8, 12, 16)


def test_ini_ladders_preserve_stage_name_case(tmp_path, monkeypatch):
    """[ladders] stage keys are case-sensitive (ladder_snapshot() exports
    element names verbatim) — the ini reader must not lowercase them or
    the warm-start lookup silently misses."""
    from nnstreamer_tpu.core.config import Config, parse_ladders

    ini = tmp_path / "nns.ini"
    ini.write_text("[ladders]\nMyFilter = 1,2,6\nsrc+t+F = 1,4\n")
    monkeypatch.setenv("NNS_TPU_CONF", str(ini))
    monkeypatch.delenv("NNS_TPU_BUCKET_LADDERS", raising=False)
    cfg = Config.load()
    assert cfg.bucket_ladders == {"MyFilter": [1, 2, 6], "src+t+F": [1, 4]}
    # env path already preserved case; the two must agree
    assert parse_ladders("MyFilter:1|2|6") == {"MyFilter": [1, 2, 6]}
