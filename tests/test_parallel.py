"""Mesh / sharding / ring-attention tests on the 8-device CPU mesh.

SURVEY §4 translation: multi-node tests run on a simulated local mesh
instead of the reference's localhost-socket client/server rigs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.parallel import (
    make_mesh,
    mesh_axis_size,
    ring_attention,
    shard_batch,
    shard_params,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def dense_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(d)
    if causal:
        T = q.shape[1]
        mask = np.tril(np.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def test_make_mesh_shapes():
    mesh = make_mesh(model=2)
    assert mesh_axis_size(mesh, "data") == 4
    assert mesh_axis_size(mesh, "model") == 2
    mesh = make_mesh({"seq": 8, "data": 1})
    assert mesh_axis_size(mesh, "seq") == 8


def test_make_mesh_bad_divisor():
    with pytest.raises(ValueError):
        make_mesh(model=3)


def test_shard_batch_roundtrip():
    mesh = make_mesh()
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    y = shard_batch(mesh, x)
    assert y.sharding.num_devices == 8
    np.testing.assert_array_equal(np.asarray(y), x)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh({"data": 1, "seq": 8})
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 64, 2, 8
    q = rng.standard_normal((B, T, H, D), dtype=np.float32)
    k = rng.standard_normal((B, T, H, D), dtype=np.float32)
    v = rng.standard_normal((B, T, H, D), dtype=np.float32)
    out = ring_attention(mesh, q, k, v, causal=causal)
    ref = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_shard_params_tp_matmul():
    """TP: shard a weight over 'model', jit a matmul, result matches."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(model=2)
    w = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    params = {"w": w}
    sharded = shard_params(mesh, params, {"w": P(None, "model")})
    x = np.ones((4, 16), np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))

    @jax.jit
    def f(p, x):
        return x @ p["w"]

    out = f(sharded, xs)
    np.testing.assert_allclose(np.asarray(out), x @ w)


def test_jax_filter_data_parallel_mesh():
    """tensor_filter framework=jax mesh=data:8 shards the batch dim over
    the virtual 8-device mesh (north star: query-layer DP sharding)."""
    import nnstreamer_tpu as nt

    p = nt.Pipeline(
        "appsrc name=src caps=other/tensors,dimensions=4:8,types=float32 ! "
        "tensor_filter framework=jax model=scaler custom=scale:3.0,dims:4:8 "
        "mesh=data:8 ! tensor_sink name=out"
    )
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    with p:
        p.push("src", x)
        out = p.pull("out", timeout=60)
        p.eos()
        p.wait(timeout=30)
    np.testing.assert_allclose(np.asarray(out.tensors[0]), x * 3.0)


def test_jax_filter_mesh_too_big_rejected():
    import nnstreamer_tpu as nt
    from nnstreamer_tpu.elements.base import ElementError

    with pytest.raises(ElementError, match="devices"):
        nt.Pipeline(
            "appsrc ! tensor_filter framework=jax model=scaler "
            "custom=dims:4 mesh=data:64 ! tensor_sink name=o"
        )


def test_distributed_single_process_fallback(monkeypatch):
    """No coordinator configured -> clean single-process fallback."""
    from nnstreamer_tpu.parallel import distributed as dist

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert dist.initialize() is False
    assert not dist.is_initialized()
    assert dist.global_device_count() >= 8  # virtual CPU mesh
    assert dist.local_device_count() == dist.global_device_count()


def test_global_mesh_axes():
    from nnstreamer_tpu.parallel import global_mesh

    mesh = global_mesh(model=2)
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] * 2 == len(jax.devices())


def test_query_service_pod_sharded():
    """The north-star sentence made executable: a tensor_query server whose
    filter shards the batch dim data-parallel over the (virtual) pod mesh;
    clients see ordinary request/response."""
    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.models.zoo import register_model  # noqa: F401

    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=77 ! "
        "tensor_filter framework=jax model=scaler "
        "custom=scale:4.0,dims:8:8 mesh=data:8 ! "
        "tensor_query_serversink id=77",
        fuse=False,
    )
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=15 ! "
            "tensor_sink name=out"
        )
        with cli:
            x = np.arange(64, dtype=np.float32).reshape(8, 8)
            cli.push("src", x)
            out = cli.pull("out", timeout=15)
            np.testing.assert_allclose(out.tensors[0], 4.0 * x)
            cli.eos("src")
            cli.wait(timeout=10)


def test_graft_dryrun_detection_dp():
    """The driver's DP-inference proof (__graft_entry__._dryrun_detection_dp)
    runs on the 8-device CPU mesh."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import __graft_entry__ as g

    g._dryrun_detection_dp(8)
