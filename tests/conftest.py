"""Test environment: hermetic CPU backend with 8 virtual devices.

SURVEY §4 translation: multi-chip tests run on a simulated local mesh
(``--xla_force_host_platform_device_count=8``) instead of the reference's
localhost-socket multi-process rigs.  Must be set before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
