"""Test environment: hermetic CPU backend with 8 virtual devices.

SURVEY §4 translation: multi-chip tests run on a simulated local mesh
(``--xla_force_host_platform_device_count=8``) instead of the reference's
localhost-socket multi-process rigs.  Must be set before jax initializes.
"""

import os

# Force CPU: the dev/driver environment exports JAX_PLATFORMS=axon (a real
# TPU tunnel) globally, so a plain setdefault would silently run the whole
# suite on one remote chip — slow, non-hermetic, and the 8-device mesh tests
# would fail.  Tests are hermetic by design (SURVEY §4 translation).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
