"""Test environment: hermetic CPU backend with 8 virtual devices.

SURVEY §4 translation: multi-chip tests run on a simulated local mesh
(``--xla_force_host_platform_device_count=8``) instead of the reference's
localhost-socket multi-process rigs.  Must be set before jax initializes.
"""

import os

# Force CPU: the dev/driver environment exports JAX_PLATFORMS=axon (a real
# TPU tunnel) globally, so a plain setdefault would silently run the whole
# suite on one remote chip — slow, non-hermetic, and the 8-device mesh tests
# would fail.  Tests are hermetic by design (SURVEY §4 translation).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The interpreter may have pre-imported jax (the axon plugin does so at
# startup), in which case the env vars above arrive too late for
# JAX_PLATFORMS — force the platform through the live config instead.
# XLA_FLAGS is still read at backend init, so the device count sticks.
import sys  # noqa: E402

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    import jax

    assert jax.devices()[0].platform == "cpu", (
        "test suite must run on the virtual CPU mesh, got "
        f"{jax.devices()[0]}"
    )
    assert len(jax.devices()) >= 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())} — "
        "XLA_FLAGS was applied too late (backend already initialized?)"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(42)
