"""Bench entry-point resilience: the probe retries through a tunnel flap
and the failure path still emits one parseable JSON record (the driver
artifact's ``parsed`` field must never be null — round-2 regression)."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from nnstreamer_tpu.utils import watchdog as wd  # noqa: E402


def test_probe_recovers_after_flap(monkeypatch):
    calls = {"n": 0}

    def fake_call(fn, timeout, what):
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError(what)
        return ["cpu:0"]

    monkeypatch.setattr(wd, "call_with_watchdog", fake_call)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    ok = bench._backend_reachable(attempt_timeout_s=0.1, total_budget_s=60.0,
                                  retry_sleep_s=0.1)
    assert ok and calls["n"] == 3


def test_probe_gives_up_within_budget(monkeypatch):
    def fake_call(fn, timeout, what):
        raise TimeoutError(what)

    monkeypatch.setattr(wd, "call_with_watchdog", fake_call)
    slept = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    # monotonic advances only via our fake sleeps
    t = {"now": 0.0}

    def fake_sleep(s):
        slept.append(s)
        t["now"] += s

    monkeypatch.setattr(bench.time, "sleep", fake_sleep)
    monkeypatch.setattr(bench.time, "monotonic", lambda: t["now"])
    ok = bench._backend_reachable(attempt_timeout_s=0.1, total_budget_s=1.0,
                                  retry_sleep_s=0.4)
    assert not ok
    assert sum(slept) <= 1.0


def test_probe_fails_fast_on_deterministic_init_error(monkeypatch):
    calls = {"n": 0}

    def fake_call(fn, timeout, what):
        calls["n"] += 1
        raise RuntimeError("Unable to initialize backend 'axon'")

    monkeypatch.setattr(wd, "call_with_watchdog", fake_call)
    ok = bench._backend_reachable(attempt_timeout_s=0.1, total_budget_s=60.0,
                                  retry_sleep_s=0.1)
    assert not ok and calls["n"] == 1  # no retry of a permanent failure


def test_main_emits_failure_json_when_unreachable(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_backend_reachable", lambda: False)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--config", "detection"])
    rc = bench.main()
    assert rc == 3
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    # must match the metric name the SUCCESS path emits, or a driver
    # keying on known metric names still sees parsed=null
    assert rec["metric"] == "ssd_mobilenet_detection_fps_per_chip"
    assert rec["value"] == 0.0 and "error" in rec


def test_main_emits_one_failure_record_per_config_for_all(monkeypatch,
                                                          capsys):
    monkeypatch.setattr(bench, "_backend_reachable", lambda: False)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--config", "all"])
    rc = bench.main()
    assert rc == 3
    recs = [json.loads(l) for l in
            capsys.readouterr().out.strip().splitlines()]
    metrics = {r["metric"]: r["unit"] for r in recs}
    assert metrics == {
        "mobilenet_v1_pipeline_fps_per_chip": "frames/sec",
        "ssd_mobilenet_detection_fps_per_chip": "frames/sec",
        "posenet_pipeline_fps_per_chip": "frames/sec",
        "deeplab_segmentation_fps_per_chip": "frames/sec",
        "speech_commands_windows_per_sec_per_chip": "windows/sec",
        "llama_small_tokens_per_sec_per_chip": "tokens/sec",
    }
