"""Model zoo tests for benchmark configs #2-#4: detection, pose, audio —
each driven end-to-end through its pipeline + decoder (SURVEY §6 configs)."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import zoo


def test_zoo_lists_benchmark_models():
    names = zoo.model_names()
    for required in ("mobilenet_v1", "ssd_mobilenet", "posenet",
                     "speech_commands", "wav2vec2", "llama_tiny",
                     "llama2_7b", "deeplab_mobilenet"):
        assert required in names, f"{required} missing from zoo {names}"


def test_ssd_shapes_and_ranges():
    from nnstreamer_tpu.models import ssd

    b = zoo.build("ssd_mobilenet", {"size": "96", "classes": "7",
                                    "dtype": "float32"})
    x = np.random.default_rng(0).standard_normal((2, 96, 96, 3)).astype(np.float32)
    boxes, scores = b.apply_fn(b.params, x)
    n = ssd.build_anchors(96).shape[0]
    assert boxes.shape == (2, n, 4)
    assert scores.shape == (2, n, 7)
    boxes = np.asarray(boxes)
    scores = np.asarray(scores)
    assert (boxes >= 0).all() and (boxes <= 1).all()
    assert (scores >= 0).all() and (scores <= 1).all()


def test_ssd_detection_pipeline_e2e():
    """Config #2: video -> ssd -> bounding_boxes decoder overlay."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=96 height=96 pattern=ball ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=ssd_mobilenet custom=size:96,classes:7 ! "
        "tensor_decoder mode=bounding_boxes option3=0.0 option4=96:96 ! "
        "tensor_sink name=out"
    )
    with p:
        out = p.pull("out", timeout=120)
        p.pull("out", timeout=60)
        p.wait(timeout=60)
    assert out.tensors[0].shape == (96, 96, 4)  # RGBA overlay
    assert "detections" in out.meta


def test_posenet_pipeline_e2e():
    """Config #3: video -> posenet -> pose decoder keypoints."""
    p = nt.Pipeline(
        "videotestsrc num-buffers=1 width=96 height=96 pattern=smpte ! "
        "tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=posenet custom=size:96,width:0.5 ! "
        "tensor_decoder mode=pose_estimation option2=96:96 option3=0.0 ! "
        "tensor_sink name=out"
    )
    with p:
        out = p.pull("out", timeout=120)
        p.wait(timeout=60)
    assert out.tensors[0].shape == (96, 96, 4)
    kps = out.meta.get("keypoints")
    assert kps is not None and len(kps) == 17


def test_speech_commands_pipeline_e2e():
    """Config #4: audio stream -> aggregated window -> keyword spotter."""
    p = nt.Pipeline(
        "audiotestsrc num-buffers=4 samplesperbuffer=4000 freq=440 format=F32LE ! "
        "tensor_converter ! "
        "tensor_aggregator frames-in=4000 frames-out=16000 frames-flush=16000 frames-dim=1 ! "
        "tensor_filter framework=jax model=speech_commands custom=dtype:float32 ! "
        "tensor_sink name=out"
    )
    with p:
        out = p.pull("out", timeout=120)
        p.wait(timeout=60)
    logits = out.tensors[0]
    assert logits.shape[-1] == 12
    assert np.isfinite(logits).all()


def test_wav2vec2_logits():
    b = zoo.build("wav2vec2", {"dtype": "float32", "n_layers": "2"})
    wav = np.sin(np.linspace(0, 440 * np.pi, 16000)).astype(np.float32)[None, :]
    logits = np.asarray(b.apply_fn(b.params, wav))
    assert logits.ndim == 3 and logits.shape[0] == 1 and logits.shape[2] == 32
    assert logits.shape[1] > 10  # ~50 fps frame rate after conv strides
    assert np.isfinite(logits).all()


def test_ssd_tp_sharding_consistent():
    """SSD under TP mesh must match single-device outputs."""
    import jax
    from nnstreamer_tpu.models import ssd
    from nnstreamer_tpu.parallel import make_mesh
    from nnstreamer_tpu.parallel.sharding import shard_params

    b = zoo.build("ssd_mobilenet", {"size": "64", "classes": "4",
                                    "dtype": "float32"})
    x = np.random.default_rng(1).standard_normal((1, 64, 64, 3)).astype(np.float32)
    ref_boxes, ref_scores = b.apply_fn(b.params, x)

    mesh = make_mesh(model=2, data=1, devices=jax.devices()[:2])
    sharded = shard_params(mesh, b.params, ssd.param_pspecs())
    boxes, scores = jax.jit(b.apply_fn)(sharded, x)
    np.testing.assert_allclose(np.asarray(boxes), np.asarray(ref_boxes),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(scores), np.asarray(ref_scores),
                               rtol=1e-5, atol=1e-5)


def test_ssd_anchor_layout_matches_head():
    """Anchor index must be cell-major (y*fm + x)*A + a — the head's
    (B,H,W,A*4)->(B,N,4) reshape order (regression: aspect-major layout
    decoded every box against the wrong cell's anchor)."""
    from nnstreamer_tpu.models import ssd

    size = 64
    fm = size // 16
    A = ssd.num_anchors_per_cell()
    anc = ssd.build_anchors(size)
    grid_a = anc[: fm * fm * A].reshape(fm, fm, A, 4)
    centers = (np.arange(fm, dtype=np.float32) + 0.5) / fm
    # all A anchors of one cell share that cell's center
    for y in (0, fm - 1):
        for x in (0, fm // 2):
            np.testing.assert_allclose(grid_a[y, x, :, 0], centers[x], atol=1e-6)
            np.testing.assert_allclose(grid_a[y, x, :, 1], centers[y], atol=1e-6)
    # aspect varies along the per-cell axis: widths differ across a
    widths = grid_a[0, 0, :, 2]
    assert len(np.unique(np.round(widths, 5))) >= 3


def test_posenet_odd_size_fm():
    """257x257 (the reference posenet's own input) -> 17x17 heatmaps via the
    SAME-padded ceil chain, and the declared out_spec must match reality."""
    from nnstreamer_tpu.models import zoo

    b = zoo.build("posenet", {"size": "257", "width": "0.25", "dtype": "float32"})
    x = np.zeros((1, 257, 257, 3), np.float32)
    heat, off = b.apply_fn(b.params, x)
    assert heat.shape[1:3] == (17, 17)
    assert tuple(b.out_spec[0].shape[1:3]) == (17, 17)


class TestYolo:
    """YOLOv5-shaped zoo model (the second half of config #2)."""

    def test_output_layout_and_ranges(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.models import yolo
        from nnstreamer_tpu.models.zoo import build

        b = build("yolov5", {"size": "96", "classes": "7", "batch": "2",
                             "dtype": "float32"})
        x = jnp.zeros((2, 96, 96, 3), jnp.float32)
        out = np.asarray(b.apply_fn(b.params, x))
        n = yolo.num_predictions(96)
        assert out.shape == (2, n, 12)  # cx cy w h obj + 7 classes
        # decoded centers normalized; obj/cls are sigmoids
        assert (out[..., 4:] >= 0).all() and (out[..., 4:] <= 1).all()
        # yolov5's (2*sig-0.5+grid)/g decode reaches +-0.5/g past [0,1]
        assert out[..., 0].min() > -0.2 and out[..., 0].max() < 1.2
        # objectness prior: random weights mostly predict background
        assert float(np.median(out[..., 4])) < 0.1

    def test_bundle_spec_matches_output(self):
        from nnstreamer_tpu.models.zoo import build

        b = build("yolov5", {"size": "64", "classes": "3", "batch": "1"})
        assert b.out_spec[0].shape[1] == b.apply_fn(
            b.params, np.zeros((1, 64, 64, 3), np.float32)).shape[1]

    def test_size_must_be_multiple_of_32(self):
        from nnstreamer_tpu.models.zoo import build

        with pytest.raises(ValueError, match="multiple of 32"):
            build("yolov5", {"size": "100"})

    def test_yolov8_output_layout(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.models import yolo
        from nnstreamer_tpu.models.zoo import build

        b = build("yolov8", {"size": "96", "classes": "7", "batch": "2",
                             "dtype": "float32"})
        x = jnp.zeros((2, 96, 96, 3), jnp.float32)
        out = np.asarray(b.apply_fn(b.params, x))
        n = yolo.num_predictions_v8(96)
        assert out.shape == (2, 11, n)  # channels-first: 4 box + 7 classes
        # class scores are sigmoids; anchor-free => no objectness column
        assert (out[:, 4:, :] >= 0).all() and (out[:, 4:, :] <= 1).all()
        assert float(np.median(out[:, 4:, :])) < 0.1  # background prior

    def test_fused_yolov8_detection_pipeline(self):
        import nnstreamer_tpu as nt

        p = nt.Pipeline(
            "videotestsrc device=true batch=2 num-buffers=4 width=64 "
            "height=64 pattern=ball name=src ! "
            "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
            "tensor_filter framework=jax model=yolov8 "
            "custom=size:64,classes:5,batch:2 ! "
            "tensor_decoder mode=bounding_boxes option1=yolov8 option3=0.3 "
            "option4=64:64 option7=device ! tensor_sink name=out")
        fused = [s for s in p.stages if len(s.node_ids) > 1]
        assert fused and len(fused[0].node_ids) == 4
        with p:
            b = p.pull("out", timeout=120)
            p.wait(timeout=60)
        assert b.tensors[0].shape == (2, 64, 64, 4)

    def test_fused_yolo_detection_pipeline(self):
        import nnstreamer_tpu as nt

        p = nt.Pipeline(
            "videotestsrc device=true batch=2 num-buffers=4 width=64 "
            "height=64 pattern=ball name=src ! "
            "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
            "tensor_filter framework=jax model=yolov5 "
            "custom=size:64,classes:5,batch:2 ! "
            "tensor_decoder mode=bounding_boxes option1=yolov5 option3=0.3 "
            "option4=64:64 option7=device ! tensor_sink name=out")
        fused = [s for s in p.stages if len(s.node_ids) > 1]
        # device source folds in: src+transform+filter+decoder
        assert fused and len(fused[0].node_ids) == 4
        with p:
            b = p.pull("out", timeout=120)
            p.wait(timeout=60)
        assert b.tensors[0].shape == (2, 64, 64, 4)


def test_deeplab_segmentation_pipeline_fused():
    """Segmentation family (SURVEY §2.5 image_segment example): deeplab
    zoo model -> fused device argmax decode -> RGBA overlay."""
    p = nt.Pipeline(
        "videotestsrc device=true batch=2 num-buffers=4 width=64 height=64 "
        "pattern=smpte name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=deeplab_mobilenet "
        "custom=size:64,classes:6,batch:2,width:0.25,dtype:float32 ! "
        "tensor_decoder mode=image_segment ! tensor_sink name=out")
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused and len(fused[0].node_ids) == 4  # src+transform+filter+dec
    with p:
        b = p.pull("out", timeout=120)
        p.wait(timeout=60)
    overlay = np.asarray(b.tensors[0])
    assert overlay.shape == (2, 64, 64, 4)  # full-res RGBA, batched
    assert overlay.dtype == np.uint8


def test_deeplab_output_is_full_resolution_scores():
    from nnstreamer_tpu.models import zoo as _zoo

    b = _zoo.build("deeplab_mobilenet",
                   {"size": "32", "classes": "5", "batch": "1",
                    "width": "0.25", "dtype": "float32"})
    x = np.random.default_rng(0).random((1, 32, 32, 3), np.float32)
    out = np.asarray(b.apply_fn(b.params, x))
    assert out.shape == (1, 32, 32, 5)
    assert np.isfinite(out).all()


class TestYolov5s:
    """Real-geometry CSP-YOLOv5s (VERDICT r3 Missing #3): the faithful
    CSPDarknet+SPPF+PANet detector at the reference's compute class."""

    def test_output_layout_and_param_count(self):
        import jax

        from nnstreamer_tpu.models import yolo
        from nnstreamer_tpu.models.zoo import build

        b = build("yolov5s", {"size": "128", "classes": "80", "batch": "1",
                              "dtype": "float32"})
        x = np.zeros((1, 128, 128, 3), np.float32)
        out = np.asarray(b.apply_fn(b.params, x))
        n = yolo.num_predictions_v5s(128)
        assert out.shape == (1, n, 85)
        # parameter count within 5% of ultralytics yolov5s (7.2M)
        nparams = sum(int(np.prod(np.asarray(l).shape))
                      for l in jax.tree.leaves(b.params))
        assert abs(nparams - 7.2e6) / 7.2e6 < 0.05
        # sigmoid activations in range; background objectness prior
        assert (out[..., 4:] >= 0).all() and (out[..., 4:] <= 1).all()
        assert float(np.median(out[..., 4])) < 0.1

    def test_flops_scale_to_real_geometry(self):
        """~17 GF/frame at 640 implies ~0.68 GF at 128 (flops scale with
        area); the compiled cost analysis must land in that class — this
        is the check that the model is NOT the toy backbone."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.zoo import build

        b = build("yolov5s", {"size": "128", "batch": "1",
                              "dtype": "float32"})
        ca = jax.jit(b.apply_fn).lower(
            b.params, jnp.zeros((1, 128, 128, 3))).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        gf = ca.get("flops", 0.0) / 1e9
        # 17 GF @640 -> 0.68 GF @128; allow compiler-accounting slack
        assert gf > 0.5, f"yolov5s @128 reports only {gf} GF"

    def test_decoder_compatibility(self):
        """v5s output feeds bounding_boxes option1=yolov5 unchanged."""
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.decoders.bounding_boxes import BoundingBoxes
        from nnstreamer_tpu.models.zoo import build

        b = build("yolov5s", {"size": "128", "classes": "10", "batch": "1",
                              "dtype": "float32"})
        rng = np.random.default_rng(0)
        x = rng.random((1, 128, 128, 3), np.float32)
        out = np.asarray(b.apply_fn(b.params, x))[0]
        d = BoundingBoxes({"option1": "yolov5", "option3": "0.0",
                           "option4": "128:128", "option9": "tensors"})
        res = d.decode([out], Buffer([out]))
        assert len(res.meta["detections"]) > 0  # threshold 0: something

    def test_fused_pipeline_e2e(self):
        import nnstreamer_tpu as nt

        p = nt.Pipeline(
            "videotestsrc device=true batch=2 num-buffers=4 width=96 "
            "height=96 pattern=ball name=src ! "
            "tensor_transform mode=arithmetic "
            "option=typecast:float32,div:255.0 ! "
            "tensor_filter framework=jax model=yolov5s "
            "custom=size:96,classes:7,batch:2,dtype:float32 name=f ! "
            "tensor_decoder mode=bounding_boxes option1=yolov5 option3=0.3 "
            "option4=96:96 option7=device option9=tensors ! "
            "tensor_sink name=out")
        with p:
            b = p.pull("out", timeout=600)
            p.wait(timeout=120)
        assert len(b.tensors) == 4
        assert b.tensors[0].shape[0] == 2
