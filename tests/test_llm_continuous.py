"""Continuous LLM serving over the block-paged KV cache (ISSUE 6).

Covers the contracts docs/SERVING.md §4 promises:

* paged decode emits token-for-token what the dense-cache per-request
  path emits, at EVERY occupancy 1..slots;
* the block allocator never leaks across stream churn and a recycled
  slot never sees a previous stream's cache rows;
* chunked prefill equals monolithic prefill;
* the standing loop's program census is CLOSED: stream join/leave/
  complete causes ZERO new XLA compilations once the loop is warm
  (the fixed-decode-signature pin);
* the Pallas paged-attention kernel (interpret mode on CPU) matches the
  reference formulation block for block;
* int4 continuous serving routes through the same paged path and
  matches the int4 per-request stream;
* the deep lint prices the block pool + the continuous decode programs.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.models import llama


def _fw(custom, model="llama_tiny"):
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": model, "custom": custom})
    return fw


def _plain_tokens(prompt, custom, model="llama_tiny"):
    """Reference: the per-request streaming path (dense KV cache)."""
    fw = _fw(custom, model)
    try:
        return [int(ids[0]) for ids, *_ in fw.invoke_stream([prompt])]
    finally:
        fw.close()


def _serve_tokens(fw, prompts, timeout=300.0):
    """Submit ``prompts`` into a continuous loop together; returns the
    per-stream ordered token lists."""
    import threading

    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
    assert fw.drain(timeout=timeout)
    return got


BASE = "max_new:5,stream_chunk:2,temperature:0.0,dtype:float32"


class TestPagedVsDense:
    def test_bit_identical_at_every_occupancy(self):
        """occupancy k = k prompts admitted together into a slots=4 loop;
        every stream must emit exactly its independent dense-path ids."""
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 500, (t,), dtype=np.int32)
                   for t in (3, 7, 5, 9)]
        want = [_plain_tokens(p, BASE) for p in prompts]
        fw = _fw(BASE + ",serve:continuous,slots:4,block_size:8")
        try:
            for k in range(1, 5):
                got = _serve_tokens(fw, prompts[:k])
                for i in range(k):
                    assert got[i] == want[i], f"occupancy {k}, stream {i}"
        finally:
            fw.close()

    def test_chunked_prefill_matches_monolithic(self):
        # 19 tokens with prefill_chunk:4 -> 5 chunks (last chunk: 3 real
        # rows + 1 pad); the dense reference prefills all 19 in one shot.
        rng = np.random.default_rng(1)
        prompt = rng.integers(1, 500, (19,), dtype=np.int32)
        want = _plain_tokens(prompt, BASE)
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:8,"
                 "prefill_chunk:4")
        try:
            got = _serve_tokens(fw, [prompt])
        finally:
            fw.close()
        assert got[0] == want

    def test_int4_paged_matches_int4_stream(self):
        # satellite: the paged decode must route through the SAME
        # nibble-packed mats (_INT4_GROUPS fused qkv/gate-up) as the
        # static int4 path — greedy ids prove the routing end to end.
        rng = np.random.default_rng(2)
        prompt = rng.integers(1, 500, (6,), dtype=np.int32)
        base = BASE + ",quant:int4"
        want = _plain_tokens(prompt, base)
        fw = _fw(base + ",serve:continuous,slots:2,block_size:8")
        try:
            got = _serve_tokens(fw, [prompt])
        finally:
            fw.close()
        assert got[0] == want


class TestBlockAllocator:
    def test_churn_frees_every_block_and_slot(self):
        # kv_blocks sized so TWO streams fit but three defer: admission
        # must serialize the overflow, every stream must finish, and the
        # pool must drain back to fully free.
        fw = _fw(BASE + ",serve:continuous,slots:2,block_size:4,"
                 "kv_blocks:8")
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 500, (t,), dtype=np.int32)
                   for t in (3, 6, 4, 8, 5)]
        try:
            got = _serve_tokens(fw, prompts)
            assert all(len(v) == 5 for v in got.values())
            serve = fw._serve
            assert sorted(serve._free) == list(range(serve.n_blocks))
            assert (serve._tables == serve.sentinel).all()
            assert all(not b for b in serve._slot_blocks)
            assert (serve._pos == serve.park).all()
        finally:
            fw.close()

    def test_recycled_slot_emits_reference_tokens(self):
        # slots:1 forces every stream through the SAME slot; stream i+1
        # decodes over blocks stream i just freed.  Any stale row leaking
        # through a recycled block/table would corrupt the greedy ids.
        rng = np.random.default_rng(4)
        prompts = [rng.integers(1, 500, (t,), dtype=np.int32)
                   for t in (4, 9, 6)]
        want = [_plain_tokens(p, BASE) for p in prompts]
        fw = _fw(BASE + ",serve:continuous,slots:1,block_size:4")
        try:
            got = _serve_tokens(fw, prompts)
        finally:
            fw.close()
        for i in range(3):
            assert got[i] == want[i], f"stream {i} after slot recycle"

    def test_impossible_reservation_rejected_not_wedged(self):
        # pool of 8 tokens total (kv_blocks:2 x block_size:4); a legal
        # (< max_seq) prompt whose T+max_new reservation can NEVER fit
        # must be rejected with stream_aborted — deferring would wedge
        # the FIFO head forever — and the loop stays serviceable.
        fw = _fw(BASE + ",serve:continuous,slots:1,block_size:4,"
                 "kv_blocks:2")
        metas = []
        try:
            fw.submit([np.arange(1, 8, dtype=np.int32)], {},
                      lambda t, m: metas.append(m))  # T=7, n=5 -> 12 > 8
            assert fw.drain(timeout=60)
            assert metas and metas[0].get("stream_aborted") is True
            got = _serve_tokens(fw, [np.array([1, 2, 3], np.int32)])
            assert len(got[0]) == 5  # a fitting prompt still completes
        finally:
            fw.close()

    def test_oversize_prompt_rejected_with_abort(self):
        fw = _fw(BASE + ",serve:continuous,slots:1,max_seq:64")
        metas = []
        try:
            fw.submit([np.ones((64,), np.int32)], {},
                      lambda t, m: metas.append(m))
            assert fw.drain(timeout=60)
        finally:
            fw.close()
        assert metas and metas[0].get("stream_aborted") is True
        assert metas[0].get("stream_last") is True


class TestFixedDecodeSignature:
    def test_zero_recompiles_across_join_leave_complete(self):
        """The compile-counter pin: once the loop is warm, admitting
        streams of NEW lengths, draining them, and re-admitting must not
        compile anything — block tables/positions/occupancy are VALUES,
        not shapes, in every program the loop runs."""
        fw = _fw(BASE + ",serve:continuous,slots:3,block_size:8,"
                 "prefill_chunk:4")
        rng = np.random.default_rng(5)
        try:
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32)])
            serve = fw._serve
            warm = {
                "decode": serve._decode._cache_size(),
                "prefill": serve._prefill._cache_size(),
                "set_tok": serve._set_tok._cache_size(),
            }
            assert warm == {"decode": 1, "prefill": 1, "set_tok": 1}
            # churn: new lengths, concurrent joins, full drain, rejoin
            _serve_tokens(fw, [rng.integers(1, 500, (t,), np.int32)
                               for t in (1, 7, 13)])
            _serve_tokens(fw, [rng.integers(1, 500, (9,), np.int32)])
            after = {
                "decode": serve._decode._cache_size(),
                "prefill": serve._prefill._cache_size(),
                "set_tok": serve._set_tok._cache_size(),
            }
        finally:
            fw.close()
        assert after == warm, f"recompile on churn: {warm} -> {after}"


class TestPagedForward:
    """models/llama.py forward_paged against the dense forward_cached."""

    def test_matches_dense_cache_logits(self):
        import jax.numpy as jnp

        cfg = llama.PRESETS["llama_tiny"]
        params = llama.init_params(cfg, seed=0)
        rng = np.random.default_rng(6)
        T = 5
        prompt = rng.integers(1, cfg.vocab, (1, T), np.int32)

        dense = llama.init_cache(cfg, 1, dtype="float32")
        ref, dense = llama.forward_cached(params, prompt, dense, 0, cfg,
                                          compute_dtype="float32")
        nxt = np.array([[7]], np.int32)
        ref2, _ = llama.forward_cached(params, nxt, dense, T, cfg,
                                       compute_dtype="float32")

        bs, max_blocks = 4, 8
        pool = llama.init_paged_cache(cfg, 16, bs, dtype="float32")
        tables = np.full((1, max_blocks), 16, np.int32)
        tables[0, :3] = [11, 2, 7]  # 3 blocks cover T+1 <= 12 rows
        lg, pool = llama.forward_paged(
            params, jnp.asarray(prompt), pool, jnp.asarray(tables),
            jnp.zeros((1,), jnp.int32), cfg, compute_dtype="float32")
        np.testing.assert_allclose(np.asarray(lg[:, -1]),
                                   np.asarray(ref[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        lg2, _ = llama.forward_paged(
            params, jnp.asarray(nxt), pool, jnp.asarray(tables),
            jnp.full((1,), T, jnp.int32), cfg, compute_dtype="float32")
        np.testing.assert_allclose(np.asarray(lg2[:, 0]),
                                   np.asarray(ref2[:, 0]),
                                   rtol=2e-4, atol=2e-4)

    def test_parked_row_never_writes_pool(self):
        import jax.numpy as jnp

        cfg = llama.PRESETS["llama_tiny"]
        params = llama.init_params(cfg, seed=0)
        bs, max_blocks = 4, 8
        pool = llama.init_paged_cache(cfg, 6, bs, dtype="float32")
        before = np.asarray(pool["k"]).copy()
        tables = np.full((2, max_blocks), 6, np.int32)
        tables[0, 0] = 3  # row 0 live in block 3; row 1 parked
        toks = np.array([[5], [5]], np.int32)
        pos = jnp.asarray(np.array([0, max_blocks * bs], np.int32))
        _, pool = llama.forward_paged(
            params, jnp.asarray(toks), pool, jnp.asarray(tables), pos,
            cfg, compute_dtype="float32")
        after = np.asarray(pool["k"])
        assert not np.array_equal(after[:, 3], before[:, 3])  # live wrote
        mask = np.ones(6, bool)
        mask[3] = False  # every OTHER block untouched
        np.testing.assert_array_equal(after[:, mask], before[:, mask])


class TestPagedAttentionKernel:
    def _case(self, rng, B=4, H=4, hkv=2, D=16, bs=8, n_blocks=16,
              max_blocks=4, lens=(1, 5, 8, 29)):
        import jax.numpy as jnp

        q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
        k_pool = jnp.asarray(
            rng.standard_normal((n_blocks, bs, hkv, D)), jnp.float32)
        v_pool = jnp.asarray(
            rng.standard_normal((n_blocks, bs, hkv, D)), jnp.float32)
        tables = np.full((B, max_blocks), n_blocks, np.int32)
        blocks = rng.permutation(n_blocks)
        i = 0
        for b, ln in enumerate(lens):
            need = -(-ln // bs)
            tables[b, :need] = blocks[i:i + need]
            i += need
        lens = jnp.asarray(np.asarray(lens, np.int32))
        return q, k_pool, v_pool, jnp.asarray(tables), lens

    def test_interpret_kernel_matches_reference(self):
        from nnstreamer_tpu.ops.attention import (
            paged_attention, paged_attention_reference)

        rng = np.random.default_rng(7)
        q, kp, vp, tbl, lens = self._case(rng)
        got = np.asarray(paged_attention(q, kp, vp, tbl, lens,
                                         interpret=True))
        ref = np.asarray(paged_attention_reference(q, kp, vp, tbl, lens))
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_idle_row_zero_output_no_dma(self):
        # context len 0 = idle slot: the kernel's fori_loop runs zero
        # iterations (no block DMA) and the row emits finite zeros.
        from nnstreamer_tpu.ops.attention import paged_attention

        rng = np.random.default_rng(8)
        q, kp, vp, tbl, _ = self._case(rng)
        import jax.numpy as jnp

        lens = jnp.asarray(np.array([0, 5, 0, 29], np.int32))
        got = np.asarray(paged_attention(q, kp, vp, tbl, lens,
                                         interpret=True))
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got[0], np.zeros_like(got[0]))
        np.testing.assert_array_equal(got[2], np.zeros_like(got[2]))


class TestServingTelemetry:
    def test_prefill_pad_waste_counter(self):
        # 5 real tokens with prefill_chunk:8 -> one 8-row chunk, waste 3
        # (the satellite replacing power-of-two bucketing's up-to-2x).
        before = metrics.snapshot()
        fw = _fw(BASE + ",serve:continuous,slots:1,block_size:4,"
                 "prefill_chunk:8")
        try:
            _serve_tokens(fw, [np.array([3, 1, 4, 1, 5], np.int32)])
        finally:
            fw.close()
        after = metrics.snapshot()

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        assert delta("llm.serve.prefill_tokens") == 8
        assert delta("llm.serve.prefill_pad_waste") == 3

    def test_serve_spans_recorded_through_pipeline(self):
        # trace_mode=ring + the element->framework recorder handoff:
        # admit/prefill-chunk/decode spans land in the flight recorder.
        import nnstreamer_tpu as nt
        from nnstreamer_tpu.utils import tracing

        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter framework=llm "
            "model=llama_tiny custom=max_new:4,serve:continuous,slots:2,"
            "temperature:0.0,block_size:8 invoke-dynamic=true ! "
            "tensor_sink name=out", trace_mode="ring")
        with p:
            p.push("src", np.array([1, 5, 9, 2], np.int32))
            bufs = [p.pull("out", timeout=120) for _ in range(4)]
            p.eos("src")
            p.wait(timeout=120)
        assert sum(1 for b in bufs if b.meta.get("stream_last")) == 1
        kinds = {e.kind for e in tracing.recorder.events()
                 if e.stage == "llm.serve"}
        assert {"serve.admit", "serve.prefill_chunk",
                "serve.decode"} <= kinds
        # the taxonomy documents what it records
        for k in ("serve.admit", "serve.prefill_chunk", "serve.decode"):
            assert k in tracing.SPAN_KINDS


class TestDeepLintPricing:
    DESC = ("appsrc name=src ! tensor_filter framework=llm "
            "model=llama_tiny custom=max_new:4,serve:continuous,slots:2,"
            "block_size:8,prefill_chunk:8 invoke-dynamic=true ! "
            "tensor_sink name=out")

    def test_pool_and_programs_priced(self):
        import nnstreamer_tpu as nt
        from nnstreamer_tpu.filters.llm import serving_plan

        report = nt.analyze(self.DESC, deep=True)
        stage = next(s for s in report.resources.stages if s.pool_bytes)
        cfg = llama.PRESETS["llama_tiny"]
        plan = serving_plan(cfg, slots=2, block_size=8, prefill_chunk=8)
        assert stage.pool_bytes == plan["pool_bytes"]
        assert stage.pool_bytes == llama.paged_cache_bytes(
            cfg, plan["n_blocks"], 8)
        assert stage.variants == plan["programs"] == 3
        assert stage.param_bytes == llama.param_bytes_estimate(cfg)
        # the pool is in the high-water total and the census
        assert report.resources.hbm_estimate >= stage.pool_bytes
        assert report.resources.compiled_variants >= 3
        assert "kv pool" in report.resources.render()
        # no recompile-unbounded: the serving signature is CLOSED
        assert not any(d.code == "recompile-unbounded" for d in report)

    def test_budget_warning_names_the_pool(self):
        import nnstreamer_tpu as nt

        report = nt.analyze(self.DESC, deep=True, hbm_budget_bytes=1024)
        diag = next(d for d in report if d.code == "hbm-budget")
        assert "kv pool" in diag.message

    def test_checkpoint_model_is_unpriced_not_unbounded(self):
        import nnstreamer_tpu as nt

        desc = self.DESC.replace("model=llama_tiny",
                                 "model=/nonexistent/llm.gguf")
        report = nt.analyze(desc, deep=True)
        codes = [d.code for d in report]
        assert "serving-unpriced" in codes
        assert "recompile-unbounded" not in codes


class TestServingPlan:
    def test_worst_case_pool_and_table_span(self):
        from nnstreamer_tpu.filters.llm import serving_plan

        cfg = llama.PRESETS["llama_tiny"]  # max_seq 256
        plan = serving_plan(cfg, slots=4, block_size=16, prefill_chunk=32)
        assert plan["n_blocks"] == 4 * 16  # slots * ceil(256/16)
        # table spans the largest chunk-padded prompt: ceil(255/32)*32=256
        assert plan["max_blocks"] == 16
        assert plan["pool_bytes"] == llama.paged_cache_bytes(cfg, 64, 16)

    def test_kv_blocks_clamped_to_worst_case(self):
        from nnstreamer_tpu.filters.llm import serving_plan

        cfg = llama.PRESETS["llama_tiny"]
        plan = serving_plan(cfg, slots=2, block_size=16, kv_blocks=10_000)
        assert plan["n_blocks"] == 2 * 16
        small = serving_plan(cfg, slots=2, block_size=16, kv_blocks=5)
        assert small["n_blocks"] == 5


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
