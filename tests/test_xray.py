"""nns-xray: predicted-vs-actual reconciliation (ISSUE 13 tentpole).

The contract: with ``Pipeline(xray=True)`` every jit entry point
registers its compiles with the process-wide program registry, which
reconciles the live program set against the deep lint's predicted
census — an unpredicted signature (count past the budget, or a trigger
batch dim outside the ladder) fires ``census-drift`` with the
field-level signature diff and a flight-recorder dump; clean pipelines
(including the llm 3-program serve loop under churn and the device
aggregator) measure drift == 0.  Device time is attributed per stage
(``mfu`` / ``roofline_fraction`` / ``pad_waste_flops`` gauges + a
``device:<stage>`` Chrome-trace track), the HBM ledger reconciles
measured bytes against the deep-lint estimate per category, and
``Pipeline.explain()`` / the doctor CLI join everything into one
JSON-serializable report.  With xray OFF, the hooks are structurally
inert (registry methods monkeypatched to raise — the trace_mode=off
discipline) and every pipeline-owned thread stops on ``stop()``.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.utils import tracing, xray
from nnstreamer_tpu.utils.profiler import (OPENMETRICS_CONTENT_TYPE,
                                           metrics_text,
                                           start_metrics_server,
                                           stop_metrics_server)
from nnstreamer_tpu.utils.tracing import recorder
from nnstreamer_tpu.utils.xray import (ProgramRegistry, TrackedProgram,
                                       abstract_signature,
                                       explain_signature_drift, registry)

DIMS = 16
DESC = (
    f"appsrc name=src caps=other/tensors,dimensions={DIMS},types=float32 ! "
    f"tensor_filter framework=jax model=scaler custom=scale:2.0,dims:{DIMS} "
    "name=f ! tensor_sink name=out"
)


@pytest.fixture(autouse=True)
def _clean_state():
    metrics.reset()
    registry.reset()
    recorder.configure("off")
    recorder.clear()
    yield
    metrics.reset()
    registry.reset()
    recorder.configure("off")
    recorder.clear()


def _frames(n, dims=DIMS):
    return [np.full((dims,), float(i % 7), np.float32) for i in range(n)]


def _run(desc, frames, timeout=120, explain=False, **kw):
    p = nt.Pipeline(desc, **kw)
    outs, rep = [], None
    try:
        p.start()
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
        if explain:
            rep = p.explain()  # BEFORE stop(): the ledger reads live fws
    finally:
        p.stop()
    return (outs, rep) if explain else outs


# -- signatures -------------------------------------------------------------

def test_abstract_signature_distinguishes_weak_scalars():
    import jax.numpy as jnp

    a = abstract_signature((jnp.zeros((4,), jnp.int32), np.int32(0)), {})
    b = abstract_signature((jnp.zeros((4,), jnp.int32), 0), {})
    assert a != b
    assert a[1][0] == "t" and b[1] == ("py", "int")


def test_signature_drift_diff_names_the_field():
    import jax.numpy as jnp

    base = abstract_signature((jnp.zeros((4,), jnp.float32),), {})
    drifted = abstract_signature((jnp.zeros((8,), jnp.float32),), {})
    diff = explain_signature_drift(drifted, base)
    assert "8" in diff and "4" in diff
    # python-scalar leaves fall back to the leaf-level diff
    trap = abstract_signature((0,), {})
    one = abstract_signature((np.int32(0),), {})
    diff = explain_signature_drift(trap, one)
    assert "py:int" in diff
    assert "arity" in explain_signature_drift(base, base + base)


# -- tracked programs / registry -------------------------------------------

def test_tracked_program_registers_compiles_and_costs():
    import jax

    reg = ProgramRegistry()
    fn = reg.track(jax.jit(lambda x: x * 2.0), "s1", "stage")
    assert isinstance(fn, TrackedProgram)
    fn(np.ones((4,), np.float32))
    fn(np.ones((4,), np.float32))  # cache hit: a dispatch, not a compile
    fn(np.ones((8,), np.float32))  # new signature
    census = reg.census()
    e = census["s1/stage"]
    assert e["live_compiles"] == 2
    assert len(e["live_signatures"]) == 2
    assert metrics.snapshot().get("s1.compiles") == 2
    assert fn.flops > 0  # lowered cost analysis attached
    assert fn.disp_n == 1 and fn.disp_ns > 0
    # track() is idempotent; delegation keeps the jit surface usable
    assert reg.track(fn, "s1", "stage") is fn
    assert fn._cache_size() == 2


def test_budget_overflow_fires_census_drift_with_diff(caplog):
    """The PR 11 ``_set_tok`` trap, reproduced at the registry level: a
    numpy-scalar argument mints a second signature past the 1-program
    budget and must fire census-drift carrying the field-level diff."""
    import jax
    import jax.numpy as jnp
    import logging

    reg = ProgramRegistry()
    reg.expect("llm.serve", "set_tok", budget=1)
    fn = reg.track(jax.jit(lambda a, i, v: a.at[i].set(v)),
                   "llm.serve", "set_tok")
    tok = jnp.zeros((4,), jnp.int32)
    fn(tok, np.int32(0), np.int32(5))  # the predicted signature
    assert reg.drift_count() == 0
    with caplog.at_level(logging.WARNING):
        fn(tok, 0, np.int32(5))  # python int: weak-typed — the trap
    assert reg.drift_count() == 1
    d = reg.drifts()[0]
    assert d["stage"] == "llm.serve" and d["kind"] == "set_tok"
    assert "exceed the predicted census of 1" in d["reason"]
    assert "py:int" in d["diff"]
    assert metrics.snapshot().get("xray.census_drifts") == 1
    assert any("census-drift" in r.message for r in caplog.records)
    # the storm throttle: further drifts on the SAME key count but warn
    # at debug only (one ring dump per key — the watchdog discipline)
    with caplog.at_level(logging.WARNING):
        caplog.clear()
        fn(tok, np.int64(1), np.float32(2.0))  # a 3rd signature
    assert reg.drift_count() == 2
    assert metrics.snapshot().get("xray.census_drifts") == 2
    assert not any(r.levelno >= logging.WARNING for r in caplog.records)


def test_ladder_allow_set_fires_drift_on_unpredicted_bucket():
    import jax

    reg = ProgramRegistry()
    reg.expect("f", "batch", budget=3, allow={1, 2, 4})
    prog = reg.track(jax.jit(lambda x: x + 1), "f", "batch", rows=3)
    prog(np.ones((3, 4), np.float32))
    assert reg.drift_count() == 1
    assert "not in the predicted bucket ladder" in reg.drifts()[0]["reason"]


def test_reinstalled_expectation_retires_stale_drift():
    """A fresh expectation (a new pipeline generation for the stage)
    resets the live count AND retires the key's past drift verdicts —
    a clean successor must not inherit a predecessor's findings."""
    import jax

    reg = ProgramRegistry()
    reg.expect("s", "stage", budget=1)
    fn = reg.track(jax.jit(lambda x: x), "s", "stage")
    fn(np.ones((2,), np.float32))
    fn(np.ones((3,), np.float32))
    assert reg.drift_count() == 1
    reg.expect("s", "stage", budget=1)  # pipeline generation 2
    assert reg.drift_count() == 0
    assert reg.census()["s/stage"]["live_compiles"] == 0


def test_drift_dumps_ring_and_records_span():
    import jax

    recorder.configure("ring")
    recorder.record("stage", "ctx", 1, time.monotonic_ns(), 1000)
    reg = ProgramRegistry()
    reg.expect("s", "stage", budget=1)
    fn = reg.track(jax.jit(lambda x: x), "s", "stage")
    fn(np.ones((2,), np.float32))
    fn(np.ones((3,), np.float32))  # over budget
    kinds = {e.kind for e in recorder.events()}
    assert "xray.drift" in kinds


# -- pipeline end-to-end ----------------------------------------------------

def test_clean_pipeline_census_drift_zero_and_gauges():
    outs, rep = _run(DESC, _frames(32), queue_capacity=32, batch_max=4,
                     data_parallel=1, xray=True, trace_mode="ring",
                     explain=True)
    assert len(outs) == 32
    assert rep["census"]["drift_total"] == 0
    assert registry.drift_count() == 0
    progs = rep["census"]["programs"]
    assert progs["f/batch"]["predicted"] == 3  # ladder(4) = (1, 2, 4)
    assert progs["f/batch"]["allow"] == [1, 2, 4]
    assert progs["f/batch"]["within"] and progs["f/stage"]["within"]
    # at least one compile registered somewhere on the filter stage
    snap = metrics.snapshot()
    assert snap.get("f.compiles", 0) >= 1
    # gauges land in the Prometheus exposition after a reconciler tick
    registry.publish()
    text = metrics_text()
    assert "nnstpu_f_mfu" in text
    assert "nnstpu_f_roofline_fraction" in text
    assert "nnstpu_xray_census_drift 0" in text
    # report is the doctor CLI's machine-readable twin
    json.dumps(rep)
    assert rep["ok"] is True
    assert rep["plan"]["batch_max"] == 4
    assert rep["hbm"]["categories"]["params"]["ok"]


def test_sharded_census_stays_clean():
    """Under the 8-virtual-device data mesh the sharded single-program
    path's per-bucket signatures are shard-rounded — still inside the
    predicted allow set, drift 0."""
    outs, rep = _run(DESC, _frames(24), queue_capacity=32, batch_max=4,
                     data_parallel=2, xray=True, explain=True)
    assert len(outs) == 24
    assert rep["census"]["drift_total"] == 0
    e = rep["census"]["programs"]["f/batch"]
    assert e["within"] and e["allow"] == [1, 2, 4]


def test_pad_waste_flops_counts_padded_rows():
    """3 same-spec buffers pushed into a batch_max=4 runner with linger:
    the drain pads 3 -> 4 and the pad waste is priced in FLOPs."""
    outs = _run(DESC, _frames(3), queue_capacity=16, batch_max=4,
                data_parallel=1, batch_linger_ms=150.0, xray=True)
    assert len(outs) == 3
    snap = metrics.snapshot()
    if snap.get("f.batch_pad_waste", 0) > 0:  # a 3-row drain happened
        assert snap.get("f.pad_waste_flops", 0) > 0


def test_device_track_in_chrome_trace(tmp_path):
    _run(DESC, _frames(24), queue_capacity=32, batch_max=4, xray=True,
         trace_mode="ring")
    out = tmp_path / "trace.json"
    tracing.dump_chrome(recorder.events(), str(out))
    with open(out) as f:
        obj = json.load(f)
    assert not tracing.validate_chrome(obj)
    names = {e["args"]["name"] for e in obj["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert any(n.startswith("device:") for n in names)
    assert any(e.get("name") == "device" for e in obj["traceEvents"])


def test_hbm_ledger_params_match_deep_estimate():
    _, rep = _run(DESC, _frames(8), queue_capacity=16, batch_max=4,
                  xray=True, explain=True)
    params = rep["hbm"]["categories"]["params"]
    assert params["predicted"] is not None and params["predicted"] > 0
    assert params["measured"] == params["predicted"]  # same accounting
    assert params["ratio"] == 1.0
    for cat in ("kv_pool", "agg_rings", "activations"):
        assert rep["hbm"]["categories"][cat]["ok"]


def test_second_pipeline_same_stage_names_no_false_drift():
    """The registry is process-wide: a second pipeline re-using stage
    names re-installs its expectations, which must RESET the live
    counts — its own warmup compiles are not drift."""
    for _ in range(2):
        _, rep = _run(DESC, _frames(12), queue_capacity=16, batch_max=4,
                      data_parallel=1, xray=True, explain=True)
        assert rep["census"]["drift_total"] == 0
        assert rep["census"]["programs"]["f/batch"]["within"]
    assert registry.drift_count() == 0


def test_explain_works_without_xray():
    _, rep = _run(DESC, _frames(4), queue_capacity=8, explain=True)
    assert rep["xray"] is False
    assert rep["census"]["programs"] == {}
    assert rep["ok"] is True
    json.dumps(rep)


def test_explain_after_stop_does_not_reload_frameworks():
    """The ledger probe on a STOPPED pipeline must not resurrect closed
    frameworks (param_bytes() lazily reloads — at llm scale that is a
    multi-GiB checkpoint load just to read a byte count)."""
    p = nt.Pipeline(DESC, queue_capacity=8, xray=True)
    with p:
        p.push("src", nt.Buffer([_frames(1)[0]]))
        p.pull("out", timeout=60)
        p.eos()
        p.wait(timeout=60)
    assert p.element("f").fw is None  # stop() closed it
    rep = p.explain()
    assert p.element("f").fw is None  # ...and explain() left it closed
    assert rep["hbm"]["categories"]["params"]["measured"] == 0


# -- the off pin ------------------------------------------------------------

def test_xray_off_structural_pin(monkeypatch):
    """With xray off (the default) the registry must be STRUCTURALLY
    bypassed: every registry entry point monkeypatched to raise, and a
    batched + traced pipeline still completes — the disabled hook is one
    pointer check, no wrappers, no cost_analysis."""

    def boom(*a, **k):
        raise AssertionError("xray hook ran with xray off")

    monkeypatch.setattr(ProgramRegistry, "track", boom)
    monkeypatch.setattr(ProgramRegistry, "register", boom)
    monkeypatch.setattr(ProgramRegistry, "expect", boom)
    monkeypatch.setattr(TrackedProgram, "__call__", boom)
    outs = _run(DESC, _frames(12), queue_capacity=16, batch_max=4,
                trace_mode="ring")
    assert len(outs) == 12
    assert registry.drift_count() == 0
    assert "compiles" not in metrics_text()


# -- llm serve loop + aggregator census ------------------------------------

LLM_BASE = "max_new:4,stream_chunk:2,temperature:0.0,dtype:float32"


def _llm_fw(xray_on=True):
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": "llama_tiny",
             "custom": LLM_BASE + ",serve:continuous,slots:2,block_size:8"})
    if xray_on:
        fw.attach_xray(registry, "llm")
    return fw


def _serve(fw, prompts, timeout=300.0):
    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
    assert fw.drain(timeout=timeout)
    return got


def test_llm_serve_loop_census_clean_under_churn():
    """The PR 6 acceptance twin, measured live: stream churn through the
    continuous loop compiles EXACTLY the 3 predicted programs — measured
    census drift 0, live program set == serving_plan()'s census."""
    rng = np.random.default_rng(3)
    fw = _llm_fw()
    try:
        for wave in range(3):  # join/leave/complete churn
            prompts = [rng.integers(1, 500, (t,), dtype=np.int32)
                       for t in (3, 6)]
            got = _serve(fw, prompts)
            assert all(len(v) for v in got.values())
        census = registry.census()
        for kind in ("decode", "prefill", "set_tok"):
            e = census[f"llm.serve/{kind}"]
            assert e["predicted"] == 1
            assert e["live_compiles"] == 1, (kind, e)
            assert e["within"]
        assert registry.drift_count() == 0
        snap = metrics.snapshot()
        assert snap.get("llm.serve.compiles") == 3
    finally:
        fw.close()


def test_llm_set_tok_numpy_scalar_trap_fires_drift_in_pipeline():
    """The golden DRIFTED pipeline: a serving pipeline deliberately
    mints the unpredicted 4th signature (the PR 11 trap — a weak-typed
    python scalar where the loop always passes strongly typed arrays) —
    census-drift must fire carrying the signature diff, while the run
    up to that point measured drift 0."""
    import jax.numpy as jnp

    p = nt.Pipeline(
        "appsrc name=src ! tensor_filter framework=llm "
        "model=llama_tiny custom=max_new:4,serve:continuous,slots:2,"
        "temperature:0.0,block_size:8 invoke-dynamic=true name=f ! "
        "tensor_sink name=out", xray=True, trace_mode="ring")
    try:
        p.start()
        p.push("src", np.array([1, 5, 9, 2], np.int32))
        bufs = [p.pull("out", timeout=120) for _ in range(4)]
        assert sum(1 for b in bufs if b.meta.get("stream_last")) == 1
        assert registry.drift_count() == 0  # the clean serve measured 0
        # the ledger closes exactly on the serving categories: live
        # params AND the paged pool match the deep-lint estimate
        clean = p.explain()
        for cat in ("params", "kv_pool"):
            c = clean["hbm"]["categories"][cat]
            assert c["measured"] > 0 and c["measured"] == c["predicted"]
        loop = p.element("f").fw._serve
        # a FRESH donated array (never the loop's own tok state); the
        # python-int index is the weak-typed trap
        loop._set_tok(jnp.zeros((2,), jnp.int32), 0, np.int32(7))
        assert registry.drift_count() == 1
        d = registry.drifts()[0]
        # the serve census is keyed by the ELEMENT's stage name (+.serve)
        assert d["stage"] == "f.serve" and d["kind"] == "set_tok"
        assert "py:int" in d["diff"]
        rep = p.explain()
        assert rep["ok"] is False
        assert rep["census"]["drift_total"] == 1
        assert any(e.kind == "xray.drift"
                   for e in recorder.events())
        p.eos("src")
        p.wait(timeout=120)
    finally:
        p.stop()


def test_aggregator_device_census_is_three_programs():
    desc = ("appsrc name=src caps=other/tensors,dimensions=8,"
            "types=float32 ! tensor_aggregator frames_in=1 frames_out=4 "
            "frames_dim=0 device=true name=agg ! tensor_sink name=out")
    p = nt.Pipeline(desc, xray=True)
    try:
        p.start()
        for i in range(8):
            p.push("src", np.full((8,), float(i), np.float32))
        wins = [p.pull("out", timeout=60) for _ in range(2)]
        assert len(wins) == 2
        census = registry.census()
        e = census["agg/agg"]
        assert e["predicted"] == 3
        assert e["live_compiles"] == 3 and e["within"]
        assert registry.drift_count() == 0
        p.eos()
        p.wait(timeout=60)
    finally:
        p.stop()


# -- openmetrics + thread audit satellites ---------------------------------

def test_openmetrics_negotiation_and_scrape_twice_identical():
    metrics.count("f.compiles", 2)
    metrics.count("web.requests", 1, tenant="acme")  # labeled family
    metrics.gauge("xray.hbm.params", 1024.0)
    metrics.observe_latency("out.e2e_latency", 0.01, tenant="acme")
    srv = start_metrics_server()
    try:
        url = f"http://127.0.0.1:{srv.server_port}/metrics"
        req = urllib.request.Request(
            url, headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(req) as r:
            body1 = r.read().decode()
            assert r.headers["Content-Type"] == OPENMETRICS_CONTENT_TYPE
        assert body1.rstrip().endswith("# EOF")
        with urllib.request.urlopen(req) as r:
            body2 = r.read().decode()
        assert body1 == body2  # labeled + xray families scrape stable
        assert 'tenant="acme"' in body1
        assert "nnstpu_xray_hbm_params" in body1
        # OpenMetrics: typed counter SAMPLES carry the mandatory _total
        assert "nnstpu_f_compiles_total 2" in body1
        with urllib.request.urlopen(url) as r:  # no negotiation
            plain = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
        assert "# EOF" not in plain
        # the classic exposition is untouched: bare counter names, and
        # scraping it twice stays identical too
        assert "nnstpu_f_compiles 2" in plain
        assert "_total" not in plain
        with urllib.request.urlopen(url) as r:
            assert r.read().decode() == plain
    finally:
        stop_metrics_server(srv)


def test_all_pipeline_threads_stop_on_stop():
    """The shutdown audit: SLO engine, metrics sampler, and the xray
    reconciler all verifiably stop on Pipeline.stop() — assert via a
    threading.enumerate delta (a warmup run first absorbs jax's own
    lazily-spawned pools)."""
    slo = {"tenants": [{"tenant": "t", "p99_ms": 10000.0}]}
    kw = dict(queue_capacity=8, batch_max=2, xray=True, trace_mode="ring",
              slo=slo, tenant="t")
    _run(DESC, _frames(4), **kw)  # warmup: backend pools spawn here
    before = set(threading.enumerate())
    _run(DESC, _frames(4), **kw)
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"threads leaked past stop(): {leaked}"
    # the named pipeline threads specifically are gone
    names = {t.name for t in threading.enumerate()}
    for prefix in ("nns-sampler", "nns-xray", "nns-slo"):
        assert not any(n.startswith(prefix) for n in names), names


def test_journal_flusher_thread_stops_on_close(tmp_path):
    """The remaining audited daemon: a batch-fsync journal's flusher is
    alive while open and verifiably joined by close()."""
    from nnstreamer_tpu.utils.journal import Journal

    j = Journal(str(tmp_path / "wal"), fsync="batch")
    names = {t.name for t in threading.enumerate()}
    assert "nns-journal-flush" in names
    j.close()
    leaked = [t for t in threading.enumerate()
              if t.name == "nns-journal-flush" and t.is_alive()]
    assert not leaked


# -- doctor -----------------------------------------------------------------

def test_doctor_cli_bench_pipeline(tmp_path, capsys):
    from nnstreamer_tpu.tools import doctor

    out = tmp_path / "report.json"
    rc = doctor.main(["--frames", "48", "--json", str(out), "--gate"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines[0] == "census drift 0"
    assert lines[-1] == "doctor: OK"
    with open(out) as f:
        rep = json.load(f)
    assert rep["ok"] is True
    assert rep["census"]["drift_total"] == 0
    for cat in ("params", "kv_pool", "agg_rings", "activations"):
        assert rep["hbm"]["categories"][cat]["ok"]
