"""nns-armor (ISSUE 12, docs/ROBUSTNESS.md): poison-pill quarantine to
the DLQ, typed abort_reason=poison answers, the repeat-offender circuit
breaker, nan_guard, and the durable-journal pipeline wiring."""

import os
import socket
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.filters.custom_easy import register_custom_easy
from nnstreamer_tpu.utils import armor, tracing, wire
from nnstreamer_tpu.utils.armor import (
    CircuitBreaker, DeadLetterQueue, QuarantinePolicy, load_dlq_entry,
    poison_terminator)
from nnstreamer_tpu.utils.journal import replay_unanswered

SPEC = TensorsSpec.from_string("4", "float32")

#: requests whose first element is this value make the work stage raise
POISON_PILL = -666.0
#: ... and this one makes it emit NaN (the nan_guard trigger)
NAN_PILL = -777.0


def _register_work(name="armor-work"):
    def work(ins):
        v = float(np.asarray(ins[0]).ravel()[0])
        if v == POISON_PILL:
            raise RuntimeError("deliberately poisoned request")
        if v == NAN_PILL:
            out = np.asarray(ins[0], np.float32).copy()
            out[0] = np.nan
            return [out]
        return [np.asarray(ins[0], np.float32) * 2.0]

    register_custom_easy(name, work, in_spec=SPEC, out_spec=SPEC)


def _req(v, mid, tenant="t0"):
    return Buffer([np.full((4,), v, np.float32)],
                  meta={"_query_msg": mid, "_tenant": tenant})


class TestUnits:
    def test_policy_of(self, tmp_path):
        p = QuarantinePolicy.of(str(tmp_path))
        assert p.dir == str(tmp_path)
        p2 = QuarantinePolicy.of({"dir": "/x", "breaker_threshold": 5})
        assert p2.breaker_threshold == 5
        with pytest.raises(ValueError, match="unknown"):
            QuarantinePolicy.of({"nope": 1})
        with pytest.raises(ValueError):
            QuarantinePolicy.of(42)

    def test_dlq_roundtrip(self, tmp_path):
        dlq = DeadLetterQueue(str(tmp_path))
        buf = Buffer([np.arange(4, dtype=np.float32)],
                     meta={"_query_msg": 3, "_tenant": "bad"})
        path = dlq.put(buf, error="RuntimeError: boom", stage="f",
                       tenant="bad", ring=["  +0.0ms f stage 1.0ms"])
        got, _flags = load_dlq_entry(path)
        np.testing.assert_array_equal(got.tensors[0], buf.tensors[0])
        rec = got.meta[armor.META_DLQ]
        assert rec["error"] == "RuntimeError: boom"
        assert rec["stage"] == "f"
        assert rec["tenant"] == "bad"
        assert rec["ring"] and "stage" in rec["ring"][0]

    def test_dlq_bounded_evicts_oldest(self, tmp_path):
        dlq = DeadLetterQueue(str(tmp_path), max_entries=4)
        for i in range(9):
            dlq.put(Buffer([np.full((4,), float(i), np.float32)]),
                    error=f"e{i}", stage="f")
        entries = dlq.entries()
        assert len(entries) <= 4
        kept = [load_dlq_entry(p)[0].meta[armor.META_DLQ]["error"]
                for p in entries]
        assert kept[-1] == "e8"  # newest kept, oldest evicted
        assert "e0" not in kept

    def test_breaker_trip_edge_and_reset(self):
        flips = []
        br = CircuitBreaker(3, 10.0,
                            lambda t, engage: flips.append((t, engage)))
        assert not br.record_poison("a")
        assert not br.record_poison("a")
        assert br.record_poison("a")          # third inside window: trip
        assert not br.record_poison("a")      # latched: edge, not level
        assert "a" in br.tripped
        # the latch RE-ASSERTS on further poisons (self-healing against
        # the autoscaler popping the shared override) — same value,
        # never a new trip edge
        assert flips == [("a", True), ("a", True)]
        assert br.record_poison("b") is False  # independent per tenant
        assert br.reset("a")
        assert flips[-1] == ("a", False)
        assert not br.reset("a")  # idempotent

    def test_breaker_window_expires(self):
        br = CircuitBreaker(2, 0.05, lambda t, e: None)
        assert not br.record_poison("a")
        time.sleep(0.08)
        assert not br.record_poison("a")  # first hit aged out

    def test_breaker_untenanted_never_trips(self):
        br = CircuitBreaker(1, 10.0, lambda t, e: None)
        assert not br.record_poison(None)

    def test_poison_terminator_meta(self):
        buf = Buffer([np.ones((4,), np.float32)],
                     meta={"_query_msg": 5, "_query_conn": 1,
                           "stream_index": 2})
        term = poison_terminator(buf, RuntimeError("x"))
        assert term.tensors == []
        assert term.meta["abort_reason"] == "poison"
        assert term.meta["_query_msg"] == 5  # routing meta survives
        assert term.meta["stream_last"] and term.meta["stream_aborted"]


class _FrontDoor:
    """serversrc ! armor-work ! serversink with a raw-socket client."""

    def __init__(self, tmp_path, sid, **pipe_kw):
        _register_work()
        self.srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id={sid} "
            f"admission=shed max-backlog=64 ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id={sid}", **pipe_kw)

    def __enter__(self):
        from nnstreamer_tpu.utils.net import client_handshake

        self.srv.start()
        port = self.srv.element("ssrc").bound_port
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=5.0)
        client_handshake(self.sock, "hello", caps="other/tensors",
                         topic="", tenant="t0")
        self.sock.settimeout(5.0)
        return self

    def send(self, v, mid, tenant="t0"):
        wire.write_frame(self.sock,
                         wire.encode_buffer(_req(v, mid, tenant)))

    def recv_all(self, n, timeout=15.0):
        got = {}
        t0 = time.monotonic()
        while len(got) < n and time.monotonic() - t0 < timeout:
            try:
                raw = wire.read_frame(self.sock)
            except socket.timeout:
                continue
            assert raw is not None, "server closed the connection"
            buf, _ = wire.decode_buffer(raw)
            got[int(buf.meta["_query_msg"])] = buf
        return got

    def __exit__(self, *exc):
        try:
            self.sock.close()
        except OSError:
            pass
        self.srv.stop()


class TestPoisonQuarantine:
    def test_poison_answered_typed_pipeline_survives(self, tmp_path):
        metrics.reset()
        tracing.recorder.clear()
        dlq_dir = str(tmp_path / "dlq")
        with _FrontDoor(tmp_path, sid=70, quarantine=dlq_dir,
                        trace_mode="ring") as fd:
            for mid in range(3):
                fd.send(float(mid + 1), mid)
            fd.send(POISON_PILL, 3)
            for mid in range(4, 7):
                fd.send(float(mid), mid)
            got = fd.recv_all(7)
            assert len(got) == 7
            # the poisoned request got the TYPED terminator
            assert got[3].meta["abort_reason"] == "poison"
            assert "deliberately poisoned" in got[3].meta["error"]
            assert got[3].tensors == []
            # everyone else got real answers — the pipeline survived
            for mid in (0, 1, 2, 4, 5, 6):
                assert "abort_reason" not in got[mid].meta
                v = float(mid + 1) if mid < 3 else float(mid)
                np.testing.assert_allclose(
                    np.asarray(got[mid].tensors[0]),
                    np.full((4,), 2.0 * v, np.float32))
            # DLQ holds the quarantined request with ring + context
            entries = DeadLetterQueue(dlq_dir).entries()
            assert len(entries) == 1
            rec, _ = load_dlq_entry(entries[0])
            ctx = rec.meta[armor.META_DLQ]
            assert "RuntimeError" in ctx["error"]
            assert ctx["tenant"] == "t0"
            assert ctx["ring"], "flight-recorder excerpt not attached"
            np.testing.assert_allclose(
                np.asarray(rec.tensors[0]),
                np.full((4,), POISON_PILL, np.float32))
            snap = metrics.snapshot()
            assert snap.get("armor.quarantined") == 1.0
            assert metrics.labeled_counters().get(
                ("armor.quarantined", "t0")) == 1.0
            kinds = [e.kind for e in tracing.recorder.events()]
            assert "armor.quarantine" in kinds

    def test_nan_guard_quarantines(self, tmp_path):
        metrics.reset()
        dlq_dir = str(tmp_path / "dlq")
        with _FrontDoor(tmp_path, sid=71, quarantine=dlq_dir,
                        nan_guard=True) as fd:
            fd.send(1.0, 0)
            fd.send(NAN_PILL, 1)
            fd.send(2.0, 2)
            got = fd.recv_all(3)
            assert got[1].meta["abort_reason"] == "poison"
            assert "non-finite" in got[1].meta["error"]
            for mid, v in ((0, 1.0), (2, 2.0)):
                np.testing.assert_allclose(
                    np.asarray(got[mid].tensors[0]),
                    np.full((4,), 2.0 * v, np.float32))
            assert len(DeadLetterQueue(dlq_dir).entries()) == 1
        # without nan_guard the NaN flows through untouched (opt-in)
        with _FrontDoor(tmp_path, sid=72,
                        quarantine=str(tmp_path / "dlq2")) as fd:
            fd.send(NAN_PILL, 0)
            got = fd.recv_all(1)
            assert "abort_reason" not in got[0].meta
            assert np.isnan(np.asarray(got[0].tensors[0])[0])

    def test_breaker_flips_tenant_to_shed(self, tmp_path):
        metrics.reset()
        tracing.recorder.clear()
        pol = {"dir": str(tmp_path / "dlq"), "breaker_threshold": 3,
               "breaker_window_s": 30.0}
        with _FrontDoor(tmp_path, sid=73, quarantine=pol,
                        trace_mode="ring") as fd:
            for mid in range(3):
                fd.send(POISON_PILL, mid)
            got = fd.recv_all(3)
            assert all(b.meta.get("abort_reason") == "poison"
                       for b in got.values())
            # breaker tripped: t0 is now SHED at admission
            core = fd.srv.element("ssrc")._core
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline \
                    and core.tenant_admission.get("t0") != "shed-all":
                time.sleep(0.02)
            assert core.tenant_admission.get("t0") == "shed-all"
            fd.send(1.0, 3)  # healthy request, but the tenant is shed
            got = fd.recv_all(1)
            assert got[3].meta.get("shed") is True
            assert metrics.labeled_counters().get(
                ("armor.breaker_trips", "t0")) == 1.0
            spans = [e for e in tracing.recorder.events()
                     if e.kind == "armor.breaker"]
            assert spans and spans[0].args["tenant"] == "t0"
            assert spans[0].args["edge"] == "trip"
            # reset restores the configured policy
            assert fd.srv._armor.breaker.reset("t0")
            assert "t0" not in core.tenant_admission

    def test_client_cannot_supply_poison_marker(self, tmp_path):
        """Trust boundary: a client-stamped '_poison' meta key must be
        stripped at the reader — otherwise its requests bypass stage
        invokes and force inflight flushes on batching stages."""
        with _FrontDoor(tmp_path, sid=83,
                        quarantine=str(tmp_path / "dlq")) as fd:
            buf = _req(3.0, 0)
            buf.meta["_poison"] = True
            wire.write_frame(fd.sock, wire.encode_buffer(buf))
            got = fd.recv_all(1)
            # the stage RAN: a real doubled answer, not a forwarded fake
            np.testing.assert_allclose(
                np.asarray(got[0].tensors[0]),
                np.full((4,), 6.0, np.float32))

    def test_breaker_reasserts_after_external_override_pop(self):
        """Latch self-healing: the autoscaler's relax edge shares the
        tenant_admission map and may pop a tripped tenant's override —
        the next poison from that tenant must re-assert it."""
        overrides = {}

        def apply(t, engage):
            if engage:
                overrides[t] = "shed-all"
            else:
                overrides.pop(t, None)

        br = CircuitBreaker(2, 30.0, apply)
        br.record_poison("a")
        assert br.record_poison("a")  # trip
        assert overrides == {"a": "shed-all"}
        overrides.pop("a")  # the autoscaler relax edge
        assert not br.record_poison("a")  # latched: no new trip edge...
        assert overrides == {"a": "shed-all"}  # ...but re-asserted

    def test_other_tenant_unaffected_by_breaker(self, tmp_path):
        pol = {"dir": str(tmp_path / "dlq"), "breaker_threshold": 2,
               "breaker_window_s": 30.0}
        metrics.reset()
        with _FrontDoor(tmp_path, sid=74, quarantine=pol) as fd:
            for mid in range(2):
                fd.send(POISON_PILL, mid, tenant="evil")
            fd.recv_all(2)
            core = fd.srv.element("ssrc")._core
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline \
                    and core.tenant_admission.get("evil") != "shed-all":
                time.sleep(0.02)
            assert core.tenant_admission.get("evil") == "shed-all"
            fd.send(5.0, 2, tenant="good")
            got = fd.recv_all(1)
            assert "shed" not in got[2].meta
            np.testing.assert_allclose(
                np.asarray(got[2].tensors[0]),
                np.full((4,), 10.0, np.float32))


class TestAppPathQuarantine:
    """Poison quarantine on a non-query pipeline: the terminator rides
    to the app sink, the pipeline keeps accepting pushes (the pre-armor
    behavior was a stage error + dead pipeline)."""

    def test_appsrc_poison_keeps_serving(self, tmp_path):
        _register_work()
        metrics.reset()
        pipe = nt.Pipeline(
            "appsrc name=src ! tensor_filter name=f "
            "framework=custom-easy model=armor-work ! "
            "tensor_sink name=out",
            quarantine=str(tmp_path / "dlq"))
        with pipe:
            pipe.push("src", Buffer([np.full((4,), 3.0, np.float32)]))
            out = pipe.pull("out", timeout=10)
            np.testing.assert_allclose(np.asarray(out.tensors[0]),
                                       np.full((4,), 6.0, np.float32))
            pipe.push("src",
                      Buffer([np.full((4,), POISON_PILL, np.float32)]))
            term = pipe.pull("out", timeout=10)
            assert term.meta["abort_reason"] == "poison"
            assert term.tensors == []
            # the pipeline is still alive and serving
            pipe.push("src", Buffer([np.full((4,), 5.0, np.float32)]))
            out = pipe.pull("out", timeout=10)
            np.testing.assert_allclose(np.asarray(out.tensors[0]),
                                       np.full((4,), 10.0, np.float32))
            pipe.eos("src")
            pipe.wait(timeout=10)  # no stage error recorded
        assert metrics.snapshot().get("f.poisoned") == 1.0
        assert len(DeadLetterQueue(str(tmp_path / "dlq")).entries()) == 1


class TestBatchPoisonIsolation:
    def test_only_the_pill_row_is_quarantined(self, tmp_path):
        """Regression: a poison pill sharing a micro-batch with innocent
        requests must not quarantine (or breaker-penalize) the whole
        dispatch — the failed batch is re-invoked per buffer and only
        the actual pill aborts."""
        metrics.reset()

        def work(ins):
            arr = np.asarray(ins[0])
            if np.any(arr == POISON_PILL):
                raise RuntimeError("pill in the batch")
            return [arr * 2.0]

        register_custom_easy("armor-batch-work", work, in_spec=SPEC,
                             out_spec=SPEC)
        pipe = nt.Pipeline(
            "appsrc name=src ! tensor_filter name=f "
            "framework=custom-easy model=armor-batch-work ! "
            "tensor_sink name=out",
            batch_max=4, quarantine=str(tmp_path / "dlq"))
        with pipe:
            vals = [1.0, 2.0, POISON_PILL, 3.0]
            for v in vals:
                pipe.push("src", Buffer([np.full((4,), v, np.float32)]))
            outs = [pipe.pull("out", timeout=15) for _ in vals]
            pipe.eos("src")
            pipe.wait(timeout=15)
        poisoned = [o for o in outs
                    if o.meta.get("abort_reason") == "poison"]
        healthy = sorted(float(np.asarray(o.tensors[0])[0])
                         for o in outs
                         if "abort_reason" not in o.meta)
        assert len(poisoned) == 1
        assert healthy == [2.0, 4.0, 6.0]
        assert metrics.snapshot().get("armor.quarantined") == 1.0
        assert len(DeadLetterQueue(str(tmp_path / "dlq")).entries()) == 1


class TestJournalPipeline:
    """The durable journal on a live front door: accepted requests
    append, answers ack, a restart with journal_replay=True re-admits
    exactly the unanswered entries and answers them exactly once."""

    def test_answered_requests_all_acked(self, tmp_path):
        _register_work()
        metrics.reset()
        jdir = str(tmp_path / "wal")
        with _FrontDoor(tmp_path, sid=75) as fd:
            pass  # just to reuse the register; real server below
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=76 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=76")
        from nnstreamer_tpu.utils.net import client_handshake

        with srv:
            port = srv.element("ssrc").bound_port
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=5.0)
            try:
                client_handshake(sock, "hello", caps="other/tensors",
                                 topic="", tenant="t0")
                sock.settimeout(5.0)
                for mid in range(5):
                    wire.write_frame(
                        sock, wire.encode_buffer(_req(1.0 + mid, mid)))
                got = 0
                t0 = time.monotonic()
                while got < 5 and time.monotonic() - t0 < 30:
                    try:
                        raw = wire.read_frame(sock)
                    except socket.timeout:
                        continue
                    buf, _ = wire.decode_buffer(raw)
                    # the journal seqno never leaks to the client
                    assert "_journal_seq" not in buf.meta
                    got += 1
            finally:
                sock.close()
            assert got == 5
            # poll: the sink acks AFTER the send the client just read
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline \
                    and metrics.snapshot().get("journal.acks",
                                               0.0) < 5.0:
                time.sleep(0.02)
        assert replay_unanswered(jdir) == []  # every answer acked
        snap = metrics.snapshot()
        assert snap.get("journal.appends") == 5.0
        assert snap.get("journal.acks") == 5.0

    def test_replay_answers_unanswered_exactly_once(self, tmp_path):
        """Seed a journal with answered + unanswered entries (as a
        killed process would leave it), then start a replaying server:
        only the unanswered ones re-admit, each is answered (acked)
        exactly once, and a SECOND restart replays nothing."""
        from nnstreamer_tpu.utils.journal import Journal, scan

        _register_work()
        metrics.reset()
        jdir = str(tmp_path / "wal")
        j = Journal(jdir, fsync="always")
        for i in range(6):
            seq = j.append(wire.encode_buffer(_req(float(i + 1), i)))
            if i < 2:
                j.ack(seq)  # first two were answered pre-kill
        j.close()
        assert [s for s, _ in replay_unanswered(jdir)] == [3, 4, 5, 6]

        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=77 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=77",
            journal_replay=True)
        with srv:
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline \
                    and replay_unanswered(jdir):
                time.sleep(0.05)
        assert replay_unanswered(jdir) == []
        snap = metrics.snapshot()
        assert snap.get("query_server.replayed") == 4.0
        assert snap.get("query_server.replay_answered") == 4.0
        st = scan(jdir)
        assert all(m == 1 for m in st.ack_multiplicity.values())
        # second restart: nothing left to replay
        metrics.reset()
        srv2 = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=78 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=78",
            journal_replay=True)
        with srv2:
            time.sleep(0.3)
        assert metrics.snapshot().get("query_server.replayed", 0.0) == 0.0

    def test_nan_guard_without_dlq_dir_still_typed(self, tmp_path):
        """Regression: nan_guard-only armor (no quarantine= dir) must
        answer typed and count — not stack-trace on makedirs('')."""
        metrics.reset()
        with _FrontDoor(tmp_path, sid=81, nan_guard=True) as fd:
            fd.send(NAN_PILL, 0)
            fd.send(1.0, 1)
            got = fd.recv_all(2)
            assert got[0].meta["abort_reason"] == "poison"
            np.testing.assert_allclose(np.asarray(got[1].tensors[0]),
                                       np.full((4,), 2.0, np.float32))
        assert metrics.snapshot().get("armor.quarantined") == 1.0

    def test_undeliverable_answer_acks_entry(self, tmp_path):
        """Regression: a client that vanishes before its answer must
        not pin the journal forever — the undeliverable answer acks
        the entry (the work was done; replaying to nobody is waste)."""
        from nnstreamer_tpu.utils.net import client_handshake

        _register_work()
        jdir = str(tmp_path / "wal")
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=82 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=82")
        with srv:
            port = srv.element("ssrc").bound_port
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=5.0)
            try:
                client_handshake(sock, "hello", caps="other/tensors",
                                 topic="", tenant="ghost")
                wire.write_frame(
                    sock, wire.encode_buffer(_req(1.0, 0, "ghost")))
            finally:
                sock.close()  # gone before the answer
            from nnstreamer_tpu.utils.journal import scan
            deadline = time.monotonic() + 15.0
            # The reader journals the request asynchronously — an empty
            # WAL also has no unanswered entries, so polling for absence
            # alone can win the race against the append and exit before
            # the server ever saw the request (teardown then strands the
            # entry).  Establish presence first, then poll for the ack.
            while time.monotonic() < deadline \
                    and not scan(jdir).requests:
                time.sleep(0.05)
            assert scan(jdir).requests, "request never journaled"
            while time.monotonic() < deadline \
                    and replay_unanswered(jdir):
                time.sleep(0.05)
        assert replay_unanswered(jdir) == []

    def test_hello_fallback_tenant_persisted_in_journal(self, tmp_path):
        """Regression: a tenant sent only in the connection hello (not
        per-frame meta) must still ride the JOURNALED payload, or a
        replayed entry loses quota/SLO/breaker attribution."""
        from nnstreamer_tpu.utils.journal import scan
        from nnstreamer_tpu.utils.net import client_handshake

        _register_work()
        jdir = str(tmp_path / "wal")
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=79 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=79")
        with srv:
            port = srv.element("ssrc").bound_port
            sock = socket.create_connection(("127.0.0.1", port),
                                            timeout=5.0)
            try:
                client_handshake(sock, "hello", caps="other/tensors",
                                 topic="", tenant="hello-only")
                sock.settimeout(5.0)
                buf = Buffer([np.full((4,), 1.0, np.float32)],
                             meta={"_query_msg": 0})  # no _tenant key
                wire.write_frame(sock, wire.encode_buffer(buf))
                while True:
                    try:
                        wire.read_frame(sock)
                        break
                    except socket.timeout:
                        continue
            finally:
                sock.close()
        st = scan(jdir)
        assert len(st.requests) == 1
        rec, _ = wire.decode_buffer(next(iter(st.requests.values())))
        assert rec.meta.get("_tenant") == "hello-only"
        assert "_query_conn" not in rec.meta  # record stays conn-free

    def test_replay_backlog_larger_than_max_backlog(self, tmp_path):
        """Regression: more unanswered entries than max-backlog must
        replay through generate()'s own backpressure, not deadlock
        start() force-feeding a queue no runner drains yet."""
        from nnstreamer_tpu.utils.journal import Journal

        _register_work()
        metrics.reset()
        jdir = str(tmp_path / "wal")
        j = Journal(jdir, fsync="always")
        n = 12
        for i in range(n):
            j.append(wire.encode_buffer(_req(float(i + 1), i)))
        j.close()
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=80 "
            f"max-backlog=4 journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-work ! "
            f"tensor_query_serversink id=80",
            journal_replay=True)
        with srv:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline \
                    and replay_unanswered(jdir):
                time.sleep(0.05)
        assert replay_unanswered(jdir) == []
        assert metrics.snapshot().get("query_server.replayed") == float(n)

    def test_replayed_entry_with_forged_poison_marker_is_processed(
            self, tmp_path):
        """Trust boundary on the REPLAY path too: a journaled frame
        whose meta carries a client-minted '_poison' must still be
        processed after restart — not forwarded unprocessed and acked
        as answered."""
        from nnstreamer_tpu.utils.journal import Journal

        ran = []

        def spy(ins):
            ran.append(float(np.asarray(ins[0]).ravel()[0]))
            return [np.asarray(ins[0], np.float32) * 2.0]

        register_custom_easy("armor-spy", spy, in_spec=SPEC,
                             out_spec=SPEC)
        metrics.reset()
        jdir = str(tmp_path / "wal")
        j = Journal(jdir, fsync="always")
        forged = _req(7.0, 0)
        forged.meta["_poison"] = True
        j.append(wire.encode_buffer(forged))
        j.close()
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id=84 "
            f"journal={jdir} journal-fsync=always ! "
            f"tensor_filter framework=custom-easy model=armor-spy ! "
            f"tensor_query_serversink id=84",
            journal_replay=True)
        with srv:
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline \
                    and replay_unanswered(jdir):
                time.sleep(0.05)
        assert replay_unanswered(jdir) == []
        assert ran == [7.0]  # the stage RAN on the replayed request

    def test_shed_request_is_acked_not_replayed(self, tmp_path):
        """A shed IS an answer: its journal entry must not replay."""
        from nnstreamer_tpu.elements.query import _ServerCore
        from nnstreamer_tpu.utils.journal import Journal

        jdir = str(tmp_path / "wal")
        journal = Journal(jdir, fsync="always")
        core = _ServerCore("127.0.0.1", 0, max_backlog=1,
                           admission="shed", journal=journal)
        try:
            b1 = _req(1.0, 0)
            raw = wire.encode_buffer(b1)
            b1.meta["_journal_seq"] = journal.append(raw)
            assert core._admit(b1) == "ok"
            b2 = _req(2.0, 1)
            b2.meta["_journal_seq"] = journal.append(
                wire.encode_buffer(b2))
            assert core._admit(b2) == "shed"  # backlog full -> shed+ack
            assert [s for s, _ in replay_unanswered(jdir)] == [1]
        finally:
            core.close()
            journal.close()


class TestLlmNanGuardPoison:
    @pytest.mark.slow
    def test_poisoned_prompt_typed_abort(self, tmp_path):
        """A serve-loop prompt whose prefill logits go non-finite is
        quarantined and answered abort_reason=poison; the loop keeps
        serving (filters/llm.py nan_guard)."""
        import jax

        metrics.reset()
        pipe = nt.Pipeline(
            "appsrc name=src ! tensor_filter name=f framework=llm "
            "model=llama_tiny custom=max_new:4,serve:continuous,"
            "slots:2,stream_chunk:2,dtype:float32,nan_guard:1 "
            "invoke-dynamic=true ! tensor_sink name=out",
            quarantine=str(tmp_path / "dlq"))
        with pipe:
            fw = pipe.element("f").fw
            # poison the weights BEFORE the loop's first submit captures
            # them: every admitted prompt now prefills to NaN logits
            fw.bundle.params = jax.tree_util.tree_map(
                lambda a: (a * np.float32("nan"))
                if hasattr(a, "dtype") and a.dtype.kind == "f" else a,
                fw.bundle.params)
            pipe.push("src", Buffer(
                [np.array([[1, 2, 3]], np.int32)]))
            term = pipe.pull("out", timeout=60)
            assert term.meta.get("stream_aborted") is True
            assert term.meta.get("abort_reason") == "poison"
        assert metrics.snapshot().get("llm.serve.poisoned") == 1.0
        assert len(DeadLetterQueue(
            str(tmp_path / "dlq")).entries()) == 1
