"""`.tflite` ingestion tests (VERDICT r2 missing #3 / SURVEY §2.4 tflite row,
§7 "model ingestion" hard part).

The fixture files are emitted by models/tflite_build.py (flatbuffer writer)
and parsed back by models/tflite.py (flatbuffer reader) — two independent
codings of the public format.  Numerics are cross-checked against torch
(an independent conv/pool implementation present in the environment), so a
matching bug in writer+reader would still fail the golden comparison.
"""

import os

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import tflite, tflite_build, zoo


def _build_cnn_file(tmp_path, seed=0):
    """conv(SAME,s2,relu6) -> dwconv(SAME) -> avgpool -> reshape -> fc ->
    softmax: the MobileNet op vocabulary in miniature, with real weights."""
    rng = np.random.default_rng(seed)
    mw = tflite_build.ModelWriter()
    x = mw.add_input([1, 8, 8, 3])
    w1 = mw.add_const(rng.standard_normal((4, 3, 3, 3)).astype(np.float32) * 0.3,
                      "conv_w")
    b1 = mw.add_const(rng.standard_normal((4,)).astype(np.float32) * 0.1,
                      "conv_b")
    y = mw.add_op("CONV_2D", [x, w1, b1], [1, 4, 4, 4],
                  options={"padding": "SAME", "stride": (2, 2),
                           "act": "relu6"})
    wd = mw.add_const(rng.standard_normal((1, 3, 3, 4)).astype(np.float32) * 0.3,
                      "dw_w")
    bd = mw.add_const(np.zeros((4,), np.float32), "dw_b")
    y = mw.add_op("DEPTHWISE_CONV_2D", [y, wd, bd], [1, 4, 4, 4],
                  options={"padding": "SAME", "stride": (1, 1)})
    y = mw.add_op("AVERAGE_POOL_2D", [y], [1, 2, 2, 4],
                  options={"padding": "VALID", "stride": (2, 2),
                           "filter": (2, 2)})
    y = mw.add_op("RESHAPE", [y], [1, 16],
                  options={"new_shape": [1, 16]})
    wf = mw.add_const(rng.standard_normal((5, 16)).astype(np.float32) * 0.2,
                      "fc_w")
    bf = mw.add_const(rng.standard_normal((5,)).astype(np.float32) * 0.1,
                      "fc_b")
    y = mw.add_op("FULLY_CONNECTED", [y, wf, bf], [1, 5])
    y = mw.add_op("SOFTMAX", [y], [1, 5])
    blob = mw.finish(outputs=[y])
    path = tmp_path / "tiny_cnn.tflite"
    path.write_bytes(blob)
    return str(path), rng


def _torch_golden(path, x):
    """Independent execution of the fixture graph with torch."""
    import torch
    import torch.nn.functional as F

    g = tflite.TFLiteGraph(open(path, "rb").read())
    c = {i: torch.from_numpy(np.array(a)) for i, a in g.constants.items()}
    names = {g.tensor_names[i]: i for i in g.constants}
    t = torch.from_numpy(x).permute(0, 3, 1, 2)  # NHWC -> NCHW

    def same_pad(t, k, s):
        ih, iw = t.shape[2], t.shape[3]
        ph = max((-(ih // -s) - 1) * s + k - ih, 0)
        pw = max((-(iw // -s) - 1) * s + k - iw, 0)
        return F.pad(t, (pw // 2, pw - pw // 2, ph // 2, ph - ph // 2))

    w1 = c[names["conv_w"]]  # OHWI
    t = F.conv2d(same_pad(t, 3, 2), w1.permute(0, 3, 1, 2),
                 c[names["conv_b"]], stride=2)
    t = torch.clamp(t, 0, 6)
    wd = c[names["dw_w"]]  # [1, kh, kw, C]
    t = F.conv2d(same_pad(t, 3, 1), wd.permute(3, 0, 1, 2),
                 c[names["dw_b"]], groups=4)
    t = F.avg_pool2d(t, 2, 2)
    flat = t.permute(0, 2, 3, 1).reshape(1, 16)  # back to NHWC order
    logits = flat @ c[names["fc_w"]].T + c[names["fc_b"]]
    return torch.softmax(logits, dim=-1).numpy()


class TestParser:
    def test_graph_structure(self, tmp_path):
        path, _ = _build_cnn_file(tmp_path)
        g = tflite.TFLiteGraph(open(path, "rb").read())
        assert [op.kind for op in g.ops] == [
            "CONV_2D", "DEPTHWISE_CONV_2D", "AVERAGE_POOL_2D", "RESHAPE",
            "FULLY_CONNECTED", "SOFTMAX"]
        assert len(g.inputs) == 1 and len(g.outputs) == 1
        assert g.shapes[g.inputs[0]] == [1, 8, 8, 3]
        assert g.shapes[g.outputs[0]] == [1, 5]
        conv = g.ops[0]
        assert conv.attrs["padding"] == "SAME"
        assert conv.attrs["strides"] == (2, 2)
        # real weights made it out of the buffers
        assert any(a.shape == (4, 3, 3, 3) for a in g.constants.values())

    def test_rejects_non_tflite(self):
        with pytest.raises(tflite.TFLiteError, match="TFL3"):
            tflite.TFLiteGraph(b"\x00" * 64)

    def test_matches_torch_golden(self, tmp_path):
        path, rng = _build_cnn_file(tmp_path)
        bundle = tflite.load_bundle(path)
        x = rng.standard_normal((1, 8, 8, 3)).astype(np.float32)
        got = np.asarray(bundle.apply_fn(bundle.params, x))
        want = _torch_golden(path, x)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
        assert got.shape == (1, 5)
        np.testing.assert_allclose(got.sum(), 1.0, rtol=1e-5)

    def test_jittable(self, tmp_path):
        import jax

        path, rng = _build_cnn_file(tmp_path)
        bundle = tflite.load_bundle(path)
        x = rng.standard_normal((1, 8, 8, 3)).astype(np.float32)
        eager = np.asarray(bundle.apply_fn(bundle.params, x))
        jitted = np.asarray(jax.jit(bundle.apply_fn)(bundle.params, x))
        np.testing.assert_allclose(eager, jitted, rtol=1e-6)

    def test_specs_reflect_graph_io(self, tmp_path):
        path, _ = _build_cnn_file(tmp_path)
        bundle = tflite.load_bundle(path)
        assert bundle.in_spec.specs[0].shape == (1, 8, 8, 3)
        assert bundle.out_spec.specs[0].shape == (1, 5)
        assert bundle.in_spec.specs[0].dtype == np.float32


class TestElementwiseOps:
    def test_add_mul_concat_mean(self, tmp_path):
        rng = np.random.default_rng(1)
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4, 4, 2])
        c = mw.add_const(rng.standard_normal((1, 4, 4, 2)).astype(np.float32))
        s = mw.add_op("ADD", [x, c], [1, 4, 4, 2], options={"act": "relu"})
        m = mw.add_op("MUL", [s, c], [1, 4, 4, 2])
        cc = mw.add_op("CONCATENATION", [s, m], [1, 4, 4, 4],
                       options={"axis": 3})
        axes = mw.add_const(np.array([1, 2], np.int32), "axes")
        out = mw.add_op("MEAN", [cc, axes], [1, 4],
                        options={"keep_dims": False})
        path = tmp_path / "ew.tflite"
        path.write_bytes(mw.finish(outputs=[out]))

        bundle = tflite.load_bundle(str(path))
        xv = rng.standard_normal((1, 4, 4, 2)).astype(np.float32)
        got = np.asarray(bundle.apply_fn(bundle.params, xv))
        cv = next(a for a in bundle.params.values() if a.shape == (1, 4, 4, 2))
        sv = np.maximum(xv + cv, 0)
        want = np.concatenate([sv, sv * cv], axis=3).mean(axis=(1, 2))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestPipelineIntegration:
    def test_tensor_filter_loads_tflite_file(self, tmp_path):
        """The reference's default usage, verbatim: tensor_filter
        framework=jax model=<path.tflite> (SURVEY §2.3)."""
        path, rng = _build_cnn_file(tmp_path)
        p = nt.Pipeline(
            f"appsrc name=src caps=other/tensors,dimensions=3:8:8:1,"
            f"types=float32 ! "
            f"tensor_filter framework=jax model={path} ! "
            f"tensor_sink name=out")
        x = rng.standard_normal((1, 8, 8, 3)).astype(np.float32)
        with p:
            p.push("src", x)
            buf = p.pull("out", timeout=60)
            p.eos()
            p.wait(timeout=30)
        got = np.asarray(buf.tensors[0])
        want = np.asarray(
            tflite.load_bundle(path).apply_fn(
                tflite.load_bundle(path).params, x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_zoo_build_missing_file(self):
        with pytest.raises(KeyError, match="not found"):
            zoo.build("/nonexistent/model.tflite")

    def test_quantized_activations_recorded_for_io(self):
        # fully-quantized graph: integer activations parse into io_quant
        # (dequantized-execution contract) instead of being rejected
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4], dtype=np.uint8, quant_scale=[0.5],
                         quant_zero_point=[128])
        w = mw.add_const(np.zeros((4, 4), np.uint8), "qw",
                         quant_scale=[0.5])
        out = mw.add_op("FULLY_CONNECTED", [x, w], [1, 4],
                        out_dtype=np.uint8, quant_scale=[0.25],
                        quant_zero_point=[3])
        blob = mw.finish(outputs=[out])
        g = tflite.TFLiteGraph(blob)
        assert g.io_quant[x] == (0.5, 128, np.dtype(np.uint8))
        assert g.io_quant[out] == (0.25, 3, np.dtype(np.uint8))

    def test_quantized_weights_dequantize(self):
        # hybrid model: int8 weights with per-axis scale + zero_point run
        # as float (the common published-model format)
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 3])
        q = np.array([[10, -10, 0], [20, 0, -20]], np.int8)  # [out=2, in=3]
        w = mw.add_const(q, "qw", quant_scale=[0.1, 0.5],
                         quant_zero_point=[0, 4], quant_axis=0)
        y = mw.add_op("FULLY_CONNECTED", [x, w], [1, 2])
        blob = mw.finish(outputs=[y])
        g = tflite.TFLiteGraph(blob)
        wq = g.constants[w]
        assert wq.dtype == np.float32
        want = np.array([[1.0, -1.0, 0.0], [8.0, -2.0, -12.0]], np.float32)
        np.testing.assert_allclose(wq, want)
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "q.tflite")
            open(p, "wb").write(blob)
            b = tflite.load_bundle(p)
            got = np.asarray(b.apply_fn(b.params,
                                        np.ones((1, 3), np.float32)))
            np.testing.assert_allclose(got, want.sum(axis=1)[None, :])

    def test_new_ops_transpose_s2d_div_resize(self, tmp_path):
        import jax

        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4, 4, 2])
        perm = mw.add_const(np.array([0, 2, 1, 3], np.int32), "perm")
        y = mw.add_op("TRANSPOSE", [x, perm], [1, 4, 4, 2])
        y = mw.add_op("SPACE_TO_DEPTH", [y], [1, 2, 2, 8],
                      options={"block": 2})
        two = mw.add_const(np.full((1,), 2.0, np.float32), "two")
        y = mw.add_op("DIV", [y, two], [1, 2, 2, 8])
        path = tmp_path / "ops.tflite"
        path.write_bytes(mw.finish(outputs=[y]))
        b = tflite.load_bundle(str(path))
        xv = np.arange(32, dtype=np.float32).reshape(1, 4, 4, 2)
        got = np.asarray(jax.jit(b.apply_fn)(b.params, xv))
        t = xv.transpose(0, 2, 1, 3)
        s2d = t.reshape(1, 2, 2, 2, 2, 2).transpose(0, 1, 3, 2, 4, 5).reshape(
            1, 2, 2, 8)
        np.testing.assert_allclose(got, s2d / 2.0)

    def test_resize_bilinear_matches_torch(self, tmp_path):
        import jax
        import torch
        import torch.nn.functional as F

        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4, 4, 3])
        size = mw.add_const(np.array([8, 8], np.int32), "size")
        y = mw.add_op("RESIZE_BILINEAR", [x, size], [1, 8, 8, 3],
                      options={"half_pixel": True})
        path = tmp_path / "resize.tflite"
        path.write_bytes(mw.finish(outputs=[y]))
        b = tflite.load_bundle(str(path))
        xv = np.random.default_rng(0).standard_normal(
            (1, 4, 4, 3)).astype(np.float32)
        got = np.asarray(jax.jit(b.apply_fn)(b.params, xv))
        # torch align_corners=False == tflite half_pixel_centers=True
        want = F.interpolate(torch.from_numpy(xv).permute(0, 3, 1, 2),
                             size=(8, 8), mode="bilinear",
                             align_corners=False).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_mul_fused_activation_roundtrips(self, tmp_path):
        # writer emits MulOptions (review r3 finding): relu must clamp
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4])
        c = mw.add_const(np.array([[-1, 1, -1, 1]], np.float32))
        y = mw.add_op("MUL", [x, c], [1, 4], options={"act": "relu"})
        path = tmp_path / "mul.tflite"
        path.write_bytes(mw.finish(outputs=[y]))
        g = tflite.TFLiteGraph(path.read_bytes())
        assert g.ops[0].attrs["act"] == 1  # RELU
        bundle = tflite.load_bundle(str(path))
        got = np.asarray(bundle.apply_fn(
            bundle.params, np.array([[2, 2, -3, -3]], np.float32)))
        np.testing.assert_array_equal(got, [[0, 2, 3, 0]])

    def test_shared_static_and_data_constant(self, tmp_path):
        # ONE constant consumed both as RESHAPE's static shape operand and
        # as ADD's data operand must keep its params slot AND resolve as a
        # trace-time constant (review r3 finding)
        mw = tflite_build.ModelWriter()
        x = mw.add_input([2, 2], dtype=np.int32)
        c = mw.add_const(np.array([4], np.int32), "four")
        flat = mw.add_op("RESHAPE", [x, c], [4], out_dtype=np.int32)
        y = mw.add_op("ADD", [flat, c], [4], out_dtype=np.int32)
        path = tmp_path / "shared.tflite"
        path.write_bytes(mw.finish(outputs=[y]))
        bundle = tflite.load_bundle(str(path))
        import jax

        got = np.asarray(jax.jit(bundle.apply_fn)(
            bundle.params, np.ones((2, 2), np.int32)))
        np.testing.assert_array_equal(got, [5, 5, 5, 5])

    def test_unknown_option_rejected(self, tmp_path):
        path, _ = _build_cnn_file(tmp_path)
        with pytest.raises(tflite.TFLiteError, match="param_dtype"):
            tflite.load_bundle(path, {"nope": "1"})

    def test_param_dtype_option(self, tmp_path):
        from nnstreamer_tpu.core.types import bfloat16

        path, _ = _build_cnn_file(tmp_path)
        bundle = tflite.load_bundle(path, {"param_dtype": "bfloat16"})
        floats = [a for a in bundle.params.values()
                  if a.dtype in (np.float32, bfloat16)]
        assert floats and all(a.dtype == bfloat16 for a in floats)

    def test_static_operands_jit_clean(self, tmp_path):
        """MEAN axes / PAD widths / shape-tensor RESHAPE resolve as trace-
        time constants — a graph using them must survive jax.jit (the
        jax_fw filter jits apply_fn unconditionally)."""
        import jax

        rng = np.random.default_rng(2)
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4, 4, 2])
        pads = mw.add_const(
            np.array([[0, 0], [1, 1], [1, 1], [0, 0]], np.int32), "pads")
        y = mw.add_op("PAD", [x, pads], [1, 6, 6, 2])
        axes = mw.add_const(np.array([1, 2], np.int32), "axes")
        y = mw.add_op("MEAN", [y, axes], [1, 2])
        shp = mw.add_const(np.array([2, 1], np.int32), "shape")
        y = mw.add_op("RESHAPE", [y, shp], [2, 1])
        path = tmp_path / "static.tflite"
        path.write_bytes(mw.finish(outputs=[y]))

        bundle = tflite.load_bundle(str(path))
        # static operands are excluded from the device params pytree
        assert all(a.dtype != np.int32 for a in bundle.params.values())
        xv = rng.standard_normal((1, 4, 4, 2)).astype(np.float32)
        got = np.asarray(jax.jit(bundle.apply_fn)(bundle.params, xv))
        want = np.pad(xv, [(0, 0), (1, 1), (1, 1), (0, 0)]).mean(
            axis=(1, 2)).reshape(2, 1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestQuantEdgeCases:
    def test_stale_scale_on_float_weight_untouched(self, tmp_path):
        # schema-legal: converter leaves scale metadata on a FLOAT weight;
        # values must pass through unchanged (review r3 finding)
        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 2])
        wv = np.array([[1.5, -2.5], [0.5, 3.0]], np.float32)
        w = mw.add_const(wv, "fw", quant_scale=[0.1])
        y = mw.add_op("FULLY_CONNECTED", [x, w], [1, 2])
        blob = mw.finish(outputs=[y])
        g = tflite.TFLiteGraph(blob)
        np.testing.assert_array_equal(g.constants[w], wv)


class TestFullyQuantized:
    """Fully-quantized (integer-activation) graphs — the reference's
    canonical mobilenet_v1_quant class — run by DEQUANTIZED EXECUTION
    (VERDICT r3 ask #4): integer IO contract at the boundary, float on
    the MXU inside; numerics match the float graph within quantization
    error."""

    def _files(self, tmp_path):
        rng = np.random.default_rng(7)
        wf = rng.standard_normal((8, 3, 3, 3)).astype(np.float32) * 0.3
        bf = rng.standard_normal((8,)).astype(np.float32) * 0.1

        # float twin
        mf = tflite_build.ModelWriter()
        x = mf.add_input([1, 8, 8, 3])
        w = mf.add_const(wf, "w")
        b = mf.add_const(bf, "b")
        y = mf.add_op("CONV_2D", [x, w, b], [1, 4, 4, 8],
                      options={"stride": (2, 2), "padding": "SAME",
                               "act": "relu6"})
        fblob = mf.finish(outputs=[y])

        # quantized twin: uint8 activations, int8 per-axis weights,
        # int32 bias (scale = s_in * s_w, TFLite convention)
        s_in, z_in = 1.0 / 255.0, 0
        s_out, z_out = 6.0 / 255.0, 0  # RELU6 output range [0, 6]
        sw = np.abs(wf).max(axis=(1, 2, 3)) / 127.0  # per-out-channel
        wq = np.clip(np.round(wf / sw[:, None, None, None]),
                     -127, 127).astype(np.int8)
        bq = np.round(bf / (s_in * sw)).astype(np.int32)
        mq = tflite_build.ModelWriter()
        xq = mq.add_input([1, 8, 8, 3], dtype=np.uint8,
                          quant_scale=[s_in], quant_zero_point=[z_in])
        wqi = mq.add_const(wq, "wq", quant_scale=list(sw),
                           quant_zero_point=[0] * 8, quant_axis=0)
        bqi = mq.add_const(bq, "bq", quant_scale=list(s_in * sw),
                           quant_zero_point=[0] * 8, quant_axis=0)
        yq = mq.add_op("CONV_2D", [xq, wqi, bqi], [1, 4, 4, 8],
                       out_dtype=np.uint8,
                       options={"stride": (2, 2), "padding": "SAME",
                                "act": "relu6"},
                       quant_scale=[s_out], quant_zero_point=[z_out])
        qblob = mq.finish(outputs=[yq])

        pf = os.path.join(tmp_path, "f.tflite")
        pq = os.path.join(tmp_path, "q.tflite")
        open(pf, "wb").write(fblob)
        open(pq, "wb").write(qblob)
        return pf, pq, (s_in, z_in, s_out, z_out)

    def test_quant_graph_matches_float_within_tolerance(self, tmp_path):
        pf, pq, (s_in, z_in, s_out, z_out) = self._files(str(tmp_path))
        bf = tflite.load_bundle(pf)
        bq = tflite.load_bundle(pq)
        rng = np.random.default_rng(3)
        xf = rng.random((1, 8, 8, 3)).astype(np.float32)
        xu = np.clip(np.round(xf / s_in) + z_in, 0, 255).astype(np.uint8)
        yf = np.asarray(bf.apply_fn(bf.params, xf))
        yq = np.asarray(bq.apply_fn(bq.params, xu))
        assert yq.dtype == np.uint8
        ydq = (yq.astype(np.float32) - z_out) * s_out
        # error budget: input quantization (~s_in * |W|_1) + output step
        np.testing.assert_allclose(ydq, yf, atol=4 * s_out + 0.02)

    def test_integer_io_specs(self, tmp_path):
        _, pq, _ = self._files(str(tmp_path))
        b = tflite.load_bundle(pq)
        assert b.in_spec[0].dtype == np.uint8
        assert b.out_spec[0].dtype == np.uint8

    def test_pipeline_feeds_uint8_directly(self, tmp_path):
        """The reference's quant-model usage: uint8 camera frames feed the
        filter with NO normalization transform; uint8 comes back."""
        import nnstreamer_tpu as nt

        _, pq, _ = self._files(str(tmp_path))
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,"
            "dimensions=3:8:8:1,types=uint8 ! "
            f"tensor_filter framework=jax model={pq} name=f ! "
            "tensor_sink name=out")
        x = np.random.default_rng(0).integers(
            0, 256, (1, 8, 8, 3), dtype=np.uint8)
        with p:
            p.push("src", x)
            out = p.pull("out", timeout=120)
            p.eos()
            p.wait(timeout=30)
        assert out.tensors[0].dtype == np.uint8
        assert out.tensors[0].shape == (1, 4, 4, 8)

    def test_jittable(self, tmp_path):
        import jax

        _, pq, _ = self._files(str(tmp_path))
        b = tflite.load_bundle(pq)
        x = np.zeros((1, 8, 8, 3), np.uint8)
        got = np.asarray(jax.jit(b.apply_fn)(b.params, x))
        assert got.dtype == np.uint8
