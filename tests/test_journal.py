"""Durable request journal (ISSUE 12, docs/ROBUSTNESS.md): record
framing, fsync policies, segment rotation + GC, seqno-dedup replay, and
the crash-consistency property — a writer killed at ANY byte offset
loses at most the torn tail, never a fully-CRC'd entry."""

import os
import signal
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from nnstreamer_tpu.utils import journal
from nnstreamer_tpu.utils.journal import (
    Journal, pack_record, replay_unanswered, scan, MAGIC_REQ)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestJournalBasics:
    def test_append_ack_replay_roundtrip(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        seqs = [j.append(f"req-{i}".encode()) for i in range(5)]
        assert seqs == [1, 2, 3, 4, 5]
        j.ack(2)
        j.ack(4)
        j.close()
        got = replay_unanswered(str(tmp_path))
        assert [(s, p) for s, p in got] == [
            (1, b"req-0"), (3, b"req-2"), (5, b"req-4")]

    def test_ack_idempotent_and_closed_journal_noops(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        seq = j.append(b"one")
        assert j.ack(seq) is True
        assert j.ack(seq) is False  # second ack: no record written
        assert j.ack(999) is False  # unknown seqno
        j.close()
        # racing reader threads after close(): no AttributeError, no
        # record — the request is simply not journaled
        assert j.append(b"late") == 0
        assert j.ack(seq) is False
        st = scan(str(tmp_path))
        assert st.ack_multiplicity == {seq: 1}
        assert list(st.requests) == [seq]

    def test_bad_fsync_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="fsync"):
            Journal(str(tmp_path), fsync="sometimes")

    def test_reopen_resumes_seqnos(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        j.append(b"a")
        j.append(b"b")
        j.ack(1)
        j.close()
        j2 = Journal(str(tmp_path), fsync="always")
        assert j2.append(b"c") == 3  # continues, never reuses seqnos
        assert j2.unacked_count() == 2  # b + c
        j2.close()
        assert [s for s, _ in replay_unanswered(str(tmp_path))] == [2, 3]

    def test_segment_rotation_and_gc(self, tmp_path):
        j = Journal(str(tmp_path), fsync="off", segment_bytes=1 << 12)
        payload = b"x" * 256
        seqs = [j.append(payload) for _ in range(64)]
        for s in seqs:
            j.ack(s)
        # force one more rotation so fully-acked segments collect
        for _ in range(32):
            s = j.append(payload)
            j.ack(s)
        j.close()
        segs = [n for n in os.listdir(tmp_path) if n.startswith("wal-")]
        assert len(segs) >= 1
        # GC dropped fully-acked history: far fewer segments than the
        # ~96 * 276B / 4KiB  (~7+) an unbounded log would hold
        total = sum(os.path.getsize(os.path.join(tmp_path, n))
                    for n in segs)
        assert total < 96 * 300
        assert replay_unanswered(str(tmp_path)) == []

    def test_replay_spans_segments_in_order(self, tmp_path):
        j = Journal(str(tmp_path), fsync="off", segment_bytes=1 << 12)
        seqs = [j.append(b"p" * 200) for _ in range(40)]
        j.close()
        got = [s for s, _ in replay_unanswered(str(tmp_path))]
        assert got == seqs

    def test_gc_is_strictly_prefix_acks_for_old_reqs_survive(
            self, tmp_path):
        """Regression: a fully-acked NEWER segment must not be GC'd
        while an older segment still holds an unacked request — its
        records include the ACKs for the old segment's answered
        requests, and deleting them would resurrect answered work at
        the next replay."""
        j = Journal(str(tmp_path), fsync="off", segment_bytes=1 << 12)
        payload = b"x" * 300
        first_wave = [j.append(payload) for _ in range(12)]
        straggler = first_wave[1]  # never answered (client vanished)
        # answers land later — their ACK records live in LATER segments
        for s in first_wave:
            if s != straggler:
                j.ack(s)
        # plenty of fully-answered follow-on traffic to force rotations
        for _ in range(60):
            s = j.append(payload)
            j.ack(s)
        j.close()
        got = [s for s, _ in replay_unanswered(str(tmp_path))]
        assert got == [straggler]  # nothing answered came back

    def test_recovered_snapshot_excludes_post_open_entries(
            self, tmp_path):
        """The replay source is the snapshot taken at open: entries
        accepted AFTER the journal (re)opened — a reconnected client's
        resends — must not be in it."""
        j = Journal(str(tmp_path), fsync="always")
        j.append(b"old-unanswered")
        j.close()
        j2 = Journal(str(tmp_path), fsync="always")
        assert [s for s, p in j2.recovered_unanswered] == [1]
        j2.append(b"new-after-open")
        assert [s for s, p in j2.recovered_unanswered] == [1]
        j2.close()

    def test_duplicate_seqno_dedup(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        j.append(b"one")
        j.close()
        # forge a duplicate REQ record with the same seqno
        seg = os.path.join(tmp_path, sorted(os.listdir(tmp_path))[0])
        with open(seg, "ab") as f:
            f.write(pack_record(MAGIC_REQ, 1, b"forged"))
        st = scan(str(tmp_path))
        assert st.duplicate_seqnos == 1
        assert st.requests[1] == b"one"  # first durable copy wins

    def test_batch_fsync_flushes_on_interval(self, tmp_path):
        # batch mode: appends are buffered writes; the BACKGROUND
        # flusher makes them durable within batch_interval_s — the
        # fsync never sits on the request path
        j = Journal(str(tmp_path), fsync="batch", batch_every=1000,
                    batch_interval_s=0.01)
        j.append(b"a")
        j.append(b"b")
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if set(scan(str(tmp_path)).requests) == {1, 2}:
                break
            time.sleep(0.005)
        assert set(scan(str(tmp_path)).requests) == {1, 2}
        j.close()

    def test_batch_every_backstop_bounds_loss_window(self, tmp_path):
        j = Journal(str(tmp_path), fsync="batch", batch_every=8,
                    batch_interval_s=60.0)  # interval timer idle
        for i in range(9):
            j.append(b"x")
        # the 8th write crossed the backstop and KICKED the flusher
        # (never an inline fsync): durable within ms, not 60 s
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and len(scan(str(tmp_path)).requests) < 8:
            time.sleep(0.01)
        assert len(scan(str(tmp_path)).requests) >= 8
        j.close()


class TestTornTail:
    """Property: truncating the last segment at ANY byte offset loses
    only records at/after the cut — every fully-CRC'd entry before it
    replays, and nothing torn ever comes back."""

    def _build(self, path, n=24):
        j = Journal(path, fsync="always", segment_bytes=1 << 20)
        offsets = []  # byte offset AFTER each record
        seg = j._seg_path(0)
        for i in range(n):
            j.append(f"entry-{i:03d}".encode() * (1 + i % 3))
            j.flush()
            offsets.append(os.path.getsize(seg))
        j.close()
        return seg, offsets

    def test_truncate_at_random_offsets(self, tmp_path):
        rng = np.random.default_rng(42)
        seg, offsets = self._build(str(tmp_path))
        with open(seg, "rb") as f:
            full = f.read()
        for _ in range(25):
            cut = int(rng.integers(0, len(full) + 1))
            with open(seg, "wb") as f:
                f.write(full[:cut])
            got = replay_unanswered(str(tmp_path))
            # recovered = exactly the records fully before the cut
            want = sum(1 for off in offsets if off <= cut)
            assert len(got) == want, f"cut at {cut}"
            for k, (s, payload) in enumerate(got):
                assert s == k + 1
                assert payload == f"entry-{k:03d}".encode() * (1 + k % 3)
        with open(seg, "wb") as f:
            f.write(full)

    def test_corrupt_byte_in_tail_drops_from_there(self, tmp_path):
        seg, offsets = self._build(str(tmp_path), n=8)
        with open(seg, "rb") as f:
            full = f.read()
        # flip one byte inside record 6's payload: records 1-5 recover
        pos = offsets[4] + journal._REC_SIZE + 2
        bad = bytearray(full)
        bad[pos] ^= 0xFF
        with open(seg, "wb") as f:
            f.write(bytes(bad))
        got = replay_unanswered(str(tmp_path))
        assert [s for s, _ in got] == [1, 2, 3, 4, 5]


_WRITER = r"""
import sys, time
sys.path.insert(0, {repo!r})
from nnstreamer_tpu.utils.journal import Journal
j = Journal(sys.argv[1], fsync="always", segment_bytes=1 << 14)
i = 0
while True:
    seq = j.append(("payload-%06d" % i).encode() * 4)
    # a printed seqno is a DURABLE claim: append() fsynced before
    # returning (fsync=always), so the kill test may assert it survives
    print("REQ %d" % seq, flush=True)
    if i % 3 == 0:
        j.ack(seq)
        print("ACK %d" % seq, flush=True)
    i += 1
    time.sleep(0.001)
"""


class TestSigkillWriter:
    """The committed crash-consistency property test: SIGKILL a real
    writer subprocess mid-append stream, then assert replay recovers
    every durably-reported entry (no lost accepted requests), drops the
    torn tail, and never duplicates an answer (ack multiplicity 1)."""

    @staticmethod
    def _await_traffic(tmp_path, timeout=20.0):
        """Anchor the kill timer on actual journal bytes, not interpreter
        startup (imports dwarf millisecond-scale delays)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            segs = [n for n in os.listdir(tmp_path)
                    if n.startswith("wal-")]
            if segs and any(os.path.getsize(os.path.join(tmp_path, n))
                            for n in segs):
                return True
            time.sleep(0.005)
        return False

    @pytest.mark.parametrize("delay_ms", [40, 110, 230])
    def test_sigkill_mid_append(self, tmp_path, delay_ms):
        script = _WRITER.format(repo=REPO)
        proc = subprocess.Popen(
            [sys.executable, "-c", script, str(tmp_path)],
            stdout=subprocess.PIPE, text=True, cwd=REPO)
        assert self._await_traffic(tmp_path), "writer never started"
        time.sleep(delay_ms / 1e3)
        os.kill(proc.pid, signal.SIGKILL)
        out, _ = proc.communicate(timeout=10)
        reported_reqs, reported_acks = set(), set()
        for line in out.splitlines():
            kind, _, seq = line.partition(" ")
            if kind == "REQ":
                reported_reqs.add(int(seq))
            elif kind == "ACK":
                reported_acks.add(int(seq))
        if not reported_reqs:
            pytest.skip("writer was killed before its first append")
        st = scan(str(tmp_path))
        # 1. no lost accepted requests: every seqno the writer REPORTED
        # (durably appended) is recovered
        missing = reported_reqs - set(st.requests)
        assert not missing, f"lost durable entries {sorted(missing)}"
        # 2. the torn tail is dropped, not resurrected: at most one
        # unreported record can have completed (the one mid-kill)
        extra = set(st.requests) - reported_reqs
        assert len(extra) <= 1, f"resurrected records {sorted(extra)}"
        # 3. exactly-once watermark: no seqno acked twice, every
        # reported ack durable
        assert all(m == 1 for m in st.ack_multiplicity.values())
        assert reported_acks - st.acked == set()
        # 4. replay = reqs minus acks, ordered, deduped
        got = [s for s, _ in replay_unanswered(str(tmp_path))]
        assert got == sorted(set(st.requests) - st.acked)
        assert len(got) == len(set(got))

    def test_restart_after_kill_continues_cleanly(self, tmp_path):
        """The journal a killed writer leaves behind must accept a new
        writer (seqnos continue past the recovered max) — the restart
        path the yank_process soak drives end-to-end."""
        script = _WRITER.format(repo=REPO)
        proc = subprocess.Popen(
            [sys.executable, "-c", script, str(tmp_path)],
            stdout=subprocess.PIPE, text=True, cwd=REPO)
        assert TestSigkillWriter._await_traffic(tmp_path), \
            "writer never started"
        time.sleep(0.15)
        os.kill(proc.pid, signal.SIGKILL)
        proc.communicate(timeout=10)
        before = scan(str(tmp_path))
        j = Journal(str(tmp_path), fsync="always")
        seq = j.append(b"post-restart")
        assert seq == before.max_seqno + 1
        for s, _ in replay_unanswered(str(tmp_path)):
            if s != seq:
                j.ack(s)
        j.close()
        assert [s for s, _ in replay_unanswered(str(tmp_path))] == [seq]
