"""int4 weight-only path: packing, kernel-vs-reference equivalence
(Pallas interpret mode on the CPU mesh), quantization error bounds, and
the llama/llm integration (VERDICT r4 Next #1 follow-through: fewer
bytes/token past the measured HBM roofline)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import llama
from nnstreamer_tpu.ops.int4_matmul import (
    matmul_int4, matmul_int4_reference, pack_int4, quantize_int4,
    unpack_int4,
)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    wq = rng.integers(-8, 8, (64, 256)).astype(np.int8)
    packed = np.asarray(pack_int4(jnp.asarray(wq)))
    assert packed.shape == (32, 256)
    back = np.asarray(unpack_int4(jnp.asarray(packed)))
    np.testing.assert_array_equal(back, wq)


def test_quantize_error_bound():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    packed, s = quantize_int4(jnp.asarray(w))
    deq = np.asarray(unpack_int4(packed)).astype(np.float32) * np.asarray(s)
    # symmetric 4-bit grid: |w - deq| <= s/2 everywhere except clip range
    assert np.all(np.abs(w - deq) <= np.asarray(s)[0] / 2 + 1e-6)


def test_reference_matches_dense_dequant():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    h = rng.standard_normal((3, 64)).astype(np.float32)
    packed, s = quantize_int4(jnp.asarray(w))
    deq = np.asarray(unpack_int4(packed)).astype(np.float32) * np.asarray(s)
    want = h @ deq
    got = np.asarray(matmul_int4_reference(jnp.asarray(h), packed, s))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_interpret_matches_reference():
    """The Pallas kernel (interpret mode, bit-level unpack semantics)
    against the XLA reference: the activation-mixing algebra introduces
    only bf16-level rounding."""
    rng = np.random.default_rng(3)
    d, f = 256, 256
    w = rng.standard_normal((d, f)).astype(np.float32) * 0.05
    h = rng.standard_normal((2, d)).astype(np.float32)
    packed, s = quantize_int4(jnp.asarray(w))
    hb = jnp.asarray(h, jnp.bfloat16)
    want = np.asarray(matmul_int4_reference(hb, packed, s), np.float32)
    got = np.asarray(
        matmul_int4(hb, packed, s, block_d2=64, interpret=True), np.float32)
    scale = np.abs(want).max() + 1e-9
    assert np.abs(got - want).max() / scale < 2e-2


def test_fb_blocking_picks_vmem_safe_divisor():
    from nnstreamer_tpu.ops.int4_matmul import _pick_fb

    # lm_head scale at max kernel rows: MUST block (a [32, 32000] f32
    # accumulator + unpack temps overflowed the 16 MB VMEM on chip)
    fb = _pick_fb(32000, 32, 128)
    assert 0 < fb < 32000 and fb % 128 == 0 and 32000 % fb == 0
    # decode-scale F fits whole
    assert _pick_fb(11008, 16, 128) == 11008


def test_kernel_interpret_blocked_f_matches_reference():
    """Multi-F-block grid (the lm_head shape class) against the XLA
    reference — the revisited accumulator + per-block scales must
    reassemble the full row exactly."""
    rng = np.random.default_rng(7)
    din, f = 512, 32000
    w = rng.standard_normal((din, f)).astype(np.float32) * 0.05
    h = rng.standard_normal((32, din)).astype(np.float32)
    packed, s = quantize_int4(jnp.asarray(w))
    hb = jnp.asarray(h, jnp.bfloat16)
    want = np.asarray(matmul_int4_reference(hb, packed, s), np.float32)
    got = np.asarray(
        matmul_int4(hb, packed, s, block_d2=128, interpret=True),
        np.float32)
    scale = np.abs(want).max() + 1e-9
    assert np.abs(got - want).max() / scale < 2e-2


def test_matmul_int4_shape_validation():
    packed = jnp.zeros((8, 128), jnp.int8)
    s = jnp.ones((1, 128), jnp.float32)
    with pytest.raises(ValueError, match="packed rows"):
        matmul_int4(jnp.zeros((1, 17), jnp.bfloat16), packed, s)


CFG = llama.PRESETS["llama_tiny"]


def test_quantize_int4_params_pytree():
    params = llama.init_params(CFG, seed=0)
    # quantize donates the big mats: snapshot the comparison input FIRST
    wq0 = np.array(params["layers"]["wq"][0])
    qp = llama.quantize_int4_params(params)
    lay = qp["layers"]
    L, D = CFG.n_layers, CFG.dim
    hd = CFG.head_dim
    qkv_out = (CFG.n_heads + 2 * CFG.n_kv_heads) * hd
    # fused layout (_INT4_GROUPS): q|k|v and gate|up share one packed mat
    assert lay["wqkv_p"].shape == (L, D // 2, qkv_out)
    assert lay["wqkv_s"].shape == (L, 1, qkv_out)
    assert lay["wo_p"].shape == (L, CFG.n_heads * hd // 2, D)
    assert lay["wgu_p"].shape == (L, D // 2, 2 * CFG.ffn_hidden)
    assert lay["w_down_p"].shape == (L, CFG.ffn_hidden // 2, D)
    assert qp["lm_head_p"].shape == (CFG.dim // 2, CFG.vocab)
    # the fused wqkv block for q IS quantize(wq) — both paths quantize
    # member-wise and only packed nibbles concatenate.  The oracle here
    # runs EAGERLY while production runs under the lax.map jit, whose
    # max-reduction can differ by 1 f32 ULP, shifting a boundary value
    # one quantization step — so dequantized values compare within one
    # step of each column's scale.
    pq, sq = quantize_int4(jnp.asarray(wq0))
    ncol = CFG.n_heads * hd
    deq_fused = (np.asarray(unpack_int4(lay["wqkv_p"][0, :, :ncol]),
                            np.float32) * np.asarray(lay["wqkv_s"][0, :, :ncol]))
    deq_alone = (np.asarray(unpack_int4(pq), np.float32) * np.asarray(sq))
    step = np.asarray(sq)[0] * (1 + 1e-5) + 1e-7  # one step per column
    assert np.all(np.abs(deq_fused - deq_alone) <= step[None, :])
    # and almost every integer CODE must agree exactly (scales may
    # differ in the last f32 ULP, so compare codes, not products)
    codes_fused = np.asarray(unpack_int4(lay["wqkv_p"][0, :, :ncol]))
    codes_alone = np.asarray(unpack_int4(pq))
    assert (codes_fused != codes_alone).mean() < 1e-3


def test_init_params_int4_matches_quantize_of_init():
    a = llama.init_params_int4(CFG, seed=0, gen_dtype="float32")
    b = llama.quantize_int4_params(llama.init_params(CFG, seed=0))
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(flat_a) == len(flat_b)
    for path, leaf in flat_a:
        np.testing.assert_array_equal(
            np.asarray(leaf), np.asarray(flat_b[path]), err_msg=str(path))


def test_int4_forward_equals_dense_dequant():
    """The REAL correctness invariant: the packed int4 forward must
    equal a normal full-precision forward over densely dequantized
    weights (proves pack layout + matmul algebra end-to-end; measured
    corr 0.9999 on CPU).  Absolute agreement with the un-quantized model
    is NOT asserted — 4-bit noise on a tiny chaotic random model
    legitimately reorders logits (dense-dequant control showed the same
    decorrelation)."""
    prompt = np.array([[1, 7, 3, 9]], np.int32)
    params = llama.init_params(CFG, seed=0)
    # quantize_int4_params donates the big mats (the 7B HBM discipline),
    # so build the dense-dequant twin FIRST
    dq = {"embed": params["embed"], "ln_out": params["ln_out"],
          "layers": dict(params["layers"])}
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        w = jnp.asarray(params["layers"][k])
        mats = []
        for i in range(w.shape[0]):
            p4, s4 = quantize_int4(w[i])
            mats.append(np.asarray(unpack_int4(p4), np.float32)
                        * np.asarray(s4))
        dq["layers"][k] = jnp.asarray(np.stack(mats))
    p4, s4 = quantize_int4(jnp.asarray(params["lm_head"]))
    dq["lm_head"] = jnp.asarray(
        np.asarray(unpack_int4(p4), np.float32) * np.asarray(s4))

    qp = llama.quantize_int4_params(llama.init_params(CFG, seed=0))
    tp = jnp.asarray(prompt)
    ldq = np.asarray(llama.forward(dq, tp, CFG, compute_dtype="float32"))
    l4 = np.asarray(llama.forward(qp, tp, CFG, compute_dtype="float32"))
    np.testing.assert_allclose(l4, ldq, rtol=2e-3, atol=2e-3)

    t4a = llama.generate_scan(qp, prompt, CFG, max_new=8, temperature=0.0,
                              compute_dtype="float32")
    t4b = llama.generate_scan(qp, prompt, CFG, max_new=8, temperature=0.0,
                              compute_dtype="float32")
    assert t4a.shape == (1, 8)
    np.testing.assert_array_equal(np.asarray(t4a), np.asarray(t4b))
    assert np.asarray(t4a).min() >= 0


def test_kernel_disable_refcount():
    """TP filters refcount the kernel disable: nesting works, over-
    release clamps, and the default state is enabled."""
    from nnstreamer_tpu.ops import int4_matmul as i4

    assert i4.kernel_enabled()
    i4.disable_kernel()
    i4.disable_kernel()
    assert not i4.kernel_enabled()
    i4.enable_kernel()
    assert not i4.kernel_enabled()  # one holder still active
    i4.enable_kernel()
    assert i4.kernel_enabled()
    i4.enable_kernel()  # over-release must clamp, not go negative
    assert i4.kernel_enabled()
    i4.disable_kernel()
    assert not i4.kernel_enabled()
    i4.enable_kernel()
    assert i4.kernel_enabled()


def test_llm_tp_open_disables_kernel_and_close_restores():
    from nnstreamer_tpu.filters.llm import LLMFramework
    from nnstreamer_tpu.ops import int4_matmul as i4

    fw = LLMFramework()
    fw.open({"model": "llama_tiny",
             "custom": "max_new:2,tp:2,quant:int4,dtype:float32"})
    try:
        assert not i4.kernel_enabled()
    finally:
        fw.close()
    assert i4.kernel_enabled()
    fw.close()  # idempotent: a double close must not over-release
    assert i4.kernel_enabled()


def test_llm_filter_int4_pipeline():
    import nnstreamer_tpu as nt

    p = nt.Pipeline(
        "appsrc name=src ! tensor_filter framework=llm model=llama_tiny "
        "custom=max_new:4,quant:int4,dtype:float32,stream_chunk:2 "
        "invoke-dynamic=true ! tensor_sink name=out"
    )
    with p:
        p.push("src", np.array([1, 5, 9], np.int32))
        ids = [int(np.asarray(p.pull("out", timeout=120).tensors[0])[0])
               for _ in range(4)]
        p.eos()
        p.wait(timeout=60)
    assert len(ids) == 4
    assert all(0 <= i < CFG.vocab for i in ids)
