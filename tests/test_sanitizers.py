"""Sanitizer builds of the native runtime (SURVEY §5.2: the reference CI
runs valgrind/ASan passes over its C core; the analog here compiles
``native/src/nnstpu.cpp`` with -fsanitize=thread / address and hammers
the concurrency- and bounds-sensitive paths with real threads).

A TSan report or ASan error makes the driver exit nonzero (halt_on_error
is the default for ASan; TSan exits 66 on report), failing the test.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "nnstreamer_tpu", "native", "src", "nnstpu.cpp")

DRIVER = textwrap.dedent("""
    #include <cstdint>
    #include <cstdio>
    #include <cstdlib>
    #include <cstring>
    #include <thread>
    #include <vector>

    extern "C" {
    uint32_t nns_crc32(const uint8_t *data, uint64_t len, uint32_t seed);
    void nns_strip_stride(const uint8_t *src, uint8_t *dst, uint64_t rows,
                          uint64_t row_bytes, uint64_t stride);
    uint64_t nns_wire_frame_size(const uint64_t *seg_lens, uint32_t nsegs);
    void nns_wire_gather(const uint8_t *const *segs,
                         const uint64_t *seg_lens, uint32_t nsegs,
                         uint8_t *out);
    int nns_wire_check(const uint8_t *payload, uint64_t len, uint32_t crc);
    void *nns_ring_create(const char *name, uint32_t nslots,
                          uint64_t slot_bytes);
    void *nns_ring_open(const char *name);
    uint8_t *nns_ring_acquire(void *ring);
    int nns_ring_commit(void *ring, uint64_t len);
    const uint8_t *nns_ring_peek(void *ring, uint64_t *len);
    void nns_ring_release(void *ring);
    void nns_ring_close(void *ring);
    void nns_ring_free(void *ring);
    }

    #include <unistd.h>

    int main(void) {
        /* SPSC ring: a real producer thread racing a real consumer
         * thread through the shared-memory slots.  BOTH threads use the
         * SAME handle (one mmap): TSan's shadow memory is per virtual
         * address, so separate mappings of the same shm would hide the
         * conflicting accesses from it entirely.  The cross-process open
         * path is smoke-checked separately below.  pid-suffixed name:
         * concurrent test runs must not collide on the shm object. */
        char name[64];
        snprintf(name, sizeof name, "/nns_tsan_%d", (int)getpid());
        void *prod = nns_ring_create(name, 8, 4096);
        if (!prod) { fprintf(stderr, "ring_create failed\\n"); return 1; }

        const int N = 2000;
        std::thread producer([&] {
            for (int i = 0; i < N;) {
                uint8_t *slot = nns_ring_acquire(prod);
                if (!slot) { std::this_thread::yield(); continue; }
                memset(slot, i & 0xff, 128);
                nns_ring_commit(prod, 128);
                i++;
            }
        });
        long long seen = 0;
        std::thread consumer([&] {
            for (int i = 0; i < N;) {
                uint64_t len = 0;
                const uint8_t *p = nns_ring_peek(prod, &len);
                if (!p) { std::this_thread::yield(); continue; }
                if (len != 128 || p[0] != (uint8_t)(i & 0xff)) {
                    fprintf(stderr, "slot %d corrupt\\n", i);
                    _Exit(2);
                }
                seen += p[0];
                nns_ring_release(prod);
                i++;
            }
        });
        producer.join();
        consumer.join();

        /* cross-process open path (second mapping): produce one more
         * slot, read it back through an independently-opened handle */
        void *cons = nns_ring_open(name);
        if (!cons) { fprintf(stderr, "ring_open failed\\n"); return 1; }
        uint8_t *slot = nns_ring_acquire(prod);
        if (!slot) { fprintf(stderr, "acquire failed\\n"); return 1; }
        memset(slot, 0x7e, 64);
        nns_ring_commit(prod, 64);
        uint64_t len = 0;
        const uint8_t *p = nns_ring_peek(cons, &len);
        if (!p || len != 64 || p[0] != 0x7e) {
            fprintf(stderr, "open-path readback failed\\n");
            return 2;
        }
        nns_ring_release(cons);
        nns_ring_close(prod);
        nns_ring_free(cons);
        nns_ring_free(prod);

        /* wire + crc + repack under the sanitizer's bounds checking,
         * including 0- and 1-byte segments.  Verify the crc the frame
         * ACTUALLY carries (8-byte length prefix + payload + trailing
         * crc), not a recomputation of our own. */
        uint8_t a[256], b[1];
        for (int i = 0; i < 256; i++) a[i] = (uint8_t)i;
        b[0] = 0x5a;
        const uint8_t *segs[3] = {a, b, a};
        uint64_t lens[3] = {256, 1, 0};
        uint64_t fsz = nns_wire_frame_size(lens, 3);
        std::vector<uint8_t> frame(fsz);
        nns_wire_gather(segs, lens, 3, frame.data());
        uint64_t payload_len = 0;
        memcpy(&payload_len, frame.data(), 8);
        if (payload_len != 257) {
            fprintf(stderr, "wire length header wrong: %llu\\n",
                    (unsigned long long)payload_len);
            return 3;
        }
        uint32_t trailing_crc = 0;
        memcpy(&trailing_crc, frame.data() + 8 + payload_len, 4);
        if (!nns_wire_check(frame.data() + 8, payload_len, trailing_crc)) {
            fprintf(stderr, "wire_check failed\\n");
            return 3;
        }
        std::vector<uint8_t> strided(16 * 64), packed(16 * 48);
        nns_strip_stride(strided.data(), packed.data(), 16, 48, 64);

        printf("SANITIZED OK %lld\\n", seen);
        return 0;
    }
""")


def _build_and_run(tmp_path, sanitizer: str) -> str:
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    exe = str(tmp_path / f"stress_{sanitizer}")
    src = tmp_path / "driver.cpp"
    src.write_text(DRIVER)
    base = ["g++", "-O1", "-g", "-std=c++17", str(src), SRC, "-lrt",
            "-pthread"]
    # A PLAIN compile failure is a real break in the driver or
    # nnstpu.cpp and must FAIL, not skip; only a sanitized-only failure
    # (missing libtsan/libasan on this toolchain) skips.
    plain = subprocess.run(base + ["-o", os.devnull], capture_output=True,
                           text=True, timeout=180)
    assert plain.returncode == 0, f"native build broken:\n{plain.stderr}"
    proc = subprocess.run(base + [f"-fsanitize={sanitizer}", "-o", exe],
                          capture_output=True, text=True, timeout=180)
    if proc.returncode != 0:
        pytest.skip(f"{sanitizer} runtime unavailable: "
                    f"{proc.stderr[-200:]}")
    run = subprocess.run([exe], capture_output=True, text=True,
                         timeout=180)
    assert run.returncode == 0, (
        f"{sanitizer} run failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}")
    assert "SANITIZED OK" in run.stdout
    return run.stderr


@pytest.mark.slow
def test_thread_sanitizer_ring(tmp_path):
    err = _build_and_run(tmp_path, "thread")
    assert "WARNING: ThreadSanitizer" not in err


@pytest.mark.slow
def test_address_sanitizer_paths(tmp_path):
    err = _build_and_run(tmp_path, "address")
    assert "ERROR: AddressSanitizer" not in err
