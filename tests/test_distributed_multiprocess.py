"""Two-PROCESS distribution witnesses (VERDICT r2 missing #5 / SURVEY
§5.8): the DCN half of the comm backend, previously code without a test.

* Collective path: two real OS processes join via
  ``parallel.distributed.initialize`` (jax coordinator + gloo CPU
  collectives), build a global mesh spanning both processes' devices, and
  reduce process-local shards — ordered across batches.
* Stream-feed path: a query server pipeline in a second process; the
  parent feeds batches over the real TCP wire and asserts ordered
  reassembly (the "DCN/gRPC host-level stream feed" role).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _child_env(devices_per_proc: int) -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # children pin cpu via jax.config
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


_COLLECTIVE_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.parallel import distributed as dist
    from nnstreamer_tpu.parallel import make_mesh

    pid, port = int(sys.argv[1]), sys.argv[2]
    ok = dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)
    assert ok and dist.is_initialized()
    assert dist.local_device_count() == 2, jax.local_devices()
    assert dist.global_device_count() == 4, jax.devices()

    mesh = dist.global_mesh()  # data axis absorbs all four global devices
    assert mesh.devices.size == 4
    # feed sharded batches; reductions must come back in batch order
    for k in range(3):
        local = np.arange(2, dtype=np.float32) + 10 * pid + 100 * k
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), local)
        total = jax.jit(lambda a: a.sum(),
                        out_shardings=NamedSharding(mesh, P()))(arr)
        val = float(np.asarray(jax.device_get(total)))
        expect = float(sum((np.arange(2) + 10 * p + 100 * k).sum()
                           for p in range(2)))
        assert val == expect, (k, val, expect)
        print(f"BATCH {k} {val}", flush=True)
    print("DCN OK", pid, flush=True)
""")


@pytest.mark.slow
def test_two_process_collectives_ordered(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_COLLECTIVE_CHILD)
    port = _free_port()
    env = _child_env(devices_per_proc=2)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("two-process collective child hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out}"
        assert f"DCN OK {pid}" in out
        # batches arrived in order on both processes
        lines = [l for l in out.splitlines() if l.startswith("BATCH")]
        assert [l.split()[1] for l in lines] == ["0", "1", "2"]


_SERVER_CHILD = textwrap.dedent("""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import sys
    import numpy as np
    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    spec = TensorsSpec.from_string("4", "float32")
    register_custom_easy("dcn-double", lambda ins: [ins[0] * 2],
                         in_spec=spec, out_spec=spec)
    p = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=9 ! "
        "tensor_filter framework=custom-easy model=dcn-double ! "
        "tensor_query_serversink id=9")
    p.start()
    print("PORT", p.element("ssrc").bound_port, flush=True)
    sys.stdin.read()  # parent closes stdin to stop the server
    p.stop()
""")


@pytest.mark.slow
def test_query_feed_across_processes(tmp_path):
    """Host-level stream feed over the real wire to another PROCESS:
    ordered round-trip of a batch stream through a remote pipeline."""
    import nnstreamer_tpu as nt

    script = tmp_path / "server.py"
    script.write_text(_SERVER_CHILD)
    env = _child_env(devices_per_proc=2)
    srv = subprocess.Popen([sys.executable, str(script)], env=env,
                           stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                           stderr=subprocess.STDOUT, text=True)
    try:
        line = srv.stdout.readline()
        assert line.startswith("PORT"), f"server did not start: {line}"
        port = int(line.split()[1])
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "timeout=30 ! tensor_sink name=out")
        with cli:
            for i in range(8):
                cli.push("src", np.full((4,), float(i), np.float32))
            for i in range(8):
                out = cli.pull("out", timeout=30)
                np.testing.assert_allclose(
                    np.asarray(out.tensors[0]), np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=30)
    finally:
        try:
            srv.stdin.close()
            srv.wait(timeout=20)
        except Exception:
            srv.kill()


_TP_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.models import llama
    from nnstreamer_tpu.parallel import distributed as dist
    from nnstreamer_tpu.parallel import make_mesh, shard_params

    pid, port = int(sys.argv[1]), sys.argv[2]
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)
    assert dist.global_device_count() == 2

    # model axis SPANS the two processes: every matmul's all-reduce is a
    # real cross-host collective (gloo here; ICI on a pod)
    mesh = make_mesh(model=2, data=1, devices=jax.devices())
    cfg = llama.PRESETS["llama_tiny"]
    params = llama.init_params(cfg, seed=0)
    sharded = shard_params(mesh, params, llama.param_pspecs())
    toks = np.array([[1, 7, 3, 9]], np.int32)
    logits = llama.forward(sharded, toks, cfg, compute_dtype="float32")
    out = np.asarray(jax.device_get(
        jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(
            logits)))
    ref = np.asarray(llama.forward(params, toks, cfg,
                                   compute_dtype="float32"))
    err = float(np.max(np.abs(out - ref)))
    assert err < 1e-4, f"cross-host TP diverges from local: {err}"
    print("TP OK", pid, err, flush=True)
""")


@pytest.mark.slow
def test_two_process_tensor_parallel_llama(tmp_path):
    """TP over DCN: llama_tiny's weights sharded over a model axis that
    spans two real processes; logits must match the unsharded forward."""
    script = tmp_path / "tp_child.py"
    script.write_text(_TP_CHILD)
    port = _free_port()
    env = _child_env(devices_per_proc=1)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("cross-host TP child hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out}"
        assert f"TP OK {pid}" in out
