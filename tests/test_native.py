"""Native (C++) layer: crc32, stride repack, wire gather, shm ring,
shmsrc/shmsink elements.

Reference analog for the test shape: SSAT suites drive two pipelines through
an IPC boundary on one host (SURVEY §4 "multi-node without a cluster");
here shmsink/shmsrc pipelines talk through the POSIX shm ring, including a
real second process.
"""

from __future__ import annotations

import multiprocessing as mp
import zlib

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for native library"
)


class TestCrc32:
    def test_matches_zlib(self, rng):
        for n in (0, 1, 7, 8, 64, 100_000):
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF

    def test_seeded_chaining(self, rng):
        a = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
        b = rng.integers(0, 256, 1000, dtype=np.uint8).tobytes()
        assert native.crc32(b, seed=native.crc32(a)) == native.crc32(a + b)


class TestStripStride:
    def test_strided_rows(self):
        src = np.arange(64, dtype=np.uint8)
        out = native.strip_stride(src, rows=4, row_bytes=10, src_stride=16)
        exp = np.concatenate([src[i * 16 : i * 16 + 10] for i in range(4)])
        assert np.array_equal(out, exp)

    def test_dense_passthrough(self):
        src = np.arange(40, dtype=np.uint8)
        out = native.strip_stride(src, rows=4, row_bytes=10, src_stride=10)
        assert np.array_equal(out, src)


class TestWireGather:
    def test_frame_layout(self):
        import struct

        frame = native.wire_gather([b"hello", b"world"])
        (ln,) = struct.unpack_from("<Q", frame, 0)
        assert ln == 10
        assert frame[8:18] == b"helloworld"
        (crc,) = struct.unpack_from("<I", frame, 18)
        assert native.wire_check(b"helloworld", crc)
        assert not native.wire_check(b"helloworlX", crc)


class TestShmRing:
    def test_roundtrip_and_capacity(self):
        r = native.ShmRing.create("/nnstpu_t1", 4, 256)
        try:
            c = native.ShmRing.open("/nnstpu_t1")
            assert c.try_get() is None
            for i in range(4):
                assert r.try_put(bytes([i]) * (i + 1))
            assert not r.try_put(b"overflow")  # full
            for i in range(4):
                assert c.try_get() == bytes([i]) * (i + 1)
            assert r.try_put(b"again")
            assert c.try_get() == b"again"
            c.free()
        finally:
            r.free()

    def test_close_signals_consumer(self):
        r = native.ShmRing.create("/nnstpu_t2", 2, 64)
        try:
            assert not r.closed
            r.close_write()
            assert r.closed
        finally:
            r.free()

    def test_oversize_payload_rejected(self):
        r = native.ShmRing.create("/nnstpu_t3", 2, 16)
        try:
            with pytest.raises(ValueError):
                r.try_put(b"x" * 17)
        finally:
            r.free()


def _consumer_proc(q):
    import nnstreamer_tpu as nt

    p = nt.Pipeline(
        "shmsrc socket-path=/nnstpu_e2e ! tensor_sink name=out"
    )
    with p:
        got = []
        for _ in range(3):
            got.append(p.pull("out", timeout=20))
        p.wait(timeout=20)
    q.put([np.asarray(b.tensors[0]).tolist() for b in got])


class TestShmElements:
    def test_same_process_pipelines(self):
        sink_pipe = nt.Pipeline(
            "appsrc name=src ! shmsink socket-path=/nnstpu_sp buffers=4"
        )
        with sink_pipe:
            src_pipe = nt.Pipeline("shmsrc socket-path=/nnstpu_sp ! tensor_sink name=out")
            with src_pipe:
                for i in range(3):
                    sink_pipe.push("src", np.full((2, 2), i, np.int32))
                outs = [src_pipe.pull("out", timeout=10) for _ in range(3)]
                sink_pipe.eos()
                sink_pipe.wait(timeout=10)
                src_pipe.wait(timeout=10)
        for i, b in enumerate(outs):
            assert np.array_equal(b.tensors[0], np.full((2, 2), i, np.int32))

    def test_cross_process(self):
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        sink_pipe = nt.Pipeline(
            "appsrc name=src ! shmsink socket-path=/nnstpu_e2e buffers=4"
        )
        with sink_pipe:
            proc = ctx.Process(target=_consumer_proc, args=(q,))
            proc.start()
            try:
                for i in range(3):
                    sink_pipe.push("src", np.array([i, i + 1], np.float32))
                sink_pipe.eos()
                sink_pipe.wait(timeout=20)
                got = q.get(timeout=30)
            finally:
                proc.join(timeout=30)
                if proc.is_alive():
                    proc.terminate()
        assert got == [[0.0, 1.0], [1.0, 2.0], [2.0, 3.0]]

    def test_pts_and_meta_survive(self):
        sink_pipe = nt.Pipeline("appsrc name=src ! shmsink socket-path=/nnstpu_meta")
        with sink_pipe:
            src_pipe = nt.Pipeline("shmsrc socket-path=/nnstpu_meta ! tensor_sink name=out")
            with src_pipe:
                buf = nt.Buffer([np.ones(3, np.uint8)], pts=12345)
                buf.meta["label"] = "hi"
                sink_pipe.push("src", buf)
                out = src_pipe.pull("out", timeout=10)
                sink_pipe.eos()
                sink_pipe.wait(timeout=10)
                src_pipe.wait(timeout=10)
        assert out.pts == 12345
        assert out.meta["label"] == "hi"


def test_ring_create_refuses_live_duplicate():
    r = native.ShmRing.create("/nnstpu_live", 2, 64)
    try:
        with pytest.raises(OSError):
            native.ShmRing.create("/nnstpu_live", 2, 64)
    finally:
        r.free()
    # After free (owner unlinked), the name is reusable.
    r2 = native.ShmRing.create("/nnstpu_live", 2, 64)
    r2.free()
