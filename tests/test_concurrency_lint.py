"""nns-tsan tests: golden bad fixtures for the static concurrency lint
(exact diagnostic code + caret position), a clean dogfood pass over the
shipped package, and live TrackedLock/TrackedCondition semantics —
inversion raise, self-deadlock-before-block, guarded-field assertion,
and the structurally-zero-overhead off path (docs/ANALYSIS.md "Threads
pass")."""

import os
import threading
import time

import pytest

from nnstreamer_tpu.analysis import concurrency
from nnstreamer_tpu.analysis.diagnostics import ERROR, WARNING
from nnstreamer_tpu.utils import locks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_fixture(tmp_path, source, name="fix.py"):
    p = tmp_path / name
    p.write_text(source)
    reports, stats = concurrency.lint_paths([str(p)], root=str(tmp_path))
    diags = [d for rep in reports for d in rep.diagnostics]
    return reports, diags, source


def _caret_line(report):
    """The rendered caret block for the report's first diagnostic."""
    return report.render(carets=True)


# ---------------------------------------------------------------------------
# golden bad fixtures: one per diagnostic class, exact code + position
# ---------------------------------------------------------------------------

UNGUARDED = '''\
import threading


class Counter:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        self._n += 1
'''


def test_unguarded_write_detected(tmp_path):
    reports, diags, src = _lint_fixture(tmp_path, UNGUARDED)
    assert [d.code for d in diags] == ["unguarded-write"]
    d = diags[0]
    assert d.severity == ERROR
    assert d.path.endswith("Counter.bump._n")
    # caret lands exactly on the write statement
    assert d.pos == src.index("self._n += 1")
    rendered = reports[0].render()
    assert "self._n += 1" in rendered and "^" in rendered


def test_guarded_write_clean(tmp_path):
    ok = UNGUARDED.replace(
        "    def bump(self):\n        self._n += 1\n",
        "    def bump(self):\n        with self._lock:\n"
        "            self._n += 1\n")
    _, diags, _ = _lint_fixture(tmp_path, ok)
    assert diags == []


def test_mutator_call_flagged(tmp_path):
    """unguarded-write: container mutators count as writes."""
    src = UNGUARDED.replace("self._n = 0", "self._n = []").replace(
        "self._n += 1", "self._n.append(1)")
    _, diags, s = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["unguarded-write"]
    assert diags[0].pos == s.index("self._n.append(1)")


def test_locked_helper_chain_proven(tmp_path):
    """Regression for the fixpoint call-site rule (unguarded-write):
    a ``_locked`` helper chain of depth 2 whose only entry holds the
    lock must NOT flag — Journal's append → _write_locked →
    _rotate_locked shape."""
    src = '''\
import threading


class J:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def append(self):
        with self._lock:
            self._write_locked()

    def _write_locked(self):
        self._rotate_locked()

    def _rotate_locked(self):
        self._n += 1
'''
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert diags == []


def test_unlocked_caller_breaks_the_proof(tmp_path):
    """unguarded-write names the call site that fails the proof."""
    src = '''\
import threading


class J:
    _GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def good(self):
        with self._lock:
            self._bump()

    def bad(self):
        self._bump()

    def _bump(self):
        self._n += 1
'''
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["unguarded-write"]
    assert "J.bad()" in diags[0].message


INVERSION = '''\
import threading

A = threading.Lock()
B = threading.Lock()


def forward():
    with A:
        with B:
            pass


def backward():
    with B:
        with A:
            pass
'''


def test_lock_order_inversion_detected(tmp_path):
    _, diags, _ = _lint_fixture(tmp_path, INVERSION)
    inv = [d for d in diags if d.code == "lock-order-inversion"]
    assert len(inv) == 1
    d = inv[0]
    assert d.severity == ERROR
    # both acquisition paths are named in the message
    assert ":A -> " in d.message and ":B -> " in d.message
    assert d.path.startswith("order:")


def test_consistent_order_clean(tmp_path):
    src = INVERSION.replace("    with B:\n        with A:",
                            "    with A:\n        with B:")
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d for d in diags if d.code == "lock-order-inversion"] == []


UNJOINED = '''\
import threading


class Owner:
    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        pass
'''


def test_unjoined_thread_detected(tmp_path):
    _, diags, src = _lint_fixture(tmp_path, UNJOINED)
    assert [d.code for d in diags] == ["unjoined-thread"]
    d = diags[0]
    assert d.severity == ERROR
    assert d.pos == src.index("threading.Thread(")


def test_joined_thread_clean(tmp_path):
    src = UNJOINED + '''
    def stop(self):
        self._thread.join()
'''
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert diags == []


def test_join_via_tuple_swap_dataflow(tmp_path):
    """The ``t, self._thread = self._thread, None`` idiom still counts
    as joining the owned thread (unjoined-thread dataflow)."""
    src = UNJOINED + '''
    def stop(self):
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
'''
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert diags == []


def test_daemon_thread_warned(tmp_path):
    src = UNJOINED.replace("target=self._run)",
                           "target=self._run, daemon=True)")
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["daemon-thread"]
    assert diags[0].severity == WARNING


COND_WAIT = '''\
import threading


class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []

    def get(self):
        with self._cond:
            if not self._items:
                self._cond.wait()
            return self._items.pop()
'''


def test_cond_wait_without_predicate_loop(tmp_path):
    _, diags, src = _lint_fixture(tmp_path, COND_WAIT)
    assert [d.code for d in diags] == ["cond-wait-no-predicate"]
    d = diags[0]
    assert d.severity == WARNING
    assert d.pos == src.index("self._cond.wait()")  # the bare wait


def test_cond_wait_in_while_clean(tmp_path):
    src = COND_WAIT.replace("            if not self._items:",
                            "            while not self._items:")
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert diags == []


# ---------------------------------------------------------------------------
# dogfood: the shipped package passes vs the committed baseline
# ---------------------------------------------------------------------------

def test_package_dogfood_clean_vs_baseline():
    reports, stats = concurrency.lint_package()
    baseline = set()
    with open(os.path.join(REPO, "tools", "tsan_baseline.txt")) as f:
        for ln in f:
            ln = ln.strip()
            if ln and not ln.startswith("#"):
                baseline.add(ln)
    new = [d for rep in reports for d in rep.diagnostics
           if concurrency.baseline_key(d) not in baseline]
    assert new == [], "\n".join(str(d) for d in new)
    # errors are NEVER baselined — the file may only carry warnings
    errs = [d for rep in reports for d in rep.diagnostics
            if d.severity == ERROR]
    assert errs == [], "\n".join(str(d) for d in errs)
    assert stats["guarded_classes"] >= 12
    assert stats["threaded"] >= 20


def test_baseline_keys_carry_no_line_numbers():
    reports, _ = concurrency.lint_package()
    for rep in reports:
        for d in rep.diagnostics:
            key = concurrency.baseline_key(d)
            assert key.startswith("threads:")
            assert ":char" not in key and " " not in key


# ---------------------------------------------------------------------------
# dynamic side: tracked primitives
# ---------------------------------------------------------------------------

@pytest.fixture
def tsan(monkeypatch):
    monkeypatch.setenv(locks.ENV_FLAG, "1")
    monkeypatch.setenv(locks.ENV_RAISE, "1")
    locks.reset()
    yield
    locks.reset()


def test_live_inversion_raises_with_both_paths(tsan):
    a = locks.make_lock("T.A")
    b = locks.make_lock("T.B")
    assert isinstance(a, locks.TrackedLock)

    def forward():
        with a:
            with b:
                pass

    t = threading.Thread(target=forward, name="fwd")
    t.start()
    t.join()
    with b:
        with pytest.raises(locks.LockOrderError) as ei:
            with a:
                pass
    msg = str(ei.value)
    assert "T.B -> T.A" in msg and "T.A -> T.B" in msg
    rep = locks.report()
    assert rep["enabled"] and len(rep["inversions"]) == 1
    # the liveness counter the check_tier1 tsan gate pins on: edges can
    # be 0 in a clean run, acquisitions cannot
    assert rep["acquisitions"] >= 3 and rep["edges"] >= 2


def test_inversion_recorded_without_raise(tsan, monkeypatch):
    monkeypatch.delenv(locks.ENV_RAISE, raising=False)
    a = locks.make_lock("R.A")
    b = locks.make_lock("R.B")
    with a:
        with b:
            pass
    with b:
        with a:  # records, does not raise (the soak posture)
            pass
    assert len(locks.report()["inversions"]) == 1


def test_self_deadlock_caught_before_blocking(tsan):
    lk = locks.make_lock("T.self")
    with lk:
        t0 = time.monotonic()
        with pytest.raises(locks.LockOrderError, match="self-deadlock"):
            lk.acquire()
        assert time.monotonic() - t0 < 1.0  # raised, never blocked
    assert not lk.locked()


def test_rlock_reentry_legal(tsan):
    rl = locks.make_rlock("T.re")
    with rl:
        with rl:
            assert rl.held_by_me()
    assert not rl.locked()
    assert locks.report()["inversions"] == []


def test_condition_over_shared_tracked_lock(tsan):
    lk = locks.make_lock("T.q")
    not_empty = locks.make_condition(lk, name="T.q.not_empty")
    items = []

    def producer():
        time.sleep(0.05)
        with not_empty:
            items.append(1)
            not_empty.notify()

    t = threading.Thread(target=producer, name="prod")
    t.start()
    with not_empty:
        while not items:
            assert not_empty.wait(timeout=5.0)
    t.join()
    assert items == [1]
    assert locks.report()["inversions"] == []


def test_assert_guarded_live(tsan):
    class Owner:
        _GUARDED_BY = {"_n": "_lock"}

        def __init__(self):
            self._lock = locks.make_lock("Owner._lock")
            self._n = 0

    o = Owner()
    with o._lock:
        locks.assert_guarded(o, "_n")  # held: fine
    with pytest.raises(locks.GuardViolation, match="Owner._n"):
        locks.assert_guarded(o, "_n")  # not held: flagged
    assert len(locks.report()["guard_violations"]) == 1


# ---------------------------------------------------------------------------
# off path: structurally zero overhead when the env is unset
# ---------------------------------------------------------------------------

def test_off_mode_vends_plain_primitives(monkeypatch):
    monkeypatch.delenv(locks.ENV_FLAG, raising=False)
    assert type(locks.make_lock("x")) is type(threading.Lock())
    assert isinstance(locks.make_rlock("x"),
                      type(threading.RLock()))
    assert isinstance(locks.make_condition(name="x"),
                      threading.Condition)


def test_off_mode_never_touches_the_graph(monkeypatch):
    """The CI structural pin: with the env unset, NO graph hook may
    run — the off path is the untracked path, not 'tracking that
    discards' (the tracing-off posture, tools/tracing_gate.py)."""
    monkeypatch.delenv(locks.ENV_FLAG, raising=False)

    def boom(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("LockOrderGraph hook ran in off mode")

    monkeypatch.setattr(locks.LockOrderGraph, "acquired", boom)
    monkeypatch.setattr(locks.LockOrderGraph, "released", boom)
    monkeypatch.setattr(locks.LockOrderGraph, "before_acquire", boom)
    lk = locks.make_lock("off")
    with lk:
        pass
    cond = locks.make_condition(name="off.cond")
    with cond:
        cond.notify_all()
    # a fully-plain-locked owner still runs assert_guarded for free
    monkeypatch.setattr(locks, "_active", False)

    class Owner:
        _GUARDED_BY = {"_n": "_lock"}

        def __init__(self):
            self._lock = locks.make_lock("Owner._lock")
            self._n = 0

    locks.assert_guarded(Owner(), "_n")  # no lock held: still silent
