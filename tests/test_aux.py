"""Aux subsystems: watchdog, metrics endpoint, profiler render, parser CLI.

Reference analogs: nnstreamer_watchdog.c, the latency/throughput properties
(SURVEY §5.1/§5.3/§5.5), tools/development/parser (§2.8).
"""

from __future__ import annotations

import threading
import time
import urllib.request

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.utils.profiler import metrics_text, start_metrics_server
from nnstreamer_tpu.utils.watchdog import Watchdog


class TestWatchdog:
    def test_fires_without_feed(self):
        fired = threading.Event()
        with Watchdog(0.05, fired.set):
            assert fired.wait(1.0)

    def test_feed_defers(self):
        fired = threading.Event()
        with Watchdog(0.15, fired.set) as wd:
            for _ in range(4):
                time.sleep(0.05)
                wd.feed()
            assert not fired.is_set()
        time.sleep(0.25)
        assert not fired.is_set()  # disarmed on exit

    def test_fires_once(self):
        count = []
        wd = Watchdog(0.03, lambda: count.append(1)).arm()
        time.sleep(0.2)
        wd.disarm()
        assert count == [1]
        assert wd.fired

    def test_trainer_watchdog_times_out_hung_subplugin(self):
        from nnstreamer_tpu.core.registry import register_trainer
        from nnstreamer_tpu.trainer.subplugin import TrainerSubplugin

        @register_trainer("hang")
        class HangingTrainer(TrainerSubplugin):
            name = "hang"

            def push_data(self, inputs, labels, is_validation):
                pass

            def train_epoch(self):
                time.sleep(2.0)
                return {}

            def save(self, path):
                return path

        p = nt.Pipeline(
            "appsrc name=src ! tensor_trainer framework=hang "
            "num-training-samples=1 epochs=1 watchdog-timeout=0.1 ! "
            "fakesink",
        )
        with p:
            p.push("src", [np.zeros(2, np.float32), np.zeros(1, np.int32)])
            p.eos()
            from nnstreamer_tpu.pipeline.runtime import PipelineError

            with pytest.raises(PipelineError, match="watchdog"):
                p.wait(timeout=30)


class TestMetricsEndpoint:
    def test_prometheus_text(self):
        metrics.count("aux.test.frames", 3)
        text = metrics_text()
        assert "nnstpu_aux_test_frames 3" in text

    def test_http_metrics(self):
        metrics.count("aux.http.hits", 7)
        srv = start_metrics_server(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_port}/metrics", timeout=5
            ).read().decode()
            assert "nnstpu_aux_http_hits 7" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.server_port}/nope", timeout=5
                )
        finally:
            srv.shutdown()


class TestParserCli:
    def test_valid_pipeline(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["videotestsrc num-buffers=1 ! tensor_converter ! tensor_sink name=out"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "VALID: 3 elements" in out

    def test_invalid_pipeline(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["videotestsrc !"])
        err = capsys.readouterr().err
        assert rc == 1 and "INVALID" in err

    def test_dot_output(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["--dot", "videotestsrc ! tensor_sink"])
        out = capsys.readouterr().out
        assert rc == 0 and out.startswith("digraph") and "->" in out

    def test_plan_shows_fusion(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main([
            "--plan",
            "appsrc caps=other/tensors,dimensions=4:4,types=float32 ! "
            "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:4:4 ! "
            "tensor_decoder mode=image_labeling option1=digits ! tensor_sink",
        ])
        out = capsys.readouterr().out
        assert rc == 0 and "fused:" in out

    def test_unknown_element_rejected(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["badelem ! tensor_sink"])
        err = capsys.readouterr().err
        assert rc == 1 and "unknown element" in err
