"""Aux subsystems: watchdog, metrics endpoint, profiler render, parser CLI.

Reference analogs: nnstreamer_watchdog.c, the latency/throughput properties
(SURVEY §5.1/§5.3/§5.5), tools/development/parser (§2.8).
"""

from __future__ import annotations

import threading
import time
import urllib.request

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.utils.profiler import metrics_text, start_metrics_server
from nnstreamer_tpu.utils.watchdog import Watchdog


class TestWatchdog:
    def test_fires_without_feed(self):
        fired = threading.Event()
        with Watchdog(0.05, fired.set):
            assert fired.wait(1.0)

    def test_feed_defers(self):
        fired = threading.Event()
        with Watchdog(0.15, fired.set) as wd:
            for _ in range(4):
                time.sleep(0.05)
                wd.feed()
            assert not fired.is_set()
        time.sleep(0.25)
        assert not fired.is_set()  # disarmed on exit

    def test_fires_once(self):
        count = []
        wd = Watchdog(0.03, lambda: count.append(1)).arm()
        time.sleep(0.2)
        wd.disarm()
        assert count == [1]
        assert wd.fired

    def test_disarm_during_fire_no_raise_no_double_fire(self):
        """The arm→fire→disarm race: disarm landing after ``_fire`` has
        STARTED (callback in flight, Timer.cancel can no longer stop it)
        must neither raise nor let a second fire through."""
        started = threading.Event()
        release = threading.Event()
        count = []

        def on_timeout():
            count.append(1)
            started.set()
            release.wait(2.0)

        wd = Watchdog(0.02, on_timeout).arm()
        assert started.wait(1.0)
        wd.disarm()  # callback still running: must be a clean no-op
        release.set()
        time.sleep(0.1)
        assert count == [1]
        assert wd.fired

    def test_feed_after_fire_is_noop(self):
        """feed() on a fired watchdog is a documented no-op: it must not
        resurrect the countdown or re-fire (re-arm explicitly instead)."""
        count = []
        wd = Watchdog(0.02, lambda: count.append(1)).arm()
        time.sleep(0.1)
        assert count == [1]
        wd.feed()  # fired: no-op
        time.sleep(0.1)
        assert count == [1]
        wd.disarm()
        wd.feed()  # disarmed: also a no-op
        time.sleep(0.1)
        assert count == [1]

    def test_stale_fire_cannot_outrun_feed_or_rearm(self):
        """A timer callback that already expired but lost the lock race to
        feed()/disarm()/arm() carries a stale generation and must not fire.
        Driven directly (no sleep races): _fire with a stale gen is exactly
        the thread Timer.cancel() could not stop."""
        count = []
        wd = Watchdog(60.0, lambda: count.append(1)).arm()
        stale = wd._gen
        wd.feed()  # bumps the generation; the old timer is now stale
        wd._fire(stale)
        assert count == [] and not wd.fired
        wd._fire(wd._gen)  # the CURRENT generation does fire
        assert count == [1] and wd.fired
        wd.disarm()
        # re-arm: a leftover callback from before the disarm stays dead
        old = wd._gen
        wd.arm()
        wd._fire(old)
        assert count == [1]
        wd.disarm()

    def test_trainer_watchdog_times_out_hung_subplugin(self):
        from nnstreamer_tpu.core.registry import register_trainer
        from nnstreamer_tpu.trainer.subplugin import TrainerSubplugin

        @register_trainer("hang")
        class HangingTrainer(TrainerSubplugin):
            name = "hang"

            def push_data(self, inputs, labels, is_validation):
                pass

            def train_epoch(self):
                time.sleep(2.0)
                return {}

            def save(self, path):
                return path

        p = nt.Pipeline(
            "appsrc name=src ! tensor_trainer framework=hang "
            "num-training-samples=1 epochs=1 watchdog-timeout=0.1 ! "
            "fakesink",
        )
        with p:
            p.push("src", [np.zeros(2, np.float32), np.zeros(1, np.int32)])
            p.eos()
            from nnstreamer_tpu.pipeline.runtime import PipelineError

            with pytest.raises(PipelineError, match="watchdog"):
                p.wait(timeout=30)


class TestMetricsEndpoint:
    def test_prometheus_text(self):
        metrics.count("aux.test.frames", 3)
        text = metrics_text()
        assert "nnstpu_aux_test_frames 3" in text

    def test_colliding_sanitized_names_disambiguated(self):
        """Two raw names that sanitize identically must BOTH render, under
        distinct deterministic names — one sample silently shadowing the
        other corrupts the scrape."""
        metrics.count("aux.col:x", 3)
        metrics.count("aux.col/x", 5)
        first = metrics_text()
        again = metrics_text()
        lines = [ln for ln in first.splitlines()
                 if ln.startswith("nnstpu_aux_col_x") and " " in ln]
        assert len(lines) == 2, first
        names = {ln.split()[0] for ln in lines}
        assert len(names) == 2
        assert {ln.split()[1] for ln in lines} == {"3", "5"}
        # deterministic: same registry, same rendering
        assert first == again

    def test_batching_series_carry_help_and_type(self):
        metrics.count("mystage.batch_pad_waste", 4)
        metrics.count("mystage.shard_rows.d0", 8)
        metrics.observe("mystage.batch_occupancy", 6.0)
        text = metrics_text()
        assert "# HELP nnstpu_mystage_batch_pad_waste" in text
        assert "# TYPE nnstpu_mystage_batch_pad_waste counter" in text
        assert "# TYPE nnstpu_mystage_shard_rows_d0 counter" in text
        # derived quantile samples of a distribution are gauges
        assert "# TYPE nnstpu_mystage_batch_occupancy_p50 gauge" in text
        # TYPE must precede its sample line (well-formed exposition)
        lines = text.splitlines()
        t = lines.index("# TYPE nnstpu_mystage_batch_pad_waste counter")
        assert lines[t + 1].startswith("nnstpu_mystage_batch_pad_waste 4")

    def test_http_metrics(self):
        metrics.count("aux.http.hits", 7)
        srv = start_metrics_server(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_port}/metrics", timeout=5
            ).read().decode()
            assert "nnstpu_aux_http_hits 7" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.server_port}/nope", timeout=5
                )
        finally:
            srv.shutdown()


class TestParserCli:
    def test_valid_pipeline(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["videotestsrc num-buffers=1 ! tensor_converter ! tensor_sink name=out"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "VALID: 3 elements" in out

    def test_invalid_pipeline(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["videotestsrc !"])
        err = capsys.readouterr().err
        assert rc == 1 and "INVALID" in err

    def test_dot_output(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["--dot", "videotestsrc ! tensor_sink"])
        out = capsys.readouterr().out
        assert rc == 0 and out.startswith("digraph") and "->" in out

    def test_plan_shows_fusion(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main([
            "--plan",
            "appsrc caps=other/tensors,dimensions=4:4,types=float32 ! "
            "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:4:4 ! "
            "tensor_decoder mode=image_labeling option1=digits ! tensor_sink",
        ])
        out = capsys.readouterr().out
        assert rc == 0 and "fused:" in out

    def test_unknown_element_rejected(self, capsys):
        from nnstreamer_tpu.tools.parse import main

        rc = main(["badelem ! tensor_sink"])
        err = capsys.readouterr().err
        assert rc == 1 and "unknown element" in err
