"""Device-resident tensor_aggregator state (ISSUE 10 tentpole,
docs/ARCHITECTURE.md "Streaming state").

The contract: with ``device=true`` the window carry lives as an HBM ring
written in-program (roll + dynamic-update-slice at a traced offset), so

* window outputs are BIT-IDENTICAL to the host concatenate path;
* exactly 3 programs compile for the stage's lifetime and window advances
  never recompile (occupancy/offset are values, not shapes);
* nothing crosses to host between window dispatches (transfer trap, the
  PR 7 zero-d2h technique) — ``aggregator ! tensor_filter`` chains hand
  windows filter-ward as device arrays;
* EOS drops partial windows exactly like the host path (and frees the
  ring).
"""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.elements.aggregator import TensorAggregator
from nnstreamer_tpu.elements.base import ElementError

#: 12 x 4000-sample device-generated audio buffers -> 16000-sample windows
#: advancing by 4000 (75% overlap): 9 complete windows
DESC = ("audiotestsrc device=true num-buffers=12 samplesperbuffer=4000 "
        "rate=16000 freq=880 name=src ! "
        "tensor_aggregator frames_in=4000 frames_out=16000 "
        "frames_flush=4000 frames_dim=0 name=agg {dev}! "
        "tensor_sink name=out{sink}")
N_WINDOWS = 9


def _run(dev="", sink="", n=N_WINDOWS, **kw):
    p = nt.Pipeline(DESC.format(dev=dev, sink=sink), **kw)
    outs = []
    with p:
        for _ in range(n):
            outs.append(p.pull("out", timeout=120))
        p.wait(timeout=60)
    return outs, p


# -- bit-identity -----------------------------------------------------------

def test_device_windows_bit_identical_to_host():
    """Overlapping windows, device ring vs host concatenate: pure data
    movement, so the bytes must match exactly."""
    host, _ = _run("")
    dev, _ = _run("device=true ")
    assert len(host) == len(dev) == N_WINDOWS
    for h, d in zip(host, dev):
        xh, xd = np.asarray(h.tensors[0]), np.asarray(d.tensors[0])
        assert xh.shape == xd.shape == (1, 16000)
        assert bytes(xh) == bytes(xd)
        assert h.pts == d.pts


def test_non_overlapping_windows_bit_identical():
    desc = ("audiotestsrc device=true num-buffers=8 samplesperbuffer=1000 "
            "rate=16000 name=src ! "
            "tensor_aggregator frames_in=1000 frames_out=4000 "
            "frames_flush=4000 frames_dim=0 name=agg {dev}! "
            "tensor_sink name=out")

    def run(dev):
        p = nt.Pipeline(desc.format(dev=dev))
        with p:
            outs = [p.pull("out", timeout=60) for _ in range(2)]
            p.wait(timeout=60)
        return outs

    for h, d in zip(run(""), run("device=true ")):
        assert bytes(np.asarray(h.tensors[0])) == bytes(
            np.asarray(d.tensors[0]))


# -- the 3-program zero-recompile pin ---------------------------------------

def test_zero_recompile_across_window_advances():
    """Once the ring programs are warm, pushing more buffers and emitting
    more windows must compile NOTHING: the write offset and the valid
    watermark are program VALUES."""
    el = TensorAggregator({"frames_in": 100, "frames_out": 400,
                           "frames_flush": 100, "frames_dim": 0,
                           "device": "true"}, name="agg")
    rng = np.random.default_rng(7)

    def push(i):
        return el.process("sink", Buffer(
            [rng.standard_normal((1, 100)).astype(np.float32)], pts=i))

    outs = [push(i) for i in range(6)]  # warm: ring init + append + window
    assert el._progs is not None and len(el._progs) == 3
    warm = {k: fn._cache_size() for k, fn in el._progs.items()}
    assert warm == {"init": 1, "append": 1, "window": 1}
    outs += [push(i) for i in range(6, 40)]  # many advances, varied phase
    after = {k: fn._cache_size() for k, fn in el._progs.items()}
    assert after == warm, f"recompile on window advance: {warm} -> {after}"
    assert sum(len(o) for o in outs) == 37  # (40*100 - 400)/100 + 1


# -- zero d2h between window dispatches -------------------------------------

def test_aggregator_chain_zero_d2h(monkeypatch):
    """From the device source through the ring to a to_host=false sink,
    NOTHING may cross to host: the fetch chokepoints are trapped (the
    PR 7 technique) and every delivered window is still a device array."""
    def trap(self):
        raise AssertionError("D2H on the aggregator's device-resident path")

    monkeypatch.setattr(Buffer, "to_host", trap)
    monkeypatch.setattr(Buffer, "resolve", trap)
    outs, p = _run("device=true ", sink=" to_host=false")
    assert all(o.on_device for o in outs)
    # and the planner knew: the agg -> sink edge aside, agg's downstream
    # edges count device-resident in the residency plan
    desc = DESC.format(dev="device=true ", sink="")
    p2 = nt.Pipeline(
        desc.replace("tensor_sink name=out",
                     "tensor_filter framework=jax model=speech_commands "
                     "custom=dtype:float32 name=f ! tensor_sink name=out"))
    assert p2.residency.resident_edges >= 1


def test_windows_flow_into_filter_unchanged():
    """aggregator(device) ! tensor_filter end to end: same scores as the
    host aggregator feeding the same filter."""
    tail = (" ! tensor_filter framework=jax model=speech_commands "
            "custom=dtype:float32 name=f")
    desc = DESC.replace("! tensor_sink", tail + " ! tensor_sink")

    def run(dev):
        p = nt.Pipeline(desc.format(dev=dev, sink=""))
        with p:
            outs = [p.pull("out", timeout=120) for _ in range(N_WINDOWS)]
            p.wait(timeout=60)
        return outs

    for h, d in zip(run(""), run("device=true ")):
        np.testing.assert_array_equal(np.asarray(h.tensors[0]),
                                      np.asarray(d.tensors[0]))


# -- EOS / lifecycle --------------------------------------------------------

def test_eos_partial_window_flushes_like_host():
    """A stream shorter than one window: both paths drop the partial at
    EOS (no output, clean completion), and the device path frees its
    ring."""
    desc = ("audiotestsrc device=true num-buffers=2 samplesperbuffer=1000 "
            "rate=16000 name=src ! "
            "tensor_aggregator frames_in=1000 frames_out=4000 "
            "frames_flush=4000 frames_dim=0 name=agg {dev}! "
            "tensor_sink name=out")
    for dev in ("", "device=true "):
        p = nt.Pipeline(desc.format(dev=dev))
        with p:
            p.wait(timeout=60)
        agg = p.element("agg")
        assert agg._window is None and agg._ring is None
        with pytest.raises(Exception):
            p.pull("out", timeout=0.2)


def test_device_mode_rejects_multi_tensor_windows():
    with pytest.raises(ElementError):
        TensorAggregator({"device": "true", "concat": "false"}, name="agg")


# -- analysis stays truthful ------------------------------------------------

def test_deep_lint_prices_ring_bytes():
    """The deep pass prices the HBM ring (frames_out + frames_in frames)
    and the fixed 3-program census for a device-mode aggregator."""
    desc = DESC.format(dev="device=true ", sink="")
    r = nt.analyze(desc, deep=True)
    assert not r.errors, r.render()
    [agg] = [s for s in r.resources.stages if s.label.startswith("agg")]
    # (16000 + 4000) samples x f32, batch 1
    assert agg.ring_bytes == (16000 + 4000) * 4
    assert agg.variants == 3
    assert "agg ring" in r.resources.render()
    # ring bytes land in the HBM high-water estimate (budgetable)
    assert agg.hbm_bytes >= agg.ring_bytes


def test_deep_lint_flags_flexible_upstream():
    """device=true behind a flexible stream cannot pin its ring shape:
    the census flags it instead of silently mispricing."""
    desc = ("appsrc name=src ! "
            "tensor_aggregator frames_in=1 frames_out=4 device=true "
            "name=agg ! tensor_sink name=out")
    r = nt.analyze(desc, deep=True)
    assert any(d.code == "recompile-unbounded" for d in r)
