"""Driver-entry contract tests (``__graft_entry__.py``).

The round-1/2 driver artifacts failed at the PUBLIC ``dryrun_multichip``
entry (live-backend probe hung on a dead tunnel) while the body itself was
green — so these tests pin the entry, not just the body: it must complete
inside a wall-clock bound even when the environment advertises a remote
platform, because it never touches the live backend at all.
"""

import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == (8, 1001)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_dryrun_multichip_public_entry(monkeypatch):
    # Simulate the hostile driver environment: a JAX_PLATFORMS value naming
    # a backend that does not exist here.  The entry must neither probe it
    # nor pass it through to the child (the child pins cpu via jax.config).
    monkeypatch.setenv("JAX_PLATFORMS", "nonexistent_tunnel,cpu")
    t0 = time.monotonic()
    graft.dryrun_multichip(8)
    elapsed = time.monotonic() - t0
    # Body measured ~30s on the 8-device CPU mesh; generous margin for cold
    # compile, but far below the driver's timeout (the failure mode that
    # shipped twice was an unbounded hang, not slowness).
    assert elapsed < 240, f"dryrun_multichip took {elapsed:.0f}s"
