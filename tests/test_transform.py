"""tensor_transform golden tests vs numpy (reference analog: SSAT suites
tests/transform_typecast, transform_arithmetic, transform_transpose, ...)."""

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.elements.transform import TensorTransform


def run(mode, option, arr):
    t = TensorTransform({"mode": mode, "option": option})
    out = t.transform(Buffer([arr]))
    return out.tensors[0]


def run_device(mode, option, arr):
    import jax.numpy as jnp

    t = TensorTransform({"mode": mode, "option": option})
    spec = TensorsSpec.of([arr])
    fn, out_spec = t.device_fn(spec)
    out = fn((jnp.asarray(arr),))
    host = np.asarray(out[0])
    assert out_spec[0].shape == host.shape, (out_spec, host.shape)
    assert out_spec[0].dtype == host.dtype
    return host


MODES = [
    ("typecast", "float32", np.arange(12, dtype=np.uint8).reshape(3, 4)),
    ("typecast", "int16", (np.arange(12, dtype=np.float32) * 1.7).reshape(3, 4)),
    ("arithmetic", "typecast:float32,add:-127.5,div:127.5",
     np.arange(24, dtype=np.uint8).reshape(2, 3, 4)),
    ("arithmetic", "add:10,mul:2", np.arange(6, dtype=np.int32)),
    ("clamp", "0:1", np.linspace(-2, 2, 9, dtype=np.float32)),
    ("stand", "default", np.arange(20, dtype=np.float32).reshape(4, 5)),
    ("stand", "dc-average", np.arange(20, dtype=np.float32).reshape(4, 5)),
    ("transpose", "1:0:2:3", np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)),
    ("dimchg", "0:2", np.arange(24, dtype=np.uint8).reshape(2, 3, 4)),
    ("padding", "0:1:1,1:2:0", np.ones((2, 3, 4), np.float32)),
]


@pytest.mark.parametrize("mode,option,arr", MODES)
def test_host_device_parity(mode, option, arr):
    """The fused device path must match the host path bit-for-bit."""
    h = run(mode, option, arr)
    d = run_device(mode, option, arr)
    assert h.dtype == d.dtype, (h.dtype, d.dtype)
    assert h.shape == d.shape
    np.testing.assert_allclose(h, d, rtol=1e-6, atol=1e-6)


class TestGolden:
    def test_typecast(self):
        a = np.array([250, 251, 252], np.uint8)
        out = run("typecast", "float32", a)
        np.testing.assert_array_equal(out, a.astype(np.float32))

    def test_normalize_chain(self):
        a = np.array([[0, 255], [127, 128]], np.uint8)
        out = run("arithmetic", "typecast:float32,add:-127.5,div:127.5", a)
        expected = (a.astype(np.float32) - 127.5) / 127.5
        np.testing.assert_allclose(out, expected)
        assert out.dtype == np.float32

    def test_arithmetic_int_stays_int(self):
        a = np.array([1, 2, 3], np.int32)
        out = run("arithmetic", "add:10,mul:2", a)
        np.testing.assert_array_equal(out, (a + 10) * 2)
        assert out.dtype == np.int32

    def test_arithmetic_float_const_promotes(self):
        a = np.array([1, 2, 3], np.uint8)
        out = run("arithmetic", "mul:0.5", a)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, a * 0.5)

    def test_div_promotes(self):
        a = np.array([4, 8], np.uint8)
        out = run("arithmetic", "div:2", a)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, [2.0, 4.0])

    def test_per_channel_add(self):
        a = np.zeros((2, 3), np.float32)  # dims (3, 2): dim0=3 channels
        out = run("arithmetic", "add:1|2|3@0", a)
        np.testing.assert_allclose(out, np.tile([1, 2, 3], (2, 1)))

    def test_transpose_hwc_to_chw(self):
        # dims order: in dims (C,W,H,N); option 1:0:2:3 swaps C and W
        a = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)  # N,H,W,C
        out = run("transpose", "1:0:2:3", a)
        assert out.shape == (1, 2, 4, 3)
        np.testing.assert_array_equal(out, np.swapaxes(a, 2, 3))

    def test_dimchg(self):
        # dims (C,W,H) -> move dim0 (C) to position 2: (W,H,C)
        a = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)  # H,W,C numpy
        out = run("dimchg", "0:2", a)
        assert out.shape == (4, 2, 3)
        np.testing.assert_array_equal(out, np.moveaxis(a, 2, 0))

    def test_clamp(self):
        a = np.array([-5.0, 0.5, 9.0], np.float32)
        out = run("clamp", "0:1", a)
        np.testing.assert_allclose(out, [0.0, 0.5, 1.0])

    def test_stand_default(self):
        a = np.arange(10, dtype=np.float32)
        out = run("stand", "default", a)
        np.testing.assert_allclose(out.mean(), 0.0, atol=1e-6)
        np.testing.assert_allclose(out.std(), 1.0, atol=1e-4)

    def test_padding(self):
        a = np.ones((2, 3), np.float32)  # dims (3, 2)
        out = run("padding", "0:1:1", a)  # pad innermost dim by 1 each side
        assert out.shape == (2, 5)
        np.testing.assert_array_equal(out[:, 0], 0)

    def test_spec_propagation(self):
        t = TensorTransform({"mode": "transpose", "option": "1:0:2:3"})
        spec = TensorsSpec.from_string("3:4:5:1", "uint8")
        out = t.out_spec(spec)
        assert out[0].dims == (4, 3, 5, 1)

    def test_unknown_mode(self):
        with pytest.raises(Exception):
            TensorTransform({"mode": "nope"})


class TestSaturatingCast:
    """Float -> integer typecasts SATURATE identically on the host and
    fused (device) paths (ISSUE 10): raw astype diverged — numpy wraps
    out-of-range values where XLA clamps — and with the planner fusing
    typecast transforms across dtype-quantized caps pins, the same graph
    must emit the same bytes wherever the cast runs."""

    CASES = [
        ("uint8", np.array([-1.5, 0.4, 255.0, 300.2, 99.9], np.float32),
         [0, 0, 255, 255, 99]),
        ("int8", np.array([-200.0, -128.9, 127.2, 500.0], np.float32),
         [-128, -128, 127, 127]),
        ("int16", np.array([-4e4, 4e4, 123.7], np.float32),
         [-32768, 32767, 123]),
        ("int32", np.array([-3e9, 3e9, 7.9], np.float32),
         [-2147483648, 2147483647, 7]),
    ]

    @pytest.mark.parametrize("dtype,arr,want", CASES)
    def test_host_saturates(self, dtype, arr, want):
        np.testing.assert_array_equal(run("typecast", dtype, arr), want)

    @pytest.mark.parametrize("dtype,arr,want", CASES)
    def test_device_matches_host_bitwise(self, dtype, arr, want):
        host = run("typecast", dtype, arr)
        dev = run_device("typecast", dtype, arr)
        assert bytes(host) == bytes(dev)
        np.testing.assert_array_equal(dev, want)

    def test_arith_requantize_tail_saturates_both_paths(self):
        """The quant-boundary shape: normalize in float, requantize to
        uint8 at the tail — fused and host bytes must match even when
        the float math leaves the u8 range."""
        arr = np.linspace(-80, 80, 33, dtype=np.float32)
        opt = "mul:4.0,add:128.0,typecast:uint8"
        host = run("arithmetic", opt, arr)
        dev = run_device("arithmetic", opt, arr)
        assert host.dtype == np.uint8
        assert bytes(host) == bytes(dev)
        assert host.min() == 0 and host.max() == 255  # saturated, no wrap

    def test_int_to_int_and_float_to_float_unchanged(self):
        a = np.array([300, -5, 7], np.int32)
        np.testing.assert_array_equal(
            run("typecast", "uint8", a), a.astype(np.uint8))  # wraps: not a float boundary
        f = np.array([1.5, -2.5], np.float64)
        np.testing.assert_array_equal(
            run("typecast", "float32", f), f.astype(np.float32))
