"""Integer execution of fully-quantized .tflite graphs (VERDICT r4
Missing #1 / Next #2): the MXU-bound ops must run as NATIVE int8 dots —
asserted on the jaxpr, not trusted — with exact zero-point algebra,
per-op requantization, and the r4 dequantized path still available
behind ``int_exec:0``."""

import os

import numpy as np
import pytest

import jax

from nnstreamer_tpu.models import tflite, tflite_build


def _quant_conv_file(tmp_path, *, w_dtype=np.int8, act="relu6",
                     padding="SAME", w_zp=0, name="q.tflite"):
    """One-conv fully-quantized graph with controllable weight dtype and
    zero points; returns (path, all the numpy pieces for an oracle)."""
    rng = np.random.default_rng(11)
    s_in, z_in = 0.5 / 127.0, 3
    s_out, z_out = 6.0 / 255.0, 0
    sw = np.asarray([0.02, 0.01, 0.03, 0.025], np.float32)
    if w_dtype == np.int8:
        wq = rng.integers(-127, 128, (4, 3, 3, 3)).astype(np.int8)
        wzp = [0] * 4
    else:
        wq = rng.integers(0, 256, (4, 3, 3, 3)).astype(np.uint8)
        wzp = [int(w_zp)] * 4
    bq = rng.integers(-2000, 2000, (4,)).astype(np.int32)

    m = tflite_build.ModelWriter()
    x = m.add_input([1, 8, 8, 3], dtype=np.uint8,
                    quant_scale=[s_in], quant_zero_point=[z_in])
    wi = m.add_const(wq, "w", quant_scale=list(sw),
                     quant_zero_point=wzp, quant_axis=0)
    bi = m.add_const(bq, "b", quant_scale=list(s_in * sw),
                     quant_zero_point=[0] * 4, quant_axis=0)
    y = m.add_op("CONV_2D", [x, wi, bi], [1, 4, 4, 4],
                 out_dtype=np.uint8,
                 options={"stride": (2, 2), "padding": padding,
                          "act": act},
                 quant_scale=[s_out], quant_zero_point=[z_out])
    path = os.path.join(str(tmp_path), name)
    open(path, "wb").write(m.finish(outputs=[y]))
    return path, dict(s_in=s_in, z_in=z_in, s_out=s_out, z_out=z_out,
                      sw=sw, wq=wq, wzp=np.asarray(wzp), bq=bq)


def _oracle_conv(x_u8, p, act="relu6", padding="SAME"):
    """Pure-numpy integer oracle: float conv over exactly dequantized
    operands, then requantized — the definition the int path must meet."""
    xf = (x_u8.astype(np.float64) - p["z_in"]) * p["s_in"]
    wf = ((p["wq"].astype(np.float64)
           - p["wzp"][:, None, None, None])
          * p["sw"][:, None, None, None].astype(np.float64))
    bf = p["bq"].astype(np.float64) * (p["s_in"] * p["sw"])
    B, H, W, C = xf.shape
    O, kh, kw, _ = wf.shape
    sh = sw_ = 2
    if padding == "SAME":
        oh, ow = -(-H // sh), -(-W // sw_)
        tot_h = max(0, (oh - 1) * sh + kh - H)
        tot_w = max(0, (ow - 1) * sw_ + kw - W)
        xf = np.pad(xf, ((0, 0), (tot_h // 2, tot_h - tot_h // 2),
                         (tot_w // 2, tot_w - tot_w // 2), (0, 0)))
    else:
        oh, ow = (H - kh) // sh + 1, (W - kw) // sw_ + 1
    y = np.zeros((B, oh, ow, O))
    for i in range(oh):
        for j in range(ow):
            win = xf[:, i * sh:i * sh + kh, j * sw_:j * sw_ + kw, :]
            y[:, i, j, :] = np.einsum("bhwc,ohwc->bo", win, wf)
    y = y + bf
    if act == "relu6":
        y = np.clip(y, 0, 6)
    elif act == "relu":
        y = np.maximum(y, 0)
    q = np.round(y / p["s_out"]) + p["z_out"]
    return np.clip(q, 0, 255).astype(np.uint8)


def _int8_mxu_ops(bundle, x):
    """Conv/dot equations in the jaxpr whose operands are int8 with an
    int32 result — the 'interior actually int8' assertion."""
    jaxpr = jax.make_jaxpr(bundle.apply_fn)(bundle.params, x)
    hits = []
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name in ("conv_general_dilated", "dot_general"):
            in_dts = {str(v.aval.dtype) for v in eqn.invars}
            out_dt = str(eqn.outvars[0].aval.dtype)
            hits.append((eqn.primitive.name, sorted(in_dts), out_dt))
    return [h for h in hits if h[1] == ["int8"] and h[2] == "int32"]


class TestIntegerConv:
    @pytest.mark.parametrize("w_dtype,w_zp", [(np.int8, 0),
                                              (np.uint8, 131)])
    @pytest.mark.parametrize("padding", ["SAME", "VALID"])
    def test_matches_numpy_oracle(self, tmp_path, w_dtype, w_zp, padding):
        path, p = _quant_conv_file(tmp_path, w_dtype=w_dtype, w_zp=w_zp,
                                   padding=padding)
        b = tflite.load_bundle(path)
        x = np.random.default_rng(5).integers(
            0, 256, (1, 8, 8, 3), dtype=np.uint8)
        got = np.asarray(b.apply_fn(b.params, x))
        want = _oracle_conv(x, p, padding=padding)
        # f32-multiplier requant can differ by 1 LSB on .5 boundaries
        assert got.dtype == np.uint8
        diff = np.abs(got.astype(int) - want.astype(int))
        assert diff.max() <= 1, f"max LSB diff {diff.max()}"
        assert (diff > 0).mean() < 0.05

    def test_interior_is_int8_on_the_mxu(self, tmp_path):
        path, _ = _quant_conv_file(tmp_path)
        b = tflite.load_bundle(path)
        x = np.zeros((1, 8, 8, 3), np.uint8)
        assert _int8_mxu_ops(b, x), (
            "no int8 x int8 -> int32 conv/dot in the jaxpr: integer "
            "execution fell back to float")

    def test_int_exec_opt_out_restores_dequantized_path(self, tmp_path):
        path, p = _quant_conv_file(tmp_path)
        b = tflite.load_bundle(path, {"int_exec": "0"})
        x = np.random.default_rng(5).integers(
            0, 256, (1, 8, 8, 3), dtype=np.uint8)
        assert not _int8_mxu_ops(b, x)
        got = np.asarray(b.apply_fn(b.params, x))
        want = _oracle_conv(x, p)
        assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


class TestIntegerDepthwiseFC:
    def test_depthwise_and_fc_chain(self, tmp_path):
        rng = np.random.default_rng(9)
        s_in, z_in = 1.0 / 255.0, 128
        s_mid, z_mid = 0.02, 7
        s_out, z_out = 0.05, 11
        # depthwise [1, kh, kw, cin] (mult=1), int8 weights zp=0
        dwq = rng.integers(-127, 128, (1, 3, 3, 3)).astype(np.int8)
        s_dw = np.asarray([0.01, 0.02, 0.015], np.float32)
        dwb = rng.integers(-500, 500, (3,)).astype(np.int32)
        # fc [out=4, in=27]
        fcq = rng.integers(-127, 128, (4, 27)).astype(np.int8)
        s_fc = np.asarray([0.03], np.float32)
        fcb = rng.integers(-500, 500, (4,)).astype(np.int32)

        m = tflite_build.ModelWriter()
        x = m.add_input([1, 6, 6, 3], dtype=np.uint8,
                        quant_scale=[s_in], quant_zero_point=[z_in])
        dwi = m.add_const(dwq, "dw", quant_scale=list(s_dw),
                          quant_zero_point=[0] * 3, quant_axis=3)
        dbi = m.add_const(dwb, "dwb", quant_scale=list(s_in * s_dw),
                          quant_zero_point=[0] * 3, quant_axis=0)
        h = m.add_op("DEPTHWISE_CONV_2D", [x, dwi, dbi], [1, 3, 3, 3],
                     out_dtype=np.uint8,
                     options={"stride": (2, 2), "padding": "SAME",
                              "act": None},
                     quant_scale=[s_mid], quant_zero_point=[z_mid])
        r = m.add_op("RESHAPE", [h], [1, 27], out_dtype=np.uint8,
                     options={"new_shape": [1, 27]},
                     quant_scale=[s_mid], quant_zero_point=[z_mid])
        fci = m.add_const(fcq, "fc", quant_scale=list(s_fc),
                          quant_zero_point=[0])
        fbi = m.add_const(fcb, "fcb",
                          quant_scale=[float(s_mid * s_fc[0])],
                          quant_zero_point=[0])
        y = m.add_op("FULLY_CONNECTED", [r, fci, fbi], [1, 4],
                     out_dtype=np.uint8,
                     options={"act": None},
                     quant_scale=[s_out], quant_zero_point=[z_out])
        path = os.path.join(str(tmp_path), "dwfc.tflite")
        open(path, "wb").write(m.finish(outputs=[y]))

        b = tflite.load_bundle(path)
        xv = rng.integers(0, 256, (1, 6, 6, 3), dtype=np.uint8)
        got = np.asarray(jax.jit(b.apply_fn)(b.params, xv))
        assert got.dtype == np.uint8 and got.shape == (1, 4)

        # float oracle through exactly dequantized ops
        xf = (xv.astype(np.float64) - z_in) * s_in
        wf = dwq[0].astype(np.float64) * s_dw  # [3,3,3]
        # SAME, in=6 k=3 s=2: total pad 1 -> lo 0, hi 1 (TFLite rule).
        # Padded positions must contribute ZERO, i.e. pad the DEQUANTIZED
        # domain with 0 (the integer path pads q-domain with the zp).
        xp = np.pad(xf, ((0, 0), (0, 1), (0, 1), (0, 0)))
        mid = np.zeros((1, 3, 3, 3))
        for i in range(3):
            for j in range(3):
                win = xp[:, i * 2:i * 2 + 3, j * 2:j * 2 + 3, :]
                mid[:, i, j, :] = np.einsum("bhwc,hwc->bc", win, wf)
        mid += dwb.astype(np.float64) * (s_in * s_dw)
        midq = np.clip(np.round(mid / s_mid) + z_mid, 0, 255)
        midf = (midq - z_mid) * s_mid
        yf = midf.reshape(1, 27) @ (fcq.astype(np.float64).T * s_fc[0]) \
            + fcb * (s_mid * s_fc[0])
        want = np.clip(np.round(yf / s_out) + z_out, 0, 255)
        assert np.abs(got.astype(int) - want.astype(int)).max() <= 1

        # both MXU ops int8
        kinds = {h[0] for h in _int8_mxu_ops(b, xv)}
        assert kinds == {"conv_general_dilated", "dot_general"}


def test_quantized_pipeline_still_uint8(tmp_path):
    """End-to-end through the pipeline: int exec preserves the r4 wire
    contract (uint8 frames in, uint8 out, no normalization transform)."""
    import nnstreamer_tpu as nt

    path, _ = _quant_conv_file(tmp_path, name="p.tflite")
    p = nt.Pipeline(
        "appsrc name=src caps=other/tensors,dimensions=3:8:8:1,"
        f"types=uint8 ! tensor_filter framework=jax model={path} name=f ! "
        "tensor_sink name=out")
    x = np.random.default_rng(0).integers(0, 256, (1, 8, 8, 3),
                                          dtype=np.uint8)
    with p:
        p.push("src", x)
        out = p.pull("out", timeout=120)
        p.eos()
        p.wait(timeout=30)
    assert out.tensors[0].dtype == np.uint8
    assert out.tensors[0].shape == (1, 4, 4, 4)
