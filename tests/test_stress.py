"""Concurrency/ordering stress + flexible-shape recompile behavior.

Reference analog (SURVEY §5.2): the reference leans on GStreamer/GLib
threading discipline and valgrind CI; the TPU build's equivalent is
deterministic-ordering assertions over the async executor under load.
"""

from __future__ import annotations

import numpy as np

import nnstreamer_tpu as nt


def test_ordering_preserved_under_load():
    """200 buffers through a 3-stage threaded chain arrive in push order."""
    p = nt.Pipeline(
        "appsrc name=src max-buffers=8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:1.0 ! "
        "tensor_transform mode=arithmetic option=mul:2.0 ! "
        "tensor_sink name=out",
        fuse=False,  # separate stages = separate threads: the racy case
        queue_capacity=2,
    )
    n = 200
    import threading

    def pusher():
        for i in range(n):
            p.push("src", np.full((16,), i, np.int32))

    with p:
        t = threading.Thread(target=pusher, daemon=True)
        t.start()
        vals = [float(np.asarray(p.pull("out", timeout=60).tensors[0])[0])
                for _ in range(n)]
        t.join()
        p.eos()
        p.wait(timeout=30)
    assert vals == [(i + 1) * 2.0 for i in range(n)]


def test_ordering_preserved_through_tee_and_join():
    """tee fan-out -> join first-come forwarding keeps per-branch order."""
    p = nt.Pipeline(
        "appsrc name=src ! tee name=t "
        "t. ! tensor_transform mode=arithmetic option=typecast:float32,mul:1.0 ! join name=j "
        "t. ! tensor_transform mode=arithmetic option=typecast:float32,mul:-1.0 ! j. "
        "j. ! tensor_sink name=out",
        queue_capacity=4,
    )
    n = 50
    with p:
        for i in range(n):
            p.push("src", np.full((4,), i + 1, np.int16))
        got = []
        for _ in range(2 * n):
            got.append(float(np.asarray(p.pull("out", timeout=60).tensors[0])[0]))
        p.eos()
        p.wait(timeout=30)
    pos = [v for v in got if v > 0]
    neg = [v for v in got if v < 0]
    assert pos == [float(i + 1) for i in range(n)]
    assert neg == [-float(i + 1) for i in range(n)]


def test_flexible_batch_shapes_recompile_cache():
    """Variable batch sizes through a fused chain: jit recompiles per shape
    and results stay correct (SURVEY §7 hard-parts: dynamic shapes)."""
    p = nt.Pipeline(
        "appsrc name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,mul:3.0 ! "
        "tensor_sink name=out",
    )
    with p:
        for b in (1, 7, 3, 7, 1):
            p.push("src", np.ones((b, 5), np.uint8))
        shapes = [np.asarray(p.pull("out", timeout=60).tensors[0]).shape
                  for _ in range(5)]
        p.eos()
        p.wait(timeout=30)
    assert shapes == [(1, 5), (7, 5), (3, 5), (7, 5), (1, 5)]


def test_many_pipelines_sequentially_no_leak():
    """Teardown hygiene: 20 short-lived pipelines leave no stuck threads."""
    import threading

    before = threading.active_count()
    for i in range(20):
        p = nt.Pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
            "tensor_sink name=out"
        )
        with p:
            p.pull("out", timeout=30)
            p.wait(timeout=30)
    after = threading.active_count()
    assert after - before < 10, f"thread leak: {before} -> {after}"


def test_device_videotestsrc_matches_host_patterns():
    """videotestsrc device=true generates the same gradient/ball frames as
    the host path, batched, device-resident."""
    for pattern in ("smpte", "ball", "black", "white"):
        host = nt.Pipeline(
            f"videotestsrc num-buffers=3 width=12 height=10 pattern={pattern} ! "
            "tensor_converter ! tensor_sink name=out"
        )
        with host:
            frames = [np.asarray(host.pull("out", timeout=30).tensors[0])[0]
                      for _ in range(3)]
            host.wait(timeout=30)
        dev = nt.Pipeline(
            f"videotestsrc device=true batch=3 num-buffers=3 width=12 "
            f"height=10 pattern={pattern} ! tensor_sink name=out"
        )
        with dev:
            batch = np.asarray(dev.pull("out", timeout=30).tensors[0])
            dev.wait(timeout=30)
        assert batch.shape == (3, 10, 12, 3)
        for i in range(3):
            np.testing.assert_array_equal(batch[i], frames[i], err_msg=pattern)


def test_device_videotestsrc_fuses_with_filter():
    p = nt.Pipeline(
        "videotestsrc device=true batch=4 num-buffers=4 width=8 height=8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=average custom=dims:3:8:8:4 ! "
        "tensor_sink name=out"
    )
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused, "device source output should fuse transform+filter"
    with p:
        out = p.pull("out", timeout=30)
        p.wait(timeout=30)
    assert np.asarray(out.tensors[0]).shape[0] == 4


def test_device_videotestsrc_num_buffers_contract():
    """num-buffers counts frames exactly, even when not batch-aligned."""
    p = nt.Pipeline(
        "videotestsrc device=true batch=4 num-buffers=5 width=4 height=4 ! "
        "tensor_sink name=out"
    )
    with p:
        shapes = []
        while True:
            try:
                shapes.append(np.asarray(p.pull("out", timeout=5).tensors[0]).shape[0])
            except TimeoutError:
                break
        p.wait(timeout=10)
    assert sum(shapes) == 5 and shapes == [4, 1]


def test_concurrent_streaming_clients_one_server():
    """Several clients stream LLM tokens from ONE query server
    concurrently: every client gets its full, correctly-ordered stream
    (per-connection msg pairing under interleaved generation)."""
    import threading

    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=60 ! "
        "tensor_filter framework=llm model=llama_tiny "
        "custom=max_new:4,stream_chunk:2 invoke-dynamic=true ! "
        "tensor_query_serversink id=60"
    )
    results = {}
    errors = []

    def run_client(cid, port):
        try:
            cli = nt.Pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "timeout=120 ! tensor_sink name=out"
            )
            with cli:
                cli.push("src", np.array([cid + 1, 7, 3], np.int32))
                toks = [cli.pull("out", timeout=120) for _ in range(4)]
                cli.eos("src")
                cli.wait(timeout=30)
            results[cid] = (
                [b.meta["stream_index"] for b in toks],
                [int(np.asarray(b.tensors[0])[0]) for b in toks],
                toks[-1].meta.get("stream_last"),
            )
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((cid, e))

    with srv:
        port = srv.element("ssrc").bound_port
        threads = [
            threading.Thread(target=run_client, args=(i, port))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
    assert not errors, errors
    assert set(results) == {0, 1, 2}
    for cid, (idxs, ids, last) in results.items():
        assert idxs == [0, 1, 2, 3]
        assert last is True
        # different prompts -> generation streams are per-client
    # determinism: same prompt gives same ids regardless of concurrency
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": "llama_tiny", "custom": "max_new:4,stream_chunk:2"})
    for cid in range(3):
        direct = [int(i[0]) for i, _ in fw.invoke_stream(
            [np.array([cid + 1, 7, 3], np.int32)])]
        assert results[cid][1] == direct


def test_continuous_serving_under_client_churn():
    """Continuous serving survives a rolling population: three WAVES of
    clients join the same standing decode loop (slots recycled across
    waves), with one early-terminating client per wave; every surviving
    client gets its full ordered stream.  (The guaranteed
    failed-send-mid-stream race is pinned separately by
    test_client_disconnect_mid_batched_stream_isolated.)"""
    import contextlib

    max_new = 5
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=70 ! "
        f"tensor_filter framework=llm model=llama_tiny "
        f"custom=max_new:{max_new},serve:continuous,slots:2,stream_chunk:2,"
        "temperature:0.0 invoke-dynamic=true ! "
        "tensor_query_serversink id=70"
    )
    rng = np.random.default_rng(0)
    with srv:
        port = srv.element("ssrc").bound_port
        completed = 0
        for wave in range(3):
            with contextlib.ExitStack() as stack:
                clients = [stack.enter_context(nt.Pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "timeout=60 ! tensor_sink name=out")) for _ in range(3)]
                for c in clients:
                    c.push("src", rng.integers(
                        1, 200, (4,), dtype=np.int32))
                # client 0 of each wave disconnects after one token
                clients[0].pull("out", timeout=60)
                clients[0].stop()
                for c in clients[1:]:
                    toks = [c.pull("out", timeout=60)
                            for _ in range(max_new)]
                    assert toks[-1].meta.get("stream_last") is True
                    assert [t.meta["stream_index"] for t in toks] == \
                        list(range(max_new))
                    completed += 1
                for c in clients[1:]:
                    c.eos("src")
                    c.wait(timeout=15)
        assert completed == 6


def test_stop_idempotent_under_serve():
    """Double-stop across query/llm/sink elements: a second stop() (and
    stray element-level stops) must be clean no-ops, mid-stream."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=81 ! "
        "tensor_filter name=f framework=llm model=llama_tiny "
        "custom=max_new:24,serve:continuous,slots:2,stream_chunk:2,"
        "temperature:0.0,dtype:float32 invoke-dynamic=true ! "
        "tensor_query_serversink name=ssink id=81")
    srv.start()
    port = srv.element("ssrc").bound_port
    cli = nt.Pipeline(
        f"appsrc name=src ! tensor_query_client name=qc port={port} "
        "timeout=30 reconnect=3 ! tensor_sink name=out")
    cli.start()
    cli.push("src", np.asarray([1, 2, 3], np.int32))
    cli.pull("out", timeout=60)  # at least one token flowed
    # stop everything twice, in both orders, plus element-level stops
    cli.stop()
    cli.stop()
    srv.stop()
    srv.stop()
    srv.element("ssrc").stop()
    cli.element("qc").stop()
    # the server id is free again: a fresh pair starts cleanly
    srv2 = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=81 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,mul:1.0 ! "
        "tensor_query_serversink id=81")
    with srv2:
        assert srv2.element("ssrc").bound_port > 0


def test_stop_during_reconnect_backoff():
    """stop() while the query client is mid-backoff must return promptly
    (the full-jitter sleep is stop-aware), not ride out the retries."""
    import time as _time

    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=82 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,mul:2.0 ! "
        "tensor_query_serversink id=82")
    srv.start()
    port = srv.element("ssrc").bound_port
    cli = nt.Pipeline(
        f"appsrc name=src ! tensor_query_client name=qc port={port} "
        "timeout=20 reconnect=8 reconnect-base-ms=500 "
        "reconnect-cap-ms=5000 ! tensor_sink name=out")
    cli.start()
    cli.push("src", np.ones((4,), np.float32))
    cli.pull("out", timeout=20)
    srv.stop()  # server gone: the client's rx loop enters backoff
    cli.push("src", np.ones((4,), np.float32))  # pending; send may fail
    _time.sleep(0.3)  # let the rx loop notice and start backing off
    t0 = _time.monotonic()
    cli.stop()
    cli.stop()  # idempotent
    took = _time.monotonic() - t0
    # 8 retries at up to 5 s jitter each would be ~20 s unmitigated
    assert took < 5.0, f"stop() waited out the backoff: {took:.1f}s"


def test_stop_with_orphaned_slots():
    """Stopping a continuous-serving server with live (and orphaned)
    streams must tear down cleanly: the serve loop joins, the stream
    registry drains, and a double stop stays a no-op."""
    from nnstreamer_tpu.utils import elastic

    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=83 ! "
        "tensor_filter name=f framework=llm model=llama_tiny "
        "custom=max_new:200,serve:continuous,slots:2,stream_chunk:2,"
        "temperature:0.0,dtype:float32,stream_idle_timeout:60 "
        "invoke-dynamic=true ! "
        "tensor_query_serversink name=ssink id=83")
    srv.start()
    port = srv.element("ssrc").bound_port
    cli = nt.Pipeline(
        f"appsrc name=src ! tensor_query_client port={port} "
        "timeout=30 on-timeout=drop ! tensor_sink name=out")
    cli.start()
    cli.push("src", np.asarray([5, 6, 7], np.int32))
    cli.pull("out", timeout=60)  # the stream is live server-side
    before = set(elastic.live_stream_ids())
    assert before  # at least our stream is registered
    cli.stop()  # client vanishes: the stream is now orphaned
    srv.stop()  # must not hang on the orphaned slot
    srv.stop()  # idempotent
    # the dead loop unregistered everything it owned
    assert not (set(elastic.live_stream_ids()) & before)
