"""Loadable custom-filter ABI tests: compile real .so filters with the
system toolchain and drive them through the framework and a pipeline
(reference analogs: tensor_filter_custom.c / tensor_filter_cpp.cc and the
custom_example_* .so's in the reference's test tree — SURVEY §2.3/§4)."""

import shutil
import subprocess

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.filters.custom_so import include_dir

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain")

_CPP_SCALER = r"""
#include <cstring>
#include <cstdlib>
#include "nnstpu_cppclass.hh"

// scale:<f> parsed from the custom= prop string.
class Scaler : public nnstpu::Filter {
 public:
  explicit Scaler(const char *props) : scale_(2.0f) {
    const char *p = std::strstr(props, "scale:");
    if (p) scale_ = std::strtof(p + 6, nullptr);
  }
  int getInputInfo(nnstpu_tensors_info *i) override {
    i->num = 1;
    i->info[0].rank = 2;
    i->info[0].dims[0] = 2;
    i->info[0].dims[1] = 3;
    i->info[0].dtype = NNSTPU_FLOAT32;
    return 0;
  }
  int getOutputInfo(nnstpu_tensors_info *i) override { return getInputInfo(i); }
  int invoke(const void *const *in, void *const *out) override {
    const float *x = static_cast<const float *>(in[0]);
    float *y = static_cast<float *>(out[0]);
    for (int k = 0; k < 6; ++k) y[k] = x[k] * scale_;
    return 0;
  }
 private:
  float scale_;
};
NNSTPU_REGISTER_FILTER(Scaler)
"""

_C_VTABLE = r"""
/* Hand-rolled C vtable (no C++ class sugar): u8 -> i32 cast + add. */
#include <stdlib.h>
#include "nnstpu_custom.h"

static void *c_init(const char *props) { (void)props; return malloc(1); }
static void c_finish(void *p) { free(p); }
static int c_in(void *p, nnstpu_tensors_info *i) {
  (void)p;
  i->num = 1;
  i->info[0].rank = 1;
  i->info[0].dims[0] = 4;
  i->info[0].dtype = NNSTPU_UINT8;
  return 0;
}
static int c_out(void *p, nnstpu_tensors_info *i) {
  (void)p;
  i->num = 1;
  i->info[0].rank = 1;
  i->info[0].dims[0] = 4;
  i->info[0].dtype = NNSTPU_INT32;
  return 0;
}
static int c_invoke(void *p, const void *const *in, void *const *out) {
  (void)p;
  const unsigned char *x = (const unsigned char *)in[0];
  int *y = (int *)out[0];
  for (int k = 0; k < 4; ++k) y[k] = (int)x[k] + 100;
  return 0;
}
static const nnstpu_custom_class vt = {
    NNSTPU_CUSTOM_ABI_VERSION, c_init, c_finish, c_in, c_out, c_invoke};
const nnstpu_custom_class *nnstpu_custom_get(void) { return &vt; }
"""


def _build(tmp_path, name, source, cpp=True):
    src = tmp_path / f"{name}.{'cc' if cpp else 'c'}"
    src.write_text(source)
    so = tmp_path / f"lib{name}.so"
    subprocess.run(
        [("g++" if cpp else "gcc"), "-O2", "-shared", "-fPIC",
         f"-I{include_dir()}", "-o", str(so), str(src)],
        check=True, capture_output=True, timeout=120)
    return str(so)


def test_cpp_class_filter_single_shot(tmp_path):
    so = _build(tmp_path, "scaler", _CPP_SCALER)
    s = nt.SingleShot(framework="custom", model=so, custom="scale:3.0")
    assert s.in_spec[0].shape == (2, 3)
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = s.invoke(x)
    np.testing.assert_allclose(out[0], 3.0 * x)
    s.close()


def test_c_vtable_filter_dtype_mapping(tmp_path):
    so = _build(tmp_path, "adder", _C_VTABLE, cpp=False)
    s = nt.SingleShot(framework="custom", model=so)
    assert s.in_spec[0].dtype == np.uint8
    assert s.out_spec[0].dtype == np.int32
    out = s.invoke(np.array([1, 2, 3, 4], np.uint8))
    np.testing.assert_array_equal(out[0], [101, 102, 103, 104])
    s.close()


def test_so_filter_in_pipeline(tmp_path):
    so = _build(tmp_path, "pscaler", _CPP_SCALER)
    p = nt.Pipeline(
        f"appsrc name=src ! tensor_filter framework=custom model={so} "
        "custom=scale:2.0 ! tensor_sink name=out",
        fuse=False,
    )
    with p:
        x = np.ones((2, 3), np.float32)
        p.push("src", x)
        out = p.pull("out", timeout=15)
        p.eos()
        p.wait(timeout=15)
    np.testing.assert_allclose(out.tensors[0], 2.0 * x)


def test_missing_symbol_rejected(tmp_path):
    src = tmp_path / "empty.cc"
    src.write_text("extern \"C\" int unrelated(void) { return 0; }\n")
    so = tmp_path / "libempty.so"
    subprocess.run(["g++", "-shared", "-fPIC", "-o", str(so), str(src)],
                   check=True, capture_output=True, timeout=120)
    from nnstreamer_tpu.filters.base import FrameworkError
    from nnstreamer_tpu.filters.custom_so import CustomSoFramework

    with pytest.raises(FrameworkError, match="nnstpu_custom_get"):
        CustomSoFramework().open({"model": str(so)})


def test_bad_path_rejected():
    from nnstreamer_tpu.filters.base import FrameworkError
    from nnstreamer_tpu.filters.custom_so import CustomSoFramework

    with pytest.raises(FrameworkError, match="existing .so"):
        CustomSoFramework().open({"model": "no/such/filter.so"})
