"""Mesh-sharded micro-batching semantics (ISSUE 3 tentpole).

The contract: with ``data_parallel`` resolving to N > 1, a shard-eligible
device stage's bucketed micro-batch is sharded over the ``data`` axis of a
local N-chip mesh — while every observable semantic (row values, strict
ordering, pts/meta, uneven tails, EOS flush) stays identical to the
single-device BatchRunner path, and ``data_parallel=1`` IS that path.

Runs on the suite's virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``, set by conftest.py before
jax initializes).  ``tools/check_tier1.py`` additionally runs this file as
its own pytest process so the flag can never arrive too late.
"""

import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.pipeline.batching import (BatchRunner, bucket_for,
                                              shard_bucket_for)

DESC = (
    "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
    "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
    "name=f ! tensor_sink name=out"
)


def _mesh(n):
    import jax

    from nnstreamer_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} local devices")
    return make_mesh(data=n, devices=jax.devices()[:n])


def _frames(n, dims=(16,)):
    return [np.full(dims, float(i), np.float32) for i in range(n)]


def _run(desc, frames, timeout=60, **kw):
    p = nt.Pipeline(desc, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
    return outs


def _assert_rows_bitwise(got, want):
    assert len(got) == len(want)
    for i, (a, b) in enumerate(zip(got, want)):
        assert a.pts == b.pts
        for x, y in zip(a.tensors, b.tensors):
            assert bytes(np.asarray(x)) == bytes(np.asarray(y)), f"row {i}"


# -- primitives ------------------------------------------------------------

def test_shard_bucket_rounds_to_replica_multiple():
    assert shard_bucket_for(1, 4) == 4       # ladder 1, rounded to 4
    assert shard_bucket_for(3, 4) == 4       # ladder 4, already aligned
    assert shard_bucket_for(5, 4) == 8
    assert shard_bucket_for(5, 3) == 9       # ladder 8 -> next multiple of 3
    assert shard_bucket_for(8, 8) == 8
    assert shard_bucket_for(9, 8) == 16
    assert shard_bucket_for(7, 1) == bucket_for(7)  # 1 replica = plain ladder
    assert shard_bucket_for(5, 4, [2, 6]) == 8      # custom ladder 6 -> 8


def test_rows_bit_identical_every_occupancy(rng):
    """Every occupancy of the bucket (1..9, crossing a bucket boundary):
    sharded rows are byte-equal to the single-device BatchRunner's."""
    import jax.numpy as jnp

    fn = lambda arrays: (jnp.tanh(arrays[0] * 1.5 + 0.25),)  # noqa: E731
    single = BatchRunner(fn)
    sharded = BatchRunner(fn, mesh=_mesh(8))
    assert sharded.replicas == 8
    for n in range(1, 10):
        rows = [(rng.standard_normal((24,)).astype(np.float32),)
                for _ in range(n)]
        a = single.run(list(rows))
        b = sharded.run(list(rows))
        assert len(a) == len(b) == n
        for (x,), (y,) in zip(a, b):
            assert bytes(np.asarray(x)) == bytes(np.asarray(y)), f"n={n}"


def test_batch_runner_mesh_with_unit_data_axis_is_unsharded():
    """A 1-wide data axis must select the exact single-device code path."""
    br = BatchRunner(lambda arrays: (arrays[0] * 2.0,), mesh=_mesh(1))
    assert br.mesh is None and br.replicas == 1


# -- pipeline semantics ----------------------------------------------------

def test_pipeline_uneven_tail_matches_single_device():
    """13 backlogged buffers over data_parallel=4: uneven tail buckets pad
    up to replica multiples; every row byte-equal to the dp=1 run."""
    frames = _frames(13)
    sharded = _run(DESC, frames, queue_capacity=16, batch_max=8,
                   data_parallel=4)
    reference = _run(DESC, frames, queue_capacity=16, batch_max=8,
                     data_parallel=1)
    _assert_rows_bitwise(sharded, reference)


def test_data_parallel_1_is_exact_fallback():
    """data_parallel=1 must never build or attach a mesh: the stage runs
    the pre-mesh BatchRunner path, byte-identical outputs included."""
    frames = _frames(9)
    p = nt.Pipeline(DESC, batch_max=8, data_parallel=1)
    with p:
        assert all(
            getattr(s.element, "_shard_mesh", None) is None
            for s in p.stages)
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        outs = [p.pull("out", timeout=60) for _ in frames]
        p.eos()
        p.wait(timeout=60)
    el = p.element("f")
    for entry in el._batchers.values():
        assert entry[1].mesh is None
    _assert_rows_bitwise(outs, _run(DESC, frames, batch_max=8,
                                    data_parallel=8))


def test_param_replication_happens_once():
    """Many sharded dispatches, ONE replication: the prepare hook runs
    before the first sharded dispatch only (counter hook proves it)."""
    metrics.reset()
    frames = _frames(48)
    _run(DESC, frames, queue_capacity=64, batch_max=8, data_parallel=4)
    snap = metrics.snapshot()
    assert snap.get("f.shard_dispatch", 0) >= 2, snap
    assert snap.get("f.param_replications") == 1.0


def test_per_replica_counters_prove_placement():
    """data_parallel=8: metrics_text() carries one shard-rows counter per
    device, all eight non-zero, summing to the dispatched rows."""
    from nnstreamer_tpu.utils.profiler import metrics_text

    metrics.reset()
    frames = _frames(32)
    _run(DESC, frames, queue_capacity=64, batch_max=8, data_parallel=8)
    snap = metrics.snapshot()
    rows = {k: v for k, v in snap.items() if k.startswith("f.shard_rows.")}
    if not rows:
        pytest.skip("backlog never coalesced (single-buffer dispatches)")
    assert len(rows) == 8, rows
    assert all(v > 0 for v in rows.values())
    # every sharded dispatch places bucket/8 rows per replica, so the sum
    # is the total of dispatched (incl. pad) rows: a multiple of 8
    assert sum(rows.values()) % 8 == 0
    text = metrics_text()
    assert "shard_rows" in text and "shard_dispatch" in text


def test_fused_chain_shards_and_matches():
    """A fused transform+filter chain is shard-eligible as one stage;
    sharded outputs byte-equal to the dp=1 fused run."""
    desc = (
        "appsrc name=src caps=other/tensors,dimensions=4:4,types=float32 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:2.0 ! "
        "tensor_filter framework=jax model=scaler custom=scale:4.0,dims:4:4 "
        "name=f ! tensor_sink name=out"
    )
    p = nt.Pipeline(desc, batch_max=4, data_parallel=4)
    fused = [s for s in p.stages if len(s.node_ids) > 1]
    assert fused and fused[0].batchable and fused[0].shardable
    frames = [np.full((4, 4), float(i + 1), np.float32) for i in range(11)]
    sharded = _run(desc, frames, queue_capacity=16, batch_max=4,
                   data_parallel=4)
    reference = _run(desc, frames, queue_capacity=16, batch_max=4,
                     data_parallel=1)
    _assert_rows_bitwise(sharded, reference)


def test_mesh_only_reaches_shardable_stages():
    """Host stages (converter, sinks) and flexible-spec filters must never
    see the mesh, whatever data_parallel says."""
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    register_custom_easy("shard-flex-double", lambda ins: [ins[0] * 2],
                         jax_traceable=True)
    desc = ("appsrc name=src ! "  # no caps: flexible per-buffer specs
            "tensor_filter framework=custom-easy model=shard-flex-double "
            "name=f ! tensor_sink name=out")
    p = nt.Pipeline(desc, batch_max=8, data_parallel=8)
    assert not any(s.shardable for s in p.stages)
    frames = [np.full((4 + (i % 2),), float(i), np.float32)
              for i in range(10)]
    outs = _run(desc, frames, queue_capacity=16, batch_max=8,
                data_parallel=8)
    for x, o in zip(frames, outs):
        np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)


def test_requesting_more_replicas_than_devices_fails():
    """Over-asking fails the start() cleanly: elements are torn back down
    and the instance is dead (a retry must raise, not hang a pull)."""
    import jax

    from nnstreamer_tpu.pipeline.runtime import PipelineError

    p = nt.Pipeline(DESC, batch_max=8,
                    data_parallel=len(jax.devices()) + 1)
    with pytest.raises(PipelineError, match="data_parallel"):
        p.start()
    runners = {id(r): r for r in p._runners.values()}.values()
    assert not any(r.thread.is_alive() for r in runners)
    with pytest.raises(PipelineError, match="failed startup"):
        p.start()


# -- in-flight dispatch window ---------------------------------------------

def test_in_order_emission_under_dispatch_depth():
    """dispatch_depth=2 with a randomly-slow host stage downstream: the
    window must never reorder — outputs arrive in exact pts order with
    correct values across bursty pushes."""
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    delays = np.random.default_rng(7).uniform(0.0, 0.004, 64)

    def jitter(ins):
        time.sleep(float(delays[int(np.asarray(ins[0]).flat[0]) % 64]))
        return [np.asarray(ins[0])]

    register_custom_easy("shard-jitter", jitter)  # host-only: not traceable
    desc = (
        "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
        "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
        "name=f ! "
        "tensor_filter framework=custom-easy model=shard-jitter name=j ! "
        "tensor_sink name=out"
    )
    frames = _frames(40)
    p = nt.Pipeline(desc, queue_capacity=8, batch_max=8, data_parallel=4,
                    dispatch_depth=2)
    outs = []
    with p:
        pushed = 0
        for burst in (7, 1, 12, 3, 17):  # bursty arrivals
            for _ in range(burst):
                p.push("src", nt.Buffer([frames[pushed]], pts=pushed))
                pushed += 1
            time.sleep(0.002)
        for _ in range(pushed):
            outs.append(p.pull("out", timeout=60))
        p.eos()
        p.wait(timeout=60)
    assert [o.pts for o in outs] == list(range(len(frames)))
    for x, o in zip(frames, outs):
        np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)


def test_eos_flushes_open_dispatch_window():
    """An odd trickle with depth=2 must deliver everything at EOS — the
    window can never strand a dispatched batch."""
    frames = _frames(5)
    outs = _run(DESC, frames, queue_capacity=16, batch_max=4,
                data_parallel=4, dispatch_depth=3)
    _assert_rows_bitwise(outs, _run(DESC, frames, batch_max=1))


def test_stage_failure_flushes_inflight_window():
    """A batch held in the dispatch window when a LATER batch's dispatch
    raises must still be delivered before the error propagates — exactly
    what dispatch_depth=1 would have done."""
    import threading

    from nnstreamer_tpu.pipeline.runtime import PipelineError

    p = nt.Pipeline(DESC, queue_capacity=32, batch_max=4, data_parallel=1,
                    dispatch_depth=2)
    el = p.element("f")
    first_started, release = threading.Event(), threading.Event()
    orig_process, orig_batch = el.process, el.process_batch

    def gated(pad, buf):  # holds the stage on buffer 0 so 7 more backlog
        first_started.set()
        assert release.wait(10)
        return orig_process(pad, buf)

    def flaky(pad, bufs):  # drains run 4 then 3; the 3-batch blows up
        if len(bufs) == 3:
            raise RuntimeError("boom")
        return orig_batch(pad, bufs)

    el.process, el.process_batch = gated, flaky
    frames = _frames(8)
    with p:
        p.push("src", nt.Buffer([frames[0]], pts=0))
        assert first_started.wait(10)
        for i in range(1, 8):
            p.push("src", nt.Buffer([frames[i]], pts=i))
        release.set()
        # single(1) + the 4-batch held in the window MUST arrive; the
        # failing 3-batch must not
        outs = [p.pull("out", timeout=60) for _ in range(5)]
        assert [o.pts for o in outs] == [0, 1, 2, 3, 4]
        for x, o in zip(frames, outs):
            np.testing.assert_allclose(np.asarray(o.tensors[0]), x * 2.0)
        with pytest.raises(PipelineError, match="boom"):
            p.pull("out", timeout=10)


def test_dispatch_depth_1_keeps_lockstep_semantics():
    frames = _frames(16)
    a = _run(DESC, frames, queue_capacity=32, batch_max=8, data_parallel=4,
             dispatch_depth=1)
    b = _run(DESC, frames, queue_capacity=32, batch_max=8, data_parallel=4,
             dispatch_depth=2)
    _assert_rows_bitwise(a, b)
