"""nns-weave (ISSUE 20): cross-process distributed tracing — wire-
propagated trace context, NTP-style clock alignment, ring-dump merge
with cross-wire flow arrows, and the per-stream serving timeline
(docs/OBSERVABILITY.md "Distributed tracing").

The contract under test: trace ids are epoch-prefixed so two processes
can never mint the same id; the parent context (``_tparent``) rides the
query wire both directions and the server adopts it at ingress; clock
offsets estimated from handshake echoes bound their own error; ``merge``
joins N per-process ring dumps into ONE schema-clean, ts-monotonic
Chrome trace with client→server→client flow arrows; and NONE of it
touches the trace_mode=off hot path (``record`` never runs, no stamps).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import Metrics, metrics
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.filters.custom_easy import register_custom_easy
from nnstreamer_tpu.utils import tracing
from nnstreamer_tpu.utils.slo import SLOEngine, SLOPolicy, TenantSLO
from nnstreamer_tpu.utils.tracing import (FlightRecorder, Span,
                                          clock_offset, dump_ring,
                                          load_ring, merge_ring_files,
                                          merge_rings, next_trace_id,
                                          recorder, trace_epoch,
                                          validate_chrome)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    metrics.reset()
    recorder.configure("off")
    recorder.clear()
    yield
    recorder.configure("off")
    recorder.clear()
    metrics.reset()


@pytest.fixture()
def _models():
    spec = TensorsSpec.from_string("4", "float32")
    register_custom_easy(
        "w-double", lambda ins: [ins[0] * 2], in_spec=spec, out_spec=spec,
    )
    yield


# -- epoch-prefixed trace ids ----------------------------------------------

def test_trace_ids_epoch_prefixed_and_int64_safe():
    ep = trace_epoch()
    assert 1 <= ep <= 0x7FFFFFFF
    a, b = next_trace_id(), next_trace_id()
    assert a != b and a >> 32 == ep and b >> 32 == ep
    assert a < 2 ** 63  # survives the wire codec's int64 tensors


def test_two_processes_mint_disjoint_ids():
    """Satellite 1: the epoch high bits keep two real processes' id
    spaces disjoint without coordination."""
    prog = ("from nnstreamer_tpu.utils import tracing\n"
            "import json\n"
            "print(json.dumps({'epoch': tracing.trace_epoch(),"
            " 'ids': [tracing.next_trace_id() for _ in range(64)]}))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    outs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", prog], cwd=REPO,
                              env=env, capture_output=True, text=True,
                              timeout=120)
        assert proc.returncode == 0, proc.stderr
        outs.append(json.loads(proc.stdout))
    a, b = outs
    # 31-bit random epochs: a collision here is a 1-in-2^31 fluke, and
    # it would be exactly the aliasing the epoch prefix exists to stop
    assert a["epoch"] != b["epoch"]
    assert not set(a["ids"]) & set(b["ids"])
    # and both are disjoint from THIS process's ids
    mine = {next_trace_id() for _ in range(64)}
    assert not (set(a["ids"]) | set(b["ids"])) & mine


# -- clock offset estimator ------------------------------------------------

def test_clock_offset_symmetric_delay_exact():
    """With symmetric path delay the estimate recovers the true offset
    EXACTLY and the uncertainty equals the one-way delay."""
    true_off, d, hold = 5_000_000, 40_000, 7_000
    t0 = 1_000_000
    t1 = t0 + d + true_off       # peer clock = local + true_off
    t2 = t1 + hold
    t3 = t2 - true_off + d
    off, unc = clock_offset(t0, t1, t2, t3)
    assert off == true_off
    assert unc == d


@pytest.mark.parametrize("fwd,back", [(10_000, 90_000), (90_000, 10_000),
                                      (1, 200_000)])
def test_clock_offset_asymmetric_error_within_uncertainty(fwd, back):
    """Asymmetric delay biases the estimate by (fwd-back)/2 — always
    within the reported uncertainty bound (half the round trip)."""
    true_off, hold = -3_000_000, 11_000
    t0 = 2_000_000
    t1 = t0 + fwd + true_off
    t2 = t1 + hold
    t3 = t2 - true_off + back
    off, unc = clock_offset(t0, t1, t2, t3)
    assert abs(off - true_off) <= unc
    assert unc == (fwd + back) // 2


def test_note_clock_keeps_tightest_sample():
    rec = FlightRecorder("ring")
    rec.note_clock(42, 1_000, 50_000)
    rec.note_clock(42, 1_100, 5_000)    # tighter: replaces
    assert rec.clock()[42][:2] == (1_100, 5_000)
    rec.note_clock(42, 9_999, 40_000)   # looser + fresh entry: ignored
    assert rec.clock()[42][:2] == (1_100, 5_000)
    rec.clear()
    assert rec.clock() == {}


# -- ring dump round trip --------------------------------------------------

def test_dump_load_ring_round_trip(tmp_path):
    rec = FlightRecorder("ring")
    rec.note_clock(77, -123_456, 9_000)
    spans = [
        Span(1_000, 500, "ingress", "src", next_trace_id(), None),
        Span(2_000, 0, "query.send", "qc", next_trace_id(),
             {"msg": 3, "note": "x"}),
        Span(3_000, 0, "clock.sync", "qc", None,
             {"peer_epoch": 77, "offset_ns": -123_456}),
    ]
    for s in spans:
        rec.record(s.kind, s.stage, s.tid, s.ts, s.dur, **(s.args or {}))
    p = str(tmp_path / "a.ring")
    assert dump_ring(p, rec=rec, proc="me") == 3
    ring = load_ring(p)
    assert ring["epoch"] == trace_epoch()
    assert ring["proc"] == "me"
    assert ring["clock"] == {77: (-123_456, 9_000)}
    assert ring["spans"] == spans


def test_load_ring_rejects_non_ring_files(tmp_path):
    p = str(tmp_path / "junk.ring")
    with open(p, "wb") as f:
        f.write(b"not a wire frame at all")
    with pytest.raises(ValueError):
        load_ring(p)
    empty = str(tmp_path / "empty.ring")  # a SIGKILLed worker's mkstemp
    open(empty, "wb").close()
    with pytest.raises(ValueError):
        load_ring(empty)


# -- merge: alignment, arrows, monotonicity --------------------------------

def _wire_rings(n_req=4, offset=500_000):
    """One synthetic client/server ring pair: the client clock runs
    ``offset`` ns behind the server and carries one clock sample.  The
    client ring includes its own MINTING ingress span per id — the
    real shape; pairing must skip it in favor of the server's
    adopted-ingress span (regression: it used to eat the zip slot)."""
    cli_ep, srv_ep = 111, 222
    cli, srv = [], []
    for k in range(n_req):
        tid = (cli_ep << 32) | (k + 1)
        s = 1_000_000 + k * 100_000  # server-frame send instant
        cli.append(Span(s - offset - 5_000, 0, "ingress", "src", tid,
                        None))
        cli.append(Span(s - offset, 0, "query.send", "qc", tid, None))
        srv.append(Span(s + 20_000, 10_000, "ingress", "ssrc", tid, None))
        srv.append(Span(s + 40_000, 0, "query.reply", "ssink", tid, None))
        cli.append(Span(s + 60_000 - offset, 0, "query.recv", "qc", tid,
                        None))
    return (
        {"epoch": srv_ep, "proc": "server", "clock": {}, "spans": srv},
        {"epoch": cli_ep, "proc": "client",
         "clock": {srv_ep: (offset, 2_000)}, "spans": cli},
    )


def test_merge_flow_arrows_link_both_directions():
    srv_ring, cli_ring = _wire_rings(n_req=3)
    obj, stats = merge_rings([srv_ring, cli_ring])
    assert stats == {"rings": 2, "spans": 15, "arrows": 6,
                     "unaligned": []}
    assert validate_chrome(obj) == []
    evs = obj["traceEvents"]
    pids = {e["args"]["name"].split(" epoch=")[0]: e["pid"] for e in evs
            if e.get("ph") == "M" and e["name"] == "process_name"}
    starts = {e["id"]: e for e in evs if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in evs if e.get("ph") == "f"}
    assert set(starts) == set(finishes) and len(starts) == 6
    crossings = {(starts[i]["pid"], finishes[i]["pid"]) for i in starts}
    # both wire directions, never a same-process arrow
    assert crossings == {(pids["client"], pids["server"]),
                         (pids["server"], pids["client"])}
    for i in starts:
        assert starts[i]["args"]["trace_id"] == \
            finishes[i]["args"]["trace_id"]
        assert starts[i]["args"]["uncertainty_ns"] >= 2_000
        # the arrow lands where it starts or later (offset-corrected)
        assert finishes[i]["ts"] >= starts[i]["ts"]


def test_merge_offset_correction_aligns_timebases():
    """The client ring's spans land on the server timebase: its
    query.send precedes the server ingress AFTER correction even though
    the raw client clock ran 0.5 ms behind."""
    srv_ring, cli_ring = _wire_rings(n_req=1)
    raw_send = cli_ring["spans"][0].ts
    raw_ingress = srv_ring["spans"][0].ts
    assert raw_send < raw_ingress  # true even uncorrected here
    obj, _ = merge_rings([srv_ring, cli_ring])
    xs = {}
    for e in obj["traceEvents"]:
        if e.get("ph") in ("X", "i") and e.get("args", {}).get("trace_id"):
            if e["name"] != "ingress" or e.get("dur"):  # server's ingress
                xs[e["name"]] = e
    # corrected: send sits 20 us before the ADOPTED ingress, not 520 us
    gap_us = xs["ingress"]["ts"] - xs["query.send"]["ts"]
    assert 15 <= gap_us <= 25
    align = {a["proc"]: a for a in obj["otherData"]["weave"]}
    assert align["client"]["aligned"] and align["client"]["offset_ns"] == \
        500_000
    assert align["server"]["offset_ns"] == 0


def test_merge_monotonic_over_shuffled_rings(tmp_path):
    """Satellite 4: ring order on the command line and span order inside
    each ring must not matter — the merged trace is globally ts-sorted
    and schema-clean either way."""
    import random

    rng = random.Random(7)
    srv_ring, cli_ring = _wire_rings(n_req=8)
    third = {"epoch": 333, "proc": "client2",
             "clock": {222: (-250_000, 1_500)},
             "spans": [Span(5_000_000 + k * 9_000, 0, "query.send", "qc",
                            (333 << 32) | k, None) for k in range(16)]}
    for ring in (srv_ring, cli_ring, third):
        rng.shuffle(ring["spans"])
    for order in ([srv_ring, cli_ring, third],
                  [third, cli_ring, srv_ring]):
        obj, stats = merge_rings(order)
        assert validate_chrome(obj) == []
        assert stats["unaligned"] == []
        ts = [e["ts"] for e in obj["traceEvents"]]
        assert ts == sorted(ts)


def test_merge_unaligned_ring_is_flagged_not_hidden():
    srv_ring, cli_ring = _wire_rings(n_req=1)
    stray = {"epoch": 999, "proc": "stray", "clock": {},
             "spans": [Span(10, 0, "ingress", "s", None, None)]}
    obj, stats = merge_rings([srv_ring, cli_ring, stray])
    assert stats["unaligned"] == ["stray"]
    align = {a["proc"]: a for a in obj["otherData"]["weave"]}
    assert align["stray"]["aligned"] is False
    assert validate_chrome(obj) == []


def test_merge_cli_end_to_end(tmp_path, monkeypatch):
    """python -m nnstreamer_tpu.tools.trace merge over real dump_ring
    files from two (simulated) processes → one validating trace."""
    paths = []
    for ring in _wire_rings(n_req=2):
        rec = FlightRecorder("ring")
        for pe, (off, unc) in ring["clock"].items():
            rec.note_clock(pe, off, unc)
        for s in ring["spans"]:
            rec.record(s.kind, s.stage, s.tid, s.ts, s.dur)
        monkeypatch.setattr(tracing, "_PROCESS_EPOCH", ring["epoch"])
        p = str(tmp_path / f"{ring['proc']}.ring")
        dump_ring(p, rec=rec, proc=ring["proc"])
        paths.append(p)
    obj, stats = merge_ring_files(paths)
    assert stats["rings"] == 2 and stats["arrows"] == 4
    assert validate_chrome(obj) == []
    out = str(tmp_path / "merged.json")
    from nnstreamer_tpu.tools import trace as trace_cli
    assert trace_cli.main(["merge", *paths, "--out", out]) == 0
    with open(out) as f:
        assert validate_chrome(json.load(f)) == []
    assert trace_cli.main(["validate", out]) == 0


# -- wire propagation through real query pipelines -------------------------

def _query_roundtrip(trace_mode, n=4, sid=41):
    srv = nt.Pipeline(
        f"tensor_query_serversrc name=ssrc port=0 id={sid} ! "
        "tensor_filter framework=custom-easy model=w-double ! "
        f"tensor_query_serversink id={sid}", trace_mode=trace_mode)
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "timeout=20 ! tensor_sink name=out", trace_mode=trace_mode)
        with cli:
            for i in range(n):
                cli.push("src", np.full((4,), float(i), np.float32))
            for i in range(n):
                out = cli.pull("out", timeout=20)
                np.testing.assert_allclose(out.tensors[0],
                                           np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=20)


def test_wire_context_propagates_in_ring_mode(_models):
    """The ingress-minted trace id crosses the wire (``_tparent``) and
    comes back: client send/recv and server ingress/reply spans agree on
    the id set, and the handshake echo seeded the clock table."""
    _query_roundtrip("ring")
    by_kind = {}
    for e in recorder.events():
        if e.tid is not None:
            by_kind.setdefault(e.kind, set()).add(e.tid)
    sent = by_kind.get("query.send", set())
    assert len(sent) == 4
    assert all(t >> 32 == trace_epoch() for t in sent)
    assert sent == by_kind.get("ingress", set()) \
        == by_kind.get("query.reply", set()) \
        == by_kind.get("query.recv", set())
    clk = recorder.clock()
    assert trace_epoch() in clk  # in-process server: peer epoch == ours
    off, unc, _t = clk[trace_epoch()]
    assert unc >= 0 and abs(off) <= unc + 50_000_000


def test_off_mode_record_raises_pin(_models, monkeypatch):
    """Satellite 4: every new weave hook site is a pointer check, not
    "tracing that discards" — with trace_mode=off a raising ``record``
    proves no site runs, and nothing was stamped or noted."""

    def boom(*a, **k):
        raise AssertionError("FlightRecorder.record ran with "
                             "trace_mode=off")

    monkeypatch.setattr(FlightRecorder, "record", boom)
    _query_roundtrip("off", sid=42)
    assert recorder.events() == []
    assert recorder.clock() == {}


# -- per-stream serving timeline -------------------------------------------

def test_serve_timeline_ttft_itl_and_splits():
    from nnstreamer_tpu.filters.llm import LLMFramework

    metrics.reset()
    fw = LLMFramework()
    fw.open({"model": "llama_tiny",
             "custom": "max_new:8,serve:continuous,slots:2,"
                       "stream_chunk:2,temperature:0.0,dtype:float32"})
    try:
        done = threading.Event()
        toks = []

        def emit(tensors, meta):
            toks.append(int(tensors[0][0]) if len(tensors[0]) else -1)
            if meta.get("stream_last"):
                done.set()

        fw.submit([np.asarray([3, 5, 7, 9], np.int32)],
                  {"_tenant": "acme"}, emit)
        assert done.wait(60)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not \
                metrics.reservoir("llm.serve.decode_ms", tenant="acme"):
            time.sleep(0.05)  # splits land at retire, just after last tok
    finally:
        fw.close()
    ttft = metrics.reservoir("llm.serve.ttft_ms", tenant="acme")
    itl = metrics.reservoir("llm.serve.itl_ms", tenant="acme")
    assert len(ttft) == 1 and ttft[0] > 0
    # 8 new tokens = 1 first + 7 inter-token gaps
    assert len(itl) == 7 and all(v >= 0 for v in itl)
    for series in ("llm.serve.queue_ms", "llm.serve.prefill_ms",
                   "llm.serve.decode_ms"):
        vals = metrics.reservoir(series, tenant="acme")
        assert len(vals) == 1 and vals[0] >= 0, series
        assert metrics.reservoir(series), series  # base twin too


def test_slo_ttft_objective():
    """Satellite: ``ttft_p99_ms`` evaluates off the millisecond-valued
    reservoir — violation when the tail blows the objective, green when
    under, absent when unconfigured."""
    m = Metrics()
    for v in [10.0] * 98 + [400.0, 500.0]:
        m.observe_latency("llm.serve.ttft_ms", v, tenant="a")
    for _ in range(100):
        m.observe_latency("llm.serve.ttft_ms", 5.0, tenant="b")
    pol = SLOPolicy(tenants=[TenantSLO("a", ttft_p99_ms=50.0),
                             TenantSLO("b", ttft_p99_ms=50.0)])
    eng = SLOEngine(pol, sinks=["out"], metrics=m)
    rep = eng.evaluate()
    va, vb = rep["tenants"]["a"], rep["tenants"]["b"]
    assert va["ttft_p99_ms"] is not None and va["ttft_p99_ms"] > 50.0
    assert any("ttft p99" in v for v in va["violations"])
    assert vb["ttft_p99_ms"] is not None and vb["ttft_p99_ms"] <= 50.0
    assert not any("ttft" in v for v in vb["violations"])
    # unconfigured tenants don't grow a surprise objective
    pol2 = SLOPolicy(tenants=[TenantSLO("a")])
    rep2 = SLOEngine(pol2, sinks=["out"], metrics=m).evaluate()
    assert rep2["tenants"]["a"]["ttft_p99_ms"] is None
