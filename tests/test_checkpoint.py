"""Checkpoint ingestion tests (VERDICT r2 missing #3, second half): real
weights enter models/llama.py's documented pytree via safetensors/npz.

Strategy: start from a native ``init_params`` pytree, EXPORT it to
HF-format tensors (the inverse transpose/unstack of the importer), write a
real .safetensors file + config.json, import it back, and require exact
pytree equality plus identical forward logits — proving the name mapping,
transposes, and stacking, not just "it loads".
"""

import json

import numpy as np
import pytest

from nnstreamer_tpu.models import checkpoint as ckpt
from nnstreamer_tpu.models import llama, zoo


CFG = llama.LlamaConfig(vocab=96, dim=32, n_layers=2, n_heads=2,
                        n_kv_heads=1, ffn_hidden=48, max_seq=64)


def _to_hf(params, cfg):
    """Invert load_checkpoint's mapping: stacked native -> HF names."""
    out = {"model.embed_tokens.weight": np.asarray(params["embed"]),
           "model.norm.weight": np.asarray(params["ln_out"]),
           "lm_head.weight": np.ascontiguousarray(
               np.asarray(params["lm_head"]).T)}
    lay = params["layers"]
    hf = {"wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
          "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
          "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
          "w_down": "mlp.down_proj"}
    for i in range(cfg.n_layers):
        for k, name in hf.items():
            out[f"model.layers.{i}.{name}.weight"] = np.ascontiguousarray(
                np.asarray(lay[k])[i].T)
        out[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
            lay["ln_attn"])[i]
        out[f"model.layers.{i}.post_attention_layernorm.weight"] = \
            np.asarray(lay["ln_mlp"])[i]
    return out


def _write_config(dirpath, cfg):
    (dirpath / "config.json").write_text(json.dumps({
        "vocab_size": cfg.vocab, "hidden_size": cfg.dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.ffn_hidden,
        "max_position_embeddings": cfg.max_seq,
        "rope_theta": cfg.rope_theta, "rms_norm_eps": cfg.norm_eps,
    }))


def _assert_tree_equal(got, want):
    import jax

    flat_g = jax.tree_util.tree_leaves_with_path(got)
    flat_w = dict(jax.tree_util.tree_leaves_with_path(want))
    assert len(flat_g) == len(flat_w)
    for path, g in flat_g:
        w = flat_w[path]
        np.testing.assert_array_equal(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            err_msg=str(path))


class TestSafetensors:
    def test_roundtrip_dtypes(self, tmp_path):
        from nnstreamer_tpu.core.types import bfloat16

        rng = np.random.default_rng(0)
        tensors = {
            "a": rng.standard_normal((3, 4)).astype(np.float32),
            "b": (rng.standard_normal((8,)) * 10).astype(np.float16),
            "c": rng.integers(0, 100, (2, 2)).astype(np.int64),
            "d": rng.standard_normal((4, 2)).astype(np.float32).astype(bfloat16),
        }
        p = str(tmp_path / "t.safetensors")
        ckpt.write_safetensors(p, tensors)
        back = ckpt.read_safetensors(p)
        assert set(back) == set(tensors)
        for k in tensors:
            assert back[k].dtype == tensors[k].dtype
            np.testing.assert_array_equal(np.asarray(back[k], np.float32),
                                          np.asarray(tensors[k], np.float32))

    def test_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.safetensors"
        p.write_bytes(b"\xff" * 64)
        with pytest.raises(ckpt.CheckpointError):
            ckpt.read_safetensors(str(p))

    def test_sharded_index(self, tmp_path):
        a = {"x": np.arange(6, dtype=np.float32).reshape(2, 3)}
        b = {"y": np.ones((4,), np.float32)}
        ckpt.write_safetensors(str(tmp_path / "s1.safetensors"), a)
        ckpt.write_safetensors(str(tmp_path / "s2.safetensors"), b)
        idx = tmp_path / "model.safetensors.index.json"
        idx.write_text(json.dumps({"weight_map": {
            "x": "s1.safetensors", "y": "s2.safetensors"}}))
        out = ckpt.load_tensors(str(idx))
        np.testing.assert_array_equal(out["x"], a["x"])
        np.testing.assert_array_equal(out["y"], b["y"])
        # directory form resolves to the same index
        out2 = ckpt.load_tensors(str(tmp_path))
        assert set(out2) == {"x", "y"}


class TestLlamaImport:
    def test_hf_roundtrip_exact(self, tmp_path):
        params = llama.init_params(CFG, seed=3)
        ckpt.write_safetensors(str(tmp_path / "model.safetensors"),
                               _to_hf(params, CFG))
        _write_config(tmp_path, CFG)
        got, cfg = llama.load_checkpoint(
            str(tmp_path / "model.safetensors"), dtype="float32")
        assert cfg == CFG  # config.json read back verbatim
        _assert_tree_equal(got, params)

    def test_forward_logits_match(self, tmp_path):
        params = llama.init_params(CFG, seed=3)
        ckpt.write_safetensors(str(tmp_path / "model.safetensors"),
                               _to_hf(params, CFG))
        _write_config(tmp_path, CFG)
        got, cfg = llama.load_checkpoint(
            str(tmp_path / "model.safetensors"), dtype="float32")
        toks = np.array([[1, 5, 9, 2]], np.int32)
        a = np.asarray(llama.forward(params, toks, CFG,
                                     compute_dtype="float32"))
        b = np.asarray(llama.forward(got, toks, cfg,
                                     compute_dtype="float32"))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_tied_embeddings_fallback(self, tmp_path):
        params = llama.init_params(CFG, seed=1)
        hf = _to_hf(params, CFG)
        del hf["lm_head.weight"]
        ckpt.write_safetensors(str(tmp_path / "model.safetensors"), hf)
        _write_config(tmp_path, CFG)
        got, _ = llama.load_checkpoint(
            str(tmp_path / "model.safetensors"), dtype="float32")
        np.testing.assert_array_equal(got["lm_head"],
                                      np.asarray(got["embed"]).T)

    def test_missing_tensor_clear_error(self, tmp_path):
        hf = _to_hf(llama.init_params(CFG, seed=0), CFG)
        del hf["model.layers.1.mlp.up_proj.weight"]
        ckpt.write_safetensors(str(tmp_path / "model.safetensors"), hf)
        _write_config(tmp_path, CFG)
        with pytest.raises(ckpt.CheckpointError, match="up_proj"):
            llama.load_checkpoint(str(tmp_path / "model.safetensors"))

    def test_wrong_config_shape_error(self, tmp_path):
        hf = _to_hf(llama.init_params(CFG, seed=0), CFG)
        ckpt.write_safetensors(str(tmp_path / "model.safetensors"), hf)
        bad = llama.LlamaConfig(vocab=96, dim=32, n_layers=2, n_heads=2,
                                n_kv_heads=1, ffn_hidden=64)  # wrong F
        with pytest.raises(ValueError, match="w_gate"):
            llama.load_checkpoint(str(tmp_path / "model.safetensors"),
                                  cfg=bad)

    def test_non_llama_checkpoint_clear_error(self, tmp_path):
        # a BERT-ish file with neither naming scheme nor config.json must
        # fail with a CheckpointError naming the file, not a bare KeyError
        p = str(tmp_path / "bert.safetensors")
        ckpt.write_safetensors(p, {
            "bert.encoder.layer.0.attention.self.query.weight":
                np.zeros((4, 4), np.float32)})
        with pytest.raises(ckpt.CheckpointError, match="bert.safetensors"):
            llama.load_checkpoint(p)

    def test_native_npz_roundtrip(self, tmp_path):
        params = llama.init_params(CFG, seed=2)
        flat = {"embed": params["embed"], "ln_out": params["ln_out"],
                "lm_head": params["lm_head"]}
        for k, v in params["layers"].items():
            flat[f"layers.{k}"] = v
        p = str(tmp_path / "native.npz")
        np.savez(p, **{k: np.asarray(v) for k, v in flat.items()})
        got, cfg = llama.load_checkpoint(p, cfg=CFG, dtype="float32")
        _assert_tree_equal(got, params)

    def test_zoo_builds_from_checkpoint_directory(self, tmp_path):
        # HF sharded layout as a DIRECTORY path (review r3 finding)
        params = llama.init_params(CFG, seed=4)
        hf = _to_hf(params, CFG)
        keys = sorted(hf)
        half = len(keys) // 2
        ckpt.write_safetensors(str(tmp_path / "s1.safetensors"),
                               {k: hf[k] for k in keys[:half]})
        ckpt.write_safetensors(str(tmp_path / "s2.safetensors"),
                               {k: hf[k] for k in keys[half:]})
        (tmp_path / "model.safetensors.index.json").write_text(json.dumps({
            "weight_map": {k: ("s1.safetensors" if k in keys[:half]
                               else "s2.safetensors") for k in keys}}))
        _write_config(tmp_path, CFG)
        bundle = zoo.build(str(tmp_path), {"param_dtype": "float32",
                                           "dtype": "float32"})
        assert bundle.config == CFG
        toks = np.array([[3, 1]], np.int32)
        np.testing.assert_allclose(
            np.asarray(bundle.apply_fn(bundle.params, toks)),
            np.asarray(llama.forward(params, toks, CFG,
                                     compute_dtype="float32")), rtol=1e-6)

    def test_zoo_builds_bundle_from_safetensors(self, tmp_path):
        params = llama.init_params(CFG, seed=3)
        path = tmp_path / "model.safetensors"
        ckpt.write_safetensors(str(path), _to_hf(params, CFG))
        _write_config(tmp_path, CFG)
        bundle = zoo.build(str(path), {"param_dtype": "float32",
                                       "dtype": "float32"})
        assert bundle.config.vocab == CFG.vocab
        toks = np.array([[1, 2, 3]], np.int32)
        logits = np.asarray(bundle.apply_fn(bundle.params, toks))
        want = np.asarray(llama.forward(params, toks, CFG,
                                        compute_dtype="float32"))
        np.testing.assert_allclose(logits, want, rtol=1e-6)
