"""SentencePiece tokenizer + sampler tests (reference: the llama.cpp
sub-plugin's text path, ``tensor_filter_llamacpp.cc``, SURVEY §2.4
[UNVERIFIED]): vocab from GGUF metadata, greedy-merge encode, per-piece
streaming decode, EOS termination, and top-k/top-p sampling."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import gguf, llama
from nnstreamer_tpu.models.tokenizer import (
    TYPE_BYTE, TYPE_CONTROL, TYPE_NORMAL, TYPE_UNKNOWN,
    SentencePieceTokenizer, load_gguf_tokenizer, toy_vocab)

CFG = llama.LlamaConfig(vocab=384, dim=32, n_layers=2, n_heads=2,
                        n_kv_heads=1, ffn_hidden=48, max_seq=64)


def _hello_vocab():
    """Merge pieces scored so 'hello world' tokenizes into real words."""
    # every multi-char piece is reachable by pairwise merges of smaller
    # pieces (the SPM property real vocabs have by construction)
    return toy_vocab({
        "he": -1.0, "ll": -1.5, "llo": -1.2, "hello": -0.5,
        "▁hello": -0.1, "or": -1.0, "ld": -1.1, "orld": -0.8,
        "▁w": -2.0, "▁world": -0.2,
    })


class TestEncode:
    def test_merges_to_best_pieces(self):
        tok = _hello_vocab()
        ids = tok.encode_text("hello world")
        pieces = [tok.pieces[i] for i in ids]
        assert pieces == ["▁hello", "▁world"]

    def test_prefix_space_and_roundtrip(self):
        tok = _hello_vocab()
        for text in ("hello world", "hello", "a b  c", "x!?"):
            ids = tok.encode_text(text)
            assert tok.decode(ids) == text

    def test_encode_prepends_bos(self):
        tok = _hello_vocab()
        ids = tok.encode(b"hello")
        assert ids[0] == tok.bos

    def test_byte_fallback_for_unknown_chars(self):
        tok = _hello_vocab()
        text = "héllo"  # é is not in the vocab -> 2 UTF-8 byte tokens
        ids = tok.encode_text(text)
        assert all(0 <= i < tok.n_vocab for i in ids)
        bs = "é".encode("utf-8")
        byte_ids = [tok._byte_ids[b] for b in bs]
        assert all(b in ids for b in byte_ids)
        assert tok.decode(ids) == text

    def test_no_byte_pieces_falls_back_to_unk(self):
        tok = SentencePieceTokenizer(
            ["<unk>", "<s>", "</s>", "▁", "a"],
            [0.0, 0.0, 0.0, -1.0, -1.0],
            [TYPE_UNKNOWN, TYPE_CONTROL, TYPE_CONTROL,
             TYPE_NORMAL, TYPE_NORMAL])
        ids = tok.encode_text("aé")
        assert tok.unk in ids

    def test_empty_text(self):
        tok = _hello_vocab()
        assert tok.encode_text("") == []
        assert tok.encode(b"") == [tok.bos]

    def test_merge_priority_follows_scores(self):
        # "ab" scores better than "bc": "abc" -> [▁, ab, c]
        tok = toy_vocab({"ab": -0.5, "bc": -0.9})
        pieces = [tok.pieces[i] for i in tok.encode_text("abc")]
        assert "ab" in pieces and "bc" not in pieces


class TestDecode:
    def test_control_tokens_are_silent(self):
        tok = _hello_vocab()
        assert tok.decode_piece(tok.bos) == b""
        assert tok.decode_piece(tok.eos) == b""
        assert tok.decode_piece(tok.unk) == b""

    def test_byte_token_decodes_to_byte(self):
        tok = _hello_vocab()
        bid = tok._byte_ids[0x41]
        assert tok.decode_piece(bid) == b"A"

    def test_out_of_range_id(self):
        tok = _hello_vocab()
        assert tok.decode_piece(-1) == b""
        assert tok.decode_piece(tok.n_vocab + 5) == b""

    def test_space_marker_maps_to_space(self):
        tok = _hello_vocab()
        i = tok._index["▁hello"]
        assert tok.decode_piece(i) == b" hello"


class TestGGUFMetadata:
    def test_vocab_roundtrip_through_gguf(self, tmp_path):
        tok = _hello_vocab()
        p = str(tmp_path / "v.gguf")
        meta = {"general.architecture": "llama"}
        meta.update(tok.to_gguf_meta())
        gguf.write(p, meta, {"x": np.zeros((2, 2), np.float32)})
        got = load_gguf_tokenizer(p)
        assert got is not None
        assert got.pieces == tok.pieces
        assert got.scores == pytest.approx(tok.scores, abs=1e-6)
        assert got.types == tok.types
        assert (got.bos, got.eos, got.unk) == (tok.bos, tok.eos, tok.unk)
        assert got.encode_text("hello world") == \
            tok.encode_text("hello world")

    def test_weights_only_gguf_has_no_tokenizer(self, tmp_path):
        params = llama.init_params(CFG, seed=3)
        p = str(tmp_path / "w.gguf")
        gguf.export_llama(p, params, CFG)
        assert load_gguf_tokenizer(p) is None

    def test_read_metadata_skips_tensor_blob(self, tmp_path):
        tok = _hello_vocab()
        p = str(tmp_path / "v.gguf")
        meta = gguf.llama_metadata(CFG)
        meta.update(tok.to_gguf_meta())
        gguf.write(p, meta, gguf.llama_to_tensors(
            llama.init_params(CFG, seed=1), CFG))
        m = gguf.read_metadata(p)
        assert m["llama.block_count"] == CFG.n_layers
        assert len(m["tokenizer.ggml.tokens"]) == tok.n_vocab


class TestLLMFilterTextPath:
    """End-to-end: a .gguf carrying BOTH weights and vocab drives the llm
    filter's text contract — the reference sub-plugin's usage."""

    def _export(self, tmp_path, tok=None, zero_head=False):
        params = llama.init_params(CFG, seed=7)
        if zero_head:
            # zero lm_head -> all logits equal -> greedy argmax is id 0 at
            # every step: generation is pinned to a known token
            params["lm_head"] = np.zeros_like(params["lm_head"])
        p = str(tmp_path / "model.gguf")
        gguf.export_llama(p, params, CFG, tokenizer=tok)
        return p

    def test_text_prompt_roundtrip(self, tmp_path):
        tok = _hello_vocab()
        p = self._export(tmp_path, tok)
        pl = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=1:1,"
            "types=uint8,format=flexible ! "
            f"tensor_filter framework=llm model={p} "
            "custom=max_new:4,param_dtype:float32,dtype:float32,"
            "stop_eos:0 ! "
            "tensor_sink name=out")
        pieces = []
        with pl:
            pl.push("src", np.frombuffer(b"hello world", np.uint8))
            for _ in range(4):
                out = pl.pull("out", timeout=120)
                if len(out.tensors) > 1:
                    pieces.append(bytes(np.asarray(out.tensors[1])))
            pl.eos()
            pl.wait(timeout=30)
        assert len(pieces) == 4  # streaming text path alive
        # every emitted piece decodes through the model's own vocab
        assert all(isinstance(b, bytes) for b in pieces)

    def test_eos_stops_generation(self, tmp_path):
        # eos id 0 + zeroed lm_head: the first greedy token IS eos, so a
        # max_new:8 request must yield exactly one token
        pieces = ["</s>", "<s>", "<unk>", "▁", "h", "i"]
        types = [TYPE_CONTROL, TYPE_CONTROL, TYPE_UNKNOWN,
                 TYPE_NORMAL, TYPE_NORMAL, TYPE_NORMAL]
        tok = SentencePieceTokenizer(
            pieces, [0.0] * len(pieces), types, bos=1, eos=0, unk=2)
        p = self._export(tmp_path, tok, zero_head=True)
        pl = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=1:1,"
            "types=uint8,format=flexible ! "
            f"tensor_filter framework=llm model={p} "
            "custom=max_new:8,param_dtype:float32,dtype:float32 ! "
            "tensor_sink name=out")
        with pl:
            pl.push("src", np.frombuffer(b"hi", np.uint8))
            out = pl.pull("out", timeout=120)
            first = int(np.asarray(out.tensors[0]).ravel()[0])
            # the stream ended at EOS: no second token ever arrives
            with pytest.raises(TimeoutError):
                pl.pull("out", timeout=3)
            pl.eos()
            pl.wait(timeout=30)
        assert first == 0  # the EOS id itself is emitted, then silence

    def test_stop_eos_opt_out(self, tmp_path):
        pieces = ["</s>", "<s>", "<unk>", "▁", "h", "i"]
        types = [TYPE_CONTROL, TYPE_CONTROL, TYPE_UNKNOWN,
                 TYPE_NORMAL, TYPE_NORMAL, TYPE_NORMAL]
        tok = SentencePieceTokenizer(
            pieces, [0.0] * len(pieces), types, bos=1, eos=0, unk=2)
        p = self._export(tmp_path, tok, zero_head=True)
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": p,
                 "custom": "max_new:3,param_dtype:float32,dtype:float32,"
                           "stop_eos:0"})
        try:
            outs = list(fw.invoke_stream(
                [np.frombuffer(b"hi", np.uint8)]))
            assert len(outs) == 3  # fixed-length decode, EOS ignored
        finally:
            fw.close()

    def test_greedy_ids_match_fixture(self, tmp_path):
        """Greedy generation from a seeded checkpoint is a recorded,
        reproducible sequence (float32 on the hermetic CPU backend)."""
        tok = _hello_vocab()
        p = self._export(tmp_path, tok)
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": p,
                 "custom": "max_new:6,param_dtype:float32,dtype:float32,"
                           "stop_eos:0"})
        try:
            ids = [int(np.asarray(outs[0]).ravel()[0])
                   for outs in fw.invoke_stream(
                       [np.frombuffer(b"hello world", np.uint8)])]
        finally:
            fw.close()
        assert len(ids) == 6
        # determinism is the contract (greedy + fixed seed): two runs agree
        fw2 = LLMFramework()
        fw2.open({"model": p,
                  "custom": "max_new:6,param_dtype:float32,dtype:float32,"
                            "stop_eos:0"})
        try:
            ids2 = [int(np.asarray(outs[0]).ravel()[0])
                    for outs in fw2.invoke_stream(
                        [np.frombuffer(b"hello world", np.uint8)])]
        finally:
            fw2.close()
        assert ids == ids2

    def test_explicit_tokenizer_option(self, tmp_path):
        tok = _hello_vocab()
        vocab_file = str(tmp_path / "vocab.gguf")
        meta = {"general.architecture": "llama"}
        meta.update(tok.to_gguf_meta())
        gguf.write(vocab_file, meta, {"x": np.zeros((1,), np.float32)})
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": "llama_tiny",
                 "custom": f"max_new:2,tokenizer:{vocab_file}"})
        try:
            assert isinstance(fw.tokenizer, SentencePieceTokenizer)
            assert fw.stop_eos
        finally:
            fw.close()


class TestSampling:
    def _logits(self):
        # token 0 dominant, then 1, 2, ... sharply decaying
        v = np.array([[8.0, 6.0, 5.0, 2.0, 1.0, 0.0, -1.0, -2.0]],
                     np.float32)
        return v

    def test_greedy_unchanged(self):
        import jax

        ids = llama.sample_token(self._logits(), jax.random.PRNGKey(0),
                                 0.0, top_k=2, top_p=0.5)
        assert int(np.asarray(ids)[0]) == 0

    def test_top_k_restricts_support(self):
        import jax

        hits = set()
        for s in range(64):
            ids = llama.sample_token(
                self._logits(), jax.random.PRNGKey(s), 2.0, top_k=2)
            hits.add(int(np.asarray(ids)[0]))
        assert hits <= {0, 1}
        assert len(hits) == 2  # high temperature actually explores both

    def test_top_p_restricts_support(self):
        import jax

        # softmax of [8,6,5,...]: p(0)≈0.84 -> top_p=0.5 keeps ONLY token 0
        for s in range(32):
            ids = llama.sample_token(
                self._logits(), jax.random.PRNGKey(s), 1.0, top_p=0.5)
            assert int(np.asarray(ids)[0]) == 0

    def test_top_p_keeps_minimal_covering_set(self):
        import jax

        hits = set()
        for s in range(128):
            ids = llama.sample_token(
                self._logits(), jax.random.PRNGKey(s), 2.0, top_p=0.75)
            hits.add(int(np.asarray(ids)[0]))
        # at temperature 2: p ≈ softmax([4,3,2.5,...]) = (.52,.19,.12,…);
        # exclusive-cumsum cut at 0.75 keeps {0,1,2}
        assert hits <= {0, 1, 2}
        assert 0 in hits

    def test_top_k_and_p_compose_in_jit(self):
        import jax

        @jax.jit
        def f(lg, key):
            return llama.sample_token(lg, key, 1.0, top_k=3, top_p=0.9)

        ids = f(self._logits(), jax.random.PRNGKey(1))
        assert int(np.asarray(ids)[0]) in {0, 1, 2}

    def test_batched_rows_independent(self):
        import jax

        lg = np.array([[10.0, 0.0, 0.0], [0.0, 0.0, 10.0]], np.float32)
        ids = llama.sample_token(lg, jax.random.PRNGKey(0), 1.0, top_k=1)
        assert list(np.asarray(ids)) == [0, 2]
