"""Cross-validate the SPM tokenizer against an INDEPENDENT oracle
(VERDICT r4 Missing #4).

Oracle: HuggingFace ``tokenizers`` (Rust) BPE with merges ranked by
descending merged-piece score — the published conversion of a
SentencePiece-BPE vocab (transformers' SpmConverter recipe: Prepend/
Replace normalizer, ``byte_fallback=True``).  With UNIQUE scores the
greedy highest-score merge (llama.cpp ``llm_tokenizer_spm``) and BPE
lowest-rank merge orders coincide, so any id-sequence disagreement is a
real bug in one side's merge procedure, normalization, or byte fallback.

Also pins hand-derived fixtures the fuzz can't force deterministically:
equal-score tie-breaks (leftmost pair wins), UTF-8 multibyte fallback,
unknown-byte -> UNK, and the unconditional dummy-prefix rule the oracle
caught (" a" -> "▁▁a", two markers).
"""

from __future__ import annotations

import random

import pytest

from nnstreamer_tpu.models.tokenizer import (
    _SPACE, TYPE_BYTE, TYPE_CONTROL, TYPE_NORMAL, TYPE_UNKNOWN,
    SentencePieceTokenizer, toy_vocab,
)

tokenizers = pytest.importorskip("tokenizers")


def build_vocab(rng: random.Random, alphabet: str, n_pieces: int):
    """Random SPM vocab: specials + full byte range + single chars +
    random multi-char merge pieces, all with UNIQUE scores."""
    pieces = ["<unk>", "<s>", "</s>"]
    types = [TYPE_UNKNOWN, TYPE_CONTROL, TYPE_CONTROL]
    scores = [0.0, 0.0, 0.0]
    for b in range(256):
        pieces.append(f"<0x{b:02X}>")
        types.append(TYPE_BYTE)
        scores.append(0.0)
    singles = [_SPACE] + list(alphabet)
    # unique low scores for singles
    for i, ch in enumerate(singles):
        pieces.append(ch)
        types.append(TYPE_NORMAL)
        scores.append(-1e4 - i)
    seen = set(pieces)
    merged = []
    while len(merged) < n_pieces:
        ln = rng.randint(2, 5)
        p = "".join(rng.choice(singles) for _ in range(ln))
        if p in seen:
            continue
        seen.add(p)
        merged.append(p)
    # unique scores drawn without replacement
    vals = rng.sample(range(1, 100000), len(merged))
    for p, v in zip(merged, vals):
        pieces.append(p)
        types.append(TYPE_NORMAL)
        scores.append(-v / 100.0)
    return SentencePieceTokenizer(pieces, scores, types, bos=1, eos=2,
                                  unk=0)


def build_oracle(tok: SentencePieceTokenizer):
    """The HF-tokenizers twin of an SPM vocab (SpmConverter recipe)."""
    from tokenizers import Tokenizer, models, normalizers

    vocab = {}
    for i, p in enumerate(tok.pieces):
        vocab.setdefault(p, i)  # first occurrence wins, like ours
    merges = []
    for p, i in vocab.items():
        if tok.types[i] != TYPE_NORMAL or len(p) < 2:
            continue
        for cut in range(1, len(p)):
            a, b = p[:cut], p[cut:]
            if a in vocab and b in vocab and \
                    tok.types[vocab[a]] != TYPE_BYTE and \
                    tok.types[vocab[b]] != TYPE_BYTE:
                merges.append((tok.scores[i], a, b))
    merges.sort(key=lambda m: (-m[0], len(m[1] + m[2])))
    t = Tokenizer(models.BPE(
        vocab=vocab, merges=[(a, b) for _, a, b in merges],
        byte_fallback=True, unk_token="<unk>", fuse_unk=True))
    t.normalizer = normalizers.Sequence([
        normalizers.Prepend(_SPACE),
        normalizers.Replace(" ", _SPACE),
    ])
    return t


def random_text(rng: random.Random, alphabet: str,
                literal_block: bool = True) -> str:
    # literal ▁ exercises the encode path but is inherently lossy on
    # decode (SPM maps it back to space), so round-trip fuzz excludes it
    pool = alphabet + "  " + "éß中😀" + ("▁" if literal_block else "")
    return "".join(rng.choice(pool)
                   for _ in range(rng.randint(1, 40))).strip() or "a"


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_vs_hf_tokenizers(seed):
    rng = random.Random(seed)
    alphabet = "abcdefgh"
    tok = build_vocab(rng, alphabet, n_pieces=120)
    oracle = build_oracle(tok)
    for case in range(200):
        text = random_text(rng, alphabet)
        ours = tok.encode_text(text)
        ref = oracle.encode(text, add_special_tokens=False).ids
        assert ours == ref, (
            f"seed={seed} case={case} text={text!r}: "
            f"ours={[tok.pieces[i] for i in ours]} "
            f"oracle={[tok.pieces[i] for i in ref]}")


def test_fuzz_decode_round_trip():
    rng = random.Random(99)
    tok = build_vocab(rng, "abcd", n_pieces=60)
    for _ in range(100):
        text = random_text(rng, "abcd", literal_block=False)
        ids = tok.encode_text(text)
        # SPM normalization is space -> ▁ with a dummy prefix; decode
        # inverts both, so round-trip must reproduce the input exactly
        assert tok.decode(ids) == text


# -- pinned fixtures (hand-derived, no oracle needed) ---------------------

def test_equal_score_tie_break_leftmost():
    # "ab" and "bc" share a score over "abc": the LEFTMOST candidate pair
    # merges first (llama.cpp orders its bigram queue by score then left
    # index), so the result is [▁, ab, c], never [▁, a, bc].
    tok = toy_vocab({"ab": -1.0, "bc": -1.0})
    ids = tok.encode_text("abc")
    assert [tok.pieces[i] for i in ids] == [_SPACE, "ab", "c"]


def test_merge_order_follows_score_not_length():
    # higher-scoring short merge beats a longer lower-scoring one
    tok = toy_vocab({"ab": -1.0, "abc": -50.0, "bc": -2.0})
    ids = tok.encode_text("abc")
    assert [tok.pieces[i] for i in ids] == [_SPACE, "abc"]
    # the path matters: ab (best) then ab+c via "abc" piece


def test_unconditional_dummy_prefix():
    # " a" must become ▁▁a (prefix prepended BEFORE space escaping);
    # the pre-fix implementation produced a single ▁ here
    tok = toy_vocab()
    ids = tok.encode_text(" a")
    assert [tok.pieces[i] for i in ids] == [_SPACE, _SPACE, "a"]
    assert tok.decode(ids) == " a"


def test_literal_block_char_keeps_prefix():
    # text that already starts with ▁ still gets the dummy prefix
    tok = toy_vocab()
    ids = tok.encode_text("▁x")
    assert [tok.pieces[i] for i in ids][:2] == [_SPACE, _SPACE]


def test_multibyte_byte_fallback():
    # é = C3 A9: no single-char piece, so two byte tokens
    tok = toy_vocab()
    ids = tok.encode_text("é")
    assert [tok.pieces[i] for i in ids] == [_SPACE, "<0xC3>", "<0xA9>"]
    assert tok.decode(ids) == "é"


def test_no_byte_pieces_falls_back_to_unk():
    pieces = ["<unk>", "<s>", "</s>", _SPACE, "a"]
    types = [TYPE_UNKNOWN, TYPE_CONTROL, TYPE_CONTROL, TYPE_NORMAL,
             TYPE_NORMAL]
    tok = SentencePieceTokenizer(pieces, [0, 0, 0, -1, -2], types)
    ids = tok.encode_text("aQ")
    assert ids == [3, 4, 0]  # ▁, a, <unk>


def test_merged_piece_via_either_split():
    # "abc" reachable as ab+c or a+bc; both paths must land on the piece
    tok_l = toy_vocab({"ab": -1.0, "abc": -0.5})
    tok_r = toy_vocab({"bc": -1.0, "abc": -0.5})
    for tok in (tok_l, tok_r):
        ids = tok.encode_text("abc")
        assert [tok.pieces[i] for i in ids] == [_SPACE, "abc"]
