"""Pipeline-string parser tests (reference analog: tools/development/parser
pipeline-grammar validation)."""

import pytest

from nnstreamer_tpu.core.caps import MediaType
from nnstreamer_tpu.pipeline.parser import ParseError, parse


def kinds(g):
    return [n.kind for n in g.topo_order()]


class TestChains:
    def test_linear(self):
        g = parse("videotestsrc ! tensor_converter ! tensor_sink name=out")
        assert kinds(g) == ["videotestsrc", "tensor_converter", "tensor_sink"]
        assert len(g.edges) == 2

    def test_properties(self):
        g = parse('videotestsrc num-buffers=5 pattern=ball ! tensor_sink name=x')
        src = g.topo_order()[0]
        assert src.props["num_buffers"] == 5
        assert src.props["pattern"] == "ball"
        assert g.by_name["x"].kind == "tensor_sink"

    def test_quoted_property(self):
        g = parse('appsrc caps="video/x-raw,format=RGB,width=4,height=4" ! tensor_sink')
        src = g.topo_order()[0]
        assert "width=4" in src.props["caps"]

    def test_capsfilter(self):
        g = parse("videotestsrc ! video/x-raw,format=RGB,width=64,height=32 ! tensor_converter")
        caps_node = [n for n in g.nodes.values() if n.kind == "capsfilter"][0]
        assert caps_node.caps.media == MediaType.VIDEO
        assert caps_node.caps.get("width") == 64

    def test_framerate_fraction(self):
        g = parse("videotestsrc ! video/x-raw,framerate=30/1 ! tensor_sink")
        caps_node = [n for n in g.nodes.values() if n.kind == "capsfilter"][0]
        assert caps_node.caps.get("framerate") == (30, 1)


class TestBranches:
    def test_tee(self):
        g = parse(
            "videotestsrc ! tee name=t "
            "t. ! tensor_converter ! tensor_sink name=a "
            "t. ! tensor_converter ! tensor_sink name=b"
        )
        tee = g.by_name["t"]
        assert len(g.out_edges(tee.id)) == 2
        pads = {e.src_pad for e in g.out_edges(tee.id)}
        assert pads == {"src_0", "src_1"}

    def test_mux_named_pads(self):
        g = parse(
            "tensor_mux name=m ! tensor_sink name=out "
            "videotestsrc ! tensor_converter ! m.sink_0 "
            "videotestsrc ! tensor_converter ! m.sink_1"
        )
        m = g.by_name["m"]
        assert {e.dst_pad for e in g.in_edges(m.id)} == {"sink_0", "sink_1"}

    def test_mux_auto_pads(self):
        g = parse(
            "tensor_mux name=m ! tensor_sink "
            "videotestsrc ! tensor_converter ! m. "
            "videotestsrc ! tensor_converter ! m."
        )
        m = g.by_name["m"]
        assert {e.dst_pad for e in g.in_edges(m.id)} == {"sink_0", "sink_1"}


class TestErrors:
    def test_empty(self):
        with pytest.raises(ParseError):
            parse("")

    def test_trailing_bang(self):
        with pytest.raises(ParseError):
            parse("videotestsrc !")

    def test_double_bang(self):
        with pytest.raises(ParseError):
            parse("videotestsrc ! ! tensor_sink")

    def test_unknown_ref(self):
        with pytest.raises(ParseError):
            parse("nosuch. ! tensor_sink")

    def test_duplicate_name(self):
        with pytest.raises(Exception):
            parse("videotestsrc name=a ! tensor_sink name=a")

    def test_same_src_pad_twice_needs_tee(self):
        with pytest.raises(Exception):
            parse(
                "videotestsrc name=v ! tensor_sink name=s1 v. ! tensor_sink name=s2"
            )


def test_branch_then_continue_linear():
    g = parse(
        "videotestsrc ! tensor_converter ! tensor_transform mode=typecast "
        "option=float32 ! tensor_sink name=out"
    )
    t = [n for n in g.nodes.values() if n.kind == "tensor_transform"][0]
    assert t.props["mode"] == "typecast"
    assert t.props["option"] == "float32"


class TestInspectTool:
    """gst-inspect analog (tools/inspect.py)."""

    def test_list_all_covers_registries(self):
        import io

        from nnstreamer_tpu.tools import inspect as insp

        out = io.StringIO()
        insp.list_all(out=out)
        text = out.getvalue()
        for header in ("== element", "== filter", "== decoder",
                       "== converter"):
            assert header in text
        for name in ("tensor_filter", "tensor_mux", "jax", "custom",
                     "bounding_boxes"):
            assert name in text

    def test_show_detail_and_missing(self):
        import io

        from nnstreamer_tpu.tools import inspect as insp

        out = io.StringIO()
        assert insp.show("tensor_filter", out=out)
        text = out.getvalue()
        assert "elements/filter.py" in text or "elements.filter" in text
        assert not insp.show("definitely_not_registered", out=io.StringIO())

    def test_cli(self):
        from nnstreamer_tpu.tools.inspect import main

        assert main([]) == 0
        assert main(["tensor_sink"]) == 0
        assert main(["--kind", "filter"]) == 0
        assert main(["nope_nope"]) == 1
