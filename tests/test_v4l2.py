"""v4l2src — the literal camera ingest element (VERDICT r4 Missing #2 /
Next #5).  No camera exists in CI, so the raw backend streams from a
FIFO/file of raw frames (the same polling machinery tensor_src_iio
uses); the native ioctl/mmap backend is compile-checked and gated on a
real /dev/video* node."""

import os
import threading

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.elements.base import ElementError


W, H = 16, 12
FRAME = W * H * 3


def _frames(n):
    rng = np.random.default_rng(3)
    return [rng.integers(0, 256, (H, W, 3), dtype=np.uint8)
            for i in range(n)]


def test_streams_from_fifo(tmp_path):
    fifo = os.path.join(str(tmp_path), "cam")
    os.mkfifo(fifo)
    frames = _frames(3)

    def writer():
        with open(fifo, "wb") as f:
            for fr in frames:
                f.write(fr.tobytes())

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    p = nt.Pipeline(
        f"v4l2src device={fifo} width={W} height={H} num-buffers=3 ! "
        "tensor_converter ! tensor_sink name=out")
    with p:
        for fr in frames:
            out = p.pull("out", timeout=30)
            got = np.asarray(out.tensors[0])
            np.testing.assert_array_equal(got.reshape(H, W, 3), fr)
        p.wait(timeout=30)
    t.join(timeout=5)


def test_replay_from_file(tmp_path):
    path = os.path.join(str(tmp_path), "frames.raw")
    frames = _frames(4)
    with open(path, "wb") as f:
        for fr in frames:
            f.write(fr.tobytes())
    p = nt.Pipeline(
        f"v4l2src device={path} width={W} height={H} ! "
        "tensor_converter ! tensor_sink name=out")
    with p:
        for fr in frames:
            got = np.asarray(p.pull("out", timeout=30).tensors[0])
            np.testing.assert_array_equal(got.reshape(H, W, 3), fr)
        p.wait(timeout=30)  # EOF -> EOS


def test_north_star_pipeline_runs(tmp_path):
    """The SURVEY §7 sentence made executable: v4l2src ->
    tensor_converter -> tensor_transform -> tensor_filter -> sink."""
    path = os.path.join(str(tmp_path), "frames.raw")
    rng = np.random.default_rng(1)
    with open(path, "wb") as f:
        for _ in range(2):
            f.write(rng.integers(0, 256, (16, 16, 3),
                                 dtype=np.uint8).tobytes())
    p = nt.Pipeline(
        f"v4l2src device={path} width=16 height=16 ! tensor_converter ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=average custom=dims:3:16:16:1 ! "
        "tensor_sink name=out")
    with p:
        for _ in range(2):
            out = p.pull("out", timeout=60)
            v = np.asarray(out.tensors[0]).ravel()
            assert v.shape == (1,) and np.isfinite(v).all()
        p.wait(timeout=30)


def test_missing_device_fails_loudly():
    p = nt.Pipeline(
        "v4l2src device=/nonexistent/video9 width=8 height=8 ! "
        "tensor_converter ! tensor_sink name=out")
    with pytest.raises(ElementError, match="cannot stat device"):
        with p:
            pass


def test_bad_format_rejected_at_construction():
    with pytest.raises(ElementError, match="format"):
        nt.Pipeline("v4l2src format=YV12 ! tensor_converter ! "
                    "tensor_sink name=out")


def test_native_symbols_compiled():
    """The ioctl/mmap backend must at least BUILD everywhere (the real
    capture path is gated on hardware below)."""
    from nnstreamer_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    lib = native._load()
    for sym in ("nns_v4l2_open", "nns_v4l2_capture", "nns_v4l2_close",
                "nns_v4l2_frame_bytes"):
        assert hasattr(lib, sym)
    assert native.fourcc("RGB3") == 0x33424752  # '3','B','G','R' LE


@pytest.mark.skipif(not os.path.exists("/dev/video0"),
                    reason="no v4l2 capture hardware")
def test_real_device_native_capture():  # pragma: no cover - hw gated
    p = nt.Pipeline(
        "v4l2src device=/dev/video0 width=320 height=240 num-buffers=2 ! "
        "tensor_converter ! tensor_sink name=out")
    with p:
        out = p.pull("out", timeout=30)
        assert np.asarray(out.tensors[0]).size > 0
        p.wait(timeout=30)
