"""Native C API tests: build libnnstpu_capi.so, compile a REAL C driver
program against nnstpu_capi.h, and run it — proving the framework is
callable from plain C the way the reference's ML C-API is (SURVEY §3.5).
"""

import os
import subprocess
import sys
import sysconfig
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "nnstreamer_tpu", "native")

C_DRIVER = textwrap.dedent("""
    #include <stdio.h>
    #include <string.h>
    #include "nnstpu_capi.h"

    int main(void) {
        char err[512] = "";
        char in_desc[256], out_desc[256];
        nnstpu_single_h h = nnstpu_single_open(
            "average", "jax", "dims:4:1", err, sizeof err);
        if (h < 0) { fprintf(stderr, "open: %s\\n", err); return 1; }
        if (nnstpu_single_info(h, in_desc, sizeof in_desc,
                               out_desc, sizeof out_desc,
                               err, sizeof err) != 0) {
            fprintf(stderr, "info: %s\\n", err); return 1;
        }
        printf("IN %s OUT %s\\n", in_desc, out_desc);

        float in[4] = {1.0f, 2.0f, 3.0f, 4.0f};
        const void *ins[1] = {in};
        size_t in_sz[1] = {sizeof in};
        void *outs[4];
        size_t out_sz[4];
        int n = nnstpu_single_invoke(h, ins, in_sz, 1, outs, out_sz, 4,
                                     err, sizeof err);
        if (n < 0) { fprintf(stderr, "invoke: %s\\n", err); return 1; }
        float *o = (float *)outs[0];
        printf("N %d BYTES %zu VAL %.3f\\n", n, out_sz[0], o[0]);
        if (n != 1 || o[0] != 2.5f) return 2;

        /* error path: wrong payload size must fail with a message */
        size_t bad_sz[1] = {7};
        if (nnstpu_single_invoke(h, ins, bad_sz, 1, outs, out_sz, 4,
                                 err, sizeof err) != -1 ||
            strstr(err, "bytes") == NULL) {
            fprintf(stderr, "bad-size accepted? err=%s\\n", err); return 3;
        }

        nnstpu_free(outs[0]);
        nnstpu_single_close(h);

        /* pipeline surface: construct from the DSL, push, pull, eos */
        nnstpu_pipeline_h p = nnstpu_pipeline_open(
            "appsrc name=src caps=other/tensors,dimensions=4:1,"
            "types=float32 ! "
            "tensor_transform mode=arithmetic option=add:1.0 ! "
            "tensor_sink name=out", err, sizeof err);
        if (p < 0) { fprintf(stderr, "popen: %s\\n", err); return 4; }
        float pin[4] = {1.0f, 2.0f, 3.0f, 4.0f};
        const void *pins[1] = {pin};
        size_t pin_sz[1] = {sizeof pin};
        if (nnstpu_pipeline_push(p, "src", pins, pin_sz, 1,
                                 err, sizeof err) != 0) {
            fprintf(stderr, "push: %s\\n", err); return 4;
        }
        char pdesc[128];
        n = nnstpu_pipeline_pull(p, "out", 30000, outs, out_sz, 4,
                                 pdesc, sizeof pdesc, err, sizeof err);
        if (n != 1) { fprintf(stderr, "pull: %s\\n", err); return 4; }
        float *po = (float *)outs[0];
        printf("PIPE %s %.1f %.1f %.1f %.1f\\n", pdesc,
               po[0], po[1], po[2], po[3]);
        if (po[0] != 2.0f || po[3] != 5.0f) return 5;
        nnstpu_free(outs[0]);
        if (nnstpu_pipeline_eos(p, "src", err, sizeof err) != 0) return 6;
        nnstpu_pipeline_close(p);

        printf("CAPI OK\\n");
        return 0;
    }
""")


@pytest.fixture(scope="module")
def capi_binary(tmp_path_factory):
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    td = tmp_path_factory.mktemp("capi")
    lib = str(td / "libnnstpu_capi.so")
    # Derive embed flags from THE RUNNING interpreter (a PATH
    # python3-config may describe a different Python whose site-packages
    # lack jax/numpy)
    includes = [f"-I{sysconfig.get_paths()['include']}"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ldver = sysconfig.get_config_var("LDVERSION") or \
        f"{sys.version_info.major}.{sys.version_info.minor}"
    ldflags = [f"-L{libdir}", f"-lpython{ldver}", "-ldl", "-lm"]
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
         os.path.join(NATIVE, "src", "nnstpu_capi.cpp"), "-o", lib]
        + includes + ldflags, check=True, timeout=180)
    exe = str(td / "capi_demo")
    src = td / "capi_demo.c"
    src.write_text(C_DRIVER)
    subprocess.run(
        ["g++", "-O2", "-o", exe, str(src),
         f"-I{os.path.join(NATIVE, 'include')}", lib]
        + ldflags + [f"-Wl,-rpath,{td}"],
        check=True, timeout=120)
    return exe


@pytest.mark.slow
def test_c_program_single_shot(capi_binary):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    env["LD_LIBRARY_PATH"] = libdir + os.pathsep + env.get(
        "LD_LIBRARY_PATH", "")
    proc = subprocess.run([capi_binary], env=env, capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "CAPI OK" in proc.stdout
    assert "IN 4:1,float32" in proc.stdout
    assert "VAL 2.500" in proc.stdout
    assert "PIPE 4:1,float32 2.0 3.0 4.0 5.0" in proc.stdout


class TestBridgeModule:
    """The Python half, exercised directly (fast, no compiler)."""

    def test_open_invoke_close(self):
        from nnstreamer_tpu import capi

        h = capi.single_open("average", "jax", "dims:8:2")
        try:
            ins, outs = capi.single_info(h)
            assert ins == "8:2,float32"
            x = np.arange(16, dtype=np.float32)
            res = capi.single_invoke_bytes(h, [x.tobytes()])
            got = np.frombuffer(res[0], np.float32)
            np.testing.assert_allclose(
                got, x.reshape(2, 8).mean(axis=1))
        finally:
            capi.single_close(h)

    def test_wrong_size_and_count_rejected(self):
        from nnstreamer_tpu import capi

        h = capi.single_open("average", "jax", "dims:4:1")
        try:
            with pytest.raises(ValueError, match="bytes"):
                capi.single_invoke_bytes(h, [b"\x00" * 7])
            with pytest.raises(ValueError, match="input tensor"):
                capi.single_invoke_bytes(h, [b"\x00" * 16, b"\x00" * 16])
        finally:
            capi.single_close(h)

    def test_invalid_handle(self):
        from nnstreamer_tpu import capi

        with pytest.raises(KeyError):
            capi.single_info(999999)

    def test_pipeline_bridge(self):
        from nnstreamer_tpu import capi

        h = capi.pipeline_open(
            "appsrc name=src caps=other/tensors,dimensions=4:2,"
            "types=float32 ! "
            "tensor_transform mode=arithmetic option=mul:2.0 ! "
            "tensor_sink name=out")
        try:
            x = np.arange(8, dtype=np.float32)
            capi.pipeline_push(h, "src", [x.tobytes()])
            blobs, desc = capi.pipeline_pull(h, "out", timeout=15.0)
            assert desc == "4:2,float32"
            np.testing.assert_allclose(
                np.frombuffer(blobs[0], np.float32), x * 2)
            capi.pipeline_eos(h, "src")
        finally:
            capi.pipeline_close(h)

    def test_pipeline_push_size_validated(self):
        from nnstreamer_tpu import capi

        h = capi.pipeline_open(
            "appsrc name=src caps=other/tensors,dimensions=4:1,"
            "types=float32 ! tensor_sink name=out")
        try:
            with pytest.raises(ValueError, match="bytes"):
                capi.pipeline_push(h, "src", [b"\x00" * 5])
        finally:
            capi.pipeline_close(h)

    def test_model_file_through_capi(self, tmp_path):
        # the C API loads model FILES too (the reference's default shape)
        from nnstreamer_tpu import capi
        from nnstreamer_tpu.models import tflite_build

        mw = tflite_build.ModelWriter()
        x = mw.add_input([1, 4])
        w = mw.add_const(np.eye(4, dtype=np.float32) * 3, "w")
        y = mw.add_op("FULLY_CONNECTED", [x, w], [1, 4])
        path = tmp_path / "m.tflite"
        path.write_bytes(mw.finish(outputs=[y]))
        h = capi.single_open(str(path), "jax", "")
        try:
            res = capi.single_invoke_bytes(
                h, [np.ones(4, np.float32).tobytes()])
            np.testing.assert_allclose(
                np.frombuffer(res[0], np.float32), 3.0)
        finally:
            capi.single_close(h)
