"""nns-learn (ISSUE 14): streaming on-TPU fine-tuning inside the pipeline
— device-resident trainer state, fixed-signature census, mesh-sharded
training, checkpoint/resume durability, train-while-serve param hot-swap,
datarepo epoch-semantics parity, deep-lint pricing, and the nns-xray
``train_state`` ledger.  docs/TRAINING.md.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.models.zoo import ModelBundle
from nnstreamer_tpu.pipeline.runtime import PipelineError
from nnstreamer_tpu.trainer.checkpoint import load_checkpoint, save_checkpoint
from nnstreamer_tpu.trainer.subplugin import (JaxTrainer, TRAINER_PROGRAMS,
                                              _build_mlp, train_plan)
from nnstreamer_tpu.utils import tracing, xray


def _toy(n=24, in_dim=4, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((in_dim, classes)).astype(np.float32)
    xs = rng.standard_normal((n, in_dim)).astype(np.float32)
    ys = np.argmax(xs @ w, axis=1).astype(np.int32)[:, None]
    return xs, ys


def _write_dataset(tmp_path, n=24, in_dim=4, classes=3, seed=0,
                   name="data"):
    xs, ys = _toy(n, in_dim, classes, seed)
    data = tmp_path / f"{name}.bin"
    meta = tmp_path / f"{name}.json"
    with open(data, "wb") as f:
        for i in range(n):
            f.write(xs[i].tobytes())
            f.write(ys[i].tobytes())
    json.dump(
        {"dims": f"{in_dim},1", "types": "float32,int32",
         "total_samples": n, "sample_size": in_dim * 4 + 4},
        open(meta, "w"))
    return str(data), str(meta), xs, ys


def _params_equal(a, b) -> bool:
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def serve_mlp_bundle(opts=None):
    """A trainable-shaped serving model for the swap tests: the SAME
    param tree as ``JaxTrainer(model=mlp:4:8:3)``, applied per-vector."""
    params, apply = _build_mlp([4, 8, 3], seed=0)
    return ModelBundle(
        params=params, apply_fn=lambda p, x: apply(p, x[None])[0],
        in_spec=TensorsSpec.from_string("4", "float32"),
        out_spec=TensorsSpec.from_string("3", "float32"))


SERVE_MODEL = "tests.test_learn:serve_mlp_bundle"


def _train_stream(tr, xs, ys, epochs=1, n_valid=0):
    stats = []
    n = len(xs)
    for _ in range(epochs):
        for i in range(n):
            tr.push_data([xs[i]], [ys[i]], is_validation=i >= n - n_valid)
        stats.append(tr.train_epoch())
    return stats


# ---------------------------------------------------------------------------
# the device-resident streaming trainer
# ---------------------------------------------------------------------------

class TestStreamingTrainer:
    def test_streaming_matches_host_accumulated(self):
        """The device-window streaming path is BIT-IDENTICAL to the
        legacy host-accumulated epoch (same masked step program): same
        losses, same params, same step count — including a partial tail
        window (23 % 8 != 0)."""
        xs, ys = _toy(23)
        runs = []
        for host in (False, True):
            tr = JaxTrainer()
            tr.open({"model": "mlp:4:8:3", "learning_rate": 0.05,
                     "batch_size": 8,
                     "host_accumulate": "true" if host else "false"})
            stats = _train_stream(tr, xs, ys, epochs=3)
            runs.append((tr, stats))
        (ts, ss), (th, sh) = runs
        assert [s["training_loss"] for s in ss] == \
            [s["training_loss"] for s in sh]
        assert _params_equal(ts.params, th.params)
        assert ts.step == th.step == 9  # ceil(23/8) x 3

    def test_census_pinned_across_epoch_churn(self):
        """Append/step/eval each compile EXACTLY once for the stage
        lifetime — partial tail windows and validation evals reuse the
        same programs (TRAINER_PROGRAMS census, the PR 10 ring
        discipline)."""
        xs, ys = _toy(23)
        tr = JaxTrainer()
        tr.open({"model": "mlp:4:8:3", "learning_rate": 0.05,
                 "batch_size": 8})
        _train_stream(tr, xs, ys, epochs=4, n_valid=3)
        # a DIFFERENT validation count (the EOS partial-epoch shape) and
        # a set bigger than one window must reuse the same masked eval
        # program — validation chunks through the window shape
        _train_stream(tr, xs[:20], ys[:20], epochs=1, n_valid=11)
        counts = tr.compile_counts()
        assert counts == {"append": 1, "step": 1, "eval": 1}
        assert len(counts) == TRAINER_PROGRAMS

    @staticmethod
    def _need_devices(n: int) -> None:
        import jax

        if len(jax.devices()) < n:
            pytest.skip(f"needs {n} local devices")

    def test_mesh_data_parallel_trajectory(self):
        self._need_devices(4)
        """data:4 training vs single-device: the forward loss of the
        first step is BIT-identical (per-row math never crosses chips —
        the PR 3 contract), the 3-epoch loss/param trajectories agree to
        f32 round-off (the gradient all-reduce sums per-shard partials
        in a different order than one chip's matmul — a documented
        1-2 ulp effect, docs/TRAINING.md), and the census stays pinned.
        A DEGENERATE data:1 mesh is exactly bit-identical."""
        xs, ys = _toy(24)

        def run(mesh):
            tr = JaxTrainer()
            p = {"model": "mlp:4:8:3", "learning_rate": 0.05,
                 "batch_size": 8}
            if mesh:
                p["mesh"] = mesh
            tr.open(p)
            losses = []
            for _ in range(3):
                for i in range(24):
                    tr.push_data([xs[i]], [ys[i]], False)
                losses.append(tr.train_epoch()["training_loss"])
            return tr, losses

        t0, l0 = run(None)
        t1, l1 = run("data:1")
        t4, l4 = run("data:4")
        # degenerate mesh: exact
        assert l0 == l1 and _params_equal(t0.params, t1.params)
        # sharded: first-step forward bit-identical, trajectory f32-tight
        assert np.float32(l0[0]) == np.float32(l4[0])
        assert np.allclose(l0, l4, rtol=1e-5, atol=1e-7)
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(t0.params),
                        jax.tree_util.tree_leaves(t4.params)):
            assert np.allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)
        assert t4.compile_counts() == {"append": 1, "step": 1, "eval": 0}

    def test_mesh_2d_pspecs_shard_params(self):
        """(data:2, model:2) training of a ``param_pspecs`` zoo model:
        pointwise-conv kernels shard over the model axis (per-chip
        weight HBM halves), the census stays pinned across epochs, and
        the shard/replica placement counters prove it."""
        self._need_devices(4)
        before = metrics.snapshot().get("trainer.param_shards", 0.0)
        tr = JaxTrainer()
        tr.open({"model": "mobilenet_v1", "classes": "4", "width": "0.25",
                 "size": "32", "batch_size": 4, "mesh": "data:2,model:2",
                 "learning_rate": 0.01})
        rng = np.random.default_rng(0)
        x = rng.standard_normal((32, 32, 3)).astype(np.float32)
        for e in range(2):
            for i in range(4):
                tr.push_data([x], [np.asarray([i % 4], np.int32)], False)
            s = tr.train_epoch()
        assert np.isfinite(s["training_loss"])
        assert tr.compile_counts() == {"append": 1, "step": 1, "eval": 0}
        import jax

        specs = {str(getattr(lf, "sharding").spec)
                 for lf in jax.tree_util.tree_leaves(tr.params)
                 if hasattr(lf, "sharding")}
        assert any("model" in s for s in specs), specs
        snap = metrics.snapshot()
        assert snap.get("trainer.param_shards", 0.0) > before
        assert snap.get("trainer.param_replicas", 0.0) > 0

    def test_train_plan_matches_live_state(self):
        """The static plan (eval_shape-abstracted optax tree) prices the
        LIVE device-resident training state exactly — the ledger's
        ratio-1.0 contract."""
        xs, ys = _toy(16)
        props = {"model": "mlp:4:8:3", "learning_rate": 0.05,
                 "batch_size": 8}
        tr = JaxTrainer()
        tr.open(dict(props))
        _train_stream(tr, xs, ys)
        plan = train_plan(props)
        assert plan["programs"] == TRAINER_PROGRAMS
        assert plan["grad_bytes"] == plan["param_bytes"] \
            == tr.param_nbytes()
        assert tr.train_state_bytes() == \
            plan["opt_bytes"] + plan["window_bytes"]


# ---------------------------------------------------------------------------
# durability: step-versioned fsync'd checkpoints, bit-identical resume
# ---------------------------------------------------------------------------

class TestDurability:
    def test_save_kill_resume_bit_identical(self, tmp_path):
        """2 epochs + checkpoint + a FRESH trainer resuming 2 more
        epochs == 4 epochs straight, bitwise (params, opt moments, step
        counter) — the killed-pipeline restart contract."""
        xs, ys = _toy(24)
        ck = str(tmp_path / "resume.ckpt")

        straight = JaxTrainer()
        straight.open({"model": "mlp:4:8:3", "learning_rate": 0.05,
                       "batch_size": 8})
        _train_stream(straight, xs, ys, epochs=4)

        first = JaxTrainer()
        first.open({"model": "mlp:4:8:3", "learning_rate": 0.05,
                    "batch_size": 8})
        _train_stream(first, xs, ys, epochs=2)
        first.save(ck)

        resumed = JaxTrainer()
        resumed.open({"model": "mlp:4:8:3", "learning_rate": 0.05,
                      "batch_size": 8, "model_load_path": ck})
        assert resumed.step == first.step
        _train_stream(resumed, xs, ys, epochs=2)
        assert resumed.step == straight.step
        assert _params_equal(resumed.params, straight.params)
        assert _params_equal(resumed.opt_state, straight.opt_state)

    def test_fsync_checkpoint_atomic(self, tmp_path, monkeypatch):
        """The portable (no-orbax) path writes tmp → fsync → atomic
        rename: the roundtrip is exact and no temp sibling survives."""
        monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
        params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3)}
        path = str(tmp_path / "ck")
        got = save_checkpoint(path, params, step=5, fsync=True)
        back, _, step = load_checkpoint(got)
        assert step == 5
        np.testing.assert_array_equal(np.asarray(back["a"]), params["a"])
        leftovers = [f for f in os.listdir(tmp_path) if ".tmp." in f]
        assert not leftovers

    def test_element_periodic_step_versioned_checkpoints(self, tmp_path):
        """``checkpoint-every=1`` writes the primary checkpoint AND a
        step-versioned sibling per epoch, span-stamped ``learn.ckpt``;
        ``model-load-path`` resume through the ELEMENT continues where
        the killed pipeline stopped."""
        data, meta, xs, ys = _write_dataset(tmp_path, n=16)
        ck = str(tmp_path / "m.ckpt")
        desc = (
            f"datareposrc location={data} json={meta} epochs=2 ! "
            "tensor_trainer framework=jax model=mlp:4:8:3 "
            "num-training-samples=16 epochs=2 batch-size=8 "
            f"learning-rate=0.05 checkpoint-every=1 model-save-path={ck} "
            "! tensor_sink name=stats")
        p = nt.Pipeline(desc, trace_mode="ring")
        with p:
            for _ in range(2):
                p.pull("stats", timeout=60)
            p.wait(timeout=30)
        # epoch 1's versioned sibling (2 steps of bs=8 over 16 samples)
        assert os.path.exists(ck) or os.path.exists(ck + ".opt")
        versioned = [f for f in os.listdir(tmp_path) if ".step" in f]
        assert versioned, "no step-versioned checkpoint written"
        kinds = {e.kind for e in tracing.recorder.events()}
        assert "learn.ckpt" in kinds and "learn.step" in kinds

        params, _, step = load_checkpoint(ck)
        resumed = nt.Pipeline(
            f"datareposrc location={data} json={meta} epochs=1 ! "
            "tensor_trainer framework=jax model=mlp:4:8:3 "
            "num-training-samples=16 epochs=1 batch-size=8 "
            f"learning-rate=0.05 model-load-path={ck} "
            f"model-save-path={ck}.more ! tensor_sink name=stats")
        with resumed:
            resumed.pull("stats", timeout=60)
            resumed.wait(timeout=30)
        _, _, step2 = load_checkpoint(f"{ck}.more")
        assert step2 == step + 2  # continued, not restarted


# ---------------------------------------------------------------------------
# train-while-serve: Pipeline.swap_params
# ---------------------------------------------------------------------------

class TestSwapParams:
    SERVE_DESC = (
        "appsrc name=in ! other/tensors,dimensions=4,types=float32 ! "
        f"tensor_filter framework=jax model={SERVE_MODEL} name=serve ! "
        "tensor_sink name=out")

    def test_noop_swap_bit_identity_then_update(self):
        """A no-op swap (same values) leaves serving outputs BITWISE
        identical; a real swap serves the new weights from the next
        dispatch; both are VALUE moves — one compiled program, zero
        census drift under xray."""
        import jax

        p = nt.Pipeline(self.SERVE_DESC, xray=True)
        with p:
            x = np.arange(4, dtype=np.float32)
            p.push("in", [x])
            o1 = np.asarray(p.pull("out", timeout=10).tensors[0])
            fw = p.element("serve").fw
            clone = jax.tree_util.tree_map(
                lambda a: np.asarray(a).copy(), fw.bundle.params)
            v1 = p.swap_params("serve", clone)
            p.push("in", [x])
            o2 = np.asarray(p.pull("out", timeout=10).tensors[0])
            np.testing.assert_array_equal(o1, o2)

            tr = JaxTrainer()
            tr.open({"model": "mlp:4:8:3", "learning_rate": 0.5,
                     "batch_size": 4})
            xs, ys = _toy(8)
            _train_stream(tr, xs, ys)
            v2 = p.swap_params("serve", tr.export_params())
            p.push("in", [x])
            o3 = np.asarray(p.pull("out", timeout=10).tensors[0])
            assert not np.array_equal(o1, o3)
            p.eos()
            p.wait(timeout=10)
        assert (v1, v2) == (1, 2)
        census = xray.registry.census()
        assert census["serve/stage"]["live_compiles"] == 1
        assert xray.registry.drift_count() == 0

    def test_swap_mismatch_raises_named(self):
        p = nt.Pipeline(self.SERVE_DESC)
        with p:
            with pytest.raises(PipelineError, match="mismatch"):
                p.swap_params("serve", {"wrong": np.zeros(3, np.float32)})
            tr = JaxTrainer()
            tr.open({"model": "mlp:4:16:3"})  # wrong hidden width
            with pytest.raises(PipelineError, match="mismatch"):
                p.swap_params("serve", tr.export_params())
            p.eos()
            p.wait(timeout=10)

    def test_swap_on_fused_stage_raises_named(self):
        """A filter fused into a device chain bakes params into the
        composed closure — swap refuses with the named remediation
        instead of silently not taking."""
        desc = (
            "appsrc name=in ! other/tensors,dimensions=4,types=float32 ! "
            "tensor_transform mode=arithmetic option=mul:2.0 ! "
            f"tensor_filter framework=jax model={SERVE_MODEL} name=serve "
            "! tensor_sink name=out")
        p = nt.Pipeline(desc)
        with p:
            with pytest.raises(PipelineError, match="fused"):
                p.swap_params("serve", {})
            p.eos()
            p.wait(timeout=10)

    def test_swap_on_batched_stage_raises_named(self):
        """A micro-batched stage's bucket programs snapshot params into
        pure_fn closures (the fusion trap's twin) — swap refuses with
        the named remediation instead of bumping the version while
        serving stale weights."""
        p = nt.Pipeline(self.SERVE_DESC, batch_max=4)
        with p:
            with pytest.raises(PipelineError, match="micro-batched"):
                p.swap_params("serve", {})
            p.eos()
            p.wait(timeout=10)

    def test_swap_from_checkpoint_path(self, tmp_path):
        xs, ys = _toy(8)
        tr = JaxTrainer()
        tr.open({"model": "mlp:4:8:3", "learning_rate": 0.5,
                 "batch_size": 4})
        _train_stream(tr, xs, ys)
        ck = tr.save(str(tmp_path / "swap.ckpt"))
        p = nt.Pipeline(self.SERVE_DESC)
        with p:
            x = np.arange(4, dtype=np.float32)
            p.push("in", [x])
            o1 = np.asarray(p.pull("out", timeout=10).tensors[0])
            assert p.swap_params("serve", ck) == 1
            p.push("in", [x])
            o2 = np.asarray(p.pull("out", timeout=10).tensors[0])
            assert not np.array_equal(o1, o2)
            p.eos()
            p.wait(timeout=10)

    def test_train_while_serve_e2e(self):
        """THE acceptance pipeline: live traffic tee'd into a trainer
        branch (inputs + labels via the stream; the serving filter
        selects the input tensor via input-combination), ``swap-to``
        hot-swapping refreshed params into the serving stage at every
        epoch boundary — >= 2 swaps under live traffic, ZERO recompiles
        on the serving stage (xray census 1 program, drift 0), and
        post-swap outputs reflect the newly trained params."""
        desc = (
            "appsrc name=in ! "
            "other/tensors,dimensions=4.1,types=float32.int32 ! "
            "tee name=t "
            f"t. ! tensor_filter framework=jax model={SERVE_MODEL} "
            "name=serve input-combination=0 ! tensor_sink name=out "
            "t. ! tensor_trainer framework=jax model=mlp:4:8:3 "
            "num-training-samples=8 epochs=3 batch-size=8 "
            "learning-rate=0.5 swap-to=serve ! tensor_sink name=stats")
        xs, ys = _toy(24, seed=3)
        p = nt.Pipeline(desc, xray=True, trace_mode="ring")
        serve_el = p.element("serve")
        with p:
            x0 = np.arange(4, dtype=np.float32)
            outs = []
            stats = []
            for epoch in range(3):
                for i in range(8):
                    p.push("in", [xs[epoch * 8 + i], ys[epoch * 8 + i]])
                stats.append(np.asarray(
                    p.pull("stats", timeout=60).tensors[0]))
                # live traffic between epochs: probe the serving stage
                p.push("in", [x0, np.asarray([0], np.int32)])
                outs.append(np.asarray(p.pull("out", timeout=30,
                                              ).tensors[0]))
                # drain the probe's stats-side copy (the tee feeds both
                # branches; the trainer banks it toward the next epoch)
            p.eos()
            p.wait(timeout=60)
        assert serve_el._param_version >= 2  # >= 2 swaps landed
        # the swap changed what the serving stage answers
        assert not np.array_equal(outs[0], outs[-1])
        census = xray.registry.census()
        assert census["serve/stage"]["live_compiles"] == 1
        assert xray.registry.drift_count() == 0
        kinds = [e.kind for e in tracing.recorder.events()]
        assert "learn.swap" in kinds and "learn.step" in kinds

    def test_llm_serve_loop_swap_census(self):
        """Hot-swap into a LIVE continuous llm serve loop: executed at a
        chunk boundary, version bumps, streams keep completing, and the
        3-program census is untouched (zero recompiles)."""
        from tests.test_elastic import Collector, make_fw

        fw = make_fw()
        try:
            c1 = Collector()
            fw.submit([np.asarray([3, 5, 7], np.int32)], {}, c1)
            assert c1.done.wait(60)
            import jax

            loop = fw._serve
            before = (loop._decode._cache_size(),
                      loop._prefill._cache_size())
            clone = jax.tree_util.tree_map(
                lambda a: np.asarray(a).copy(), fw.bundle.params)
            assert fw.swap_params(clone) == 1
            c2 = Collector()
            fw.submit([np.asarray([3, 5, 7], np.int32)], {}, c2)
            assert c2.done.wait(60)
            # greedy + identical weights: the post-swap stream matches
            assert c2.ids == c1.ids
            assert (loop._decode._cache_size(),
                    loop._prefill._cache_size()) == before
            with pytest.raises(Exception, match="mismatch"):
                fw.swap_params({"nope": np.zeros(2, np.float32)})
        finally:
            fw.close()


# ---------------------------------------------------------------------------
# datarepo epoch-semantics parity
# ---------------------------------------------------------------------------

class TestDataRepoParity:
    def test_shuffle_seed_determinism_and_divergence(self, tmp_path):
        """Epoch k's order is a pure function of (shuffle-seed, k):
        identical across runs, DIFFERENT across epochs, and a different
        seed reorders."""
        data, meta, xs, _ = _write_dataset(tmp_path, n=8)

        def orders(seed, epochs=3):
            p = nt.Pipeline(
                f"datareposrc location={data} json={meta} "
                f"epochs={epochs} is-shuffle=true shuffle-seed={seed} ! "
                "tensor_sink name=out")
            got = []
            with p:
                for _ in range(8 * epochs):
                    got.append(p.pull("out", timeout=10).meta)
                p.wait(timeout=10)
            return [[m["sample_index"] for m in got[e * 8:(e + 1) * 8]]
                    for e in range(epochs)]

        a = orders(7)
        b = orders(7)
        c = orders(11)
        assert a == b  # deterministic replay
        assert a[0] != a[1]  # epochs see different orders
        assert a != c  # the seed matters
        for ep in a:
            assert sorted(ep) == list(range(8))

    def test_manifest_file_list(self, tmp_path):
        """A ``files`` manifest concatenates shards in list order;
        relative entries resolve against the meta's directory."""
        xs, ys = _toy(12)
        for shard, sl in (("s0", slice(0, 5)), ("s1", slice(5, 12))):
            with open(tmp_path / f"{shard}.bin", "wb") as f:
                for i in range(*sl.indices(12)):
                    f.write(xs[i].tobytes())
                    f.write(ys[i].tobytes())
        meta = tmp_path / "set.json"
        json.dump({"dims": "4,1", "types": "float32,int32",
                   "sample_size": 20, "files": ["s0.bin", "s1.bin"]},
                  open(meta, "w"))
        p = nt.Pipeline(f"datareposrc json={meta} ! tensor_sink name=out")
        with p:
            got = [p.pull("out", timeout=10) for _ in range(12)]
            p.wait(timeout=10)
        for i, b in enumerate(got):
            np.testing.assert_array_equal(b.tensors[0], xs[i])
        # a shard with a torn sample errors, never yields garbage
        with open(tmp_path / "s1.bin", "ab") as f:
            f.write(b"\x00" * 3)
        from nnstreamer_tpu.elements.datarepo import DataRepoSrc

        src = DataRepoSrc({"json": str(meta)})
        src.configure({}, ["src"])
        with pytest.raises(Exception, match="whole number"):
            list(src.generate())

    def test_sink_capture_manifest_replays(self, tmp_path):
        """datareposink manifest=true capture → datareposrc replay by
        json= alone (no location prop) → tensor_trainer consumes it:
        the live-stream capture→train contract."""
        xs, ys = _toy(16)
        data = str(tmp_path / "cap.bin")
        meta = str(tmp_path / "cap.json")
        cap = nt.Pipeline(
            f"appsrc name=src ! datareposink location={data} json={meta} "
            "manifest=true")
        with cap:
            for i in range(16):
                cap.push("src", [xs[i], ys[i]])
            cap.eos()
            cap.wait(timeout=30)
        m = json.load(open(meta))
        assert m["files"] == ["cap.bin"] and m["total_samples"] == 16

        p = nt.Pipeline(
            f"datareposrc json={meta} epochs=2 is-shuffle=true ! "
            "tensor_trainer framework=jax model=mlp:4:8:3 "
            "num-training-samples=16 epochs=2 batch-size=8 "
            "learning-rate=0.1 ! tensor_sink name=stats")
        with p:
            s = [np.asarray(p.pull("stats", timeout=60).tensors[0])
                 for _ in range(2)]
            p.wait(timeout=30)
        assert s[1][0] < s[0][0]  # it learned from the captured stream


# ---------------------------------------------------------------------------
# observability: stats buffers on the tracing/tenant rails
# ---------------------------------------------------------------------------

class TestLearnTracing:
    def test_stats_buffer_rides_trace_and_tenant_rails(self, tmp_path):
        """Stats buffers inherit the triggering sample's trace id +
        tenant (so sinks' e2e spans and per-tenant histograms see them)
        and every epoch records a ``learn.step`` span — trainer
        emissions join the Perfetto timeline."""
        data, meta, xs, ys = _write_dataset(tmp_path, n=8)
        p = nt.Pipeline(
            f"datareposrc location={data} json={meta} epochs=2 ! "
            "tensor_trainer framework=jax model=mlp:4:8:3 name=learn "
            "num-training-samples=8 epochs=2 batch-size=8 "
            "learning-rate=0.05 ! tensor_sink name=stats",
            trace_mode="ring", tenant="lab")
        with p:
            bufs = [p.pull("stats", timeout=60) for _ in range(2)]
            p.wait(timeout=30)
        for b in bufs:
            assert b.meta.get(tracing.META_TRACE_ID) is not None
            assert b.meta.get(tracing.META_TENANT) == "lab"
        steps = [e for e in tracing.recorder.events()
                 if e.kind == "learn.step" and e.stage == "learn"]
        assert len(steps) == 2
        assert all(e.args.get("tenant") == "lab" for e in steps)
        assert all(e.tid is not None for e in steps)
        # spans validate into the Chrome dump beside every other stage
        out = str(tmp_path / "trace.json")
        assert p.dump_trace(out) > 0
        assert any(e["name"] == "learn.step"
                   for e in json.load(open(out))["traceEvents"]
                   if e.get("ph") == "X")


# ---------------------------------------------------------------------------
# priced and verified: deep lint + nns-xray ledger
# ---------------------------------------------------------------------------

class TestPricedAndVerified:
    DESC = ("datareposrc location=/tmp/none.bin json=/tmp/none.json ! "
            "tensor_trainer framework=jax model=mlp:4:16:3 "
            "num-training-samples=24 batch-size=8 epochs=3 ! "
            "tensor_sink name=stats")

    def test_deep_lint_prices_train_state(self):
        rep = nt.analyze(self.DESC, deep=True)
        assert rep.clean
        cats = rep.resources.by_category()
        plan = train_plan({"model": "mlp:4:16:3", "batch_size": 8})
        assert cats["train_state"] == \
            plan["opt_bytes"] + plan["window_bytes"]
        # gradients price as transient activation-class bytes
        assert cats["activations"] >= plan["grad_bytes"]
        assert "train state" in rep.resources.render()
        stage = next(s for s in rep.resources.stages
                     if "tensor_trainer" in s.label)
        assert stage.variants == TRAINER_PROGRAMS

    def test_deep_lint_budget_names_trainer(self):
        rep = nt.analyze(self.DESC, deep=True, hbm_budget_bytes=512)
        hits = [d for d in rep.diagnostics if d.code == "hbm-budget"]
        assert hits and "tensor_trainer" in hits[0].path

    def test_deep_lint_unpriceable_model_warns(self):
        rep = nt.analyze(
            "datareposrc location=/tmp/x.bin json=/tmp/x.json ! "
            "tensor_trainer framework=jax model=mlp:bogus "
            "num-training-samples=8 ! tensor_sink", deep=True)
        assert any(d.code == "training-unpriced"
                   for d in rep.diagnostics)

    def test_xray_ledger_train_state_ratio_one(self, tmp_path):
        """Live run: the reconciler's ``train_state`` category measures
        the trainer's actual opt-state + window bytes at ratio ~1.0
        against the deep-lint estimate, with census drift 0 under epoch
        churn — the lint predicts, xray verifies."""
        data, meta, xs, ys = _write_dataset(tmp_path, n=24)
        p = nt.Pipeline(
            f"datareposrc location={data} json={meta} epochs=3 ! "
            "tensor_trainer framework=jax model=mlp:4:16:3 name=learn "
            "num-training-samples=24 epochs=3 batch-size=8 "
            "learning-rate=0.05 ! tensor_sink name=stats", xray=True)
        with p:
            for _ in range(3):
                p.pull("stats", timeout=60)
            p.wait(timeout=30)
            measured = xray.measure_hbm(p)
            predicted = xray.predicted_hbm(p)
        assert predicted["train_state"] > 0
        ratio = measured["train_state"] / predicted["train_state"]
        assert ratio == pytest.approx(1.0, rel=0.05)
        assert xray.registry.drift_count() == 0
        census = xray.registry.census()
        for kind in ("append", "step", "eval"):
            ent = census.get(f"learn.learn/{kind}")
            assert ent is not None and ent["within"], (kind, ent)
