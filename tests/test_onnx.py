"""`.onnx` ingestion tests (SURVEY §2.4 onnxruntime row).

The fixtures are exported by TORCH'S OWN ONNX exporter — a fully
independent protobuf serializer — so these tests check real third-party
interop, not a round-trip of our own writer.  Numerics are compared
against the torch module that produced each file.
"""

import os

import numpy as np
import pytest
import torch
import torch.nn as nn

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import onnx as nx
from nnstreamer_tpu.models import zoo


@pytest.fixture(autouse=True, scope="module")
def _patch_exporter():
    # torch's legacy exporter serializes its own protobuf but insists on
    # the `onnx` package for a final (optional) onnxscript post-step —
    # skip it; the serialized ModelProto is already complete.
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda model_bytes, custom: model_bytes
    yield
    onnx_proto_utils._add_onnxscript_fn = orig


def _export(tmp_path, module, x, name="m.onnx", opset=13):
    path = str(tmp_path / name)
    module.eval()
    with torch.no_grad():
        torch.onnx.export(module, x, path, opset_version=opset,
                          dynamo=False)
    return path


def _compare(path, module, x, rtol=1e-4, atol=1e-5):
    import jax

    bundle = nx.load_bundle(path)
    got = np.asarray(jax.jit(bundle.apply_fn)(bundle.params, x.numpy()))
    with torch.no_grad():
        want = module(x).numpy()
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    return bundle


class TestTorchExportedModels:
    def test_small_cnn(self, tmp_path):
        torch.manual_seed(0)
        m = nn.Sequential(
            nn.Conv2d(3, 8, 3, stride=2, padding=1), nn.ReLU(),
            nn.Conv2d(8, 8, 3, padding=1, groups=8), nn.ReLU6(),
            nn.MaxPool2d(2),
            nn.Flatten(), nn.Linear(8 * 2 * 2, 5), nn.Softmax(dim=1))
        x = torch.randn(2, 3, 8, 8)
        _compare(_export(tmp_path, m, x), m, x)

    def test_batchnorm_and_avgpool(self, tmp_path):
        torch.manual_seed(1)
        m = nn.Sequential(
            nn.Conv2d(3, 4, 3, padding=1), nn.BatchNorm2d(4), nn.ReLU(),
            nn.AvgPool2d(2), nn.Conv2d(4, 6, 1), nn.Sigmoid())
        m.eval()
        # non-trivial running stats (export uses them in eval mode)
        m[1].running_mean.uniform_(-1, 1)
        m[1].running_var.uniform_(0.5, 2.0)
        x = torch.randn(1, 3, 8, 8)
        _compare(_export(tmp_path, m, x), m, x)

    def test_global_pool_residual(self, tmp_path):
        torch.manual_seed(2)

        class Block(nn.Module):
            def __init__(self):
                super().__init__()
                self.c1 = nn.Conv2d(4, 4, 3, padding=1)
                self.c2 = nn.Conv2d(4, 4, 3, padding=1)
                self.head = nn.Linear(4, 3)

            def forward(self, x):
                h = torch.relu(self.c1(x))
                h = self.c2(h) + x  # residual Add
                h = torch.nn.functional.adaptive_avg_pool2d(h, 1)
                return self.head(h.flatten(1))

        m = Block()
        x = torch.randn(2, 4, 6, 6)
        _compare(_export(tmp_path, m, x), m, x)

    def test_transpose_pad_mean(self, tmp_path):
        torch.manual_seed(3)

        class M(nn.Module):
            def forward(self, x):
                h = x.permute(0, 2, 1)
                h = torch.nn.functional.pad(h, (1, 1), value=0.5)
                return h.mean(dim=-1)

        m = M()
        x = torch.randn(2, 3, 5)
        _compare(_export(tmp_path, m, x), m, x)

    def test_reflect_pad_and_ceil_pool(self, tmp_path):
        torch.manual_seed(6)
        m = nn.Sequential(
            nn.ReflectionPad2d(1),
            nn.Conv2d(2, 3, 3),
            nn.MaxPool2d(2, ceil_mode=True))  # 5x5 -> 3x3 under ceil
        x = torch.randn(1, 2, 5, 5)
        _compare(_export(tmp_path, m, x), m, x)

    def test_avgpool_ceil_mode(self, tmp_path):
        m = nn.Sequential(nn.AvgPool2d(2, ceil_mode=True))
        x = torch.randn(1, 2, 5, 5)
        _compare(_export(tmp_path, m, x), m, x)

    def test_weight_transpose_under_jit(self, tmp_path):
        # a hostable op (Transpose) applied to a WEIGHT initializer must
        # run traced, not through the numpy fast path (review r3 finding)
        torch.manual_seed(7)

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.w = nn.Parameter(torch.randn(3, 5))

            def forward(self, x):
                return x @ self.w.t()

        m = M()
        x = torch.randn(2, 5)
        _compare(_export(tmp_path, m, x), m, x)

    def test_mlp_gemm(self, tmp_path):
        torch.manual_seed(4)
        m = nn.Sequential(nn.Linear(10, 16), nn.Tanh(), nn.Linear(16, 4))
        x = torch.randn(3, 10)
        bundle = _compare(_export(tmp_path, m, x), m, x)
        # weights really came from the file
        assert any(v.shape == (16, 10) for v in bundle.params.values())


class TestTransformerAndYolo:
    """Ops real-world exports need beyond the CNN basics."""

    def test_attention_block(self, tmp_path):
        """A full pre-norm transformer block (LayerNorm decomposition,
        chunked qkv, softmax attention, GELU-via-Erf) exported by torch."""
        torch.manual_seed(8)

        class Attn(nn.Module):
            def __init__(self, d=32, h=4):
                super().__init__()
                self.h, self.hd = h, d // h
                self.qkv = nn.Linear(d, 3 * d)
                self.o = nn.Linear(d, d)
                self.ln1 = nn.LayerNorm(d)
                self.ln2 = nn.LayerNorm(d)
                self.ff1 = nn.Linear(d, 64)
                self.ff2 = nn.Linear(64, d)

            def forward(self, x):
                B, T, D = x.shape
                q, k, v = self.qkv(self.ln1(x)).chunk(3, dim=-1)
                q = q.view(B, T, self.h, self.hd).transpose(1, 2)
                k = k.view(B, T, self.h, self.hd).transpose(1, 2)
                v = v.view(B, T, self.h, self.hd).transpose(1, 2)
                a = torch.softmax(
                    q @ k.transpose(-1, -2) / (self.hd ** 0.5), dim=-1)
                y = (a @ v).transpose(1, 2).reshape(B, T, D)
                x = x + self.o(y)
                return x + self.ff2(
                    torch.nn.functional.gelu(self.ff1(self.ln2(x))))

        m = Attn()
        x = torch.randn(2, 6, 32)
        _compare(_export(tmp_path, m, x, opset=14), m, x, rtol=2e-4,
                 atol=2e-5)

    def test_yolo_block_leaky_resize_split_max(self, tmp_path):
        torch.manual_seed(9)

        class Y(nn.Module):
            def __init__(self):
                super().__init__()
                self.c = nn.Conv2d(8, 16, 3, padding=1)

            def forward(self, x):
                h = torch.nn.functional.leaky_relu(self.c(x), 0.1)
                h = torch.nn.functional.interpolate(
                    h, scale_factor=2, mode="nearest")
                a, b = torch.split(h, 8, dim=1)
                return torch.maximum(a, b)

        m = Y()
        x = torch.randn(1, 8, 8, 8)
        _compare(_export(tmp_path, m, x), m, x)

    def test_bilinear_upsample(self, tmp_path):
        class U(nn.Module):
            def forward(self, x):
                return torch.nn.functional.interpolate(
                    x, scale_factor=2, mode="bilinear",
                    align_corners=False)

        x = torch.randn(1, 3, 5, 5)
        _compare(_export(tmp_path, U(), x), U(), x)

    def test_resize_spec_default_round_prefer_floor(self):
        # ONNX defaults (coord=half_pixel, nearest_mode=round_prefer_floor)
        # differ from torch's floor/asymmetric export — check directly
        n = nx._Node()
        n.op, n.name = "Resize", "r"
        n.inputs, n.outputs = ["x", "", "scales"], ["y"]
        n.attrs = {}
        x = np.arange(4, dtype=np.float32).reshape(1, 1, 4, 1)
        env = {"x": x}
        out = np.asarray(nx._resize(
            env, lambda name: np.array([1, 1, 2, 1], np.float32), n))
        # spec: source rows [0,0,1,1,2,2,3,3]
        np.testing.assert_array_equal(out.ravel(),
                                      [0, 0, 1, 1, 2, 2, 3, 3])

    def test_resize_unknown_coord_mode_rejected(self):
        n = nx._Node()
        n.op, n.name = "Resize", "r"
        n.inputs, n.outputs = ["x", "", "scales"], ["y"]
        a = nx._Attr()
        a.f = a.i = a.t = None
        a.s = "tf_crop_and_resize"
        a.floats, a.ints = [], []
        n.attrs = {"coordinate_transformation_mode": a}
        with pytest.raises(nx.ONNXError, match="tf_crop_and_resize"):
            nx._resize({"x": np.zeros((1, 1, 4, 4), np.float32)},
                       lambda name: np.array([1, 1, 2, 2], np.float32), n)

    def test_embedding_gather_traced_indices(self, tmp_path):
        # Gather with DATA indices (token ids), not shape math
        torch.manual_seed(10)

        class E(nn.Module):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(50, 16)
                self.head = nn.Linear(16, 4)

            def forward(self, ids):
                return self.head(self.emb(ids).mean(dim=1))

        m = E()
        ids = torch.randint(0, 50, (3, 7))
        path = _export(tmp_path, m, ids)
        import jax

        bundle = nx.load_bundle(path)
        got = np.asarray(jax.jit(bundle.apply_fn)(bundle.params,
                                                  ids.numpy()))
        with torch.no_grad():
            want = m(ids).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestErrorsAndOptions:
    def test_not_onnx(self, tmp_path):
        p = tmp_path / "junk.onnx"
        p.write_bytes(b"\x00\x01\x02\x03" * 8)
        with pytest.raises(nx.ONNXError):
            nx.load_bundle(str(p))

    def test_unsupported_op_listed(self, tmp_path):
        class M(nn.Module):
            def forward(self, x):
                return torch.fft.fft(x).real

        x = torch.randn(4)
        try:
            path = _export(tmp_path, M(), x)
        except Exception:
            pytest.skip("torch cannot export fft to onnx")
        with pytest.raises(nx.ONNXError, match="unsupported op"):
            nx.load_bundle(path)

    def test_unknown_option_rejected(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 2))
        x = torch.randn(1, 4)
        path = _export(tmp_path, m, x)
        with pytest.raises(nx.ONNXError, match="param_dtype"):
            nx.load_bundle(path, {"bogus": "1"})


class TestPipelineIntegration:
    def test_tensor_filter_loads_onnx_file(self, tmp_path):
        torch.manual_seed(5)
        m = nn.Sequential(
            nn.Conv2d(3, 4, 3, stride=2, padding=1), nn.ReLU(),
            nn.Flatten(), nn.Linear(4 * 4 * 4, 5), nn.Softmax(dim=1))
        x = torch.randn(1, 3, 8, 8)
        path = _export(tmp_path, m, x)
        p = nt.Pipeline(
            f"appsrc name=src caps=other/tensors,dimensions=8:8:3:1,"
            f"types=float32 ! "
            f"tensor_filter framework=jax model={path} ! "
            f"tensor_sink name=out")
        with p:
            p.push("src", x.numpy())
            buf = p.pull("out", timeout=60)
            p.eos()
            p.wait(timeout=30)
        with torch.no_grad():
            want = m(x).numpy()
        np.testing.assert_allclose(np.asarray(buf.tensors[0]), want,
                                   rtol=1e-4, atol=1e-5)

    def test_zoo_routes_onnx(self, tmp_path):
        m = nn.Sequential(nn.Linear(4, 2))
        x = torch.randn(1, 4)
        path = _export(tmp_path, m, x)
        bundle = zoo.build(path)
        assert bundle.in_spec.specs[0].shape == (1, 4)
