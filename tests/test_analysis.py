"""nns-lint static analyzer tests: golden diagnostics for bad pipelines,
clean passes over every shipped pipeline string, and the jit-purity
dogfood over the framework's own elements (a purity regression in a
shipped device_fn fails HERE before it silently falls off the fused-XLA
path)."""

import os
import time as _time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.analysis import PipelineLintError, analyze
from nnstreamer_tpu.analysis.purity import lint_callable, lint_module
from nnstreamer_tpu.core.caps import (
    Caps, MediaType, explain_mismatch, intersect_template)
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.filters.custom_easy import (
    register_custom_easy, unregister_custom_easy)
from nnstreamer_tpu.pipeline.parser import ParseError, parse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(report):
    return set(report.codes())


# ---------------------------------------------------------------------------
# golden diagnostics: one bad pipeline per failure class
# ---------------------------------------------------------------------------

BAD_PIPELINES = [
    # (description string, expected diagnostic code, "error" present?)
    ("videotestsrc ! tensor_transform mode=typecast option=float32 ! "
     "tensor_sink",
     "caps-mismatch", True),  # raw video into a tensors-only pad
    ("appsrc caps=other/tensors,dimensions=3:8:8:1,types=uint8 ! "
     "tensor_filter framework=custom-easy model=missing "
     "input=3:8:8:1 inputtype=float32 ! tensor_sink",
     "caps-mismatch", True),  # dtype uint8 ⊄ float32 at the filter
    ("videotestsrc width=8 height=8 ! video/x-raw,format=GRAY8 ! "
     "tensor_converter ! tensor_sink",
     "caps-mismatch", True),  # capsfilter: RGB upstream vs GRAY8 filter
    ("videotestsrc name=v ! tensor_converter ! nosuch. ",
     "dangling-pad-ref", True),
    ("appsrc name=a ! tee name=t "
     "t. ! tensor_mux name=m ! tensor_sink "
     "t. ! tensor_transform mode=typecast option=float32 ! m.",
     "tee-deadlock", True),  # queue-less diamond into slowest-sync mux
    ("tensor_mux name=m ! tensor_transform mode=typecast option=float32 "
     "! m.",
     "cycle", True),
    ("appsrc name=src ! tensor_transform mode=typecast option=float32 "
     "tensor_sink name=out",
     "no-input", True),  # the classic missing-'!' juxtaposition
    ("appsrc ! tensor_transfrom mode=typecast ! tensor_sink",
     "unknown-element", True),  # typo'd kind, with did-you-mean
    ("appsrc caps=other/tensors,dimensions=4.4,types=float32.float32 "
     "name=a ! tensor_demux name=d "
     "d.src_0 ! tensor_sink name=s0 "
     "d.src_5 ! tensor_sink name=s5",
     "pad-arity", True),  # demux pad past the 2-tensor upstream spec
    ("appsrc name=a ! mux.sink_0 appsrc name=b ! mux.sink_3 "
     "tensor_mux name=mux ! tensor_sink",
     "pad-gap", True),  # sink_0/sink_3 gap stalls slowest-sync forever
    ("appsrc name=a caps=other/tensors,dimensions=4,types=float32 ! "
     "mux.sink_0 "
     "appsrc name=b caps=other/tensors,dimensions=4,types=float32 ! "
     "mux.sink_1 "
     "tensor_merge name=mux mode=linear option=7 ! tensor_sink",
     "caps-incompat", True),  # merge dim 7 out of range (configure check)
    ("videotestsrc ! videotestsrc ! tensor_sink",
     "source-has-input", True),
    ("appsrc ! tensor_sink name=s ! tensor_sink",
     "sink-has-output", True),
]


@pytest.mark.parametrize(
    "desc,code,is_error",
    BAD_PIPELINES,
    ids=[c for _, c, _ in BAD_PIPELINES])
def test_bad_pipeline_diagnosed(desc, code, is_error):
    report = analyze(desc)
    assert code in codes(report), report.render()
    if is_error:
        assert any(d.code == code and d.severity == "error" for d in report)
    # every diagnostic for these pipelines carries an element path or pos
    diag = next(d for d in report if d.code == code)
    assert diag.path or diag.pos is not None


def test_malformed_props_are_diagnostics_not_crashes():
    """The analyzer's contract: report, never raise."""
    for desc in (
        "appsrc ! tensor_filter framework=custom-easy model=m "
        "input=garbage ! tensor_sink",
        "appsrc caps=other/tensors,dimensions=4,types=float32 ! "
        "tensor_filter input-combination=a,b input=4 ! tensor_sink",
        "appsrc caps=other/tensors,dimensions=4.4,types=float32.float32 "
        "name=a ! tensor_demux name=d tensorpick=x d.src_0 ! tensor_sink",
    ):
        report = analyze(desc)  # must not raise
        assert "caps-incompat" in codes(report), report.render()
        assert "analyzer-error" not in codes(report)


def test_both_dangling_refs_reported_after_phantom():
    report = analyze("badref.src ! other.sink")
    names = {d.path for d in report if d.code == "dangling-pad-ref"}
    assert names == {"badref.src", "other.sink"}


def test_phantom_fed_node_not_flagged_missing_bang():
    """'badname. ! tensor_sink' has exactly one problem — the dangling
    ref — not a derived 'missing !' on the element it feeds."""
    report = analyze("badname. ! tensor_sink")
    assert "dangling-pad-ref" in codes(report)
    assert "no-input" not in codes(report)
    assert "unreachable" not in codes(report)


def test_dangling_sink_ref_no_derived_leaf_warning():
    """'appsrc ! b.sink' with unknown b: the user DID link appsrc out —
    only the target name is wrong.  One finding, not two."""
    report = analyze("appsrc name=a ! b.sink")
    assert "dangling-pad-ref" in codes(report)
    assert "leaf-not-sink" not in codes(report)


def test_multiline_source_caret_points_at_the_right_column():
    desc = "appsrc name=a !\n  tensor_transfrom ! tensor_sink"
    report = analyze(desc)
    out = report.render()
    caret_line = None
    lines = out.splitlines()
    for i, ln in enumerate(lines):
        if ln.strip() == "^" and "tensor_transfrom" in lines[i - 1]:
            caret_line = (lines[i - 1], ln)
    assert caret_line is not None, out
    src, caret = caret_line
    assert src[caret.index("^")] == "t"  # first char of the typo'd kind


def test_host_cast_is_warning_not_error():
    """int()/float() on a non-constant may be plain host-scalar math —
    the lint cannot prove a tracer is involved, so it must not block
    validate=True startup (only .item() is certain)."""
    diags = lint_callable(_impure_sync, "x")
    d = next(d for d in diags if d.code == "jit-host-sync")
    assert d.severity == "warning"


def test_parse_error_becomes_diagnostic_with_position():
    report = analyze("videotestsrc ! ! tensor_sink")
    assert codes(report) == {"parse-error"}
    d = report.diagnostics[0]
    assert d.pos == 15
    assert "^" in report.render()  # caret rendered into the source line


def test_all_problems_reported_in_one_run():
    """The analyzer's whole reason to exist: N independent mistakes, ONE
    report — not the runtime's first-failure loop."""
    report = analyze(
        "videotestsrc ! tensor_transform mode=typecast option=float32 ! "
        "tensor_sink "  # caps mismatch (video into tensors pad)
        "appsrc ! tensor_transfrom ! fakesink "  # unknown element
        "ghost. ! tensor_sink name=x"  # dangling ref
    )
    assert {"caps-mismatch", "unknown-element",
            "dangling-pad-ref"} <= codes(report)
    assert len(report.errors) >= 3


def test_dtype_mismatch_names_the_field():
    report = analyze(
        "appsrc caps=other/tensors,dimensions=3:8:8:1,types=uint8 ! "
        "tensor_filter framework=custom-easy model=missing "
        "input=3:8:8:1 inputtype=float32 ! tensor_sink")
    msg = next(d.message for d in report if d.code == "caps-mismatch")
    assert "uint8" in msg and "float32" in msg and "⊄" in msg


def test_queue_on_every_branch_silences_deadlock():
    report = analyze(
        "appsrc name=a ! tee name=t "
        "t. ! queue ! tensor_mux name=m ! tensor_sink "
        "t. ! queue ! tensor_transform mode=typecast option=float32 ! m.")
    assert "tee-deadlock" not in codes(report)


def test_cycle_through_tensor_repo_is_legal():
    report = analyze(
        "appsrc name=src ! tensor_mux name=m ! tee name=t "
        "t. ! tensor_sink name=out "
        "t. ! queue ! tensor_reposink slot-name=loop "
        "tensor_reposrc slot-name=loop "
        "caps=other/tensors,dimensions=4,types=float32 ! m.")
    assert "cycle" not in codes(report)


# ---------------------------------------------------------------------------
# clean passes: every pipeline string the repo ships must lint clean
# ---------------------------------------------------------------------------

def _load_baseline():
    path = os.path.join(REPO, "tools", "lint_baseline.txt")
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        return {ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")}


@pytest.mark.parametrize("fname", [
    "tests/test_pipeline_e2e.py",
    "examples",
])
def test_shipped_pipeline_strings_lint_clean(fname):
    from nnstreamer_tpu.tools.lint import (
        _diag_key, extract_pipeline_strings)

    path = os.path.join(REPO, fname)
    files = ([os.path.join(path, f) for f in sorted(os.listdir(path))
              if f.endswith(".py")] if os.path.isdir(path) else [path])
    baseline = _load_baseline()
    checked = 0
    bad = []
    for f in files:
        strings, _ = extract_pipeline_strings(f)
        for desc in strings:
            checked += 1
            report = analyze(desc)
            for d in report.errors:
                if _diag_key(os.path.basename(f), d, desc) not in baseline:
                    bad.append((desc, str(d)))
    assert checked > 0
    assert not bad, bad


def test_dogfood_own_device_fns_are_pure():
    """Every device_fn the framework ships promises the planner a pure
    traced fn; a host side effect creeping in fails CI right here."""
    import importlib

    from nnstreamer_tpu.core.registry import _BUILTIN_MODULES

    diags = []
    for modname in _BUILTIN_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        diags.extend(lint_module(mod))
    assert [str(d) for d in diags if d.severity == "error"] == []


# ---------------------------------------------------------------------------
# jit-purity pass
# ---------------------------------------------------------------------------

_COUNTER = 0


def _impure_numpy(ins):
    return [np.argmax(np.asarray(ins[0]))]


def _impure_sync(ins):
    x = ins[0]
    return [x * float(x.sum())]


def _impure_rng(ins):
    noise = np.random.default_rng(0).standard_normal(ins[0].shape)
    return [ins[0] + noise]


def _impure_time(ins):
    t = _time.time()
    return [ins[0] * t]


def _impure_global(ins):
    global _COUNTER
    _COUNTER += 1
    print("invoked", _COUNTER)
    return [ins[0]]


@pytest.mark.parametrize("fn,code", [
    (_impure_numpy, "jit-host-call"),
    (_impure_sync, "jit-host-sync"),
    (_impure_rng, "jit-rng"),
    (_impure_time, "jit-host-time"),
    (_impure_global, "jit-global-mutation"),
], ids=lambda v: v if isinstance(v, str) else v.__name__)
def test_lint_callable_flags_host_effects(fn, code):
    diags = lint_callable(fn, fn.__name__)
    assert code in {d.code for d in diags}, [str(d) for d in diags]


def test_print_is_flagged_as_warning():
    diags = lint_callable(_impure_global, "x")
    d = next(d for d in diags if d.code == "jit-print")
    assert d.severity == "warning"


def test_impure_registered_filter_fn_flagged_in_pipeline():
    register_custom_easy(
        "lint-impure", _impure_rng,
        in_spec=TensorsSpec.from_string("4", "float32"),
        out_spec=TensorsSpec.from_string("4", "float32"),
        jax_traceable=True)
    try:
        report = analyze(
            "appsrc caps=other/tensors,dimensions=4,types=float32 ! "
            "tensor_filter framework=custom-easy model=lint-impure ! "
            "tensor_sink")
        assert "jit-rng" in codes(report)
        assert any("custom-easy:lint-impure" in d.path for d in report)
    finally:
        unregister_custom_easy("lint-impure")


def test_pure_jnp_callable_is_clean():
    def pure(arrays):
        import jax.numpy as jnp

        return [jnp.tanh(arrays[0])]

    assert lint_callable(pure, "pure") == []


def test_jax_collectives_and_sharding_are_jit_legal():
    """jax.lax collectives and shard_map/with_sharding_constraint inside a
    traced fn are the sharded hot path's vocabulary — never diagnostics
    (ISSUE 3 satellite: no false positives from the sharded code paths)."""
    import jax

    def sharded(arrays):
        x = jax.lax.with_sharding_constraint(arrays[0], None)
        s = jax.lax.psum(x, axis_name="data")
        g = jax.lax.all_gather(s, axis_name="data")
        return [g]

    assert lint_callable(sharded, "sharded") == []

    from jax.lax import psum  # noqa: F401 - exercises the bare-name path

    def bare(arrays):
        return [psum(arrays[0], axis_name="data")]

    assert lint_callable(bare, "bare") == []


def test_jax_numpy_aliased_to_np_is_not_flagged():
    """``import jax.numpy as np`` must hit the jax allowlist, not the
    host-numpy rules — module identity decides, not the alias name."""
    import jax.numpy as np

    def pure(arrays):
        return [np.sqrt(np.abs(arrays[0]))]

    assert lint_callable(pure, "pure") == []


# ---------------------------------------------------------------------------
# parse/plan hook + parser positions
# ---------------------------------------------------------------------------

def test_pipeline_validate_hook_raises_with_all_errors():
    desc = ("videotestsrc ! tensor_transform mode=typecast option=float32 "
            "! tensor_sink "
            "appsrc ! tensor_transfrom ! fakesink")
    with pytest.raises(PipelineLintError) as ei:
        nt.Pipeline(desc, validate=True)
    assert len(ei.value.report.errors) >= 2
    assert "caps-mismatch" in ei.value.report.codes()


def test_pipeline_validate_hook_passes_clean():
    p = nt.Pipeline(
        "videotestsrc num-buffers=1 width=8 height=8 ! tensor_converter "
        "! tensor_sink name=out", validate=True)
    with p:
        p.pull("out", timeout=10)
        p.wait(timeout=10)


def test_parse_error_carries_position():
    with pytest.raises(ParseError) as ei:
        parse("videotestsrc ! ! tensor_sink")
    assert ei.value.pos == 15
    assert "at char 15" in str(ei.value)


def test_nodes_carry_source_positions():
    g = parse("videotestsrc ! tensor_converter ! tensor_sink")
    kinds = {n.kind: n.pos for n in g.nodes.values()}
    assert kinds["videotestsrc"] == 0
    assert kinds["tensor_converter"] == 15
    assert kinds["tensor_sink"] == 34


# ---------------------------------------------------------------------------
# caps template helpers (core/caps.py offline surface)
# ---------------------------------------------------------------------------

def test_intersect_template_alternatives():
    video = Caps.new(MediaType.VIDEO, format="RGB")
    tmpl = (Caps.new(MediaType.AUDIO), Caps.new(MediaType.VIDEO))
    assert intersect_template(video, tmpl) is not None
    assert intersect_template(video, Caps.new(MediaType.TENSORS)) is None


def test_explain_mismatch_spec_fields():
    a = Caps.tensors(TensorsSpec.from_string("3:8:8:1", "uint8"))
    b = Caps.tensors(TensorsSpec.from_string("3:8:8:1", "float32"))
    assert explain_mismatch(a, b) == "dtype uint8 ⊄ float32"
    c = Caps.tensors(TensorsSpec.from_string("3:16:16:1", "uint8"))
    assert "dims" in explain_mismatch(a, c)
