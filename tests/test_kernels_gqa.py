"""GQA/MQA-grouped attention kernels: value + traffic contracts.

The grouped kernels (ops/attention.py) take K/V UNREPEATED at
``[*, Hkv, *]`` and share each streamed block across the whole
query-head group.  Three things must hold, and each gets pinned here:

1. **Values**: the grouped layout is bit-identical to feeding the SAME
   kernel a pre-repeated ``Hkv == H`` layout (the pre-refactor data
   path) at every ratio, including MQA — the refactor moved bytes, not
   math.  (Vs the materialized XLA reference it is allclose, not
   bitwise: blockwise online softmax re-associates the reduction.)
2. **Stream count**: the flash grid is ``(B * Hkv, Sq / block_q)`` —
   one K/V stream per (batch, KV head), NOT per query head — and the
   paged grid is ``(B,)``; K/V operands ride ANY memory space (the
   kernel's own DMAs stream them), so HBM reads scale with ``Hkv``.
3. **DMA structure**: each grid cell issues exactly one double-buffered
   K stream and one V stream (6 ``make_async_copy`` call sites: 2 warm
   starts + 2 prefetches + 2 waits), with NO per-query-head DMA loop —
   the count is invariant in H/Hkv.  Interpret mode traces the cell
   body once, so call-site counting is exact.

Plus the prediction side: ``serving_plan``'s
``decode_bytes_per_ctx_token`` must price the pool at ``n_kv_heads``
(the grouped kernel's actual traffic), not ``n_heads`` — the stale
over-prediction nns-xray's reconciliation flagged.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import llama
from nnstreamer_tpu.ops import attention as A
from nnstreamer_tpu.filters.llm import serving_plan

jax.config.update("jax_platform_name", "cpu")

RATIOS = [1, 2, 4, 8]  # H / Hkv group sizes; 8 with H=8 is MQA (Hkv=1)
H = 8


def _repeat(x, rep):
    """models/llama.py's GQA layout: query head h = kv_head * rep + g."""
    b, s, hkv, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, hkv, rep, d)).reshape(b, s, hkv * rep, d)


def _flash_inputs(hkv, *, b=2, s=256, d=32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(kq, (b, s, H, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hkv, d), jnp.float32)
    return q, k, v


class _PallasCapture:
    """Wrap ``pl.pallas_call`` (and ``pltpu.make_async_copy``) through the
    module under test, recording the grid actually launched and the
    number of DMA call sites traced."""

    def __init__(self):
        self.grids = []
        self.dma_calls = 0

    def install(self, monkeypatch):
        real_call = A.pl.pallas_call
        real_dma = A.pltpu.make_async_copy

        def spy_call(*args, **kw):
            if "grid" in kw:
                self.grids.append(tuple(kw["grid"]))
            elif "grid_spec" in kw:
                self.grids.append(tuple(kw["grid_spec"].grid))
            return real_call(*args, **kw)

        def spy_dma(*args, **kw):
            self.dma_calls += 1
            return real_dma(*args, **kw)

        monkeypatch.setattr(A.pl, "pallas_call", spy_call)
        monkeypatch.setattr(A.pltpu, "make_async_copy", spy_dma)
        return self


class TestFlashGrouped:
    @pytest.mark.parametrize("rep", RATIOS)
    def test_bit_identical_to_repeated_layout(self, rep):
        hkv = H // rep
        q, k, v = _flash_inputs(hkv)
        grouped = A.flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
        repeated = A.flash_attention(
            q, _repeat(k, rep), _repeat(v, rep), causal=True,
            block_q=64, block_k=64, interpret=True)
        assert np.array_equal(np.asarray(grouped), np.asarray(repeated))
        ref = A.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(grouped), np.asarray(ref), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("rep", RATIOS)
    def test_kv_streams_scale_with_hkv_not_h(self, rep, monkeypatch):
        hkv = H // rep
        b, s, bq = 2, 256, 64
        cap = _PallasCapture().install(monkeypatch)
        q, k, v = _flash_inputs(hkv, b=b, s=s)
        A.flash_attention(
            q, k, v, causal=True, block_q=bq, block_k=64, interpret=True)
        # one grid row per (batch, KV head): stream count is b * hkv —
        # constant H, shrinking hkv => fewer K/V streams, same output
        assert cap.grids == [(b * hkv, s // bq)]
        # exactly one double-buffered K + one V stream per cell (2 warm
        # starts + 2 prefetches + 2 waits), no per-query-head DMA loop
        assert cap.dma_calls == 6


class TestPagedGrouped:
    def _pool_case(self, hkv, *, b=3, d=32, bs=16, n_blocks=24, seed=1):
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(kq, (b, 1, H, d), jnp.float32)
        k_pool = jax.random.normal(kk, (n_blocks, bs, hkv, d), jnp.float32)
        v_pool = jax.random.normal(kv, (n_blocks, bs, hkv, d), jnp.float32)
        tbl = jnp.arange(b * 8, dtype=jnp.int32).reshape(b, 8) % n_blocks
        lens = jnp.asarray([5, bs * 3, bs * 8], jnp.int32)[:b]
        return q, k_pool, v_pool, tbl, lens

    def _repeat_pool(self, pool, rep):
        n, bs, hkv, d = pool.shape
        return jnp.broadcast_to(
            pool[:, :, :, None, :], (n, bs, hkv, rep, d)).reshape(
                n, bs, hkv * rep, d)

    @pytest.mark.parametrize("rep", RATIOS)
    def test_bit_identical_to_repeated_pool(self, rep):
        hkv = H // rep
        q, kp, vp, tbl, lens = self._pool_case(hkv)
        grouped = A.paged_attention(q, kp, vp, tbl, lens, interpret=True)
        repeated = A.paged_attention(
            q, self._repeat_pool(kp, rep), self._repeat_pool(vp, rep),
            tbl, lens, interpret=True)
        assert np.array_equal(np.asarray(grouped), np.asarray(repeated))
        ref = A.paged_attention_reference(q, kp, vp, tbl, lens)
        np.testing.assert_allclose(
            np.asarray(grouped), np.asarray(ref), atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("rep", RATIOS)
    def test_one_stream_per_row(self, rep, monkeypatch):
        hkv = H // rep
        cap = _PallasCapture().install(monkeypatch)
        q, kp, vp, tbl, lens = self._pool_case(hkv)
        A.paged_attention(q, kp, vp, tbl, lens, interpret=True)
        # one grid cell per batch row regardless of head layout; the
        # row streams ceil(len/bs) blocks of its OWN Hkv-sized pool
        assert cap.grids == [(q.shape[0],)]
        assert cap.dma_calls == 6


class TestServingPlanTraffic:
    """decode_bytes_per_ctx_token must track n_kv_heads — pricing GQA
    traffic at n_heads is the stale prediction the xray reconciliation
    regression exists to catch."""

    def test_gqa_prices_kv_heads_not_q_heads(self):
        dense = llama.PRESETS["llama2_7b"]  # n_kv_heads == n_heads == 32
        gqa = dataclasses.replace(dense, n_kv_heads=8)
        p_dense = serving_plan(dense, slots=4, dtype="bfloat16")
        p_gqa = serving_plan(gqa, slots=4, dtype="bfloat16")
        assert p_dense["kv_groups"] == 1
        assert p_gqa["kv_groups"] == 4
        # traffic coefficient shrinks by exactly the group factor
        assert (p_dense["decode_bytes_per_ctx_token"]
                == 4 * p_gqa["decode_bytes_per_ctx_token"])
        # and matches the closed form: K+V rows over all layers at Hkv
        assert p_gqa["decode_bytes_per_ctx_token"] == (
            2 * gqa.n_layers * gqa.n_kv_heads * gqa.head_dim * 2)

    def test_prng_state_priced_only_when_sampled(self):
        cfg = llama.PRESETS["llama_tiny"]
        greedy = serving_plan(cfg, slots=6, dtype="float32")
        sampled = serving_plan(cfg, slots=6, dtype="float32",
                               temperature=0.8)
        assert greedy["prng_state_bytes"] == 0
        assert sampled["prng_state_bytes"] == 6 * 2 * 4  # uint32[2]/slot
