"""Distribution layer tests: tensor_query offload + edge pub/sub.

Reference analog (SURVEY §4): query/edge suites run client & server
pipelines in one process on localhost ports — "multi-node without a
cluster".  Same here: a server pipeline (serversrc ! filter ! serversink)
and client pipelines talk over real TCP sockets on 127.0.0.1.
"""

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.pipeline.runtime import PipelineError
from nnstreamer_tpu.filters.custom_easy import register_custom_easy


@pytest.fixture(autouse=True)
def _models():
    spec = TensorsSpec.from_string("4", "float32")
    register_custom_easy(
        "q-double", lambda ins: [ins[0] * 2], in_spec=spec, out_spec=spec,
    )
    yield


def _server_pipeline(sid=0):
    return nt.Pipeline(
        f"tensor_query_serversrc name=ssrc port=0 id={sid} ! "
        "tensor_filter framework=custom-easy model=q-double ! "
        f"tensor_query_serversink id={sid}"
    )


def test_query_roundtrip():
    with _server_pipeline() as srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=10 ! "
            "tensor_sink name=out"
        )
        with cli:
            for i in range(5):
                x = np.full((4,), float(i), np.float32)
                cli.push("src", x)
            for i in range(5):
                out = cli.pull("out", timeout=10)
                np.testing.assert_allclose(out.tensors[0], np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=10)


def test_query_preserves_order_and_meta():
    with _server_pipeline(sid=1) as srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "max-in-flight=4 timeout=10 ! tensor_sink name=out"
        )
        with cli:
            n = 12
            for i in range(n):
                cli.push("src", np.full((4,), float(i), np.float32))
            outs = [cli.pull("out", timeout=10) for _ in range(n)]
            for i, out in enumerate(outs):
                np.testing.assert_allclose(out.tensors[0], np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=10)


def test_query_multiple_clients_concurrently():
    with _server_pipeline(sid=2) as srv:
        port = srv.element("ssrc").bound_port
        results = {}
        errors = []

        def run_client(cid):
            try:
                cli = nt.Pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "timeout=10 ! tensor_sink name=out"
                )
                with cli:
                    vals = []
                    for i in range(6):
                        cli.push("src", np.full((4,), cid * 100.0 + i, np.float32))
                    for _ in range(6):
                        vals.append(float(cli.pull("out", timeout=10).tensors[0][0]))
                    cli.eos("src")
                    cli.wait(timeout=10)
                results[cid] = vals
            except Exception as e:  # noqa: BLE001
                errors.append((cid, e))

        threads = [threading.Thread(target=run_client, args=(c,)) for c in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        for cid in range(3):
            assert results[cid] == [2 * (cid * 100.0 + i) for i in range(6)]


def test_query_client_timeout_error():
    # Server that never answers: a bare serversrc with no sink draining it.
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=3 ! fakesink"
    )
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=0.5 ! "
            "tensor_sink name=out"
        )
        with cli:
            cli.push("src", np.zeros((4,), np.float32))
            cli.eos("src")
            with pytest.raises(PipelineError, match="no response"):
                cli.wait(timeout=10)


def test_query_client_timeout_drop():
    srv = nt.Pipeline("tensor_query_serversrc name=ssrc port=0 id=4 ! fakesink")
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=0.5 "
            "on-timeout=drop ! tensor_sink name=out"
        )
        with cli:
            cli.push("src", np.zeros((4,), np.float32))
            cli.eos("src")
            cli.wait(timeout=10)  # drop policy: EOS flows, nothing raised


def test_edge_pubsub_fanout():
    pub = nt.Pipeline("appsrc name=src ! edgesink name=pub port=0")
    with pub:
        port = pub.element("pub").bound_port
        subs = [
            nt.Pipeline(f"edgesrc port={port} num-buffers=3 ! tensor_sink name=out")
            for _ in range(2)
        ]
        for s in subs:
            s.start()
        time.sleep(0.3)  # let subscriptions land before publishing
        for i in range(3):
            pub.push("src", np.full((2,), float(i), np.float32))
        try:
            for s in subs:
                for i in range(3):
                    out = s.pull("out", timeout=10)
                    np.testing.assert_allclose(out.tensors[0], np.full((2,), float(i)))
                s.wait(timeout=10)
        finally:
            for s in subs:
                s.stop()
        pub.eos("src")
        pub.wait(timeout=10)


def test_edge_topic_mismatch_rejected():
    pub = nt.Pipeline("appsrc name=src ! edgesink name=pub port=0 topic=video")
    with pub:
        port = pub.element("pub").bound_port
        bad = nt.Pipeline(f"edgesrc port={port} topic=audio ! tensor_sink name=out")
        with pytest.raises(Exception, match="rejected"):
            bad.start()
        bad.stop()


def test_query_streaming_llm_tokens():
    """Config #5 as described: token streaming THROUGH tensor_query — one
    prompt request, many streamed responses (stream_index/stream_last),
    delivered in generation order."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=9 ! "
        "tensor_filter framework=llm model=llama_tiny "
        "custom=max_new:6,stream_chunk:3 invoke-dynamic=true ! "
        "tensor_query_serversink id=9"
    )
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=60 ! "
            "tensor_sink name=out"
        )
        with cli:
            cli.push("src", np.array([1, 5, 9, 2], np.int32))
            toks = [cli.pull("out", timeout=60) for _ in range(6)]
            assert [b.meta["stream_index"] for b in toks] == list(range(6))
            assert toks[-1].meta.get("stream_last") is True
            assert all("stream_last" not in b.meta for b in toks[:-1])
            ids = [int(np.asarray(b.tensors[0])[0]) for b in toks]
            assert all(0 <= i for i in ids)
            cli.eos("src")
            cli.wait(timeout=30)

    # determinism: direct filter path must produce the same ids
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": "llama_tiny", "custom": "max_new:6,stream_chunk:3"})
    direct = [int(i[0]) for i, _ in fw.invoke_stream(
        [np.array([1, 5, 9, 2], np.int32)])]
    assert ids == direct


def test_query_streaming_then_plain_requests():
    """Back-to-back streamed requests on one client: bookkeeping must
    release each slot (stream_last) and indices restart per request."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=10 ! "
        "tensor_filter framework=llm model=llama_tiny "
        "custom=max_new:2 invoke-dynamic=true ! "
        "tensor_query_serversink id=10"
    )
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=60 ! "
            "tensor_sink name=out"
        )
        with cli:
            for _ in range(3):  # three prompts, 2 tokens each
                cli.push("src", np.array([3, 4], np.int32))
            got = [cli.pull("out", timeout=60) for _ in range(6)]
            assert [b.meta["stream_index"] for b in got] == [0, 1] * 3
            cli.eos("src")
            cli.wait(timeout=30)


def _client_harness():
    """TensorQueryClient with an injected emit collector, no socket."""
    from nnstreamer_tpu.elements.query import TensorQueryClient, _META_MSG

    cli = TensorQueryClient({"port": 1})
    emitted = []
    cli._async_emit = lambda outs: emitted.extend(b for _, b in outs)
    return cli, emitted, _META_MSG


def test_plain_response_waits_for_stream_done_cursor():
    """A plain response for request 1 arriving BEFORE request 0's stream
    finishes is held by the reorder cursor, then released when the
    _STREAM_DONE placeholder advances past request 0."""
    import time as _time

    cli, emitted, META = _client_harness()
    now = _time.monotonic()
    cli._pending = {0: (nt.Buffer([np.zeros(1)]), now),
                    1: (nt.Buffer([np.zeros(1)]), now)}
    cli._next_msg = 2

    def resp(mid, **meta):
        b = nt.Buffer([np.asarray([float(mid)])])
        b.meta[META] = mid
        b.meta.update(meta)
        return b

    # request 1's PLAIN response arrives first: must be held
    cli._handle_response(resp(1))
    assert emitted == []
    # request 0 streams two tokens; each emits immediately
    cli._handle_response(resp(0, stream_index=0))
    assert len(emitted) == 1
    cli._handle_response(resp(0, stream_index=1, stream_last=True))
    # stream done -> cursor passes 0 -> plain response for 1 released
    assert len(emitted) == 3
    assert emitted[0].meta["stream_index"] == 0
    assert emitted[1].meta["stream_last"] is True
    assert float(np.asarray(emitted[2].tensors[0])[0]) == 1.0
    assert cli._pending == {} and cli._done == {}


def test_stream_timeout_drop_terminates_downstream():
    """on-timeout=drop mid-stream: downstream gets an empty stream_last +
    stream_aborted terminator, and late tokens are swallowed quietly."""
    import time as _time

    cli, emitted, META = _client_harness()
    cli.on_timeout = "drop"
    cli.timeout = 0.01
    now = _time.monotonic()
    cli._pending = {0: (nt.Buffer([np.zeros(1)]), now)}
    cli._next_msg = 1

    tok = nt.Buffer([np.asarray([7.0])])
    tok.meta[META] = 0
    tok.meta["stream_index"] = 0
    cli._handle_response(tok)
    assert len(emitted) == 1  # first token delivered
    _time.sleep(0.05)
    cli._wait_outstanding(1)  # head request now overdue -> dropped
    assert len(emitted) == 2
    term = emitted[1]
    assert term.meta.get("stream_last") is True
    assert term.meta.get("stream_aborted") is True
    assert len(term.tensors) == 0
    # late token after the abort: dropped without an unmatched-warning path
    late = nt.Buffer([np.asarray([8.0])])
    late.meta[META] = 0
    late.meta["stream_index"] = 1
    cli._handle_response(late)
    assert len(emitted) == 2
    assert 0 in cli._aborted
    fin = nt.Buffer([])
    fin.meta[META] = 0
    fin.meta["stream_index"] = 2
    fin.meta["stream_last"] = True
    cli._handle_response(fin)
    assert 0 not in cli._aborted  # abort bookkeeping cleaned up


def test_query_client_round_robin_fanout():
    """hosts=h1:p1,h2:p2 round-robins requests over two servers (the
    reference's coarse DP offload); responses come back in request order
    with each server's distinct transform applied alternately."""
    register_custom_easy(
        "q-triple", lambda ins: [ins[0] * 3],
        in_spec=TensorsSpec.from_string("4", "float32"),
        out_spec=TensorsSpec.from_string("4", "float32"))
    srv_a = nt.Pipeline(
        "tensor_query_serversrc name=sa port=0 id=20 ! "
        "tensor_filter framework=custom-easy model=q-double ! "
        "tensor_query_serversink id=20")
    srv_b = nt.Pipeline(
        "tensor_query_serversrc name=sb port=0 id=21 ! "
        "tensor_filter framework=custom-easy model=q-triple ! "
        "tensor_query_serversink id=21")
    with srv_a, srv_b:
        pa = srv_a.element("sa").bound_port
        pb = srv_b.element("sb").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! "
            f"tensor_query_client hosts=127.0.0.1:{pa},127.0.0.1:{pb} "
            "timeout=15 ! tensor_sink name=out")
        with cli:
            for i in range(6):
                cli.push("src", np.full((4,), float(i + 1), np.float32))
            outs = [cli.pull("out", timeout=15) for _ in range(6)]
            cli.eos("src")
            cli.wait(timeout=15)
    # request i went to server i%2: even -> x2, odd -> x3; order preserved
    for i, b in enumerate(outs):
        factor = 2.0 if i % 2 == 0 else 3.0
        np.testing.assert_allclose(b.tensors[0], (i + 1) * factor)


class TestDynamicBatching:
    """serversrc max-batch: concurrent requests stack into ONE batched
    fused invoke (TPU-first; the reference serves one request per invoke)."""

    def _batched_server(self, sid, max_batch=4, window_ms=200):
        # The served callable ASSERTS it sees the full static batch — proof
        # requests were actually stacked, not looped.
        seen = []

        def model(ins):
            seen.append(ins[0].shape)
            assert ins[0].shape == (max_batch, 4), ins[0].shape
            return [ins[0] * 2]

        register_custom_easy(f"q-batch-{sid}", model)
        srv = nt.Pipeline(
            f"tensor_query_serversrc name=ssrc port=0 id={sid} "
            f"max-batch={max_batch} batch-window-ms={window_ms} ! "
            f"tensor_filter framework=custom-easy model=q-batch-{sid} "
            "invoke-dynamic=true ! "
            f"tensor_query_serversink id={sid}"
        )
        return srv, seen

    def test_concurrent_requests_share_one_invoke(self):
        srv, seen = self._batched_server(40, max_batch=4)
        with srv:
            port = srv.element("ssrc").bound_port
            clients = [
                nt.Pipeline(f"appsrc name=src ! tensor_query_client "
                            f"port={port} timeout=20 ! tensor_sink name=out")
                for _ in range(4)
            ]
            for c in clients:
                c.__enter__()
            try:
                for i, c in enumerate(clients):
                    c.push("src", np.full((4,), float(i + 1), np.float32))
                for i, c in enumerate(clients):
                    out = c.pull("out", timeout=20)
                    # each client gets ITS row back, unbatched
                    assert out.tensors[0].shape == (4,)
                    np.testing.assert_allclose(
                        out.tensors[0], np.full((4,), 2.0 * (i + 1)))
            finally:
                for c in clients:
                    c.eos("src")
                    c.wait(timeout=10)
                    c.__exit__(None, None, None)
        assert len(seen) >= 1  # 4 requests rode <=4 (ideally 1) invokes

    def test_partial_group_pads_and_drops_pad_rows(self):
        srv, _ = self._batched_server(41, max_batch=4, window_ms=30)
        with srv:
            port = srv.element("ssrc").bound_port
            cli = nt.Pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "timeout=20 ! tensor_sink name=out")
            with cli:
                # ONE request: the group times out at 1 valid row, pads to
                # 4 for the static-shape invoke, and exactly one response
                # returns (padded rows never reach any client).
                cli.push("src", np.full((4,), 3.0, np.float32))
                out = cli.pull("out", timeout=20)
                np.testing.assert_allclose(out.tensors[0],
                                           np.full((4,), 6.0))
                with pytest.raises(TimeoutError):
                    cli.pull("out", timeout=0.5)
                cli.eos("src")
                cli.wait(timeout=10)

    def test_batched_llm_streaming(self):
        # Two concurrent same-length prompts decode in ONE batched scan;
        # each client receives its own row of every generated token with
        # the stream flags intact.  (The llm filter emits ids-only when
        # batched: per-row byte pieces are not batch-leading.)
        max_new = 4
        srv = nt.Pipeline(
            "tensor_query_serversrc name=ssrc port=0 id=42 "
            "max-batch=2 batch-window-ms=300 ! "
            f"tensor_filter framework=llm model=llama_tiny "
            f"custom=max_new:{max_new},stream_chunk:2 invoke-dynamic=true ! "
            "tensor_query_serversink id=42"
        )
        with srv:
            port = srv.element("ssrc").bound_port
            clients = [
                nt.Pipeline(f"appsrc name=src ! tensor_query_client "
                            f"port={port} timeout=30 ! tensor_sink name=out")
                for _ in range(2)
            ]
            prompts = [np.array([1, 5, 9, 2], np.int32),
                       np.array([3, 3, 7, 8], np.int32)]
            for c in clients:
                c.__enter__()
            try:
                for c, pr in zip(clients, prompts):
                    c.push("src", pr)
                streams = []
                for c in clients:
                    toks = [c.pull("out", timeout=30)
                            for _ in range(max_new)]
                    assert [t.meta["stream_index"] for t in toks] == \
                        list(range(max_new))
                    assert toks[-1].meta.get("stream_last") is True
                    ids = [int(np.asarray(t.tensors[0]).ravel()[0])
                           for t in toks]
                    streams.append(ids)
            finally:
                for c in clients:
                    c.eos("src")
                    c.wait(timeout=10)
                    c.__exit__(None, None, None)
        # determinism: the same stacked prompt decoded directly must give
        # the same per-row ids the clients saw
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": "llama_tiny",
                 "custom": f"max_new:{max_new},stream_chunk:2"})
        direct = [out[0] for out in fw.invoke_stream([np.stack(prompts)])]
        for row, ids in enumerate(streams):
            assert ids == [int(d[row]) for d in direct]

    def test_client_disconnect_mid_batched_stream_isolated(self):
        # One of two clients sharing a batched LLM stream vanishes
        # mid-generation: its send fails and the connection drops, while
        # the surviving client still receives its complete stream (the
        # reference's multi-client isolation requirement, applied to the
        # batched path).
        import contextlib

        max_new = 6
        # A wide window costs nothing when both requests arrive (the group
        # closes the moment it reaches max-batch) but guarantees a loaded
        # CI host cannot split the two pushes into separate single-row
        # batches — which would let the test pass without exercising the
        # shared-stream scenario it documents.
        srv = nt.Pipeline(
            "tensor_query_serversrc name=ssrc port=0 id=43 "
            "max-batch=2 batch-window-ms=5000 ! "
            f"tensor_filter name=f framework=llm model=llama_tiny "
            f"custom=max_new:{max_new},stream_chunk:1 invoke-dynamic=true ! "
            "tensor_query_serversink id=43"
        )
        with srv, contextlib.ExitStack() as clients:
            port = srv.element("ssrc").bound_port
            doomed = clients.enter_context(nt.Pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "timeout=30 ! tensor_sink name=out"))
            survivor = clients.enter_context(nt.Pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "timeout=30 ! tensor_sink name=out"))
            doomed.push("src", np.array([1, 5, 9, 2], np.int32))
            survivor.push("src", np.array([3, 3, 7, 8], np.int32))
            # doomed reads one token then tears down mid-stream
            doomed.pull("out", timeout=30)
            doomed.stop()
            toks = [survivor.pull("out", timeout=30)
                    for _ in range(max_new)]
            assert toks[-1].meta.get("stream_last") is True
            assert [t.meta["stream_index"] for t in toks] == \
                list(range(max_new))
            survivor.eos("src")
            survivor.wait(timeout=10)
            # Proof the scenario actually ran batched: ONE filter invoke
            # served both clients' streams.  Polled — the counter
            # increments when the server-side stream generator finalizes,
            # which races the last token's delivery to the client.
            import time as _t

            deadline = _t.monotonic() + 5
            while srv.element("f")._n_invoked < 1 \
                    and _t.monotonic() < deadline:
                _t.sleep(0.02)
            assert srv.element("f")._n_invoked == 1


def test_continuous_serving_behind_query_server():
    """serve:continuous behind the query pair: clients arriving while
    earlier streams are mid-decode get admitted into the running loop,
    and each receives its own complete ordered stream (continuous
    batching as a SERVICE — static max-batch grouping would make a late
    client wait for the whole running group)."""
    import contextlib

    max_new = 8
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=44 ! "
        f"tensor_filter framework=llm model=llama_tiny "
        f"custom=max_new:{max_new},serve:continuous,slots:2,"
        "stream_chunk:2,temperature:0.0 invoke-dynamic=true ! "
        "tensor_query_serversink id=44"
    )
    with srv, contextlib.ExitStack() as stack:
        port = srv.element("ssrc").bound_port
        clients = [stack.enter_context(nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "timeout=60 ! tensor_sink name=out")) for _ in range(3)]
        # stagger: client 0 starts, then 1 and 2 join mid-decode
        clients[0].push("src", np.array([1, 5, 9, 2], np.int32))
        clients[0].pull("out", timeout=60)  # stream 0 demonstrably live
        clients[1].push("src", np.array([3, 3, 7, 8], np.int32))
        clients[2].push("src", np.array([6, 1, 4, 4], np.int32))
        for ci, c in enumerate(clients):
            n = max_new - (1 if ci == 0 else 0)  # client 0 pulled one
            toks = [c.pull("out", timeout=60) for _ in range(n)]
            assert toks[-1].meta.get("stream_last") is True
            start = 1 if ci == 0 else 0
            assert [t.meta["stream_index"] for t in toks] == \
                list(range(start, max_new))
        for c in clients:
            c.eos("src")
            c.wait(timeout=15)
