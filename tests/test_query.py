"""Distribution layer tests: tensor_query offload + edge pub/sub.

Reference analog (SURVEY §4): query/edge suites run client & server
pipelines in one process on localhost ports — "multi-node without a
cluster".  Same here: a server pipeline (serversrc ! filter ! serversink)
and client pipelines talk over real TCP sockets on 127.0.0.1.
"""

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.pipeline.runtime import PipelineError
from nnstreamer_tpu.filters.custom_easy import register_custom_easy


@pytest.fixture(autouse=True)
def _models():
    spec = TensorsSpec.from_string("4", "float32")
    register_custom_easy(
        "q-double", lambda ins: [ins[0] * 2], in_spec=spec, out_spec=spec,
    )
    yield


def _server_pipeline(sid=0):
    return nt.Pipeline(
        f"tensor_query_serversrc name=ssrc port=0 id={sid} ! "
        "tensor_filter framework=custom-easy model=q-double ! "
        f"tensor_query_serversink id={sid}"
    )


def test_query_roundtrip():
    with _server_pipeline() as srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=10 ! "
            "tensor_sink name=out"
        )
        with cli:
            for i in range(5):
                x = np.full((4,), float(i), np.float32)
                cli.push("src", x)
            for i in range(5):
                out = cli.pull("out", timeout=10)
                np.testing.assert_allclose(out.tensors[0], np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=10)


def test_query_preserves_order_and_meta():
    with _server_pipeline(sid=1) as srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "max-in-flight=4 timeout=10 ! tensor_sink name=out"
        )
        with cli:
            n = 12
            for i in range(n):
                cli.push("src", np.full((4,), float(i), np.float32))
            outs = [cli.pull("out", timeout=10) for _ in range(n)]
            for i, out in enumerate(outs):
                np.testing.assert_allclose(out.tensors[0], np.full((4,), 2.0 * i))
            cli.eos("src")
            cli.wait(timeout=10)


def test_query_multiple_clients_concurrently():
    with _server_pipeline(sid=2) as srv:
        port = srv.element("ssrc").bound_port
        results = {}
        errors = []

        def run_client(cid):
            try:
                cli = nt.Pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "timeout=10 ! tensor_sink name=out"
                )
                with cli:
                    vals = []
                    for i in range(6):
                        cli.push("src", np.full((4,), cid * 100.0 + i, np.float32))
                    for _ in range(6):
                        vals.append(float(cli.pull("out", timeout=10).tensors[0][0]))
                    cli.eos("src")
                    cli.wait(timeout=10)
                results[cid] = vals
            except Exception as e:  # noqa: BLE001
                errors.append((cid, e))

        threads = [threading.Thread(target=run_client, args=(c,)) for c in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        for cid in range(3):
            assert results[cid] == [2 * (cid * 100.0 + i) for i in range(6)]


def test_query_client_timeout_error():
    # Server that never answers: a bare serversrc with no sink draining it.
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=3 ! fakesink"
    )
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=0.5 ! "
            "tensor_sink name=out"
        )
        with cli:
            cli.push("src", np.zeros((4,), np.float32))
            cli.eos("src")
            with pytest.raises(PipelineError, match="no response"):
                cli.wait(timeout=10)


def test_query_client_timeout_drop():
    srv = nt.Pipeline("tensor_query_serversrc name=ssrc port=0 id=4 ! fakesink")
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} timeout=0.5 "
            "on-timeout=drop ! tensor_sink name=out"
        )
        with cli:
            cli.push("src", np.zeros((4,), np.float32))
            cli.eos("src")
            cli.wait(timeout=10)  # drop policy: EOS flows, nothing raised


def test_edge_pubsub_fanout():
    pub = nt.Pipeline("appsrc name=src ! edgesink name=pub port=0")
    with pub:
        port = pub.element("pub").bound_port
        subs = [
            nt.Pipeline(f"edgesrc port={port} num-buffers=3 ! tensor_sink name=out")
            for _ in range(2)
        ]
        for s in subs:
            s.start()
        time.sleep(0.3)  # let subscriptions land before publishing
        for i in range(3):
            pub.push("src", np.full((2,), float(i), np.float32))
        try:
            for s in subs:
                for i in range(3):
                    out = s.pull("out", timeout=10)
                    np.testing.assert_allclose(out.tensors[0], np.full((2,), float(i)))
                s.wait(timeout=10)
        finally:
            for s in subs:
                s.stop()
        pub.eos("src")
        pub.wait(timeout=10)


def test_edge_topic_mismatch_rejected():
    pub = nt.Pipeline("appsrc name=src ! edgesink name=pub port=0 topic=video")
    with pub:
        port = pub.element("pub").bound_port
        bad = nt.Pipeline(f"edgesrc port={port} topic=audio ! tensor_sink name=out")
        with pytest.raises(Exception, match="rejected"):
            bad.start()
        bad.stop()
