"""nns-trace flight recorder + metrics pipeline (ISSUE 5 tentpole).

The contract: with ``trace_mode != off`` every buffer gets a trace id at
source ingress that survives tee/demux/collator fan-out and batching;
batched dispatch spans LINK every member row's id; the ring evicts oldest
first; Chrome dumps schema-validate and are monotonic in ``ts``; watchdog
fires dump the recent window; and with ``trace_mode=off`` the recorder is
structurally bypassed (zero events, zero meta stamps).  Plus the metrics
pipeline: real Prometheus histograms, sampler gauges, bounded thread-safe
reservoirs, and a /metrics server with clean shutdown.
"""

import json
import logging
import threading
import time
import urllib.request

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.log import LATENCY_BUCKETS, Metrics, metrics
from nnstreamer_tpu.utils import tracing
from nnstreamer_tpu.utils.profiler import (metrics_server, metrics_text,
                                           start_metrics_server,
                                           stop_metrics_server)
from nnstreamer_tpu.utils.tracing import (FlightRecorder, recorder,
                                          to_chrome, validate_chrome)
from nnstreamer_tpu.utils.watchdog import Watchdog

DESC = (
    "appsrc name=src caps=other/tensors,dimensions=16,types=float32 ! "
    "tensor_filter framework=jax model=scaler custom=scale:2.0,dims:16 "
    "name=f ! tensor_sink name=out"
)


@pytest.fixture(autouse=True)
def _clean_state():
    metrics.reset()
    recorder.configure("off")
    recorder.clear()
    yield
    recorder.configure("off")
    recorder.clear()
    metrics.reset()


def _frames(n, dims=16):
    return [np.full((dims,), float(i), np.float32) for i in range(n)]


def _run(desc, frames, timeout=60, **kw):
    p = nt.Pipeline(desc, **kw)
    outs = []
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            outs.append(p.pull("out", timeout=timeout))
        p.eos()
        p.wait(timeout=timeout)
    return outs


# -- recorder primitives ---------------------------------------------------

def test_ring_eviction_order():
    rec = FlightRecorder("ring", capacity=8)
    for i in range(20):
        rec.record("stage", "s", i, ts_ns=i * 1000, dur_ns=10)
    evs = rec.events()
    assert len(evs) == 8
    assert [e.tid for e in evs] == list(range(12, 20))  # oldest evicted
    assert [e.ts for e in evs] == sorted(e.ts for e in evs)


def test_full_mode_unbounded():
    rec = FlightRecorder("full")
    for i in range(tracing.DEFAULT_RING_CAPACITY // 8):
        rec.record("stage", "s", i, i, 1)
    assert len(rec) == tracing.DEFAULT_RING_CAPACITY // 8
    rec.configure("ring", capacity=16)
    assert len(rec.events()) == 16  # re-bounding keeps the newest


def test_bad_mode_rejected():
    with pytest.raises(ValueError, match="off|ring|full"):
        FlightRecorder().configure("sometimes")
    from nnstreamer_tpu.pipeline.runtime import PipelineError

    with pytest.raises(PipelineError, match="trace_mode"):
        nt.Pipeline(DESC, trace_mode="sometimes")


def test_recent_window():
    rec = FlightRecorder("ring", capacity=64)
    rec.record("stage", "old", 1, ts_ns=0, dur_ns=1000)
    rec.record("stage", "new", 2, ts_ns=int(9e9), dur_ns=1000)
    spans = rec.recent(seconds=1.0)
    assert [e.stage for e in spans] == ["new"]


# -- trace-id propagation --------------------------------------------------

def test_trace_ids_assigned_and_unique():
    outs = _run(DESC, _frames(12), trace_mode="ring")
    tids = [o.meta.get(tracing.META_TRACE_ID) for o in outs]
    assert all(isinstance(t, int) for t in tids)
    assert len(set(tids)) == 12
    kinds = {e.kind for e in recorder.events()}
    assert {"ingress", "queue", "stage", "e2e", "fetch"} <= kinds


def test_off_mode_zero_events_and_clean_meta():
    """The instrumentation pin: with trace_mode=off the recorder must be
    STRUCTURALLY bypassed — record() monkeypatched to raise, pipeline
    still completes, no meta stamps written.  Tenant threading (ISSUE 8)
    rides the same pin: a Pipeline-level default tenant adds NO stamp on
    the off path either."""

    def boom(*a, **k):
        raise AssertionError("record() ran with trace_mode=off")

    orig = FlightRecorder.record
    FlightRecorder.record = boom
    try:
        outs = _run(DESC, _frames(8), queue_capacity=16, batch_max=4,
                    tenant="acme")
    finally:
        FlightRecorder.record = orig
    assert len(recorder.events()) == 0
    for o in outs:
        for key in (tracing.META_TRACE_ID, tracing.META_INGRESS_NS,
                    tracing.META_ENQUEUE_NS, tracing.META_TENANT):
            assert key not in o.meta


def test_tee_fanout_shares_trace_id():
    p = nt.Pipeline(
        "videotestsrc num-buffers=2 width=4 height=4 ! tensor_converter ! "
        "tee name=t t. ! tensor_sink name=a t. ! tensor_sink name=b",
        trace_mode="ring")
    with p:
        a = p.pull("a", timeout=15)
        b = p.pull("b", timeout=15)
        p.wait(timeout=15)
    assert a.meta[tracing.META_TRACE_ID] == b.meta[tracing.META_TRACE_ID]
    # both sinks recorded e2e spans for the SAME frame identity
    e2e = [e for e in recorder.events() if e.kind == "e2e"]
    assert {e.stage for e in e2e} == {"a", "b"}


def test_demux_fanout_shares_trace_id():
    p = nt.Pipeline(
        "appsrc name=src ! tensor_demux name=d "
        "d.src_0 ! tensor_sink name=a d.src_1 ! tensor_sink name=b",
        trace_mode="ring")
    with p:
        p.push("src", [np.zeros((2,), np.float32),
                       np.ones((3,), np.float32)])
        a = p.pull("a", timeout=15)
        b = p.pull("b", timeout=15)
        p.eos()
        p.wait(timeout=15)
    assert a.meta[tracing.META_TRACE_ID] == b.meta[tracing.META_TRACE_ID]


def test_collator_links_member_trace_ids():
    p = nt.Pipeline(
        "appsrc name=a caps=other/tensors,dimensions=4,types=float32 ! "
        "mux.sink_0 "
        "appsrc name=b caps=other/tensors,dimensions=4,types=float32 ! "
        "mux.sink_1 "
        "tensor_mux name=mux ! tensor_sink name=out", trace_mode="ring")
    x = np.ones((4,), np.float32)
    with p:
        p.push("a", x)
        p.push("b", 2 * x)
        out = p.pull("out", timeout=15)
        p.eos()
        p.wait(timeout=15)
    ing = {e.stage: e.tid for e in recorder.events() if e.kind == "ingress"}
    assert set(ing) == {"a", "b"}
    mux_spans = [e for e in recorder.events()
                 if e.kind == "stage" and e.stage == "mux"
                 and e.args and e.args.get("trace_ids")]
    assert mux_spans, "collation must record a linked stage span"
    assert set(mux_spans[0].args["trace_ids"]) == set(ing.values())
    assert out.meta[tracing.META_TRACE_ID] in ing.values()


@pytest.mark.parametrize("k", list(range(1, 9)))
def test_batch_span_linkage_all_occupancies(k):
    """At every backlog size 1..8 the union of linked trace ids across
    the filter's dispatch spans covers EVERY pushed buffer exactly, and
    each linked span's id count equals its row count — per-row
    attribution survives whatever occupancy partition the race produced."""
    outs = _run(DESC, _frames(k), queue_capacity=16, batch_max=8,
                trace_mode="ring")
    pushed = {o.meta[tracing.META_TRACE_ID] for o in outs}
    assert len(pushed) == k
    covered = set()
    for e in recorder.events():
        if e.kind != "stage" or e.stage != "f":
            continue
        linked = (e.args or {}).get("trace_ids")
        if linked:
            assert len(linked) == e.args["rows"]
            assert e.args["per_row_ns"] * e.args["rows"] <= e.dur + 1
            covered |= set(linked)
        else:
            covered.add(e.tid)
    assert covered == pushed


# -- Chrome export ---------------------------------------------------------

def test_chrome_dump_schema_and_monotonic(tmp_path):
    p = nt.Pipeline(DESC, queue_capacity=16, batch_max=8,
                    trace_mode="ring")
    frames = _frames(16)
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            p.pull("out", timeout=60)
        p.eos()
        p.wait(timeout=60)
    path = tmp_path / "trace.json"
    n = p.dump_trace(str(path))
    assert n == len(recorder.events())
    obj = json.loads(path.read_text())
    assert validate_chrome(obj) == []
    tss = [e["ts"] for e in obj["traceEvents"]]
    assert tss == sorted(tss)  # monotonic in ts
    names = {e["args"]["name"] for e in obj["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"src", "f", "out"} <= names
    # batch spans carry their member links into the JSON + flow arrows
    linked = [e for e in obj["traceEvents"]
              if (e.get("args") or {}).get("trace_ids")]
    assert linked
    flows = [e for e in obj["traceEvents"] if e.get("cat") == "row-link"]
    assert {f["ph"] for f in flows} <= {"s", "f"}


def test_validate_chrome_catches_problems():
    assert validate_chrome([]) != []
    assert validate_chrome({"traceEvents": "nope"}) != []
    bad = {"traceEvents": [
        {"ph": "X", "ts": 5.0, "pid": 1, "tid": 1, "name": "a", "dur": 1.0},
        {"ph": "X", "ts": 1.0, "pid": 1, "tid": 1, "name": "b", "dur": -2.0},
    ]}
    problems = validate_chrome(bad)
    assert any("monotonic" in p for p in problems)
    assert any("dur" in p for p in problems)


def test_to_chrome_empty():
    obj = to_chrome([])
    assert validate_chrome(obj) == []


def test_cli_validate_and_summary(tmp_path, capsys):
    from nnstreamer_tpu.tools import trace as trace_cli

    _run(DESC, _frames(6), trace_mode="ring")
    path = tmp_path / "t.json"
    tracing.dump_chrome(recorder.events(), str(path))
    assert trace_cli.main(["validate", str(path)]) == 0
    assert trace_cli.main(["summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "OK:" in out and "stage" in out
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert trace_cli.main(["validate", str(bad)]) == 1


# -- post-mortem dumps -----------------------------------------------------

def test_watchdog_fire_dumps_stalled_stage_span(caplog):
    recorder.configure("ring")
    recorder.record("stage", "stalled_stage", 7,
                    time.monotonic_ns(), 2_000_000)
    fired = threading.Event()
    wd = Watchdog(0.05, fired.set)
    with caplog.at_level(logging.ERROR,
                         logger="nnstreamer_tpu.utils.watchdog"):
        wd.arm()
        assert fired.wait(5.0)
        wd.disarm()
    assert "flight recorder" in caplog.text
    assert "stalled_stage" in caplog.text
    assert "watchdog fired" in caplog.text


def test_record_error_dumps_ring(caplog):
    recorder.configure("ring")
    recorder.record("stage", "exploding_stage", 9,
                    time.monotonic_ns(), 1_000_000)
    p = nt.Pipeline(DESC)
    with caplog.at_level(logging.ERROR):
        p._record_error("f", RuntimeError("boom"))
    assert "exploding_stage" in caplog.text
    assert "boom" in caplog.text


def test_dump_recent_noop_when_off(caplog):
    recorder.configure("off")
    log = logging.getLogger("test.tracing")
    with caplog.at_level(logging.ERROR):
        assert tracing.dump_recent_to_log(log) == 0
    assert "flight recorder" not in caplog.text


# -- metrics pipeline ------------------------------------------------------

def test_histogram_exposition_cumulative():
    metrics.observe_latency("t.proc", 0.003)
    metrics.observe_latency("t.proc", 0.0004)
    metrics.observe_latency("t.proc", 99.0)  # lands in +Inf
    text = metrics_text()
    assert "# TYPE nnstpu_t_proc histogram" in text
    assert "# HELP nnstpu_t_proc" in text
    assert 'nnstpu_t_proc_bucket{le="0.0005"} 1' in text
    assert 'nnstpu_t_proc_bucket{le="0.005"} 2' in text
    assert 'nnstpu_t_proc_bucket{le="10"} 2' in text
    assert 'nnstpu_t_proc_bucket{le="+Inf"} 3' in text
    assert "nnstpu_t_proc_count 3" in text
    hists = metrics.histograms()
    counts, total, n = hists["t.proc"]
    assert n == 3 and sum(counts) == 3
    assert total == pytest.approx(99.0034)
    assert len(counts) == len(LATENCY_BUCKETS) + 1


def test_histogram_and_gauge_name_collisions_disambiguated():
    """Sanitized-name collisions get the same deterministic hash-suffix
    treatment in every sample family (counters had it; histograms and
    gauges must not silently emit duplicate series)."""
    metrics.observe_latency("a.b:c", 0.001)
    metrics.observe_latency("a.b/c", 0.002)
    metrics.gauge("g.x:y", 1.0)
    metrics.gauge("g.x/y", 2.0)
    text = metrics_text()
    counts = [line.split()[0] for line in text.splitlines()
              if line and not line.startswith("#")]
    assert len(counts) == len(set(counts)), "duplicate series emitted"
    assert sum("nnstpu_a_b_c_" in line and "_count" in line
               for line in text.splitlines()) == 2


def test_off_pipeline_isolated_from_global_recorder():
    """A trace_mode=off pipeline must not record spans even while another
    pipeline's ring mode has the process-global recorder active."""
    recorder.configure("ring")
    recorder.clear()
    _run(DESC, _frames(4), queue_capacity=16, batch_max=4)  # off pipeline
    assert all(e.stage not in ("src", "f", "out")
               for e in recorder.events())


def test_gauges_in_exposition():
    metrics.gauge("q.queue_depth", 3)
    metrics.gauge("out.staleness_s", 0.25)
    text = metrics_text()
    assert "# TYPE nnstpu_q_queue_depth gauge" in text
    assert "nnstpu_q_queue_depth 3" in text
    assert "nnstpu_out_staleness_s 0.25" in text
    assert metrics.snapshot()["q.queue_depth"] == 3.0


def test_observe_reservoir_bounded_under_concurrency():
    """Satellite: a hot stage must not grow memory for the process
    lifetime, and snapshot()/percentile() must be safe under concurrent
    runner writes."""
    m = Metrics()
    errors = []

    def writer(tag):
        try:
            for i in range(20000):
                m.observe_latency(f"hot.{tag}", i * 1e-6)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                m.snapshot()
                m.percentile("hot.0", 99.0)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t % 2,))
               for t in range(4)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    snap = m.snapshot()
    for tag in (0, 1):
        assert snap[f"hot.{tag}.n"] <= m._lat_cap  # bounded reservoir
        _, _, n = m.histograms()[f"hot.{tag}"]
        assert n == 40000  # histogram counts stay exact (no decimation)


def test_metrics_server_scrape_twice_identical_and_stop():
    metrics.count("scrape.frames", 3)
    metrics.observe_latency("scrape.proc", 0.002)
    metrics.gauge("scrape.queue_depth", 1)
    # labeled twins (ISSUE 8): tenant series must render identically
    # across scrapes too, including hash-disambiguated tenant values
    metrics.observe_latency("scrape.proc", 0.004, tenant="acme")
    metrics.count("scrape.frames", 1, tenant="t:1")
    metrics.count("scrape.frames", 1, tenant="t/1")
    srv = start_metrics_server(port=0)
    try:
        url = f"http://127.0.0.1:{srv.server_port}/metrics"

        def series_names(body):
            return {line.split()[0]
                    for line in body.splitlines()
                    if line and not line.startswith("#")}

        one = urllib.request.urlopen(url, timeout=5).read().decode()
        two = urllib.request.urlopen(url, timeout=5).read().decode()
        assert one == two  # label values included, byte-identical
        assert len(series_names(one)) == len(set(series_names(one)))
        assert any(n.startswith("nnstpu_scrape_proc_bucket")
                   for n in series_names(one))
        assert 'nnstpu_scrape_proc_bucket{tenant="acme",le="0.005"} 1' \
            in one
    finally:
        stop_metrics_server(srv)
    with pytest.raises(OSError):
        urllib.request.urlopen(url, timeout=1)


def test_metrics_server_context_manager_rebinds_port():
    with metrics_server(port=0) as srv:
        port = srv.server_port
    # clean shutdown (+ SO_REUSEADDR) => the port is immediately reusable
    with metrics_server(port=port) as srv2:
        assert srv2.server_port == port


def test_sampler_gauges_during_traced_run():
    p = nt.Pipeline(DESC, trace_mode="ring")
    frames = _frames(6)
    with p:
        for i, x in enumerate(frames):
            p.push("src", nt.Buffer([x], pts=i))
        for _ in frames:
            p.pull("out", timeout=60)
        p.sample_queues()  # deterministic tick (thread also running)
        snap = metrics.snapshot()
        p.eos()
        p.wait(timeout=60)
    assert "f.queue_depth" in snap
    assert "out.watermark_pts" in snap and snap["out.watermark_pts"] == 5.0
    assert "out.staleness_s" in snap and snap["out.staleness_s"] >= 0.0


def test_e2e_and_queue_wait_series_from_traced_run():
    _run(DESC, _frames(10), trace_mode="ring")
    snap = metrics.snapshot()
    assert snap.get("out.e2e_latency.n", 0) == 10
    assert snap.get("f.queue_wait.n", 0) >= 1
    hists = metrics.histograms()
    assert "out.e2e_latency" in hists and "f.queue_wait" in hists


def test_batch_identity_unchanged_by_tracing():
    """Tracing must observe, not perturb: outputs of a traced batched run
    are value-identical to the untraced reference."""
    frames = _frames(13)
    traced = _run(DESC, frames, queue_capacity=16, batch_max=8,
                  trace_mode="ring")
    metrics.reset()
    recorder.configure("off")
    recorder.clear()
    plain = _run(DESC, frames, queue_capacity=16, batch_max=8)
    for a, b in zip(traced, plain):
        np.testing.assert_array_equal(np.asarray(a.tensors[0]),
                                      np.asarray(b.tensors[0]))
        assert a.pts == b.pts
