"""MQTT-lite broker + mqttsrc/mqttsink elements.

Reference analog: ``tests/mqtt`` SSAT suite — local broker, publish and
subscribe pipelines on localhost (SURVEY §4: "MQTT tests spin a local
mosquitto broker or skip"; here the broker is in-repo, so no skip).
"""

from __future__ import annotations

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.utils.broker import MqttLiteBroker, topic_matches


class TestTopicMatching:
    def test_exact_and_wildcards(self):
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/c")
        assert topic_matches("a/+/c", "a/x/c")
        assert not topic_matches("a/+/c", "a/x/y")
        assert topic_matches("a/#", "a/x/y")
        assert topic_matches("#", "anything/at/all")
        assert topic_matches("", "x")
        assert not topic_matches("a/b/c", "a/b")


def _wait_sub(broker, topic, timeout=10.0):
    """Block until a subscription matching ``topic`` is registered: QoS-0
    publishes that win the race against SUBSCRIBE are simply lost (only
    the retained backlog, when enabled, replays — and only the LAST
    message), which made these tests flake under CPU load."""
    import time

    deadline = time.monotonic() + timeout
    while broker.subscriber_count(topic) == 0:
        if time.monotonic() > deadline:
            raise TimeoutError(f"no subscriber for {topic!r} in {timeout}s")
        time.sleep(0.01)


class TestBrokerPipelines:
    def test_pub_sub_roundtrip(self):
        with MqttLiteBroker() as broker:
            src_pipe = nt.Pipeline(
                f"mqttsrc host=127.0.0.1 port={broker.port} topic=cam/0 "
                "num-buffers=3 ! tensor_sink name=out"
            )
            with src_pipe:
                sink_pipe = nt.Pipeline(
                    f"appsrc name=src ! mqttsink host=127.0.0.1 "
                    f"port={broker.port} topic=cam/0"
                )
                with sink_pipe:
                    _wait_sub(broker, "cam/0")
                    for i in range(3):
                        sink_pipe.push("src", np.full((2,), i, np.int16))
                    outs = [src_pipe.pull("out", timeout=15) for _ in range(3)]
                    sink_pipe.eos()
                    sink_pipe.wait(timeout=10)
                src_pipe.wait(timeout=10)
        for i, b in enumerate(outs):
            assert np.array_equal(b.tensors[0], np.full((2,), i, np.int16))

    def test_topic_filter_blocks_other_topics(self):
        with MqttLiteBroker(retain=False) as broker:
            src_pipe = nt.Pipeline(
                f"mqttsrc port={broker.port} topic=cam/1 num-buffers=1 ! "
                "tensor_sink name=out"
            )
            with src_pipe:
                pub = nt.Pipeline(
                    f"appsrc name=src ! mqttsink port={broker.port} topic=cam/0"
                )
                pub2 = nt.Pipeline(
                    f"appsrc name=src ! mqttsink port={broker.port} topic=cam/1"
                )
                with pub, pub2:
                    _wait_sub(broker, "cam/1")
                    pub.push("src", np.array([1], np.uint8))
                    pub2.push("src", np.array([2], np.uint8))
                    out = src_pipe.pull("out", timeout=15)
                    pub.eos(), pub2.eos()
                    pub.wait(timeout=10), pub2.wait(timeout=10)
                src_pipe.wait(timeout=10)
        assert out.tensors[0][0] == 2

    def test_retained_message_reaches_late_subscriber(self):
        with MqttLiteBroker() as broker:
            pub = nt.Pipeline(
                f"appsrc name=src ! mqttsink port={broker.port} topic=state"
            )
            with pub:
                pub.push("src", np.array([42], np.uint8))
                pub.eos()
                pub.wait(timeout=10)
            # subscriber connects AFTER the publisher is gone
            sub = nt.Pipeline(
                f"mqttsrc port={broker.port} topic=state num-buffers=1 ! "
                "tensor_sink name=out"
            )
            with sub:
                out = sub.pull("out", timeout=15)
                sub.wait(timeout=10)
        assert out.tensors[0][0] == 42

    def test_rebase_sync_sets_transit(self):
        with MqttLiteBroker() as broker:
            sub = nt.Pipeline(
                f"mqttsrc port={broker.port} topic=t sync=rebase "
                "num-buffers=1 ! tensor_sink name=out"
            )
            with sub:
                pub = nt.Pipeline(
                    f"appsrc name=src ! mqttsink port={broker.port} topic=t"
                )
                with pub:
                    _wait_sub(broker, "t")
                    pub.push("src", nt.Buffer([np.zeros(1, np.uint8)], pts=1000))
                    out = sub.pull("out", timeout=15)
                    pub.eos()
                    pub.wait(timeout=10)
                sub.wait(timeout=10)
        assert "transit_ns" in out.meta
        assert out.pts != 1000  # rebased onto local timeline

    def test_no_broker_clear_error(self):
        p = nt.Pipeline(
            "appsrc name=src ! mqttsink port=59999 connect-timeout=0.3"
        )
        with pytest.raises(Exception, match="broker"):
            with p:
                p.push("src", np.zeros(1, np.uint8))
                p.eos()
                p.wait(timeout=10)


class TestGrpcElements:
    def test_roundtrip(self):
        pytest.importorskip("grpc")
        port = 55191
        src_pipe = nt.Pipeline(
            f"tensor_src_grpc host=127.0.0.1 port={port} num-buffers=3 ! "
            "tensor_sink name=out"
        )
        with src_pipe:
            sink_pipe = nt.Pipeline(
                f"appsrc name=src ! tensor_sink_grpc host=127.0.0.1 port={port}"
            )
            with sink_pipe:
                for i in range(3):
                    sink_pipe.push("src", np.full((3,), i, np.float32))
                outs = [src_pipe.pull("out", timeout=15) for _ in range(3)]
                sink_pipe.eos()
                sink_pipe.wait(timeout=10)
            src_pipe.wait(timeout=10)
        for i, b in enumerate(outs):
            assert np.array_equal(b.tensors[0], np.full((3,), i, np.float32))

    def test_meta_survives(self):
        pytest.importorskip("grpc")
        port = 55192
        src_pipe = nt.Pipeline(
            f"tensor_src_grpc host=127.0.0.1 port={port} num-buffers=1 ! "
            "tensor_sink name=out"
        )
        with src_pipe:
            sink_pipe = nt.Pipeline(
                f"appsrc name=src ! tensor_sink_grpc host=127.0.0.1 port={port}"
            )
            with sink_pipe:
                buf = nt.Buffer([np.arange(4, dtype=np.int32)], pts=777)
                buf.meta["tag"] = "x"
                sink_pipe.push("src", buf)
                out = src_pipe.pull("out", timeout=15)
                sink_pipe.eos()
                sink_pipe.wait(timeout=10)
            src_pipe.wait(timeout=10)
        assert out.pts == 777 and out.meta["tag"] == "x"


class TestReconnect:
    def test_subscriber_survives_broker_restart(self):
        """Broker dies and comes back on the same port: the subscriber
        reconnects and keeps receiving (reference: nnstreamer-edge
        MQTT-hybrid reconnection)."""
        broker = MqttLiteBroker().start()
        port = broker.port
        sub = nt.Pipeline(
            f"mqttsrc port={port} topic=t num-buffers=2 reconnect=true connect-timeout=10 ! "
            "tensor_sink name=out"
        )
        with sub:
            pub = nt.Pipeline(f"appsrc name=src ! mqttsink port={port} topic=t")
            with pub:
                _wait_sub(broker, "t")
                pub.push("src", np.array([1], np.uint8))
                first = sub.pull("out", timeout=15)
                pub.eos()
                pub.wait(timeout=10)
            broker.stop()
            import time as _t0

            broker2 = None
            for _ in range(50):  # port release can lag the close()
                try:
                    broker2 = MqttLiteBroker(port=port, retain=False).start()
                    break
                except OSError:
                    _t0.sleep(0.1)
            assert broker2 is not None, "could not rebind broker port"
            try:
                pub2 = nt.Pipeline(f"appsrc name=src ! mqttsink port={port} topic=t")
                with pub2:
                    # publish until the reconnected subscriber gets one
                    import time as _t

                    second = None
                    for i in range(100):
                        pub2.push("src", np.array([2], np.uint8))
                        try:
                            second = sub.pull("out", timeout=0.3)
                            break
                        except TimeoutError:
                            _t.sleep(0.1)
                    pub2.eos()
                    pub2.wait(timeout=10)
                assert second is not None, "no buffer after broker restart"
                sub.wait(timeout=15)
            finally:
                broker2.stop()
        assert first.tensors[0][0] == 1
        assert second.tensors[0][0] == 2

    def test_no_reconnect_by_default(self):
        broker = MqttLiteBroker().start()
        port = broker.port
        sub = nt.Pipeline(
            f"mqttsrc port={port} topic=t num-buffers=5 ! "
            "tensor_sink name=out"
        )
        with sub:
            broker.stop()
            # source should end (EOS), not hang
            sub.wait(timeout=15)
