"""Decoder sub-plugin tests (reference analogs: tests/nnstreamer_decoder_*
SSAT suites)."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.decoders.bounding_boxes import BoundingBoxes
from nnstreamer_tpu.decoders.image_labeling import ImageLabeling
from nnstreamer_tpu.decoders.image_segment import ImageSegment
from nnstreamer_tpu.decoders.pose import PoseEstimation
from nnstreamer_tpu.ops.nms import center_to_corner, iou_matrix, nms_numpy
from nnstreamer_tpu.utils.wire import decode_buffer, encode_buffer


class TestImageLabeling:
    def test_argmax_label(self):
        d = ImageLabeling({"option1": "digits"})
        scores = np.zeros(10, np.float32)
        scores[7] = 0.9
        out = d.decode([scores], Buffer([scores]))
        assert out.meta["label"] == "7"
        assert out.meta["label_index"] == 7
        assert bytes(out.tensors[0].tobytes()).decode() == "7"


class TestNMS:
    def test_iou(self):
        boxes = np.array([[0, 0, 2, 2], [1, 1, 3, 3], [10, 10, 12, 12]], np.float64)
        iou = iou_matrix(boxes)
        assert iou[0, 0] == pytest.approx(1.0)
        assert iou[0, 1] == pytest.approx(1 / 7)
        assert iou[0, 2] == 0.0

    def test_greedy(self):
        boxes = np.array(
            [[0, 0, 2, 2], [0.1, 0.1, 2.1, 2.1], [5, 5, 7, 7]], np.float64
        )
        scores = np.array([0.9, 0.8, 0.7])
        keep = nms_numpy(boxes, scores, iou_threshold=0.5)
        assert list(keep) == [0, 2]

    def test_jax_matches_numpy(self):
        rng = np.random.default_rng(0)
        centers = rng.uniform(0.2, 0.8, size=(20, 2))
        wh = rng.uniform(0.05, 0.3, size=(20, 2))
        boxes = center_to_corner(np.concatenate([centers, wh], axis=1))
        scores = rng.uniform(0.1, 1.0, size=20)
        keep_np = nms_numpy(boxes, scores, 0.5, max_out=10)

        from nnstreamer_tpu.ops.nms import nms_jax

        idx, valid = nms_jax(boxes, scores, 0.5, max_out=10)
        keep_jx = np.asarray(idx)[np.asarray(valid)]
        np.testing.assert_array_equal(keep_np, keep_jx)


class TestBoundingBoxes:
    def _dets(self):
        boxes = np.array(
            [[0.1, 0.1, 0.3, 0.3], [0.11, 0.11, 0.31, 0.31], [0.6, 0.6, 0.9, 0.9]],
            np.float32,
        )
        scores = np.zeros((3, 5), np.float32)
        scores[0, 1] = 0.9
        scores[1, 1] = 0.85  # overlaps det 0 -> suppressed
        scores[2, 3] = 0.7
        return boxes, scores

    def test_ssd_decode_nms_overlay(self):
        d = BoundingBoxes({"option1": "ssd", "option4": "100:100"})
        boxes, scores = self._dets()
        out = d.decode([boxes, scores], Buffer([boxes, scores]))
        dets = out.meta["detections"]
        assert len(dets) == 2
        assert dets[0]["class_index"] == 1
        assert dets[1]["class_index"] == 3
        overlay = out.tensors[0]
        assert overlay.shape == (100, 100, 4)
        assert overlay[10, 10:30].any()  # top edge of box 0 drawn

    def test_threshold(self):
        d = BoundingBoxes({"option1": "ssd", "option3": "0.95"})
        boxes, scores = self._dets()
        out = d.decode([boxes, scores], Buffer([boxes, scores]))
        assert out.meta["detections"] == []

    def test_yolo_decode(self):
        d = BoundingBoxes({"option1": "yolov5", "option4": "64:64"})
        pred = np.zeros((4, 9), np.float32)
        pred[0] = [0.5, 0.5, 0.2, 0.2, 0.9, 0, 0.8, 0, 0]
        pred[1] = [0.2, 0.2, 0.1, 0.1, 0.1, 0, 0, 0, 0.3]  # below threshold
        out = d.decode([pred], Buffer([pred]))
        dets = out.meta["detections"]
        assert len(dets) == 1
        assert dets[0]["class_index"] == 1
        np.testing.assert_allclose(dets[0]["box"], [0.4, 0.4, 0.6, 0.6], atol=1e-6)

    def test_yolov8_decode_channels_first(self):
        # ultralytics layout: (4+C, N), no objectness column — class scores
        # are the confidence.
        d = BoundingBoxes({"option1": "yolov8", "option4": "64:64"})
        pred = np.zeros((8, 5), np.float32)  # 4 box + 4 classes, 5 anchors
        pred[:, 0] = [0.5, 0.5, 0.2, 0.2, 0.0, 0.8, 0.0, 0.0]
        pred[:, 1] = [0.2, 0.2, 0.1, 0.1, 0.3, 0.0, 0.0, 0.0]  # below thr
        out = d.decode([pred], Buffer([pred]))
        dets = out.meta["detections"]
        assert len(dets) == 1
        assert dets[0]["class_index"] == 1
        np.testing.assert_allclose(dets[0]["box"], [0.4, 0.4, 0.6, 0.6],
                                   atol=1e-6)

    def test_yolov8_pixel_coords_option8(self):
        # option8=model-input size: boxes arrive in pixels and normalize
        # against it.
        d = BoundingBoxes({"option1": "yolov8", "option4": "64:64",
                           "option8": "160"})
        pred = np.zeros((6, 3), np.float32)
        pred[:, 0] = [80.0, 80.0, 32.0, 32.0, 0.9, 0.1]
        out = d.decode([pred], Buffer([pred]))
        dets = out.meta["detections"]
        assert len(dets) == 1 and dets[0]["class_index"] == 0
        np.testing.assert_allclose(dets[0]["box"], [0.4, 0.4, 0.6, 0.6],
                                   atol=1e-6)


class TestPose:
    def test_keypoints(self):
        k = 17
        hm = np.zeros((8, 8, k), np.float32)
        for i in range(k):
            hm[i % 8, (i * 3) % 8, i] = 1.0
        d = PoseEstimation({"option2": "80:80"})
        out = d.decode([hm], Buffer([hm]))
        kps = out.meta["keypoints"]
        assert len(kps) == k
        # keypoint 2 sits at heatmap (2, 6) -> pixel (65, 25)
        assert kps[2]["x"] == pytest.approx((6 + 0.5) / 8 * 80)
        assert kps[2]["y"] == pytest.approx((2 + 0.5) / 8 * 80)
        assert out.tensors[0].shape == (80, 80, 4)


class TestSegment:
    def test_argmax_overlay(self):
        scores = np.zeros((4, 4, 3), np.float32)
        scores[:2, :, 1] = 1.0
        scores[2:, :, 2] = 1.0
        d = ImageSegment({})
        out = d.decode([scores], Buffer([scores]))
        overlay = out.tensors[0]
        assert overlay.shape == (4, 4, 4)
        assert (out.meta["class_map"][:2] == 1).all()


class TestWire:
    def test_roundtrip(self):
        buf = Buffer(
            [np.arange(6, dtype=np.float32).reshape(2, 3), np.array([7], np.uint8)],
            pts=123,
        )
        buf.meta["detections"] = [{"box": [0, 0, 1, 1], "score": 0.5}]
        raw = encode_buffer(buf)
        out, flags = decode_buffer(raw)
        assert out.pts == 123
        assert len(out.tensors) == 2
        np.testing.assert_array_equal(out.tensors[0], buf.tensors[0])
        assert out.meta["detections"][0]["score"] == 0.5

    def test_decoder_converter_pipeline_roundtrip(self):
        p = nt.Pipeline(
            "appsrc name=src ! tensor_decoder mode=flexbuf ! "
            "tensor_converter mode=flexbuf ! tensor_sink name=out"
        )
        with p:
            x = np.arange(12, dtype=np.int16).reshape(3, 4)
            p.push("src", x)
            out = p.pull("out", timeout=10)
        np.testing.assert_array_equal(out.tensors[0], x)

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            decode_buffer(b"\x00" * 64)


def test_detection_pipeline_e2e():
    """appsrc(dets) -> bounding_boxes decoder -> sink with overlay + meta."""
    p = nt.Pipeline(
        "appsrc name=src ! "
        "tensor_decoder mode=bounding_boxes option1=ssd option4=64:64 ! "
        "tensor_sink name=out"
    )
    boxes = np.array([[0.2, 0.2, 0.5, 0.5]], np.float32)
    scores = np.array([[0.0, 0.99]], np.float32)
    with p:
        p.push("src", [boxes, scores])
        out = p.pull("out", timeout=10)
    assert out.tensors[0].shape == (64, 64, 4)
    assert len(out.meta["detections"]) == 1


def test_bounding_boxes_batched_frames_independent(rng):
    """Batched detection buffers decode per frame: NMS never mixes frames
    and detections come back as one list per frame."""
    from nnstreamer_tpu.core.registry import get as reg_get, KIND_DECODER

    dec = reg_get(KIND_DECODER, "bounding_boxes")(
        {"option1": "ssd", "option3": "0.5", "option4": "32:32"}
    )
    n = 6
    boxes = np.tile(np.array([[0.1, 0.1, 0.4, 0.4]], np.float32), (2, n, 1))
    scores = np.zeros((2, n, 3), np.float32)
    scores[0, 0, 1] = 0.9   # frame 0: one confident box
    scores[1, 0, 2] = 0.8   # frame 1: one confident box, other class
    scores[1, 1, 2] = 0.75  # same spot -> NMS suppresses within the frame
    buf = nt.Buffer([boxes, scores])
    outs = dec.decode([boxes, scores], buf)
    assert isinstance(outs, list) and len(outs) == 2  # one buffer per frame
    d0 = outs[0].meta["detections"]
    d1 = outs[1].meta["detections"]
    assert len(d0) == 1 and d0[0]["class_index"] == 1
    assert len(d1) == 1 and d1[0]["class_index"] == 2
    for o in outs:
        assert o.tensors[0].shape == (32, 32, 4)  # caps-true single frames
    assert [o.meta["batch_index"] for o in outs] == [0, 1]


def test_bounding_boxes_device_topk_matches_host(rng):
    """SSD prefilter: with N >> 4*max_detections the decoder top-ks on
    device; detections must match the pure-host path."""
    from nnstreamer_tpu.core.registry import get as reg_get, KIND_DECODER

    n, c, b = 600, 5, 2
    boxes = rng.uniform(0, 1, (b, n, 2)).astype(np.float32)
    boxes = np.concatenate([boxes, boxes + rng.uniform(0.05, 0.3, (b, n, 2)).astype(np.float32)], -1)
    scores = rng.uniform(0, 1, (b, n, c)).astype(np.float32) ** 3

    def run(max_det):
        dec = reg_get(KIND_DECODER, "bounding_boxes")(
            {"option1": "ssd", "option3": "0.6", "option4": "32:32",
             "option6": str(max_det)}
        )
        buf = nt.Buffer([boxes, scores])
        return dec.decode([boxes, scores], buf)

    outs_dev = run(20)    # 4*20=80 < 600 -> device top-k path
    outs_host = run(200)  # 4*200 >= 600 -> host path
    for od, oh in zip(outs_dev, outs_host):
        dd, dh = od.meta["detections"], oh.meta["detections"][:20]
        assert [d["class_index"] for d in dd] == [d["class_index"] for d in dh]
        np.testing.assert_allclose(
            [d["score"] for d in dd], [d["score"] for d in dh], rtol=1e-6
        )


class TestFusedDecodePaths:
    """device_fn + host_post (the fused deferred-D2H path) must reproduce
    the host ``decode`` results for every decoder that offers fusion."""

    def _run_fused(self, dec, tensors):
        import jax.numpy as jnp

        from nnstreamer_tpu.core.types import TensorsSpec

        spec = TensorsSpec.of(tensors)
        df = dec.device_fn(spec)
        assert df is not None
        fn, out_spec = df
        outs = fn(tuple(jnp.asarray(t) for t in tensors))
        assert len(outs) == len(out_spec)
        host = [np.asarray(o) for o in outs]
        return dec.host_post(host, Buffer(host))

    def test_bounding_boxes_ssd_fused_matches_host(self):
        rng = np.random.default_rng(3)
        n, c = 64, 7
        boxes = np.sort(rng.random((1, n, 4), np.float32), axis=-1)
        scores = rng.random((1, n, c)).astype(np.float32) * 0.6
        scores[0, 5, 2] = 0.97  # one clear winner avoids tie-order flakes
        d = BoundingBoxes({"option1": "ssd", "option3": "0.9",
                           "option4": "64:64"})
        fused = self._run_fused(d, [boxes, scores])
        host = d.decode([boxes, scores], Buffer([boxes, scores]))
        hd = host[0].meta["detections"] if isinstance(host, list) else host.meta["detections"]
        fd = fused.meta["detections"]
        assert len(fd) == len(hd) == 1
        assert fd[0]["class_index"] == hd[0]["class_index"] == 2
        np.testing.assert_allclose(fd[0]["box"], hd[0]["box"], rtol=1e-6)

    def test_bounding_boxes_yolo_fused_matches_host(self):
        d = BoundingBoxes({"option1": "yolov5", "option4": "64:64"})
        pred = np.zeros((1, 4, 9), np.float32)
        pred[0, 0] = [0.5, 0.5, 0.2, 0.2, 0.9, 0, 0.8, 0, 0]
        pred[0, 1] = [0.2, 0.2, 0.1, 0.1, 0.1, 0, 0, 0, 0.3]
        fused = self._run_fused(d, [pred])
        dets = fused.meta["detections"]
        assert len(dets) == 1
        assert dets[0]["class_index"] == 1
        np.testing.assert_allclose(dets[0]["box"], [0.4, 0.4, 0.6, 0.6],
                                   atol=1e-6)

    def test_bounding_boxes_yolov8_fused_matches_host(self):
        d = BoundingBoxes({"option1": "yolov8", "option4": "64:64"})
        pred = np.zeros((1, 8, 5), np.float32)  # (B, 4+C, N)
        pred[0, :, 0] = [0.5, 0.5, 0.2, 0.2, 0.0, 0.8, 0.0, 0.0]
        pred[0, :, 1] = [0.2, 0.2, 0.1, 0.1, 0.3, 0.0, 0.0, 0.0]
        fused = self._run_fused(d, [pred])
        dets = fused.meta["detections"]
        assert len(dets) == 1
        assert dets[0]["class_index"] == 1
        np.testing.assert_allclose(dets[0]["box"], [0.4, 0.4, 0.6, 0.6],
                                   atol=1e-6)

    def test_bounding_boxes_fused_batched_stacks(self):
        rng = np.random.default_rng(5)
        boxes = np.sort(rng.random((3, 32, 4), np.float32), axis=-1)
        scores = rng.random((3, 32, 6)).astype(np.float32)
        d = BoundingBoxes({"option1": "ssd", "option3": "0.5",
                           "option4": "32:32"})
        fused = self._run_fused(d, [boxes, scores])
        assert fused.tensors[0].shape == (3, 32, 32, 4)
        assert len(fused.meta["detections"]) == 3

    def test_pose_fused_matches_host(self):
        k = 17
        hm = np.zeros((1, 8, 8, k), np.float32)
        for i in range(k):
            hm[0, i % 8, (i * 3) % 8, i] = 1.0
        # Non-zero offsets: the offset application path must match too.
        off = np.linspace(-0.4, 0.4, 8 * 8 * 2 * k).astype(
            np.float32).reshape(1, 8, 8, 2 * k)
        d = PoseEstimation({"option2": "80:80"})
        fused = self._run_fused(d, [hm, off])
        host = d.decode([hm[0], off[0]], Buffer([hm[0]]))
        for a, b in zip(fused.meta["keypoints"], host.meta["keypoints"]):
            assert a["x"] == pytest.approx(b["x"], abs=1e-4)
            assert a["y"] == pytest.approx(b["y"], abs=1e-4)
            assert a["score"] == pytest.approx(b["score"], abs=1e-6)
        np.testing.assert_array_equal(fused.tensors[0], host.tensors[0])

    def test_segment_fused_matches_host(self):
        rng = np.random.default_rng(11)
        x = rng.random((2, 16, 16, 7)).astype(np.float32)
        d = ImageSegment({})
        fused = self._run_fused(d, [x])
        assert fused.tensors[0].shape == (2, 16, 16, 4)
        for i in range(2):
            host = d.decode([x[i]], Buffer([x[i]]))
            np.testing.assert_array_equal(fused.tensors[0][i], host.tensors[0])
            np.testing.assert_array_equal(
                fused.meta["class_map"][i], host.meta["class_map"])

    def test_segment_fused_batch1_squeezes(self):
        rng = np.random.default_rng(13)
        x = rng.random((1, 8, 8, 5)).astype(np.float32)
        d = ImageSegment({})
        fused = self._run_fused(d, [x])
        host = d.decode([x[0]], Buffer([x[0]]))
        assert fused.tensors[0].shape == (8, 8, 4)  # batch-1 collapsed
        np.testing.assert_array_equal(fused.tensors[0], host.tensors[0])
        np.testing.assert_array_equal(fused.meta["class_map"],
                                      host.meta["class_map"])

    def test_segment_device_output_is_one_byte_per_pixel(self):
        from nnstreamer_tpu.core.types import TensorsSpec

        d = ImageSegment({})
        fn, out_spec = d.device_fn(
            TensorsSpec.of([np.zeros((2, 8, 8, 5), np.float32)]))
        assert out_spec[0].dtype == np.uint8
        assert out_spec[0].shape == (2, 8, 8)

    def test_bounding_boxes_device_nms_matches_host(self):
        """option7=device runs threshold+greedy NMS inside the fused
        program; detections must match the host NMS path (distinct scores
        avoid tie-order ambiguity)."""
        rng = np.random.default_rng(7)
        n = 48
        boxes = np.sort(rng.random((1, n, 4), np.float32), axis=-1)
        # distinct, well-separated scores
        scores = np.zeros((1, n, 3), np.float32)
        scores[0, :, 1] = np.linspace(0.95, 0.05, n)
        host_dec = BoundingBoxes({"option1": "ssd", "option3": "0.4",
                                  "option4": "64:64"})
        dev_dec = BoundingBoxes({"option1": "ssd", "option3": "0.4",
                                 "option4": "64:64", "option7": "device"})
        fused = self._run_fused(dev_dec, [boxes, scores])
        host = self._run_fused(host_dec, [boxes, scores])
        fd, hd = fused.meta["detections"], host.meta["detections"]
        assert len(fd) == len(hd) > 0
        for a, b in zip(fd, hd):
            assert a["class_index"] == b["class_index"]
            assert a["score"] == pytest.approx(b["score"], abs=1e-5)
            np.testing.assert_allclose(a["box"], b["box"], atol=1e-6)
        np.testing.assert_array_equal(fused.tensors[0], host.tensors[0])

    def test_device_nms_respects_max_detections(self):
        rng = np.random.default_rng(9)
        # far-apart boxes -> nothing suppressed; cap must bound output
        n = 32
        centers = np.linspace(0.05, 0.95, n, dtype=np.float32)
        boxes = np.stack([centers - 0.01, centers - 0.01,
                          centers + 0.01, centers + 0.01], axis=-1)[None]
        scores = rng.random((1, n, 2)).astype(np.float32) * 0.4 + 0.5
        d = BoundingBoxes({"option1": "ssd", "option3": "0.1",
                           "option4": "32:32", "option6": "5",
                           "option7": "device"})
        fused = self._run_fused(d, [boxes, scores])
        dets = fused.meta["detections"]  # B==1 collapses to one frame's list
        assert len(dets) == 5


class TestCTC:
    """ctc decoder (decode-on-edge for wav2vec2-class logits): device
    argmax + host collapse; the D2H payload shrinks by a factor of vocab."""

    def _logits(self, ids, vocab=8):
        # logits whose argmax is exactly `ids` ([B, T])
        ids = np.asarray(ids)
        out = np.zeros(ids.shape + (vocab,), np.float32)
        np.put_along_axis(out, ids[..., None], 5.0, axis=-1)
        return out

    def test_collapse_semantics(self):
        from nnstreamer_tpu.decoders.ctc import collapse_ctc

        seqs = collapse_ctc(np.array([[0, 3, 3, 0, 3, 2, 2, 0]]), blank=0)
        np.testing.assert_array_equal(seqs[0], [3, 3, 2])  # blank splits 3s

    def test_host_decode(self):
        from nnstreamer_tpu.decoders.ctc import CTC

        d = CTC({})
        logits = self._logits([[0, 5, 5, 0, 2, 0]])
        out = d.decode([logits], Buffer([logits]))
        np.testing.assert_array_equal(out.tensors[0], [[5, 2]])
        np.testing.assert_array_equal(out.meta["lengths"], [2])

    def test_fused_matches_host_and_shrinks_d2h(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.decoders.ctc import CTC

        d = CTC({})
        rng = np.random.default_rng(0)
        logits = rng.standard_normal((4, 50, 32)).astype(np.float32)
        spec = TensorsSpec.of([logits])
        fn, out_spec = d.device_fn(spec)
        outs = fn((jnp.asarray(logits),))
        # device output is ids only: vocab-factor smaller than the logits
        assert outs[0].shape == (4, 50) and outs[0].dtype == jnp.int32
        assert out_spec[0].shape == (4, 50)
        fused = d.host_post([np.asarray(o) for o in outs], Buffer([logits]))
        host = d.decode([logits], Buffer([logits]))
        for a, b in zip(fused.meta["tokens"], host.meta["tokens"]):
            np.testing.assert_array_equal(a, b)

    def test_charmap_text_output(self):
        import os
        import tempfile

        from nnstreamer_tpu.decoders.ctc import CTC

        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "chars.txt")
            with open(path, "w") as f:
                f.write("\n".join(["_", "a", "b", "c"]))
            d = CTC({"option2": path})
            logits = self._logits([[1, 1, 0, 2, 3]], vocab=4)
            out = d.decode([logits], Buffer([logits]))
            assert out.meta["text"] == ["abc"]

    def test_wav2vec2_pipeline_fuses_ctc(self):
        """The bench topology: wav2vec2's static out spec lets the ctc
        decoder join the fused XLA stage, so the sink receives ids."""
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=16000:1,types=float32 ! "
            "tensor_filter framework=jax model=wav2vec2 "
            "custom=dtype:float32,n_layers:2 name=f ! "
            "tensor_decoder mode=ctc ! tensor_sink name=out")
        fused = [s for s in p.stages if "+" in s.element.name]
        assert fused and "tensor_decoder" in fused[0].element.name
        wav = np.sin(np.linspace(0, 440 * np.pi, 16000,
                                 dtype=np.float32))[None, :]
        with p:
            p.push("src", wav)
            b = p.pull("out", timeout=60)
            p.eos()
            p.wait(timeout=30)
        assert b.tensors[0].dtype == np.int32
        assert "tokens" in b.meta


class TestTensorOutputModes:
    """option9=tensors (bounding_boxes) / option4=tensors (pose) /
    option1=classmap (image_segment): detections/keypoints/class ids ship
    AS TENSORS with no host canvas — numerics must match the overlay
    path's meta exactly (indices-not-payloads, the classification/wav2vec2
    treatment applied to the remaining decoders)."""

    def _run_fused(self, dec, tensors):
        import jax.numpy as jnp

        from nnstreamer_tpu.core.types import TensorsSpec

        spec = TensorsSpec.of(tensors)
        fn, out_spec = dec.device_fn(spec)
        outs = fn(tuple(jnp.asarray(t) for t in tensors))
        host = [np.asarray(o) for o in outs]
        return dec.host_post(host, Buffer(host))

    def test_bbox_tensors_match_overlay_detections(self):
        rng = np.random.default_rng(3)
        boxes = np.sort(rng.random((64, 4), np.float32), axis=-1)
        scores = rng.random((64, 5)).astype(np.float32) * 0.6
        scores[5, 2] = 0.97
        ov = BoundingBoxes({"option1": "ssd", "option3": "0.5",
                            "option4": "64:64"})
        tn = BoundingBoxes({"option1": "ssd", "option3": "0.5",
                            "option4": "64:64", "option9": "tensors"})
        a = ov.decode([boxes, scores], Buffer([boxes, scores]))
        b = tn.decode([boxes, scores], Buffer([boxes, scores]))
        dets = a.meta["detections"]
        assert b.meta["detections"] == dets
        tb, ts, tc = b.tensors
        assert tb.shape == (len(dets), 4) and tb.dtype == np.float32
        for i, d in enumerate(dets):
            np.testing.assert_allclose(tb[i], d["box"], rtol=1e-6)
            assert ts[i] == pytest.approx(d["score"])
            assert tc[i] == d["class_index"]

    def test_bbox_tensors_fused_device_nms_passthrough(self):
        rng = np.random.default_rng(7)
        n = 48
        boxes = np.sort(rng.random((2, n, 4), np.float32), axis=-1)
        scores = np.zeros((2, n, 3), np.float32)
        scores[:, :, 1] = np.linspace(0.95, 0.05, n)
        ov = BoundingBoxes({"option1": "ssd", "option3": "0.4",
                            "option4": "64:64", "option7": "device"})
        tn = BoundingBoxes({"option1": "ssd", "option3": "0.4",
                            "option4": "64:64", "option7": "device",
                            "option9": "tensors"})
        a = self._run_fused(ov, [boxes, scores])
        b = self._run_fused(tn, [boxes, scores])
        tb, ts, tc, valid = b.tensors
        assert tb.shape[0] == 2 and tb.shape[2] == 4
        for f in range(2):
            keep = valid[f].astype(bool)
            dets = a.meta["detections"][f]
            assert int(keep.sum()) == len(dets)
            for i, d in enumerate(dets):
                np.testing.assert_allclose(tb[f, i], d["box"], atol=1e-6)
                assert tc[f, i] == d["class_index"]

    def test_bbox_tensors_fused_host_nms_pads(self):
        rng = np.random.default_rng(9)
        boxes = np.sort(rng.random((2, 32, 4), np.float32), axis=-1)
        scores = rng.random((2, 32, 6)).astype(np.float32)
        ov = BoundingBoxes({"option1": "ssd", "option3": "0.5",
                            "option4": "32:32"})
        tn = BoundingBoxes({"option1": "ssd", "option3": "0.5",
                            "option4": "32:32", "option9": "tensors"})
        a = self._run_fused(ov, [boxes, scores])
        b = self._run_fused(tn, [boxes, scores])
        tb, ts, tc, valid = b.tensors
        assert tb.shape == (2, tn.max_detections, 4)
        for f in range(2):
            dets = a.meta["detections"][f]
            assert int(valid[f].sum()) == len(dets)
            for i, d in enumerate(dets):
                np.testing.assert_allclose(tb[f, i], d["box"], atol=1e-6)

    def test_bbox_bad_option9(self):
        with pytest.raises(ValueError, match="option9"):
            BoundingBoxes({"option9": "pixels"})

    def test_pose_tensors_match_overlay_keypoints(self):
        k = 17
        hm = np.zeros((8, 8, k), np.float32)
        for i in range(k):
            hm[i % 8, (i * 3) % 8, i] = 1.0
        ov = PoseEstimation({"option2": "80:80"})
        tn = PoseEstimation({"option2": "80:80", "option4": "tensors"})
        a = ov.decode([hm], Buffer([hm]))
        b = tn.decode([hm], Buffer([hm]))
        px, py, sc = b.tensors
        assert px.shape == (k,)
        for j, kp in enumerate(a.meta["keypoints"]):
            assert px[j] == pytest.approx(kp["x"], abs=1e-4)
            assert py[j] == pytest.approx(kp["y"], abs=1e-4)
            assert sc[j] == pytest.approx(kp["score"], abs=1e-6)

    def test_pose_tensors_fused_batched(self):
        k = 17
        hm = np.zeros((3, 8, 8, k), np.float32)
        hm[:, 2, 4, :] = 1.0
        ov = PoseEstimation({"option2": "80:80"})
        tn = PoseEstimation({"option2": "80:80", "option4": "tensors"})
        a = self._run_fused(ov, [hm])
        b = self._run_fused(tn, [hm])
        px, py, sc = b.tensors
        assert px.shape == (3, k)
        for f in range(3):
            for j, kp in enumerate(a.meta["keypoints"][f]):
                assert px[f, j] == pytest.approx(kp["x"], abs=1e-4)
                assert py[f, j] == pytest.approx(kp["y"], abs=1e-4)

    def test_segment_classmap_matches_overlay_map(self):
        rng = np.random.default_rng(11)
        x = rng.random((16, 16, 7)).astype(np.float32)
        ov = ImageSegment({})
        cm = ImageSegment({"option1": "classmap"})
        a = ov.decode([x], Buffer([x]))
        b = cm.decode([x], Buffer([x]))
        assert b.tensors[0].dtype == np.uint8
        np.testing.assert_array_equal(b.tensors[0], a.meta["class_map"])

    def test_segment_classmap_fused_stays_u8(self):
        rng = np.random.default_rng(13)
        x = rng.random((2, 16, 16, 7)).astype(np.float32)
        ov = ImageSegment({})
        cm = ImageSegment({"option1": "classmap"})
        a = self._run_fused(ov, [x])
        b = self._run_fused(cm, [x])
        assert b.tensors[0].dtype == np.uint8
        np.testing.assert_array_equal(b.tensors[0], a.meta["class_map"])

    def test_detection_tensors_pipeline_e2e(self):
        """The bench topology end-to-end: fused device NMS + tensors
        output through a real pipeline."""
        p = nt.Pipeline(
            "videotestsrc device=true batch=4 num-buffers=8 width=64 "
            "height=64 pattern=ball name=src ! "
            "tensor_transform mode=arithmetic "
            "option=typecast:float32,add:-127.5,div:127.5 ! "
            "tensor_filter framework=jax model=ssd_mobilenet "
            "custom=size:64,classes:7,batch:4 name=f ! "
            "tensor_decoder mode=bounding_boxes option1=ssd option3=0.1 "
            "option4=64:64 option7=device option9=tensors ! "
            "tensor_sink name=out")
        with p:
            b = p.pull("out", timeout=300)
            p.wait(timeout=120)
        assert len(b.tensors) == 4  # boxes, scores, classes, valid
        assert b.tensors[0].shape[0] == 4  # batch rows
        assert b.tensors[0].shape[2] == 4

    def test_pose_tensors_batched_host_path(self):
        """Non-fused batched decode must carry all three tensors
        (px, py, score), not just x (r4 review finding)."""
        k = 17
        hm = np.zeros((3, 8, 8, k), np.float32)
        hm[:, 2, 4, :] = 1.0
        tn = PoseEstimation({"option2": "80:80", "option4": "tensors"})
        out = tn.decode([hm], Buffer([hm]))
        assert len(out.tensors) == 3
        assert out.tensors[0].shape == (3, k)
        assert out.tensors[2].shape == (3, k)
