"""tensor_query under concurrency + admission control (ISSUE 8).

The satellite the query elements never had: N clients x one server with
slow/failing clients, asserting the server-side backlog never grows past
its bound and EOS stays clean — plus the admission-control policies
(``shed`` / ``downgrade``) that turn backlog into an explicit decision
instead of unbounded queue growth (docs/SERVING.md "Front door").
"""

import threading
import time

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.log import metrics
from nnstreamer_tpu.core.types import TensorsSpec
from nnstreamer_tpu.elements.query import _ServerCore
from nnstreamer_tpu.filters.custom_easy import register_custom_easy
from nnstreamer_tpu.utils.tracing import recorder


@pytest.fixture(autouse=True)
def _state():
    metrics.reset()
    recorder.configure("off")
    recorder.clear()
    spec = TensorsSpec.from_string("4", "float32")
    register_custom_easy(
        "qs-double", lambda ins: [ins[0] * 2], in_spec=spec,
        out_spec=spec)

    def slow(ins):
        time.sleep(0.02)
        return [ins[0] * 2]

    register_custom_easy("qs-slow", slow, in_spec=spec, out_spec=spec)
    yield
    recorder.configure("off")
    recorder.clear()
    metrics.reset()


# -- core admission unit tests (deterministic, no races) --------------------

class TestServerCoreAdmission:
    def _core(self, admission, max_backlog=2):
        events = []
        core = _ServerCore("127.0.0.1", 0, max_backlog=max_backlog,
                           admission=admission,
                           on_admit_event=lambda k, b, n:
                           events.append((k, b.meta.get("_tenant"), n)))
        return core, events

    @staticmethod
    def _req(tenant=None, mid=0):
        b = Buffer([np.zeros((4,), np.float32)])
        b.meta["_query_msg"] = mid
        if tenant:
            b.meta["_tenant"] = tenant
        return b

    def test_shed_when_full_counts_per_tenant_and_notifies(self):
        core, events = self._core("shed", max_backlog=2)
        try:
            for i in range(2):
                core._admit(self._req("acme", i))
            assert core.inbound.qsize() == 2
            core._admit(self._req("acme", 2))  # full -> shed
            core._admit(self._req("bob", 3))   # full -> shed
            assert core.inbound.qsize() == 2  # bounded, never grew
            snap = metrics.snapshot()
            assert snap["query_server.shed"] == 2
            lab = metrics.labeled_counters()
            assert lab[("query_server.shed", "acme")] == 1
            assert lab[("query_server.shed", "bob")] == 1
            assert [e[0] for e in events] == ["shed", "shed"]
            assert {e[1] for e in events} == {"acme", "bob"}
        finally:
            core.close()

    def test_downgrade_uses_low_lane_then_sheds(self):
        core, events = self._core("downgrade", max_backlog=2)
        try:
            for i in range(2):
                core._admit(self._req("acme", i))
            core._admit(self._req("acme", 2))  # -> low lane
            core._admit(self._req("acme", 3))  # -> low lane
            core._admit(self._req("acme", 4))  # both full -> shed
            assert core.inbound.qsize() == 2
            assert core.lowprio.qsize() == 2
            snap = metrics.snapshot()
            assert snap["query_server.downgraded"] == 2
            assert snap["query_server.shed"] == 1
            assert [e[0] for e in events] == \
                ["downgrade", "downgrade", "shed"]
            # backlog gauge reads main + low lane
            assert metrics.gauges()["query_server.backlog"] == 4.0
        finally:
            core.close()

    def test_pop_request_drains_main_before_low_lane(self):
        core, _ = self._core("downgrade", max_backlog=1)
        try:
            core._admit(self._req("acme", 0))   # main
            core._admit(self._req("acme", 1))   # low lane
            first = core.pop_request(timeout=0.05)
            second = core.pop_request(timeout=0.05)
            assert first.meta["_query_msg"] == 0
            assert second.meta["_query_msg"] == 1
            assert core.pop_request(timeout=0.05) is None
        finally:
            core.close()

    def test_bad_admission_prop_rejected(self):
        from nnstreamer_tpu.elements.query import TensorQueryServerSrc

        with pytest.raises(Exception, match="admission"):
            TensorQueryServerSrc({"admission": "panic"})
        with pytest.raises(Exception, match="max-backlog"):
            TensorQueryServerSrc({"max_backlog": 0})


# -- integration: N clients, slow/failing clients, bounded backlog ----------

def test_many_clients_bounded_backlog_and_clean_eos():
    """6 concurrent clients x 20 requests against one server whose
    backlog is bounded at 8: every client gets every (correct, ordered)
    answer, the inbound queue structurally cannot exceed its bound, and
    every pipeline EOSes cleanly."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=60 max-backlog=8 ! "
        "tensor_filter framework=custom-easy model=qs-double ! "
        "tensor_query_serversink id=60")
    with srv:
        core = srv.element("ssrc")._core
        assert core.inbound.maxsize == 8
        port = srv.element("ssrc").bound_port
        peak = {"backlog": 0}
        stop_poll = threading.Event()

        def poll():
            while not stop_poll.wait(0.002):
                peak["backlog"] = max(peak["backlog"], core.backlog())

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        results = {}
        errors = []

        def client(cid):
            try:
                cli = nt.Pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "max-in-flight=16 timeout=20 ! tensor_sink name=out")
                with cli:
                    for i in range(20):
                        cli.push("src", np.full((4,), cid * 1000.0 + i,
                                                np.float32))
                    vals = [float(cli.pull("out", timeout=20).tensors[0][0])
                            for _ in range(20)]
                    cli.eos("src")
                    cli.wait(timeout=20)
                results[cid] = vals
            except Exception as e:  # noqa: BLE001
                errors.append((cid, e))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop_poll.set()
        poller.join(timeout=2)
        assert not errors, errors
        for cid in range(6):
            assert results[cid] == [2 * (cid * 1000.0 + i)
                                    for i in range(20)]
        assert peak["backlog"] <= 8


def test_slow_client_does_not_stall_fast_client():
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=61 ! "
        "tensor_filter framework=custom-easy model=qs-double ! "
        "tensor_query_serversink id=61")
    with srv:
        port = srv.element("ssrc").bound_port
        slow = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "max-in-flight=16 timeout=30 ! tensor_sink name=out")
        fast = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "timeout=30 ! tensor_sink name=out")
        with slow, fast:
            for i in range(10):
                slow.push("src", np.full((4,), float(i), np.float32))
            t0 = time.monotonic()
            for i in range(10):
                fast.push("src", np.full((4,), 100.0 + i, np.float32))
                out = fast.pull("out", timeout=10)
                np.testing.assert_allclose(out.tensors[0],
                                           np.full((4,), 2 * (100.0 + i)))
            fast_done = time.monotonic() - t0
            # the slow client now drains ITS responses, slowly
            for i in range(10):
                out = slow.pull("out", timeout=10)
                np.testing.assert_allclose(out.tensors[0],
                                           np.full((4,), 2.0 * i))
                time.sleep(0.01)
            assert fast_done < 8.0  # never waited behind the slow reader
            for c in (slow, fast):
                c.eos("src")
                c.wait(timeout=15)


def test_client_disconnect_under_load_isolated():
    """One of three clients tears down mid-flight (pushed but never
    pulled): survivors complete correctly and the server stays healthy
    for a NEW client afterwards."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=62 ! "
        "tensor_filter framework=custom-easy model=qs-slow ! "
        "tensor_query_serversink name=ssink id=62")
    with srv:
        port = srv.element("ssrc").bound_port

        def mk():
            return nt.Pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "max-in-flight=8 timeout=30 ! tensor_sink name=out")

        doomed, s1, s2 = mk(), mk(), mk()
        doomed.start(), s1.start(), s2.start()
        try:
            for i in range(6):
                doomed.push("src", np.full((4,), float(i), np.float32))
                s1.push("src", np.full((4,), 100.0 + i, np.float32))
                s2.push("src", np.full((4,), 200.0 + i, np.float32))
            doomed.stop()  # vanishes without pulling anything
            for i in range(6):
                np.testing.assert_allclose(
                    s1.pull("out", timeout=20).tensors[0],
                    np.full((4,), 2 * (100.0 + i)))
                np.testing.assert_allclose(
                    s2.pull("out", timeout=20).tensors[0],
                    np.full((4,), 2 * (200.0 + i)))
            for c in (s1, s2):
                c.eos("src")
                c.wait(timeout=20)
        finally:
            for c in (s1, s2, doomed):
                c.stop()
        late = mk()
        with late:
            late.push("src", np.full((4,), 7.0, np.float32))
            np.testing.assert_allclose(late.pull("out", timeout=20).tensors[0],
                                       np.full((4,), 14.0))
            late.eos("src")
            late.wait(timeout=20)


# -- integration: admission control over real sockets -----------------------

def test_admission_shed_under_backlog_answers_every_request():
    """A flooding client against admission=shed max-backlog=2: sheds
    happen, are counted per tenant, reach the client as shed notices
    (meta['shed']), completed+shed covers every request, and EOS is
    clean — the queue never grew past its bound."""
    n = 40
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=63 "
        "admission=shed max-backlog=2 ! "
        "tensor_filter framework=custom-easy model=qs-slow ! "
        "tensor_query_serversink id=63")
    with srv:
        port = srv.element("ssrc").bound_port
        core = srv.element("ssrc")._core
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client name=qc port={port} "
            "tenant=acme max-in-flight=32 timeout=30 ! tensor_sink "
            "name=out")
        with cli:
            for i in range(n):
                cli.push("src", np.full((4,), float(i), np.float32))
            served = shed = 0
            for _ in range(n):
                out = cli.pull("out", timeout=30)
                if out.meta.get("shed"):
                    shed += 1
                    assert len(out.tensors) == 0
                    assert out.meta.get("_tenant") == "acme"
                else:
                    served += 1
            cli.eos("src")
            cli.wait(timeout=30)
        assert served + shed == n
        assert shed >= 1  # overload really shed
        assert served >= 1  # and really served what fit
        assert core.inbound.qsize() == 0
        snap = metrics.snapshot()
        assert snap["query_server.shed"] == shed
        assert metrics.labeled_counters()[("query_server.shed", "acme")] \
            == shed
        assert snap["qc.sheds"] == shed


def test_admission_downgrade_still_answers_with_lane_capacity():
    """admission=downgrade: overflow beyond the main backlog rides the
    low-priority lane — downgraded requests are still ANSWERED (slower),
    nothing is shed while the lane has room."""
    n = 10
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=64 "
        "admission=downgrade max-backlog=4 ! "
        "tensor_filter framework=custom-easy model=qs-slow ! "
        "tensor_query_serversink id=64")
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "tenant=acme max-in-flight=16 timeout=30 ! tensor_sink "
            "name=out")
        with cli:
            for i in range(n):
                cli.push("src", np.full((4,), float(i), np.float32))
            outs = [cli.pull("out", timeout=30) for _ in range(n)]
            cli.eos("src")
            cli.wait(timeout=30)
        assert all(not o.meta.get("shed") for o in outs)
        # responses stay in request order (msg-id reorder) even when some
        # requests took the low-priority lane
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o.tensors[0],
                                       np.full((4,), 2.0 * i))
        snap = metrics.snapshot()
        assert snap.get("query_server.shed", 0) == 0


def test_shed_span_recorded_with_tenant_and_trace_id():
    """Every shed is span-stamped ``admit.shed`` carrying the victim's
    tenant and a trace id, on the SERVER pipeline's ring."""
    srv = nt.Pipeline(
        "tensor_query_serversrc name=ssrc port=0 id=65 "
        "admission=shed max-backlog=1 ! "
        "tensor_filter framework=custom-easy model=qs-slow ! "
        "tensor_query_serversink id=65", trace_mode="ring")
    with srv:
        port = srv.element("ssrc").bound_port
        cli = nt.Pipeline(
            f"appsrc name=src ! tensor_query_client port={port} "
            "tenant=acme max-in-flight=32 timeout=30 ! tensor_sink "
            "name=out")
        with cli:
            for i in range(30):
                cli.push("src", np.full((4,), float(i), np.float32))
            got = [cli.pull("out", timeout=30) for _ in range(30)]
            cli.eos("src")
            cli.wait(timeout=30)
    sheds = [e for e in recorder.events() if e.kind == "admit.shed"]
    assert sheds, "no admit.shed spans on the ring"
    assert sum(1 for o in got if o.meta.get("shed")) == len(sheds)
    for e in sheds:
        assert e.stage == "ssrc"
        assert e.tid is not None
        assert e.args["tenant"] == "acme"
        assert "backlog" in e.args
