"""Production sampling in the serve hot loop (docs/SERVING.md §4d).

The sampled decode path must behave like a PRODUCT feature, not a
demo knob:

* **Distribution**: speculative rejection sampling emits tokens
  distributed EXACTLY as the non-spec sampler — chi-squared here
  against the target marginal by driving the module-level
  ``spec_rejection_commit`` core directly (thousands of independent
  slot keys in ONE call, no serve loop needed).
* **Reproducibility**: a stream's sampled tokens are a pure function
  of (framework seed, admission number, absolute position) — two
  same-seed runs are bitwise identical, and batch composition
  (sequential vs concurrent admission) changes nothing.
* **Elasticity**: drain/adopt carries the slot's PRNG key in the
  snapshot, so a migrated sampled stream continues bit-identically.
* **Census**: the sampler adds ZERO programs — greedy and sampled
  loops share one signature (the key folds are dead code XLA drops at
  temperature 0), so the 3-program (non-spec) and 5-program (spec)
  zero-recompile pins hold with temperature > 0.
* **Traffic**: the fused verify commits on-device; the host reads back
  only the emitted rows + accept counts, and the per-round
  device->host set never contains the proposals or any re-upload.
"""

import collections
import threading

import numpy as np
import pytest

from nnstreamer_tpu.models import llama


def _fw(custom, model="llama_tiny"):
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": model, "custom": custom})
    return fw


def _serve_tokens(fw, prompts, timeout=300.0):
    got = {i: [] for i in range(len(prompts))}
    lock = threading.Lock()

    def emit_for(i):
        def emit(tensors, meta):
            with lock:
                got[i].append(int(tensors[0][0]))
        return emit

    for i, p in enumerate(prompts):
        fw.submit([p], {}, emit_for(i))
    assert fw.drain(timeout=timeout)
    return got


class Collector:
    def __init__(self):
        self.toks = []
        self.done = threading.Event()

    def __call__(self, tensors, meta):
        self.toks.append((int(tensors[0][0]) if len(tensors[0]) else -1,
                          dict(meta)))
        if meta.get("stream_last"):
            self.done.set()

    @property
    def ids(self):
        return [t for t, m in self.toks if t >= 0]

    @property
    def sid(self):
        return self.toks[0][1].get("stream_id") if self.toks else None


SAMPLED = ("max_new:8,stream_chunk:2,temperature:0.9,seed:5,"
           "dtype:float32,serve:continuous,slots:2,block_size:8,"
           "prefill_chunk:4")
SPEC = SAMPLED + ",draft:llama_tiny,spec_k:3,draft_seed:7"


# ---------------------------------------------------------------------------
# rejection sampling is distribution-exact (the §4d guarantee)
# ---------------------------------------------------------------------------

class TestRejectionSamplingDistribution:
    """Drive spec_rejection_commit with a known target/draft pair over
    thousands of independent slot keys and chi-square the emitted
    marginals against the TARGET distribution — the draft must steer
    speed, never the law.  Fixed seeds: deterministic, not flaky."""

    V, K, B = 8, 3, 20000
    CHI2_999 = 26.02  # chi-square df=7 critical value at p = 0.999

    def _run(self, pt_row, q_row, *, seed=7):
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.llm import spec_rejection_commit

        B, K, V = self.B, self.K, self.V
        pt = jnp.broadcast_to(jnp.asarray(pt_row, jnp.float32),
                              (B, K + 1, V))
        dprobs = jnp.broadcast_to(jnp.asarray(q_row, jnp.float32),
                                  (B, K, V))
        # proposals drawn FROM the draft distribution, as propose() does
        props = jax.random.categorical(
            jax.random.PRNGKey(seed + 1),
            jnp.log(jnp.asarray(q_row, jnp.float32)),
            shape=(B, K)).astype(jnp.int32)
        keys = np.asarray(jax.random.split(jax.random.PRNGKey(seed), B),
                          np.uint32)
        pos = jnp.asarray(np.arange(B) % 97 + 4, jnp.int32)
        live = jnp.ones((B,), bool)
        em, acc = spec_rejection_commit(pt, dprobs, props, keys, pos, live)
        return np.asarray(em), np.asarray(acc), np.asarray(props)

    def _chi2(self, draws, probs):
        counts = np.bincount(draws, minlength=self.V).astype(np.float64)
        expected = len(draws) * np.asarray(probs, np.float64)
        return float(((counts - expected) ** 2 / expected).sum())

    def test_mismatched_draft_still_emits_target_marginal(self):
        """Draft mass concentrated where the target's is thin: low
        accept rate, but position 0's emitted token (accepted proposal
        OR residual resample) must still be ~ pt."""
        pt_row = np.asarray([.30, .22, .16, .12, .08, .06, .04, .02])
        q_row = pt_row[::-1].copy()  # adversarially misaligned
        em, acc, _ = self._run(pt_row, q_row)
        assert self._chi2(em[:, 0], pt_row) < self.CHI2_999
        # the mismatch must actually exercise the rejection path
        assert 0.05 < float((acc > 0).mean()) < 0.95

    def test_matched_draft_accepts_everything(self):
        """q == p: u*q < p is u < 1, always true — every proposal
        accepts, em carries the proposals verbatim, and the bonus
        column (position k) is itself a clean target draw."""
        pt_row = np.asarray([.30, .22, .16, .12, .08, .06, .04, .02])
        em, acc, props = self._run(pt_row, pt_row)
        assert (acc == self.K).all()
        assert np.array_equal(em[:, :self.K], props)
        assert self._chi2(em[:, self.K], pt_row) < self.CHI2_999

    def test_parked_rows_commit_nothing(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.llm import spec_rejection_commit

        pt_row = np.full((self.V,), 1.0 / self.V)
        em, acc, _ = self._run(pt_row, pt_row)
        # same inputs with every row parked: acc pinned to 0
        import jax

        pt = jnp.broadcast_to(jnp.asarray(pt_row, jnp.float32),
                              (4, self.K + 1, self.V))
        dprobs = pt[:, :self.K]
        props = jnp.zeros((4, self.K), jnp.int32)
        keys = np.asarray(jax.random.split(jax.random.PRNGKey(0), 4),
                          np.uint32)
        pos = jnp.full((4,), 9, jnp.int32)
        _, acc0 = spec_rejection_commit(
            pt, dprobs, props, keys, pos, jnp.zeros((4,), bool))
        assert (np.asarray(acc0) == 0).all()


# ---------------------------------------------------------------------------
# seeded reproducibility: position-keyed draws
# ---------------------------------------------------------------------------

class TestSeededReproducibility:
    def _prompts(self):
        rng = np.random.default_rng(31)
        return [rng.integers(1, 500, (t,), np.int32) for t in (3, 6)]

    @pytest.mark.parametrize("custom", [SAMPLED, SPEC],
                             ids=["plain", "spec"])
    def test_two_runs_bitwise_identical(self, custom):
        pa, pb = self._prompts()
        runs = []
        for _ in range(2):
            fw = _fw(custom)
            try:
                runs.append(_serve_tokens(fw, [pa, pb]))
            finally:
                fw.close()
        assert runs[0] == runs[1]
        assert len(runs[0][0]) == 8  # it actually decoded

    def test_seed_changes_the_stream(self):
        pa, pb = self._prompts()
        fw = _fw(SAMPLED)
        try:
            base = _serve_tokens(fw, [pa, pb])
        finally:
            fw.close()
        fw = _fw(SAMPLED.replace("seed:5", "seed:6"))
        try:
            other = _serve_tokens(fw, [pa, pb])
        finally:
            fw.close()
        assert base != other

    @pytest.mark.parametrize("custom", [SAMPLED, SPEC],
                             ids=["plain", "spec"])
    def test_batch_composition_independence(self, custom):
        """Tokens are keyed by (slot key, absolute position), NOT by
        decode-round batch state: admitting the two prompts together
        (concurrent rounds) and one after the other (solo rounds) emits
        identical streams — admission ORDER fixes the slot keys."""
        pa, pb = self._prompts()
        fw = _fw(custom)
        try:
            together = _serve_tokens(fw, [pa, pb])
        finally:
            fw.close()
        fw = _fw(custom)
        try:
            solo_a = _serve_tokens(fw, [pa])[0]
            solo_b = _serve_tokens(fw, [pb])[0]
        finally:
            fw.close()
        assert together[0] == solo_a
        assert together[1] == solo_b


# ---------------------------------------------------------------------------
# drain/adopt carries the slot PRNG
# ---------------------------------------------------------------------------

class TestSampledDrainAdopt:
    def test_sampled_stream_migrates_bit_identically(self):
        prompt = np.asarray([3, 5, 7, 9], np.int32)
        ref_c = Collector()
        fw_ref = _fw(SAMPLED)
        fw_ref.submit([prompt], {}, ref_c)
        assert ref_c.done.wait(120)
        ref = ref_c.ids

        fw_a, fw_b = _fw(SAMPLED), _fw(SAMPLED)
        got = Collector()
        seen3 = threading.Event()

        def emit_a(tensors, meta):
            got(tensors, meta)
            if len(got.toks) >= 3:
                seen3.set()

        fw_a.submit([prompt], {}, emit_a)
        assert seen3.wait(120)
        snap = fw_a.drain_stream(got.sid, timeout=60)
        assert snap["kind"] == "live" and snap["greedy"] is False
        # the slot's key rides the snapshot — the §4d migration contract
        assert len(snap["prng_key"]) == 2

        cont = Collector()
        fw_b.adopt_stream(snap, cont)
        assert cont.done.wait(120)
        assert got.ids[:snap["sidx"]] + cont.ids == ref, \
            (got.ids[:snap["sidx"]], cont.ids, ref)
        for fw in (fw_ref, fw_a, fw_b):
            fw.close()


# ---------------------------------------------------------------------------
# census: the sampler adds zero programs
# ---------------------------------------------------------------------------

class TestSampledCensus:
    def test_three_program_pin_nonspec(self):
        from nnstreamer_tpu.filters.llm import serving_plan

        plan = serving_plan(llama.PRESETS["llama_tiny"], slots=2,
                            block_size=8, prefill_chunk=4,
                            dtype="float32", temperature=0.9)
        assert plan["programs"] == 3
        assert plan["prng_state_bytes"] == 2 * 2 * 4
        rng = np.random.default_rng(40)
        fw = _fw(SAMPLED)
        try:
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32)])
            serve = fw._serve
            warm = {n: getattr(serve, n)._cache_size()
                    for n in ("_decode", "_prefill", "_set_tok")}
            assert warm == {"_decode": 1, "_prefill": 1, "_set_tok": 1}
            _serve_tokens(fw, [rng.integers(1, 500, (t,), np.int32)
                               for t in (1, 5, 7)])
            after = {n: getattr(serve, n)._cache_size()
                     for n in ("_decode", "_prefill", "_set_tok")}
            assert after == warm, f"sampler recompiled: {warm}->{after}"
        finally:
            fw.close()

    def test_five_program_pin_spec(self):
        rng = np.random.default_rng(41)
        fw = _fw(SPEC)
        try:
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32)])
            serve = fw._serve
            names = ("_prefill", "_set_tok", "_draft_prefill",
                     "_propose", "_verify")
            warm = {n: getattr(serve, n)._cache_size() for n in names}
            assert warm == {n: 1 for n in names}, warm
            assert serve._decode._cache_size() == 0
            _serve_tokens(fw, [rng.integers(1, 500, (t,), np.int32)
                               for t in (1, 5, 9)])
            after = {n: getattr(serve, n)._cache_size() for n in names}
            assert after == warm, f"sampler recompiled: {warm}->{after}"
            assert serve._decode._cache_size() == 0
        finally:
            fw.close()


# ---------------------------------------------------------------------------
# fused verify: host round-trip budget
# ---------------------------------------------------------------------------

class TestVerifyTransferBudget:
    def test_proposals_never_leave_the_device(self, monkeypatch):
        """The fused verify commits tok/tok_prev/positions in-program;
        the ONLY per-round device->host reads are the emitted rows
        [slots, k+1] and the accept counts [slots].  In particular the
        [slots, k] proposals — which the pre-fusion loop downloaded to
        run host-side acceptance — must never be fetched, and nothing
        batch-shaped is re-uploaded through the slot-token setter
        during steady decode."""
        import jax

        from nnstreamer_tpu.filters import llm as llm_mod

        real_np = llm_mod.np
        xfer = collections.Counter()

        class NpProxy:
            def __getattr__(self, name):
                val = getattr(real_np, name)
                if name == "asarray":
                    def asarray(a, *args, **kw):
                        if isinstance(a, jax.Array):
                            xfer[(tuple(a.shape), str(a.dtype))] += 1
                        return val(a, *args, **kw)
                    return asarray
                return val

        monkeypatch.setattr(llm_mod, "np", NpProxy())
        rng = np.random.default_rng(50)
        fw = _fw(SPEC)  # slots:2, spec_k:3
        try:
            # the loop is lazily built on first submit — force it now so
            # the counting wrapper is in place before ANY admission
            fw._serve = llm_mod._ContinuousLoop(fw)
            set_tok_calls = []
            real_set = fw._serve._set_tok

            def counting_set(*a, **kw):
                set_tok_calls.append(1)
                return real_set(*a, **kw)

            counting_set._cache_size = real_set._cache_size
            fw._serve._set_tok = counting_set
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32),
                               rng.integers(1, 500, (5,), np.int32)])
            admission_set_calls = len(set_tok_calls)
            em, acc = ((2, 4), "int32"), ((2,), "int32")
            # [slots, k] proposals never crossed to host
            assert ((2, 3), "int32") not in xfer, dict(xfer)
            # emitted rows + accept counts did — ONE pair per verify
            # round, plus the warmup round's emitted rows (its accept
            # count is discarded on device); the only other transfers
            # are the (2,)-uint32 PRNG key mints at init/admission
            assert xfer[acc] >= 2, dict(xfer)
            assert xfer[em] == xfer[acc] + 1, dict(xfer)
        finally:
            fw.close()
        # _set_tok traffic is per-EVENT (admission/retire), not
        # per-round: decoding 4x more tokens adds zero calls
        fw = _fw(SPEC.replace("max_new:8", "max_new:32"))
        try:
            fw._serve = llm_mod._ContinuousLoop(fw)
            set_tok_calls2 = []
            real_set2 = fw._serve._set_tok

            def counting_set2(*a, **kw):
                set_tok_calls2.append(1)
                return real_set2(*a, **kw)

            counting_set2._cache_size = real_set2._cache_size
            fw._serve._set_tok = counting_set2
            _serve_tokens(fw, [rng.integers(1, 500, (3,), np.int32),
                               rng.integers(1, 500, (5,), np.int32)])
            assert len(set_tok_calls2) == admission_set_calls
        finally:
            fw.close()
