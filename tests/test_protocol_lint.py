"""nns-proto tests: golden bad fixtures for the protocol lint (exact
diagnostic code + caret position), the unanswered-path fixpoint proof,
the bounded model checker (clean shipped models, mutated models with
counterexample traces), the model-vs-code alphabet drift gate, a clean
dogfood pass over the shipped protocol modules, the fixed true
positives in elements/query.py, and the jax-free import pin
(docs/ANALYSIS.md "Protocol pass")."""

import os
import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu.analysis import protocol, statemachine
from nnstreamer_tpu.analysis.diagnostics import ERROR, WARNING
from nnstreamer_tpu.core import meta_keys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_fixture(tmp_path, source, name="fix.py", registry=None,
                  drift=False):
    p = tmp_path / name
    p.write_text(source)
    reports, stats = protocol.lint_paths(
        [str(p)], root=str(tmp_path), registry=registry, drift_gate=drift)
    diags = [d for rep in reports for d in rep.diagnostics]
    return reports, diags, stats


# ---------------------------------------------------------------------------
# meta-key-drift: unregistered literal in a meta context, caret on the key
# ---------------------------------------------------------------------------

DRIFT = '''\
def stamp(buf):
    buf.meta["_totally_new_key"] = 1
'''


def test_meta_key_drift_detected(tmp_path):
    reports, diags, _ = _lint_fixture(tmp_path, DRIFT)
    assert [d.code for d in diags] == ["meta-key-drift"]
    d = diags[0]
    assert d.severity == ERROR
    assert "_totally_new_key" in d.message
    # caret lands exactly on the key literal
    assert DRIFT[d.pos:d.pos + len('"_totally_new_key"')] \
        == '"_totally_new_key"'


def test_registered_key_is_clean(tmp_path):
    src = ('from nnstreamer_tpu.core.meta_keys import META_SHED\n'
           'def stamp(buf):\n'
           '    buf.meta[META_SHED] = True\n'
           'def read(buf):\n'
           '    return buf.meta.get(META_SHED)\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == []


def test_control_kind_drift(tmp_path):
    src = 'def hello():\n    return {"type": "teleport", "proto": 2}\n'
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["meta-key-drift"]
    assert "control kind 'teleport'" in diags[0].message


def test_abort_reason_drift(tmp_path):
    src = ('def abort(buf):\n'
           '    buf.meta["abort_reason"] = "cosmic_ray"\n'
           '    buf.meta["stream_aborted"] = True\n'
           '    buf.meta.get("abort_reason")\n'
           '    buf.meta.get("stream_aborted")\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["meta-key-drift"]
    assert "abort reason 'cosmic_ray'" in diags[0].message


# ---------------------------------------------------------------------------
# handler totality: sent-but-unhandled / handled-but-unsent
# ---------------------------------------------------------------------------

def test_unhandled_message(tmp_path):
    src = ('def stamp(buf):\n'
           '    buf.meta["shed"] = True\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    codes = {d.code for d in diags}
    assert codes == {"unhandled-message"}
    d = [d for d in diags if d.code == "unhandled-message"][0]
    assert d.severity == ERROR and "'shed'" in d.message


def test_dead_handler(tmp_path):
    src = ('def read(buf):\n'
           '    return buf.meta.get("wire_reject")\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert [d.code for d in diags] == ["dead-handler"]
    assert diags[0].severity == WARNING


def test_external_keys_exempt_from_totality(tmp_path):
    # _tq is stamped by the runtime outside the protocol modules: a
    # lone read must not be a dead-handler
    src = ('def read(buf):\n'
           '    return buf.meta.pop("_tq", None)\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert diags == []


# ---------------------------------------------------------------------------
# unanswered-path: the fixpoint call-proof
# ---------------------------------------------------------------------------

UNANSWERED = '''\
def handle_request(core, metrics, buf):
    mid = buf.meta.get("_query_msg")
    if mid is None:
        metrics.count("server.dropped")
        return
    if not core.ready:
        return            # strands the client: armed, no answer
    core.send(mid, b"ok")
'''


def _paths(diags):
    return [d for d in diags if d.code == "unanswered-path"]


def test_unanswered_path_detected(tmp_path):
    reports, diags, stats = _lint_fixture(tmp_path, UNANSWERED)
    diags = _paths(diags)
    assert [d.code for d in diags] == ["unanswered-path"]
    d = diags[0]
    assert d.severity == ERROR and "handle_request" in d.path
    # caret on the bad return (line 7), not the accounted drop above it
    line = UNANSWERED[:d.pos].count("\n") + 1
    assert line == 7
    assert stats["handlers"] == 1 and stats["proven"] == 0


def test_pre_arming_exit_is_exempt(tmp_path):
    src = ('def handle_request(core, buf):\n'
           '    if core is None:\n'
           '        raise RuntimeError("no core")\n'
           '    mid = buf.meta.get("_query_msg")\n'
           '    core.send(mid, b"ok")\n')
    _, diags, stats = _lint_fixture(tmp_path, src)
    assert _paths(diags) == [] and stats["proven"] == 1


def test_accounted_drop_answers(tmp_path):
    src = ('def handle_request(metrics, buf):\n'
           '    mid = buf.meta.get("_query_msg")\n'
           '    if mid is None:\n'
           '        metrics.count("server.dropped")\n'
           '        return\n'
           '    buf.reply(mid)\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert _paths(diags) == []


def test_fixpoint_proves_local_helper(tmp_path):
    # handle_* answers only through a local helper, which itself
    # answers on every path — the fixpoint must prove the chain
    src = ('def _finish(core, mid):\n'
           '    if core.up:\n'
           '        core.send(mid, b"ok")\n'
           '    else:\n'
           '        core.send(mid, b"down")\n'
           '\n'
           'def handle_request(core, buf):\n'
           '    mid = buf.meta.get("_query_msg")\n'
           '    return _finish(core, mid)\n')
    _, diags, stats = _lint_fixture(tmp_path, src)
    assert _paths(diags) == [] and stats["proven"] == 1


def test_loop_body_answering_covers_batch(tmp_path):
    src = ('def handle_batch(core, buf):\n'
           '    rows = buf.meta["_query_batch"]\n'
           '    for m in rows:\n'
           '        core.send(m, b"ok")\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert _paths(diags) == []


def test_raise_after_arming_detected(tmp_path):
    src = ('def handle_request(core, buf):\n'
           '    mid = buf.meta.get("_query_msg")\n'
           '    raise RuntimeError("boom")\n')
    diags = _paths(_lint_fixture(tmp_path, src)[1])
    assert [d.code for d in diags] == ["unanswered-path"]
    assert "raise" in diags[0].message


def test_broad_except_guard_absorbs_raise(tmp_path):
    src = ('def handle_request(core, buf):\n'
           '    mid = buf.meta.get("_query_msg")\n'
           '    try:\n'
           '        if core.bad:\n'
           '            raise RuntimeError("boom")\n'
           '        core.send(mid, b"ok")\n'
           '    except Exception as e:\n'
           '        core.abort_request(mid, e)\n'
           '        raise\n')
    _, diags, _ = _lint_fixture(tmp_path, src)
    assert _paths(diags) == []


# ---------------------------------------------------------------------------
# the shipped models verify; known-bad mutations produce counterexamples
# ---------------------------------------------------------------------------

def test_shipped_models_verify_under_faults():
    for name, factory in statemachine.SHIPPED_MODELS.items():
        res = statemachine.check(factory())
        assert res.ok, f"{name}: {res.violation.render()}"
        assert res.states > 10, name


@pytest.mark.parametrize("factory,prop", [
    (lambda: statemachine.exactly_once_model(client_dedupe=False),
     "answered-at-most-once"),
    (lambda: statemachine.exactly_once_model(resend=False),
     "deadlock"),
    (lambda: statemachine.handover_model(adopt_guard=False),
     "no-duplicate-stream"),
    # never releasing source HBM blocks wedges the handover: the
    # all-done accepting state becomes unreachable (liveness, not a
    # safety invariant — the blocks are leaked, not double-used)
    (lambda: statemachine.handover_model(release_on_drain=False),
     "deadlock"),
    (lambda: statemachine.quarantine_model(dlq_guard=False),
     "quarantined-never-relive"),
    (lambda: statemachine.hysteresis_model(honor_cooldown=False),
     "no-flip-inside-cooldown"),
    # without the outstanding-probe dedup a duplicated clock_ack
    # double-applies one probe's offset sample
    (lambda: statemachine.weave_clock_model(dedup_guard=False),
     "applies-bounded-by-probes"),
])
def test_mutated_model_yields_counterexample(factory, prop):
    res = statemachine.check(factory())
    assert not res.ok
    assert prop in res.violation.prop or prop == res.violation.kind
    # the trace is a real executable path: non-empty, rendered with
    # rule names and the violating state
    assert res.violation.trace
    rendered = res.violation.render()
    assert "trace" in rendered.lower() or "->" in rendered


# ---------------------------------------------------------------------------
# model-vs-code alphabet drift gate
# ---------------------------------------------------------------------------

FIXTURE_REGISTRY = '''\
META_KV_XFER = "_kv_xfer"
PROTOCOL_META_KEYS = frozenset({META_KV_XFER})
CONTROL_TYPES = frozenset({"hello", "ack", "nack"})
ABORT_REASONS = frozenset({"wire"})
EXTERNAL_META_KEYS = frozenset(set())
'''


def test_alphabet_drift_gate_fails_on_unmodelled_kind(tmp_path):
    # a new registered message kind used by code but absent from every
    # shipped model's declared alphabet must fail the gate
    (tmp_path / "core").mkdir()
    (tmp_path / "core" / "meta_keys.py").write_text(FIXTURE_REGISTRY)
    src = ('def move(buf):\n'
           '    buf.meta["_kv_xfer"] = 1\n'
           'def recv(buf):\n'
           '    return buf.meta.get("_kv_xfer")\n')
    reg = protocol.load_registry(str(tmp_path))
    assert reg.meta_keys == {"_kv_xfer"}
    _, diags, _ = _lint_fixture(tmp_path, src, registry=reg, drift=True)
    drift = [d for d in diags if d.code == "model-alphabet-drift"]
    assert len(drift) == 1 and drift[0].severity == ERROR
    assert "_kv_xfer" in drift[0].message


def test_shipped_alphabet_matches_code_exactly():
    # the dogfood drift gate: zero drift, zero surplus
    reports, stats = protocol.lint_package()
    diags = [d for rep in reports for d in rep.diagnostics]
    assert [d for d in diags if "alphabet" in d.code] == []
    assert stats["models"] == len(statemachine.SHIPPED_MODELS) == 5


# ---------------------------------------------------------------------------
# dogfood: the shipped protocol modules are clean (nothing baselined)
# ---------------------------------------------------------------------------

def test_dogfood_clean():
    reports, stats = protocol.lint_package()
    errors = [d for rep in reports for d in rep.diagnostics
              if d.severity == ERROR]
    assert errors == []
    # the one dogfood handler (TensorQueryServerSink.process) is PROVEN
    # all-paths-answering, not merely unflagged
    assert stats["handlers"] == 1 and stats["proven"] == 1


def test_cli_proto_gate():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.tools.lint", "--proto",
         "--strict", "--baseline",
         os.path.join(REPO, "tools", "proto_baseline.txt")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "proto:" in r.stdout


# ---------------------------------------------------------------------------
# the fixed true positives in elements/query.py stay fixed
# ---------------------------------------------------------------------------

def _make_sink():
    from nnstreamer_tpu.elements import query

    sink = object.__new__(query.TensorQueryServerSink)
    sink.name = "qsink"
    return sink, query


class _FakeCore:
    def __init__(self, fail_sends=0):
        self.sent = []
        self.journal = None
        self._fail = fail_sends

    def send(self, cid, payload):
        if self._fail > 0:
            self._fail -= 1
            raise RuntimeError("socket torn")
        self.sent.append((cid, payload))
        return True


def test_batch_leading_violation_answers_before_raising():
    # regression for the unanswered-path true positive: a non-batch-
    # leading model output must answer every batched client with a
    # typed internal abort, not strand them into timeouts
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.utils import wire

    sink, query = _make_sink()
    core = _FakeCore()
    buf = Buffer([np.zeros((1, 4), dtype=np.float32)], meta={
        query._META_BATCH: [
            {query._META_CONN: 1, query._META_MSG: 10},
            {query._META_CONN: 2, query._META_MSG: 11},
        ]})
    with pytest.raises(Exception, match="batch-leading"):
        sink._send_batched(core, buf)
    assert len(core.sent) == 2
    for (cid, payload), mid in zip(core.sent, (10, 11)):
        term, _flags = wire.decode_buffer(payload)
        assert term.meta[meta_keys.META_QUERY_MSG] == mid
        assert term.meta[meta_keys.META_STREAM_ABORTED] is True
        assert term.meta[meta_keys.META_ABORT_REASON] \
            == meta_keys.ABORT_REASON_INTERNAL
        assert "batch-leading" in term.meta[meta_keys.META_ERROR]


def test_process_guard_aborts_on_unexpected_exception(monkeypatch):
    # the broad guard in process: an exception mid-processing answers
    # the routed client with abort_reason="internal" then re-raises
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.utils import wire

    sink, query = _make_sink()
    core = _FakeCore(fail_sends=1)  # the real send blows up...
    monkeypatch.setattr(query, "_get_server", lambda sid: core)
    sink.sid = 0
    buf = Buffer([np.zeros((4,), dtype=np.float32)],
                 meta={query._META_CONN: 3, query._META_MSG: 42})
    with pytest.raises(RuntimeError, match="socket torn"):
        sink.process(None, buf)
    # ...and the guard's typed abort is the second send
    assert len(core.sent) == 1
    term, _flags = wire.decode_buffer(core.sent[0][1])
    assert term.meta[meta_keys.META_QUERY_MSG] == 42
    assert term.meta[meta_keys.META_ABORT_REASON] \
        == meta_keys.ABORT_REASON_INTERNAL


# ---------------------------------------------------------------------------
# jax-free pin: the analysis side must import (and run) without jax
# ---------------------------------------------------------------------------

def test_protocol_pass_is_jax_free():
    code = (
        "import sys\n"
        "from nnstreamer_tpu.analysis import protocol, statemachine\n"
        "reports, stats = protocol.lint_package()\n"
        "res = statemachine.check(statemachine.quarantine_model())\n"
        "assert res.ok\n"
        "assert 'jax' not in sys.modules, 'protocol pass imported jax'\n"
        "print('jaxfree-ok', stats['files'], res.states)\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "jaxfree-ok" in r.stdout
