"""Routing element tests (reference analogs: tests/nnstreamer_mux, _demux,
_merge, _split, _if, _aggregator, _repo SSAT suites)."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.elements.aggregator import TensorAggregator
from nnstreamer_tpu.elements.cond import TensorIf, register_if_condition
from nnstreamer_tpu.elements.crop import TensorCrop
from nnstreamer_tpu.elements.repo import reset_slots
from nnstreamer_tpu.elements.routing import TensorDemux, TensorMerge, TensorMux, TensorSplit
from nnstreamer_tpu.elements.sparse import sparse_decode_array, sparse_encode_array


class TestMuxDemux:
    def test_mux_groups(self):
        m = TensorMux()
        m.configure({}, ["src"])
        a = Buffer([np.ones((2, 2), np.float32)], pts=10)
        b = Buffer([np.zeros((3,), np.int32)], pts=20)
        outs = m.process_group({"sink_0": a, "sink_1": b})
        assert len(outs) == 1
        buf = outs[0][1]
        assert len(buf.tensors) == 2
        assert buf.pts == 20  # slowest

    def test_demux_pick(self):
        d = TensorDemux({"tensorpick": "1"})
        d.configure({}, ["src_0"])
        buf = Buffer([np.zeros(2), np.ones(3), np.full(4, 2.0)])
        outs = d.process("sink", buf)
        assert len(outs) == 1
        np.testing.assert_array_equal(outs[0][1].tensors[0], np.ones(3))

    def test_demux_all(self):
        d = TensorDemux()
        d.configure({}, ["src_0", "src_1"])
        buf = Buffer([np.zeros(2), np.ones(3)])
        outs = d.process("sink", buf)
        assert [o[0] for o in outs] == ["src_0", "src_1"]

    def test_mux_pipeline_e2e(self):
        p = nt.Pipeline(
            "tensor_mux name=m ! tensor_sink name=out "
            "videotestsrc num-buffers=2 width=4 height=4 ! tensor_converter ! m.sink_0 "
            "videotestsrc num-buffers=2 width=2 height=2 ! tensor_converter ! m.sink_1"
        )
        with p:
            b = p.pull("out", timeout=10)
            p.wait(timeout=10)
        assert len(b.tensors) == 2
        assert b.tensors[0].shape == (1, 4, 4, 3)
        assert b.tensors[1].shape == (1, 2, 2, 3)


class TestMergeSplit:
    def test_merge_linear(self):
        m = TensorMerge({"option": 0})
        m.configure({}, ["src"])
        a = Buffer([np.ones((2, 3), np.float32)])
        b = Buffer([np.zeros((2, 2), np.float32)])
        outs = m.process_group({"sink_0": a, "sink_1": b})
        out = outs[0][1].tensors[0]
        assert out.shape == (2, 5)  # concat along innermost dim (numpy last axis)

    def test_split(self):
        s = TensorSplit({"tensorseg": "2,3", "dim": 0})
        s.configure({}, ["src_0", "src_1"])
        buf = Buffer([np.arange(10, dtype=np.float32).reshape(2, 5)])
        outs = s.process("sink", buf)
        assert outs[0][1].tensors[0].shape == (2, 2)
        assert outs[1][1].tensors[0].shape == (2, 3)
        np.testing.assert_array_equal(outs[0][1].tensors[0], [[0, 1], [5, 6]])

    def test_split_size_mismatch(self):
        s = TensorSplit({"tensorseg": "2,2", "dim": 0})
        s.configure({}, ["src_0", "src_1"])
        with pytest.raises(Exception):
            s.process("sink", Buffer([np.zeros((2, 5), np.float32)]))

    def test_merge_split_roundtrip_pipeline(self):
        p = nt.Pipeline(
            "appsrc name=src ! tensor_split tensorseg=2,2 dim=1 name=sp "
            "sp.src_0 ! tensor_sink name=a "
            "sp.src_1 ! tensor_sink name=b"
        )
        with p:
            x = np.arange(16, dtype=np.float32).reshape(4, 4)
            p.push("src", x)
            ta = p.pull("a", timeout=10).tensors[0]
            tb = p.pull("b", timeout=10).tensors[0]
        np.testing.assert_array_equal(np.concatenate([ta, tb], axis=0), x)


class TestTee:
    def test_tee_pipeline(self):
        p = nt.Pipeline(
            "videotestsrc num-buffers=2 width=4 height=4 ! tensor_converter ! "
            "tee name=t t. ! tensor_sink name=a t. ! tensor_sink name=b"
        )
        with p:
            a = p.pull("a", timeout=10)
            b = p.pull("b", timeout=10)
            p.wait(timeout=10)
        np.testing.assert_array_equal(a.tensors[0], b.tensors[0])


class TestIf:
    def test_average_gate(self):
        f = TensorIf(
            {
                "compared_value": "TENSOR_AVERAGE_VALUE",
                "compared_value_option": "0",
                "operator": "GT",
                "supplied_value": "10",
                "then": "PASSTHROUGH",
                "else": "SKIP",
            }
        )
        f.configure({}, ["src"])
        hi = Buffer([np.full((4,), 20.0, np.float32)])
        lo = Buffer([np.full((4,), 5.0, np.float32)])
        assert len(f.process("sink", hi)) == 1
        assert len(f.process("sink", lo)) == 0

    def test_range_and_pick(self):
        f = TensorIf(
            {
                "compared_value": "A_VALUE",
                "compared_value_option": "0:0",
                "operator": "RANGE_INCLUSIVE",
                "supplied_value": "2:8",
                "then": "TENSORPICK",
                "then_option": "1",
            }
        )
        f.configure({}, ["src"])
        buf = Buffer([np.array([5.0]), np.array([42.0])])
        outs = f.process("sink", buf)
        assert len(outs) == 1
        np.testing.assert_array_equal(outs[0][1].tensors[0], [42.0])

    def test_custom_condition(self):
        register_if_condition("always-no", lambda arrays: False)
        f = TensorIf({"custom": "always-no", "then": "PASSTHROUGH", "else": "SKIP"})
        f.configure({}, ["src"])
        assert f.process("sink", Buffer([np.ones(3)])) == []


class TestAggregator:
    def test_window(self):
        agg = TensorAggregator({"frames_in": 1, "frames_out": 3, "frames_dim": 1})
        agg.configure({}, ["src"])
        outs = []
        for i in range(5):
            outs += agg.process("sink", Buffer([np.full((1, 2), i, np.float32)]))
        # windows: [0,1,2] then [3,4,...] incomplete -> 1 output
        assert len(outs) == 1
        assert outs[0][1].tensors[0].shape == (3, 2)

    def test_sliding(self):
        agg = TensorAggregator(
            {"frames_in": 1, "frames_out": 2, "frames_flush": 1, "frames_dim": 1}
        )
        agg.configure({}, ["src"])
        outs = []
        for i in range(4):
            outs += agg.process("sink", Buffer([np.full((1, 1), i, np.float32)]))
        # sliding windows: [0,1],[1,2],[2,3]
        assert len(outs) == 3
        np.testing.assert_array_equal(
            outs[1][1].tensors[0].ravel(), [1, 2]
        )


class TestCrop:
    def test_crop_regions(self):
        c = TensorCrop()
        c.configure({}, ["src"])
        raw = Buffer([np.arange(16 * 16 * 3, dtype=np.uint8).reshape(1, 16, 16, 3)])
        info = Buffer([np.array([[2, 3, 4, 5]], np.uint32)])
        outs = c.process_group({"sink_0": raw, "sink_1": info})
        crop = outs[0][1].tensors[0]
        assert crop.shape == (5, 4, 3)


class TestSparse:
    def test_roundtrip(self, rng):
        x = np.zeros((8, 8), np.float32)
        x[2, 3] = 1.5
        x[7, 0] = -2.0
        blob = sparse_encode_array(x)
        assert blob.nbytes < x.nbytes  # actually compresses sparse data
        y = sparse_decode_array(blob)
        np.testing.assert_array_equal(x, y)

    def test_pipeline_roundtrip(self):
        p = nt.Pipeline(
            "appsrc name=src ! tensor_sparse_enc ! tensor_sparse_dec ! "
            "tensor_sink name=out"
        )
        with p:
            x = np.zeros((4, 4), np.int32)
            x[1, 1] = 7
            p.push("src", x)
            out = p.pull("out", timeout=10)
        np.testing.assert_array_equal(out.tensors[0], x)


class TestRepoLoop:
    def test_recurrence(self):
        reset_slots()
        # loop: reposrc emits zeros then feeds back filter output (x+1)
        from nnstreamer_tpu.core.types import TensorsSpec
        from nnstreamer_tpu.filters.custom_easy import register_custom_easy

        spec = TensorsSpec.from_string("4", "float32")
        register_custom_easy("inc", lambda ins: [ins[0] + 1], spec, spec)
        p = nt.Pipeline(
            "tensor_reposrc slot-name=loop init-dims=4 init-type=float32 num-buffers=5 ! "
            "tensor_filter framework=custom-easy model=inc ! tee name=t "
            "t. ! tensor_reposink slot-name=loop "
            "t. ! tensor_sink name=out",
            fuse=False,
        )
        with p:
            vals = [p.pull("out", timeout=10).tensors[0][0] for _ in range(5)]
        assert vals == [1.0, 2.0, 3.0, 4.0, 5.0]


class TestMuxSyncModes:
    """Reference gsttensor_mux.c sync-mode=basepad/refresh semantics under
    uneven input rates (VERDICT r1 item #5)."""

    def _push(self, m, pad, val, pts):
        return m.process(pad, Buffer([np.full((2,), val, np.float32)], pts=pts))

    def test_basepad_base_drives(self):
        m = TensorMux({"sync_mode": "basepad", "sync_option": "0"})
        m.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()}, ["src"])
        # base pad arrives first: held until pad 1's first buffer (the
        # reference queues it in collectpads rather than dropping it)
        assert self._push(m, "sink_0", 1.0, 10) == []
        # pad 1's first buffer releases the held base buffer
        outs = self._push(m, "sink_1", 9.0, 12)
        assert len(outs) == 1
        buf = outs[0][1]
        assert buf.pts == 10  # base pad's pts, not the releasing pad's
        assert buf.tensors[0][0] == 1.0 and buf.tensors[1][0] == 9.0
        # next base buffer emits immediately, pairing with pad 1's LATEST
        outs = self._push(m, "sink_0", 2.0, 20)
        assert len(outs) == 1
        buf = outs[0][1]
        assert buf.pts == 20
        assert buf.tensors[0][0] == 2.0 and buf.tensors[1][0] == 9.0
        # fast non-base pad updates are coalesced: no emission without a
        # pending base buffer
        assert self._push(m, "sink_1", 10.0, 21) == []
        assert self._push(m, "sink_1", 11.0, 22) == []
        outs = self._push(m, "sink_0", 3.0, 30)
        assert outs[0][1].tensors[1][0] == 11.0  # latest wins

    def test_basepad_duration_window_enforced(self):
        # sync-option=<pad>:<duration-ns>: a non-base buffer staler than
        # base_pts - duration must NOT be combined; the base buffer is held
        # until the slow pad catches up (reference discards too-old
        # non-base buffers and waits for fresher data).
        m = TensorMux({"sync_mode": "basepad", "sync_option": "0:5"})
        m.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()}, ["src"])
        assert self._push(m, "sink_1", 7.0, 2) == []
        # base at pts 10: pad 1's latest (pts 2) is outside [5, inf) — hold
        assert self._push(m, "sink_0", 1.0, 10) == []
        # still-stale update (pts 4 < 10-5): keeps holding
        assert self._push(m, "sink_1", 8.0, 4) == []
        # in-window update releases the held base buffer, in order
        outs = self._push(m, "sink_1", 9.0, 6)
        assert len(outs) == 1
        assert outs[0][1].pts == 10
        assert outs[0][1].tensors[1][0] == 9.0
        # newer-than-base data is always acceptable
        outs = self._push(m, "sink_0", 2.0, 11)
        assert len(outs) == 1 and outs[0][1].tensors[1][0] == 9.0

    def test_basepad_duration_window_eos_flush(self):
        m = TensorMux({"sync_mode": "basepad", "sync_option": "0:5"})
        m.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()}, ["src"])
        assert self._push(m, "sink_1", 7.0, 0) == []
        assert self._push(m, "sink_0", 1.0, 10) == []  # held: pad 1 stale
        assert self._push(m, "sink_0", 2.0, 20) == []  # held behind it
        # EOS: no fresher data is coming — flush both with last-seen data
        outs = m.finalize()
        assert [o[1].pts for o in outs] == [10, 20]
        assert all(o[1].tensors[1][0] == 7.0 for o in outs)
        assert m.finalize() == []  # idempotent

    def test_single_pad_slowest_process_passthrough(self):
        # A single-sink-pad mux in default slowest mode bypasses the
        # runtime's group collation and hits process() directly — must
        # pass through, not crash (advisor r2 finding).
        m = TensorMux()
        m.configure({"sink_0": nt.Caps.any()}, ["src"])
        outs = self._push(m, "sink_0", 5.0, 7)
        assert len(outs) == 1
        assert outs[0][1].pts == 7
        assert outs[0][1].tensors[0][0] == 5.0

    def test_refresh_any_pad_triggers(self):
        m = TensorMux({"sync_mode": "refresh"})
        m.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()}, ["src"])
        assert self._push(m, "sink_0", 1.0, 10) == []  # waiting for pad 1
        outs = self._push(m, "sink_1", 5.0, 11)
        assert len(outs) == 1 and outs[0][1].pts == 11
        # every subsequent arrival on EITHER pad re-emits with latest pair
        outs = self._push(m, "sink_0", 2.0, 20)
        assert outs[0][1].pts == 20
        assert outs[0][1].tensors[0][0] == 2.0
        assert outs[0][1].tensors[1][0] == 5.0  # reused
        outs = self._push(m, "sink_1", 6.0, 21)
        assert outs[0][1].tensors[0][0] == 2.0  # reused
        assert outs[0][1].tensors[1][0] == 6.0

    def test_merge_basepad(self):
        m = TensorMerge({"option": "0", "sync_mode": "basepad",
                         "sync_option": "1"})
        m.configure({"sink_0": nt.Caps.any(), "sink_1": nt.Caps.any()}, ["src"])
        a = Buffer([np.zeros((2,), np.float32)], pts=5)
        assert m.process("sink_0", a) == []
        outs = m.process(
            "sink_1", Buffer([np.ones((3,), np.float32)], pts=7))
        assert len(outs) == 1
        assert outs[0][1].pts == 7  # base = sink_1
        assert outs[0][1].tensors[0].shape == (5,)

    def test_bad_sync_mode_rejected(self):
        with pytest.raises(Exception):
            TensorMux({"sync_mode": "nope"})

    def test_refresh_pipeline_uneven_rates(self):
        """Two appsrc feeds at different rates through refresh-mode mux."""
        p = nt.Pipeline(
            "tensor_mux name=m sync-mode=refresh ! tensor_sink name=out "
            "appsrc name=fast ! m.sink_0 "
            "appsrc name=slow ! m.sink_1",
            fuse=False,
        )
        import time as _t

        with p:
            p.push("slow", np.full((1,), -1.0, np.float32))
            _t.sleep(0.3)  # let the slow buffer land before the fast burst
            for i in range(3):
                p.push("fast", np.full((1,), float(i), np.float32))
            got = [p.pull("out", timeout=15) for _ in range(3)]
            p.eos()
            p.wait(timeout=15)
        # fast pushes each emit, all pairing with slow's only buffer
        vals = [(b.tensors[0][0], b.tensors[1][0]) for b in got]
        assert vals == [(0.0, -1.0), (1.0, -1.0), (2.0, -1.0)]
