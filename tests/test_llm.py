"""LLM path tests (benchmark config #5): KV-cache decode, TP sharding,
ring-attention sequence parallelism, token streaming through a pipeline."""

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import llama


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.PRESETS["llama_tiny"]
    params = llama.init_params(cfg, seed=0)
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    toks = np.arange(12, dtype=np.int32).reshape(2, 6) % cfg.vocab
    logits = llama.forward(params, toks, cfg, compute_dtype="float32")
    assert logits.shape == (2, 6, cfg.vocab)
    assert np.isfinite(np.asarray(logits)).all()


def test_cached_decode_matches_full_forward(tiny):
    """Prefill+cached decode must equal the uncached full forward — the
    KV-cache correctness invariant."""
    cfg, params = tiny
    rng = np.random.default_rng(0)
    T = 10
    toks = rng.integers(0, cfg.vocab, (1, T), np.int32)

    full = np.asarray(llama.forward(params, toks, cfg, compute_dtype="float32"))

    cache = llama.init_cache(cfg, 1, dtype="float32")
    pre, cache = llama.forward_cached(params, toks[:, :4], cache, 0, cfg,
                                      compute_dtype="float32")
    np.testing.assert_allclose(np.asarray(pre), full[:, :4], rtol=2e-4, atol=2e-4)
    for i in range(4, T):
        step, cache = llama.forward_cached(params, toks[:, i : i + 1], cache,
                                           i, cfg, compute_dtype="float32")
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), full[:, i], rtol=2e-4, atol=2e-4
        )


def test_generate_scan_deterministic(tiny):
    cfg, params = tiny
    prompt = np.array([[1, 5, 9, 13]], np.int32)
    a = np.asarray(llama.generate_scan(params, prompt, cfg, max_new=8,
                                       temperature=0.0, compute_dtype="float32"))
    b = np.asarray(llama.generate_scan(params, prompt, cfg, max_new=8,
                                       temperature=0.0, compute_dtype="float32"))
    assert a.shape == (1, 8)
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < cfg.vocab).all()


def test_seq_parallel_matches_dense(tiny):
    """Ring-attention SP forward == single-device forward (SURVEY §5.7:
    long-context is first-class here, absent in the reference)."""
    import jax

    from nnstreamer_tpu.parallel import make_mesh

    cfg, params = tiny
    mesh = make_mesh(seq=4, data=1, devices=jax.devices()[:4])
    toks = np.arange(16, dtype=np.int32)[None, :] % cfg.vocab
    dense = np.asarray(llama.forward(params, toks, cfg, compute_dtype="float32"))
    sp = np.asarray(llama.forward_seq_parallel(mesh, params, toks, cfg,
                                               compute_dtype="float32"))
    np.testing.assert_allclose(sp, dense, rtol=2e-3, atol=2e-3)


def test_tp_sharded_generation_matches_single():
    """TP over the model axis must not change greedy outputs."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.parallel import make_mesh
    from nnstreamer_tpu.parallel.sharding import shard_params

    cfg = llama.PRESETS["llama_tiny"]
    params = llama.init_params(cfg, seed=0)
    prompt = np.array([[1, 7, 3]], np.int32)
    ref = np.asarray(llama.generate_scan(params, prompt, cfg, max_new=6,
                                         temperature=0.0, compute_dtype="float32"))

    mesh = make_mesh(model=2, data=1, devices=jax.devices()[:2])
    sharded = shard_params(mesh, params, llama.param_pspecs())
    out = np.asarray(llama.generate_scan(sharded, prompt, cfg, max_new=6,
                                         temperature=0.0, compute_dtype="float32"))
    np.testing.assert_array_equal(out, ref)


def test_llm_pipeline_token_streaming():
    """Full pipeline: prompt pushed as text, tokens stream out one buffer
    each (the reference llamacpp contract)."""
    p = nt.Pipeline(
        "appsrc name=src ! "
        "tensor_filter framework=llm model=llama_tiny "
        "custom=max_new:5,dtype:float32 invoke-dynamic=true ! "
        "tensor_sink name=out"
    )
    with p:
        p.push("src", "hi")
        outs = [p.pull("out", timeout=120) for _ in range(5)]
        p.eos("src")
        p.wait(timeout=60)
    for i, buf in enumerate(outs):
        assert buf.meta["stream_index"] == i
        ids = buf.tensors[0]
        assert ids.dtype == np.int32 and ids.shape == (1,)
        assert 0 <= int(ids[0]) < llama.PRESETS["llama_tiny"].vocab


def test_llm_invoke_nonstream():
    from nnstreamer_tpu.filters.llm import LLMFramework

    fw = LLMFramework()
    fw.open({"model": "llama_tiny", "custom": "max_new:4,dtype:float32"})
    prompt = np.frombuffer(b"ab", np.uint8)
    ids, text = fw.invoke([prompt])
    assert ids.shape == (1, 4)
    # determinism across invokes
    ids2, _ = fw.invoke([prompt])
    np.testing.assert_array_equal(ids, ids2)
    fw.close()


def test_llama_7b_shaped_tp_forward_matches_replicated():
    """Config #5 shape check: the REAL 7B per-layer shapes (dim 4096, 32
    heads, head_dim 128, ffn 11008) forwarded under TP=4 GSPMD sharding
    must match the replicated forward.  Layers truncated to 2 and vocab
    shrunk to keep the CPU-mesh test tractable (VERDICT r1 item #4: shapes
    real, depth truncated is acceptable for tests; the bench runs full
    depth on the chip)."""
    import dataclasses

    import jax

    from nnstreamer_tpu.parallel import make_mesh
    from nnstreamer_tpu.parallel.sharding import shard_params

    cfg = dataclasses.replace(
        llama.PRESETS["llama2_7b"], n_layers=2, vocab=1024, max_seq=64)
    assert cfg.head_dim == 128  # the real 7B head geometry
    params = llama.init_params(cfg, seed=0)
    toks = (np.arange(8, dtype=np.int32)[None, :] * 37) % cfg.vocab

    ref = np.asarray(llama.forward(params, toks, cfg, compute_dtype="float32"))

    mesh = make_mesh(model=4, data=1, devices=jax.devices()[:4])
    sharded = shard_params(mesh, params, llama.param_pspecs())
    out = jax.jit(
        lambda p, t: llama.forward(p, t, cfg, compute_dtype="float32")
    )(sharded, toks)
    out = np.asarray(out)
    assert out.shape == (1, 8, cfg.vocab)
    # GSPMD all-reduce ordering differs from the replicated reduction:
    # loose-but-meaningful tolerance on f32 logits.
    np.testing.assert_allclose(ref, out, rtol=2e-3, atol=2e-3)


def test_init_params_bf16_storage():
    """7B HBM-fit path: weights generated directly in bfloat16."""
    import jax.numpy as jnp

    cfg = llama.PRESETS["llama_tiny"]
    params = llama.init_params(cfg, seed=0, dtype="bfloat16")
    assert params["embed"].dtype == jnp.bfloat16
    assert params["layers"]["wq"].dtype == jnp.bfloat16
    toks = np.array([[1, 2, 3]], np.int32)
    logits = llama.forward(params, toks, cfg, compute_dtype="bfloat16")
    assert np.isfinite(np.asarray(logits)).all()


class TestInt8WeightOnly:
    """Weight-only int8 (custom=quant:int8): halves HBM bytes/token on
    the bandwidth-bound decode step; numerics must stay close."""

    def _cfg(self):
        from nnstreamer_tpu.models import llama

        return llama.PRESETS["llama_tiny"]

    def test_logits_close_and_storage_halved(self):
        from nnstreamer_tpu.models import llama

        cfg = self._cfg()
        params = llama.init_params(cfg, seed=0)
        qparams = llama.quantize_int8(params)
        for k in llama._QUANT_MATS:
            assert qparams["layers"][k + "_q"].dtype == np.int8
        assert qparams["lm_head_q"].dtype == np.int8
        toks = np.array([[1, 7, 3, 9, 2]], np.int32)
        a = np.asarray(llama.forward(params, toks, cfg,
                                     compute_dtype="float32"))
        b = np.asarray(llama.forward(qparams, toks, cfg,
                                     compute_dtype="float32"))
        # per-channel int8 keeps relative error small; cosine per position
        cos = (a * b).sum(-1) / (
            np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
        assert cos.min() > 0.999, cos.min()

    def test_init_params_int8_matches_quantize_after_init(self):
        # The memory-bounded per-mat path must be bit-identical to
        # quantize_int8(init_params(...)) — same RNG stream, same math
        # (this is what lets 7B int8 build without the full-precision
        # tree ever being resident).
        import jax

        from nnstreamer_tpu.models import llama

        cfg = self._cfg()
        ref = llama.quantize_int8(
            llama.init_params(cfg, seed=3, dtype="bfloat16"))
        fused = llama.init_params_int8(cfg, seed=3, gen_dtype="bfloat16")
        flat_r, tdef_r = jax.tree.flatten(ref)
        flat_f, tdef_f = jax.tree.flatten(fused)
        assert tdef_r == tdef_f
        for r, f in zip(flat_r, flat_f):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(f))

    def test_generate_scan_runs_quantized(self):
        import jax

        from nnstreamer_tpu.models import llama

        cfg = self._cfg()
        qparams = llama.quantize_int8(llama.init_params(cfg, seed=1))
        toks = llama.generate_scan(qparams, np.array([[1, 5, 9]], np.int32),
                                   cfg, max_new=4, temperature=0.0,
                                   compute_dtype="float32")
        toks = np.asarray(toks)
        assert toks.shape == (1, 4)
        assert ((toks >= 0) & (toks < cfg.vocab)).all()

    def test_tp_pspecs_match_quant_tree(self):
        import jax

        from nnstreamer_tpu.models import llama
        from nnstreamer_tpu.parallel import make_mesh, shard_params

        cfg = self._cfg()
        qparams = llama.quantize_int8(llama.init_params(cfg, seed=2))
        mesh = make_mesh(model=2, data=1, devices=jax.devices()[:2])
        sharded = shard_params(mesh, qparams, llama.param_pspecs(quant=True))
        toks = np.array([[1, 2, 3]], np.int32)
        logits = np.asarray(llama.forward(sharded, toks, cfg,
                                          compute_dtype="float32"))
        ref = np.asarray(llama.forward(qparams, toks, cfg,
                                       compute_dtype="float32"))
        np.testing.assert_allclose(logits, ref, rtol=1e-4, atol=1e-5)

    def test_llm_filter_quant_option(self):
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=1:1,"
            "types=int32,format=flexible ! "
            "tensor_filter framework=llm model=llama_tiny "
            "custom=max_new:4,quant:int8,dtype:float32 ! "
            "tensor_sink name=out")
        with p:
            p.push("src", np.array([[1, 5]], np.int32))
            toks = [int(np.asarray(p.pull("out", timeout=120)
                                   .tensors[0]).ravel()[0])
                    for _ in range(4)]
            p.eos()
            p.wait(timeout=30)
        assert len(toks) == 4

    def test_llm_filter_quant_with_tp(self):
        # quant + tp must SHARD the quantized tree (bundle pspecs), not
        # silently replicate (review r3 finding)
        p = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=1:1,"
            "types=int32,format=flexible ! "
            "tensor_filter framework=llm model=llama_tiny "
            "custom=max_new:3,quant:int8,tp:2,dtype:float32 name=f ! "
            "tensor_sink name=out")
        with p:
            fw = p.element("f").fw
            q = fw.bundle.params["layers"]["wq_q"]
            # sharded over the model axis: each device holds out/2
            shard_shapes = {tuple(s.data.shape) for s in q.addressable_shards}
            full = tuple(q.shape)
            assert shard_shapes == {(full[0], full[1], full[2] // 2)}, (
                shard_shapes, full)
            p.push("src", np.array([[1, 5]], np.int32))
            for _ in range(3):
                p.pull("out", timeout=120)
            p.eos()
            p.wait(timeout=30)


class TestPrefillBucketing:
    """SURVEY §7 "dynamic shapes vs XLA static shapes": prompts right-pad
    to power-of-two buckets so mixed-length serving compiles at most
    log2(max_seq) prefill programs — with numerics IDENTICAL to the
    unbucketed program (causal attention hides pad rows; decode
    overwrites cache row `pos` before anything can attend it)."""

    def _ids(self, prompt):
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": "llama_tiny",
                 "custom": "max_new:6,stream_chunk:2,temperature:0.7"})
        return [out[0].copy() for out in fw.invoke_stream([prompt])]

    def test_bucketed_matches_unbucketed(self):
        import dataclasses

        from nnstreamer_tpu.core import config as config_mod

        prompts = [np.arange(1, 6, dtype=np.int32),        # 5 -> bucket 32
                   np.arange(1, 41, dtype=np.int32)]       # 40 -> bucket 64
        for prompt in prompts:
            cfg = config_mod.get_config()
            try:
                config_mod.set_config(
                    dataclasses.replace(cfg, shape_bucketing=False))
                plain = self._ids(prompt)
                config_mod.set_config(
                    dataclasses.replace(cfg, shape_bucketing=True))
                bucketed = self._ids(prompt)
            finally:
                config_mod.set_config(cfg)
            assert len(plain) == len(bucketed)
            for a, b in zip(plain, bucketed):
                np.testing.assert_array_equal(a, b)

    def test_mixed_lengths_share_prefill_program(self):
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": "llama_tiny", "custom": "max_new:1"})
        for t in (3, 9, 17, 30):  # all bucket to 32
            list(fw.invoke_stream([np.arange(1, t + 1, dtype=np.int32)]))
        # jit cache: one prefill entry despite four prompt lengths
        assert fw._fwd._cache_size() == 1


class TestPerRowPositionDecode:
    """Foundation of continuous batching: a [B] position vector lets
    concurrent streams sit at different depths in one decode program.
    Per-row decode must match each stream decoded independently."""

    def test_mixed_depth_decode_matches_independent(self):
        import jax.numpy as jnp

        cfg = llama.PRESETS["llama_tiny"]
        params = llama.init_params(cfg, seed=0)
        rng = np.random.default_rng(1)
        lens = [4, 9]  # two streams at different depths
        prompts = [rng.integers(1, cfg.vocab, (1, t), np.int32)
                   for t in lens]

        # independent reference: prefill+decode each stream alone
        ref_logits = []
        for p in prompts:
            c = llama.init_cache(cfg, 1, dtype="float32")
            _, c = llama.forward_cached(params, p, c, 0, cfg,
                                        compute_dtype="float32")
            nxt = np.array([[7]], np.int32)
            lg, _ = llama.forward_cached(params, nxt, c, p.shape[1], cfg,
                                         compute_dtype="float32")
            ref_logits.append(np.asarray(lg[:, 0]))

        # batched: place both single-row prefilled caches into a 2-slot
        # cache, then ONE per-row-position decode step (host-side row
        # copy: the runtime's serving path is block-paged now, so dense
        # slot admission exists only as this test's reference rig)
        bk = np.zeros((cfg.n_layers, 2, cfg.max_seq, cfg.n_kv_heads,
                       cfg.head_dim), np.float32)
        bv = bk.copy()
        for slot, p in enumerate(prompts):
            c = llama.init_cache(cfg, 1, dtype="float32")
            _, c = llama.forward_cached(params, p, c, 0, cfg,
                                        compute_dtype="float32")
            bk[:, slot] = np.asarray(c["k"])[:, 0]
            bv[:, slot] = np.asarray(c["v"])[:, 0]
        big = {"k": jnp.asarray(bk), "v": jnp.asarray(bv)}
        toks = np.array([[7], [7]], np.int32)
        pos = jnp.asarray(np.array(lens, np.int32))
        lg, big = llama.forward_cached(params, toks, big, pos, cfg,
                                       compute_dtype="float32")
        lg = np.asarray(lg[:, 0])
        for row, ref in enumerate(ref_logits):
            np.testing.assert_allclose(lg[row], ref[0], rtol=2e-4,
                                       atol=2e-4)

    def test_idle_slot_out_of_range_write_is_dropped(self):
        import jax.numpy as jnp

        cfg = llama.PRESETS["llama_tiny"]
        params = llama.init_params(cfg, seed=0)
        big = llama.init_cache(cfg, 2, dtype="float32")
        before = np.asarray(big["k"]).copy()
        toks = np.array([[3], [3]], np.int32)
        # row 0 live at pos 0; row 1 idle, parked at max_seq (out of range)
        pos = jnp.asarray(np.array([0, cfg.max_seq], np.int32))
        _, big = llama.forward_cached(params, toks, big, pos, cfg,
                                      compute_dtype="float32")
        after = np.asarray(big["k"])
        assert not np.array_equal(after[:, 0], before[:, 0])  # live row wrote
        np.testing.assert_array_equal(after[:, 1], before[:, 1])  # idle didn't


class TestContinuousServing:
    """custom=serve:continuous — a standing decode loop with slot
    admission (continuous batching).  Late requests join a RUNNING
    decode at the next chunk boundary instead of waiting for the current
    group to finish."""

    def _fw(self, custom):
        from nnstreamer_tpu.filters.llm import LLMFramework

        fw = LLMFramework()
        fw.open({"model": "llama_tiny", "custom": custom})
        return fw

    def test_greedy_matches_plain_streaming(self):
        # temperature 0: the continuous loop must emit token-for-token
        # what the plain per-request streaming path emits.
        plain = self._fw("max_new:6,stream_chunk:2,temperature:0.0")
        prompt = np.array([2, 8, 5, 1], np.int32)
        want = [int(ids[0]) for ids, _ in plain.invoke_stream([prompt])]
        plain.close()

        fw = self._fw("max_new:6,stream_chunk:2,temperature:0.0,"
                      "serve:continuous,slots:2")
        got = []
        fw.submit([prompt], {}, lambda t, m: got.append(
            (int(t[0][0]), m["stream_index"], m.get("stream_last", False))))
        assert fw.drain(timeout=120)
        fw.close()
        assert [g[0] for g in got] == want
        assert [g[1] for g in got] == list(range(6))
        assert got[-1][2] is True

    def test_late_request_joins_running_decode(self):
        # Stream A is long; B arrives AFTER A started.  In a static group
        # B would wait for A to finish; continuous admission means B's
        # tokens arrive interleaved with A's remaining tokens.
        import threading
        import time

        fw = self._fw("max_new:24,stream_chunk:2,temperature:0.0,"
                      "serve:continuous,slots:2")
        events = []
        lock = threading.Lock()

        def emit_for(rid):
            def emit(t, m):
                with lock:
                    events.append((rid, m["stream_index"]))
            return emit

        fw.submit([np.array([1, 5, 9, 2], np.int32)], {}, emit_for("A"))
        # wait until A has demonstrably started streaming
        deadline = time.monotonic() + 60
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)
        assert events, "stream A never started"
        fw.submit([np.array([3, 3, 7, 8], np.int32)], {}, emit_for("B"))
        assert fw.drain(timeout=120)
        fw.close()
        a_idx = [i for i, e in enumerate(events) if e[0] == "A"]
        b_idx = [i for i, e in enumerate(events) if e[0] == "B"]
        assert len(a_idx) == 24 and len(b_idx) == 24
        # the continuous property: B started before A finished
        assert b_idx[0] < a_idx[-1]
        # per-stream ordering intact
        for idxs in ([e[1] for e in events if e[0] == "A"],
                     [e[1] for e in events if e[0] == "B"]):
            assert idxs == list(range(24))

    def test_more_requests_than_slots_queue(self):
        fw = self._fw("max_new:4,stream_chunk:2,temperature:0.0,"
                      "serve:continuous,slots:1")
        done = []
        for rid in range(3):
            fw.submit([np.array([1 + rid, 5, 9], np.int32)], {"rid": rid},
                      lambda t, m: done.append(m["rid"])
                      if m.get("stream_last") else None)
        assert fw.drain(timeout=180)
        fw.close()
        assert sorted(done) == [0, 1, 2]

    def test_pipeline_eos_waits_for_streams(self):
        import nnstreamer_tpu as nt

        p = nt.Pipeline(
            "appsrc name=src ! tensor_filter framework=llm model=llama_tiny "
            "custom=max_new:5,serve:continuous,slots:2,temperature:0.0 "
            "invoke-dynamic=true ! tensor_sink name=out")
        with p:
            p.push("src", np.array([1, 5, 9, 2], np.int32))
            p.push("src", np.array([3, 3, 7, 8], np.int32))
            p.eos("src")  # EOS while both streams are mid-flight
            bufs = [p.pull("out", timeout=120) for _ in range(10)]
            p.wait(timeout=120)
        assert sum(1 for b in bufs if b.meta.get("stream_last")) == 2

    def test_continuous_with_tensor_parallel(self):
        # serve:continuous composes with custom=tp:N — the sharded params
        # flow through admission prefill and the per-row decode; greedy
        # ids must match the unsharded continuous loop.
        prompt = np.array([4, 9, 1, 7], np.int32)

        def run(custom):
            fw = self._fw(custom)
            got = []
            fw.submit([prompt], {}, lambda t, m: got.append(int(t[0][0])))
            assert fw.drain(timeout=120)
            fw.close()
            return got

        base = "max_new:5,stream_chunk:2,temperature:0.0,serve:continuous"
        ids = run(base + ",slots:2")
        ids_tp = run(base + ",slots:2,tp:2")
        assert ids_tp == ids

    def test_serve_loop_crash_terminates_streams(self, monkeypatch):
        # A dying loop must terminate every live and queued stream with
        # stream_aborted (clients never hang to their timeouts) and make
        # subsequent submits fail loudly.
        from nnstreamer_tpu.filters import llm as llm_mod
        from nnstreamer_tpu.filters.llm import FrameworkError

        fw = self._fw("max_new:8,stream_chunk:2,temperature:0.0,"
                      "serve:continuous,slots:1")
        calls = {"n": 0}
        real = llm_mod.llama.sample_token

        def dying(*a, **k):
            calls["n"] += 1
            if calls["n"] > 2:
                raise RuntimeError("injected serve-loop failure")
            return real(*a, **k)

        monkeypatch.setattr(llm_mod.llama, "sample_token", dying)
        got = []
        fw.submit([np.array([1, 5, 9], np.int32)], {},
                  lambda t, m: got.append(dict(m)))
        # a second request queued behind the doomed one must also be
        # terminated, not stranded
        fw.submit([np.array([2, 6, 8], np.int32)], {},
                  lambda t, m: got.append(dict(m)))
        # drain() returns only after the crash handler has emitted every
        # stream_aborted terminator (it sets idle last), so the asserts
        # need no further synchronization.
        assert fw.drain(timeout=60)
        assert any(m.get("stream_aborted") and m.get("stream_last")
                   for m in got), got
        with pytest.raises(FrameworkError, match="serve loop died"):
            fw.submit([np.array([3], np.int32)], {}, lambda t, m: None)
        fw.close()
