"""GGUF ingestion tests — the reference's llama.cpp sub-plugin model
format (SURVEY §2.4).  Strategy mirrors test_checkpoint.py: export native
params to GGUF (including the INVERSE RoPE permutation, so the file is in
ggml's interleaved layout like a real llama.cpp checkpoint), import, and
require exact pytree equality + identical forward logits.
"""

import struct

import numpy as np
import pytest

import nnstreamer_tpu as nt
from nnstreamer_tpu.models import gguf, llama, zoo

CFG = llama.LlamaConfig(vocab=96, dim=32, n_layers=2, n_heads=2,
                        n_kv_heads=1, ffn_hidden=48, max_seq=64)


# export mapping lives in the product now (gguf.llama_to_tensors /
# llama_metadata); these aliases keep the test bodies readable
_to_gguf_tensors = gguf.llama_to_tensors
_meta = gguf.llama_metadata


class TestContainer:
    def test_roundtrip(self, tmp_path):
        from nnstreamer_tpu.core.types import bfloat16

        rng = np.random.default_rng(0)
        tensors = {
            "a": rng.standard_normal((3, 4)).astype(np.float32),
            "b": rng.standard_normal((8,)).astype(np.float16),
            "c": rng.standard_normal((2, 5)).astype(np.float32).astype(
                bfloat16),
        }
        meta = {"general.architecture": "llama", "x.count": 7,
                "x.flag": True, "x.rate": 0.5}
        p = str(tmp_path / "t.gguf")
        gguf.write(p, meta, tensors)
        m2, t2 = gguf.read(p)
        assert m2["general.architecture"] == "llama"
        assert m2["x.count"] == 7 and m2["x.flag"] is True
        assert abs(m2["x.rate"] - 0.5) < 1e-7
        for k in tensors:
            assert t2[k].dtype == tensors[k].dtype
            np.testing.assert_array_equal(
                np.asarray(t2[k], np.float32),
                np.asarray(tensors[k], np.float32))

    def test_bad_magic(self, tmp_path):
        p = tmp_path / "bad.gguf"
        p.write_bytes(b"NOPE" + b"\x00" * 32)
        with pytest.raises(gguf.GGUFError, match="magic"):
            gguf.read(str(p))

    def test_quantized_type_named_in_error(self, tmp_path):
        # hand-build a one-tensor GGUF using ggml type Q4_K (=12)
        name = b"blk.0.ffn_up.weight"
        blob = struct.pack("<IIQQ", 0x46554747, 3, 1, 0)
        blob += struct.pack("<Q", len(name)) + name
        blob += struct.pack("<I", 2)  # n_dims
        blob += struct.pack("<QQ", 4, 4)
        blob += struct.pack("<IQ", 12, 0)  # Q4_K, offset 0
        blob += b"\x00" * 64
        p = tmp_path / "q.gguf"
        p.write_bytes(blob)
        with pytest.raises(gguf.GGUFError, match="Q4_K"):
            gguf.read(str(p))


class TestLlamaImport:
    def test_roundtrip_exact_and_logits(self, tmp_path):
        params = llama.init_params(CFG, seed=5)
        p = str(tmp_path / "model.gguf")
        gguf.write(p, _meta(CFG), _to_gguf_tensors(params, CFG))
        got, cfg = llama.load_checkpoint(p, dtype="float32")
        # config from GGUF metadata (floats ride as f32 in the container)
        import dataclasses

        for f in dataclasses.fields(CFG):
            a, b = getattr(cfg, f.name), getattr(CFG, f.name)
            if isinstance(b, float):
                assert abs(a - b) <= 1e-7 * max(1.0, abs(b)), f.name
            else:
                assert a == b, f.name
        cfg = CFG  # exact eps for the numeric comparison below
        toks = np.array([[1, 9, 4, 2]], np.int32)
        a = np.asarray(llama.forward(params, toks, CFG,
                                     compute_dtype="float32"))
        b = np.asarray(llama.forward(got, toks, cfg,
                                     compute_dtype="float32"))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_tied_embeddings(self, tmp_path):
        params = llama.init_params(CFG, seed=6)
        tensors = _to_gguf_tensors(params, CFG)
        del tensors["output.weight"]
        p = str(tmp_path / "tied.gguf")
        gguf.write(p, _meta(CFG), tensors)
        got, _ = llama.load_checkpoint(p, dtype="float32")
        np.testing.assert_array_equal(got["lm_head"],
                                      np.asarray(got["embed"]).T)

    def test_llm_filter_streams_from_gguf(self, tmp_path):
        """The reference's usage end-to-end: the llm streaming filter fed
        by a GGUF model file."""
        params = llama.init_params(CFG, seed=7)
        p = str(tmp_path / "model.gguf")
        gguf.write(p, _meta(CFG), _to_gguf_tensors(params, CFG))
        pl = nt.Pipeline(
            "appsrc name=src caps=other/tensors,dimensions=1:1,"
            "types=int32,format=flexible ! "
            f"tensor_filter framework=llm model={p} "
            "custom=max_new:4,param_dtype:float32,dtype:float32 ! "
            "tensor_sink name=out")
        with pl:
            pl.push("src", np.array([[1, 5]], np.int32))
            toks = [int(np.asarray(pl.pull("out", timeout=120)
                                   .tensors[0]).ravel()[0])
                    for _ in range(4)]
            pl.eos()
            pl.wait(timeout=30)
        assert len(toks) == 4
        assert all(0 <= t < CFG.vocab for t in toks)


class TestConvertCLI:
    """tools/convert.py: round-trip weights through every output format
    and require identical forward logits at each hop."""

    def test_gguf_to_safetensors_to_npz_chain(self, tmp_path):
        """Every hop is self-contained: conversions write a config.json
        alongside (.gguf carries config in its own metadata), so reimports
        reconstruct the EXACT config — no shape-inference guessing."""
        from nnstreamer_tpu.tools import convert as cv

        params = llama.init_params(CFG, seed=11)
        g1 = str(tmp_path / "a.gguf")
        gguf.export_llama(g1, params, CFG)
        st = str(tmp_path / "b.safetensors")
        assert cv.main([g1, st]) == 0
        got_st, cfg_st = llama.load_checkpoint(st, dtype="float32")
        assert cfg_st.n_kv_heads == CFG.n_kv_heads  # from config.json
        nz = str(tmp_path / "c.npz")
        assert cv.main([st, nz]) == 0
        got_nz, cfg_nz = llama.load_checkpoint(nz, dtype="float32")
        assert cfg_nz.rope_theta == CFG.rope_theta
        toks = np.array([[3, 7, 1]], np.int32)
        want = np.asarray(llama.forward(params, toks, CFG,
                                        compute_dtype="float32"))
        for got in (got_st, got_nz):
            have = np.asarray(llama.forward(got, toks, CFG,
                                            compute_dtype="float32"))
            np.testing.assert_allclose(have, want, rtol=1e-6)

    def test_bad_output_format(self, tmp_path):
        from nnstreamer_tpu.tools import convert as cv

        params = llama.init_params(CFG, seed=12)
        g1 = str(tmp_path / "a.gguf")
        gguf.export_llama(g1, params, CFG)
        assert cv.main([g1, str(tmp_path / "x.bin")]) == 1

    def test_npz_bfloat16_rejected_loudly(self, tmp_path):
        from nnstreamer_tpu.tools import convert as cv

        params = llama.init_params(CFG, seed=13)
        g1 = str(tmp_path / "a.gguf")
        gguf.export_llama(g1, params, CFG)
        rc = cv.main([g1, str(tmp_path / "b.npz"), "--dtype", "bfloat16"])
        assert rc == 1  # loud error, not a silently unloadable file
        assert not (tmp_path / "b.npz").exists()
