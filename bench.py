#!/usr/bin/env python
"""Headline benchmark: MobileNet-v1 classification pipeline, frames/sec/chip.

BASELINE.json KPI: "frames/sec/chip on tensor_filter pipeline; p50 per-frame
latency".  North star: >=2000 fps aggregate on a v5e-8 => 250 fps/chip is
parity (vs_baseline = fps_per_chip / 250).

Pipeline under test (config #1, the reference's img-class example):

    appsrc -> tensor_transform(typecast+normalize) -> tensor_filter(jax,
    mobilenet_v1, bfloat16) -> tensor_decoder(image_labeling) -> tensor_sink

Frames stream through in batches (the TPU-native move the reference can't
make: its tflite path is frame-at-a-time); transform+filter fuse into one
jitted XLA program, so normalization rides the MXU with the convs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def run_bench(batch: int, batches: int, size: int, warmup: int) -> dict:
    import numpy as np

    import nnstreamer_tpu as nt

    desc = (
        f"appsrc name=src caps=other/tensors,dimensions=3:{size}:{size}:{batch},types=uint8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
        f"tensor_filter framework=jax model=mobilenet_v1 custom=size:{size},batch:{batch} name=f ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out"
    )
    rng = np.random.default_rng(0)
    frames = [
        rng.integers(0, 256, (batch, size, size, 3), dtype=np.uint8)
        for _ in range(4)
    ]

    push_ts = {}
    lat = []
    done = threading.Event()

    # Deep in-flight window: the whole chain is ONE fused async stage, so
    # queue capacity bounds how many batches pipeline H2D/compute/D2H.
    # Keep total pushed bytes modest (batches*batch*size*size*3) — host->TPU
    # links are burst-friendly; a short, deeply-pipelined run measures the
    # framework, not the transport's sustained cap.
    p = nt.Pipeline(desc, fuse=True, queue_capacity=16)
    with p:
        # Warmup: first push triggers XLA compile.
        for i in range(warmup):
            p.push("src", frames[i % len(frames)])
            p.pull("out", timeout=600)

        def pusher():
            for i in range(batches):
                push_ts[i] = time.perf_counter()
                p.push("src", frames[i % len(frames)])
            done.set()

        t = threading.Thread(target=pusher, daemon=True)
        t0 = time.perf_counter()
        t.start()
        for i in range(batches):
            p.pull("out", timeout=600)
            lat.append(time.perf_counter() - push_ts[i])
        t1 = time.perf_counter()
        t.join()
        p.eos()
        p.wait(timeout=60)

    total_frames = batch * batches
    wall = t1 - t0
    fps = total_frames / wall
    lat_ms = sorted(x * 1e3 for x in lat)
    p50 = lat_ms[len(lat_ms) // 2]
    p99 = lat_ms[min(len(lat_ms) - 1, int(len(lat_ms) * 0.99))]
    return {
        "metric": "mobilenet_v1_pipeline_fps_per_chip",
        "value": round(fps, 1),
        "unit": "frames/sec",
        "vs_baseline": round(fps / 250.0, 3),
        "p50_batch_ms": round(p50, 2),
        "p99_batch_ms": round(p99, 2),
        "batch": batch,
        "batches": batches,
        "wall_s": round(wall, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--batches", type=int, default=32)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--warmup", type=int, default=2)
    args = ap.parse_args()
    result = run_bench(args.batch, args.batches, args.size, args.warmup)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
