#!/usr/bin/env python
"""Benchmarks for the five BASELINE.md configs.

Default (no args) = config #1, the headline: MobileNet-v1 classification
pipeline, frames/sec/chip.  BASELINE.json KPI: "frames/sec/chip on
tensor_filter pipeline; p50 per-frame latency".  North star: >=2000 fps
aggregate on a v5e-8 => 250 fps/chip is parity (vs_baseline =
fps_per_chip / 250).

    appsrc -> tensor_transform(typecast+normalize) -> tensor_filter(jax,
    mobilenet_v1, bfloat16) -> tensor_decoder(image_labeling) -> tensor_sink

Frames stream through in batches (the TPU-native move the reference can't
make: its tflite path is frame-at-a-time); transform+filter+decoder fuse
into one jitted XLA program, so normalization rides the MXU with the convs
and only argmax indices come home.

Other configs (--config): detection (#2 SSD + bounding boxes), pose (#3),
segmentation (deeplab + fused image_segment decode), audio (#4 speech
commands / wav2vec2+ctc), llm (#5 token streaming, tokens/sec).

Prints ONE JSON line per config run:
{"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# `JAX_PLATFORMS=cpu python bench.py` must not touch (and hang on) an
# unreachable device tunnel when a site hook pre-imported jax.  Called from
# main(), NOT at import: `import bench` (the probe tests do) must stay free
# of backend side effects.
from nnstreamer_tpu.core.platform import (enable_compilation_cache,
                                           honor_jax_platforms)

# 8-deep in-flight window: measured +29% classification fps over 4 (RTT
# and host post-processing hide behind more batches); 16 adds only +2%.
_SOURCE_QUEUE_CAPACITY = 8

#: Peak dense-matmul throughput per chip by device kind (bf16 FLOP/s) —
#: public spec-sheet numbers, used only for the MFU report field.
_PEAK_FLOPS = {
    "tpu v5 lite": 197e12, "tpu v5e": 197e12,
    "tpu v5p": 459e12, "tpu v5": 459e12,
    "tpu v4": 275e12, "tpu v6 lite": 918e12, "tpu v6e": 918e12,
}


def _peak_flops_per_chip():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return None


_FLOPS_CACHE: dict = {}


def _fused_stage_flops(p):
    """FLOPs of the pipeline's fused XLA program per batch, from the
    compiled executable's own cost analysis (no hand-counted model tables).
    None when there is no fused stage or the backend can't report it.
    Memoized per (program, input spec): lower().compile() would otherwise
    repeat the 20-40s fused-stage compile per bench config just to read a
    report-only cost field."""
    try:
        import jax.numpy as jnp

        for s in p.stages:
            el = s.element
            fn = getattr(el, "_fn", None)
            in_spec = getattr(el, "_in_spec", None)
            if fn is None or in_spec is None:
                continue
            key = (id(fn), tuple((t.shape, str(t.dtype)) for t in in_spec))
            if key in _FLOPS_CACHE:
                fl = _FLOPS_CACHE[key][1]
            else:
                args = tuple(jnp.zeros(t.shape, t.dtype) for t in in_spec)
                ca = fn.lower(args).compile().cost_analysis()
                if isinstance(ca, list):
                    ca = ca[0] if ca else {}
                fl = float(ca.get("flops", 0.0))
                # Keep fn alive in the cache entry: id() keys are only
                # stable while the object lives — a freed fn's address can
                # be recycled by a different config's program.
                _FLOPS_CACHE[key] = (fn, fl)
            if fl > 0:
                return fl
            # e.g. a fused pure-preprocess stage: keep looking for the
            # model's fused stage.
    except Exception:  # noqa: BLE001 - report field only, never fail a bench
        return None
    return None


def _add_mfu(r: dict, p, batch: int) -> dict:
    """mfu = achieved model FLOP/s / chip peak (VERDICT r1 item #9)."""
    flops = _fused_stage_flops(p)
    peak = _peak_flops_per_chip()
    if flops and peak:
        r["flops_per_batch"] = round(flops)
        r["mfu"] = round((r["value"] / batch) * flops / peak, 4)
    return r


def _stage_breakdown() -> dict:
    """p50 ms of each pipeline stage's processing timer for the run."""
    from nnstreamer_tpu.core.log import metrics as _m

    snap = _m.snapshot()
    out = {}
    for name, v in snap.items():
        if name.endswith(".proc.p50") or name.endswith(".push.p50"):
            out[name.rsplit(".p50", 1)[0]] = round(v * 1e3, 2)
    return out


def _stats(lat, batch, batches, wall, metric, baseline_fps, unit,
           e2e=None):
    """``lat`` is per-batch SERVICE time (inter-completion gaps at steady
    state); ``e2e`` optionally carries push->pull round-trip times, which
    under deep pipelining include queue wait and are reported separately."""
    fps = batch * batches / wall
    lat_ms = sorted(x * 1e3 for x in lat)
    r = {
        "metric": metric,
        "value": round(fps, 1),
        "unit": unit,
        "vs_baseline": round(fps / baseline_fps, 3),
        "p50_batch_ms": round(lat_ms[len(lat_ms) // 2], 2),
        "p99_batch_ms": round(lat_ms[min(len(lat_ms) - 1, int(len(lat_ms) * 0.99))], 2),
        "batch": batch,
        "batches": batches,
        "wall_s": round(wall, 3),
    }
    if e2e:
        e2e_ms = sorted(x * 1e3 for x in e2e)
        r["p50_e2e_ms"] = round(e2e_ms[len(e2e_ms) // 2], 2)
    return r


def _pipeline_bench(desc: str, make_frame, batch: int, batches: int,
                    warmup: int, metric: str, baseline_fps: float,
                    unit: str = "frames/sec", pulls_per_push: int = 1) -> dict:
    import nnstreamer_tpu as nt

    frames = [make_frame(i) for i in range(4)]
    push_ts = {}
    lat = []

    from nnstreamer_tpu.core.log import metrics as _metrics

    _metrics.reset()  # per-bench stage timers (global registry otherwise
    # accumulates across --config all runs and mixes pipelines)

    # Deep in-flight window: fused chains are ONE async stage, so queue
    # capacity bounds how many batches pipeline H2D/compute/D2H.  Keep total
    # pushed bytes modest — host->TPU links are burst-friendly; a short,
    # deeply-pipelined run measures the framework, not the transport's
    # sustained cap.
    p = nt.Pipeline(desc, fuse=True, queue_capacity=16)
    with p:
        for i in range(warmup):  # first push triggers XLA compile
            p.push("src", frames[i % len(frames)])
            for _ in range(pulls_per_push):
                p.pull("out", timeout=600)

        rtt_ms = _fetch_rtt_ms()  # in-session link probe (tail attribution)

        def pusher():
            for i in range(batches):
                # e2e clock starts at ADMISSION (push return): under an
                # infinite offered load the client-side wait to be
                # admitted is unbounded by Little's law whatever the
                # framework does — what max-inflight bounds (and what
                # this measures) is admission->delivery time INSIDE the
                # pipeline.  The pre-push write keeps the reader from
                # KeyErroring if delivery races the post-push overwrite
                # (it would read the conservative earlier stamp).
                push_ts[i] = time.perf_counter()
                p.push("src", frames[i % len(frames)])
                push_ts[i] = time.perf_counter()

        t = threading.Thread(target=pusher, daemon=True)
        t0 = time.perf_counter()
        t.start()
        e2e = []
        prev = None
        for i in range(batches):
            for _ in range(pulls_per_push):
                p.pull("out", timeout=600)
            now = time.perf_counter()
            if prev is not None:
                # Gap from the FIRST completion on: the initial pull includes
                # pipeline-fill latency, which is not a steady-state sample.
                lat.append(now - prev)
            e2e.append(now - push_ts[i])  # includes queue wait when pipelined
            prev = now
        t1 = time.perf_counter()
        t.join()
        p.eos()
        p.wait(timeout=60)

    wall = t1 - t0
    if not lat:  # --batches 1 leaves no steady-state gap; report the wall
        lat = [wall]
    r = _stats(lat, batch, batches, wall, metric, baseline_fps, unit,
               e2e=e2e)
    _add_mfu(r, p, batch)
    r["stages"] = _stage_breakdown()
    _attribute_rtt_tail(r, lat, rtt_ms)
    _attach_fetch_stats(r)
    return r


def bench_classification(batch: int, batches: int, size: int, warmup: int,
                         source: str = "videotestsrc") -> dict:
    """The stock image-classification example.  Default source is the
    TPU-native videotestsrc (pattern generated ON DEVICE, like the
    reference benchmarking against videotestsrc — zero H2D in the loop);
    --source appsrc feeds uint8 camera-style frames from the host instead,
    measuring the ingest transport along with the pipeline."""
    import numpy as np

    if source == "videotestsrc":
        total = _source_total_frames(batch, batches, warmup)
        desc = (
            f"videotestsrc device=true batch={batch} "
            f"num-buffers={total} width={size} height={size} name=src ! "
            "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
            f"tensor_filter framework=jax model=mobilenet_v1 custom=size:{size},batch:{batch} name=f ! "
            # Bounded sink queue: results must NOT pile up ahead of the
            # measuring pull loop, or the loop measures dequeue, not the
            # pipeline (backpressure holds the stages to steady state).
            f"tensor_decoder mode=image_labeling ! tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
        )
        return _source_driven_bench(
            desc, batch, batches, warmup,
            "mobilenet_v1_pipeline_fps_per_chip", 250.0, source,
        )
    rng = np.random.default_rng(0)
    # Host-fed ingest is transport-bound over the tunnel (~60 MB/s H2D):
    # deep in-flight windows only ADD latency once the link saturates
    # (r3 measured p50 e2e of 17 s from ~16 queued 256-batches).  Bound
    # admission end-to-end (appsrc max-inflight) and keep batches small
    # enough that bound x batch-time stays interactive — throughput is
    # the link's either way.
    batch = min(batch, 64)
    # 4 = one batch in H2D flight + one computing + two resolving in the
    # sink's async fetch window (fetch_depth default 2): with ingress
    # donation reusing the steady-state device buffers and the window
    # overlapping D2H with the next dispatch, the old inflight=2 left the
    # link idle one service time per pull (the 57-rtt_stall row).  The
    # h2d/d2h wait split in the row shows where the remaining stalls live.
    inflight = 4
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions=3:{size}:{size}:{batch},types=uint8 "
        f"max-inflight={inflight} ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
        f"tensor_filter framework=jax model=mobilenet_v1 custom=size:{size},batch:{batch} name=f ! "
        "tensor_decoder mode=image_labeling ! tensor_sink name=out"
    )
    r = _pipeline_bench(
        desc,
        lambda i: rng.integers(0, 256, (batch, size, size, 3), dtype=np.uint8),
        batch, batches, warmup,
        "mobilenet_v1_pipeline_fps_per_chip", 250.0,
    )
    r["source"] = source
    r["max_inflight"] = inflight
    return r


def _quant_mobilenet_file(size: int = 224, classes: int = 1001,
                          batch: int = 256) -> str:
    """Emit a fully-quantized MobileNet-v1-shaped .tflite (uint8
    activations, int8 per-axis weights, int32 biases — the reference's
    canonical ``mobilenet_v1_..._quant`` class, random weights standing
    in for the zero-egress checkpoint).  Runs through models/tflite.py's
    INTEGER execution: every conv/dw/fc hits the MXU as int8."""
    import os
    import tempfile

    import numpy as np

    from nnstreamer_tpu.models import tflite_build

    # v2 in the name: bump when this generator's topology/scales change,
    # or a stale cached file from an earlier code state gets benchmarked;
    # classes is part of the key for the same reason
    path = os.path.join(
        tempfile.gettempdir(),
        f"nnstpu_bench_mnq_v2_{size}_{batch}_{classes}.tflite")
    if os.path.exists(path):
        return path
    rng = np.random.default_rng(42)
    s_act, z_act = 0.05, 128

    m = tflite_build.ModelWriter()
    x = m.add_input([batch, size, size, 3], dtype=np.uint8,
                    quant_scale=[s_act], quant_zero_point=[z_act])

    def qconv(h, cin, cout, k, stride, hw, dw=False):
        if dw:
            w = rng.integers(-127, 128, (1, k, k, cin)).astype(np.int8)
            ax, nscale = 3, cin
            kind, fan = "DEPTHWISE_CONV_2D", k * k
        else:
            w = rng.integers(-127, 128, (cout, k, k, cin)).astype(np.int8)
            ax, nscale = 0, cout
            kind, fan = "CONV_2D", k * k * cin
        # unit-variance-ish dequantized weights keep activations in range
        sw = [2.0 / (127.0 * np.sqrt(fan))] * nscale
        wi = m.add_const(w, f"w{hw}_{cin}_{cout}", quant_scale=sw,
                         quant_zero_point=[0] * nscale, quant_axis=ax)
        bi = m.add_const(np.zeros((cout if not dw else cin,), np.int32),
                         f"b{hw}_{cin}_{cout}",
                         quant_scale=[s_act * sw[0]] * nscale,
                         quant_zero_point=[0] * nscale, quant_axis=0)
        oh = -(-hw // stride)
        return m.add_op(kind, [h, wi, bi],
                        [batch, oh, oh, cout if not dw else cin],
                        out_dtype=np.uint8,
                        options={"padding": "SAME",
                                 "stride": (stride, stride),
                                 "act": "relu6"},
                        quant_scale=[s_act], quant_zero_point=[z_act]), oh

    h, hw = qconv(x, 3, 32, 3, 2, size)
    cin = 32
    for cout, stride in ((64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
                         (512, 2), (512, 1), (512, 1), (512, 1), (512, 1),
                         (512, 1), (1024, 2), (1024, 1)):
        h, hw = qconv(h, cin, cin, 3, stride, hw, dw=True)
        h, hw = qconv(h, cin, cout, 1, 1, hw)
        cin = cout
    axes = m.add_const(np.asarray([1, 2], np.int32), "mean_axes")
    h = m.add_op("MEAN", [h, axes], [batch, cin], out_dtype=np.uint8,
                 options={"keep_dims": False},
                 quant_scale=[s_act], quant_zero_point=[z_act])
    fw = rng.integers(-127, 128, (classes, cin)).astype(np.int8)
    fwi = m.add_const(fw, "fcw",
                      quant_scale=[2.0 / (127.0 * np.sqrt(cin))],
                      quant_zero_point=[0])
    fbi = m.add_const(np.zeros((classes,), np.int32), "fcb",
                      quant_scale=[s_act * 2.0 / (127.0 * np.sqrt(cin))],
                      quant_zero_point=[0])
    y = m.add_op("FULLY_CONNECTED", [h, fwi, fbi], [batch, classes],
                 out_dtype=np.uint8, options={"act": None},
                 quant_scale=[0.1], quant_zero_point=[128])
    with open(path, "wb") as f:
        f.write(m.finish(outputs=[y]))
    return path


def bench_classification_quant(batch: int, batches: int, size: int,
                               warmup: int) -> dict:
    """Quantized-classification row (VERDICT r4 Next #2 'done when'): a
    fully-quantized MobileNet-v1-shaped .tflite through the pipeline —
    uint8 frames into the filter behind an explicit dtype-boundary caps
    pin (the idiomatic way to pin the wire dtype at a quantized
    boundary), int8 MXU inside, logits dequantized and decoded on the
    way out.  The ISSUE 10 fusion-gap row: the caps pin and the
    dequant/decoder tail used to split the graph into THREE dispatch
    stages (0.2217 vs 0.247 MFU on the float twin of the same graph);
    the planner now fuses straight through the pin, so the whole front
    is ONE program — ``fused_stage`` carries the '+'-joined proof."""
    path = _quant_mobilenet_file(size, batch=batch)
    total = _source_total_frames(batch, batches, warmup)
    desc = (
        f"videotestsrc device=true batch={batch} num-buffers={total} "
        f"width={size} height={size} name=src ! "
        f"other/tensors,num_tensors=1,dimensions=3:{size}:{size}:{batch},"
        "types=uint8,format=static ! "
        f"tensor_filter framework=jax model={path} name=f ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-128.0,mul:0.1 name=deq ! "
        "tensor_decoder mode=image_labeling ! "
        f"tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
    )
    r = _source_driven_bench(
        desc, batch, batches, warmup,
        "mobilenet_v1_quant_pipeline_fps_per_chip", 250.0, "videotestsrc")
    r["int_exec"] = True
    r["fused_stage"] = max(
        (s.rsplit(".", 1)[0] for s in r.get("stages", {})),
        key=lambda s: s.count("+"), default="")
    return r


def _drain_batches() -> int:
    """Batches pulled (and discarded) before timing starts: must exceed the
    total queue slots across stages, or batches pre-computed during the
    first compile leak into the measured window."""
    return 4 * _SOURCE_QUEUE_CAPACITY + 8


def _source_total_frames(batch: int, batches: int, warmup: int) -> int:
    """num-buffers for a free-running source: warmup + drain + measured."""
    return (warmup + _drain_batches() + batches) * batch


def _source_driven_bench(desc: str, batch: int, batches: int, warmup: int,
                         metric: str, baseline_fps: float, source: str,
                         pulls_per_batch: int = 1) -> dict:
    """Benchmark a pipeline whose source free-runs (no app pushes): pull
    `batches` batch-buffers off the sink and measure wall time.  The
    caller builds desc with num-buffers=_source_total_frames(...) and this
    runner burns warmup+_drain_batches() pulls before timing.
    ``pulls_per_batch`` accounts for decoders that un-batch."""
    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics

    _metrics.reset()  # per-bench stage timers
    p = nt.Pipeline(desc, fuse=True, queue_capacity=_SOURCE_QUEUE_CAPACITY)
    lat = []
    with p:
        # Link probe BEFORE the drain pulls: probing after would let the
        # free-running source refill the prefetch queue during the
        # ~5-RTT probe, leaking pre-computed batches into the measured
        # window (the exact hazard _drain_batches() guards against).
        rtt_ms = _fetch_rtt_ms()
        for _ in range((warmup + _drain_batches()) * pulls_per_batch):
            p.pull("out", timeout=600)  # compile + drain pre-buffered
        t0 = time.perf_counter()
        prev = t0
        for _ in range(batches):
            for _ in range(pulls_per_batch):
                p.pull("out", timeout=600)
            now = time.perf_counter()
            lat.append(now - prev)
            prev = now
        t1 = time.perf_counter()
        p.wait(timeout=120)
    wall = t1 - t0
    r = _stats(lat, batch, batches, wall, metric, baseline_fps, "frames/sec")
    r["source"] = source
    _add_mfu(r, p, batch)
    r["stages"] = _stage_breakdown()
    _attribute_rtt_tail(r, lat, rtt_ms)
    _attach_fetch_stats(r)
    if p.residency.reduced_outputs:
        r["reduced_outputs"] = list(p.residency.reduced_outputs)
    return r


def _attach_fetch_stats(r: dict) -> None:
    """Fetch-engine accounting (docs/FETCH.md): the h2d/d2h stall split
    (appsrc admission wait vs sink materialization wait — the two sides
    ``rtt_stalls`` used to conflate), the fetch time that OVERLAPPED
    pipeline work instead of blocking a pull, and the async fetch window
    depth.  Summed across elements from the run's metric snapshot."""
    from nnstreamer_tpu.core.log import metrics as _m

    snap = _m.snapshot()
    fields = {
        "h2d_wait_ms": "h2d_wait_ms", "rtt_stalls_h2d": "h2d_stalls",
        "d2h_wait_ms": "d2h_wait_ms", "rtt_stalls_d2h": "d2h_stalls",
        "fetch_overlap_ms": "fetch_overlap_ms",
    }
    for out_key, metric in fields.items():
        total = sum(v for k, v in snap.items()
                    if k.endswith("." + metric))
        r[out_key] = round(total, 1)
    depth = max((v for k, v in snap.items()
                 if k.endswith(".fetch_window_peak")), default=0.0)
    r["fetch_window_depth"] = int(depth)


def _attribute_rtt_tail(r: dict, lat, rtt_ms: float) -> None:
    """Attribute the latency tail (VERDICT r4 Weak #5): over the
    tunneled chip the consumer periodically drains the sink's prefetch
    queue and one pull waits a REAL fetch roundtrip — a link event, not
    device work.  A stall is a sample at least half an RTT ABOVE the
    median service time (an absolute 0.5*RTT cut would flag 100% of
    samples on any config whose steady-state step exceeds it), so a
    p99 ~= p50 + fetch_rtt_ms is self-evidencing against the same
    session's link."""
    import numpy as np

    p50_ms = float(np.percentile(lat, 50)) * 1e3 if lat else 0.0
    cut_ms = p50_ms + 0.5 * rtt_ms
    stalls = [l for l in lat if l * 1e3 > cut_ms]
    r["fetch_rtt_ms"] = round(rtt_ms, 2)
    r["rtt_stalls"] = len(stalls)
    r["rtt_stall_ms_total"] = round(sum(stalls) * 1e3, 1)


def _fetch_rtt_ms() -> float:
    """Median small-fetch roundtrip to the device (the quantum a pull
    pays whenever it catches the prefetcher; block_until_ready is a
    no-op over the tunnel, so only a byte fetch measures it).  Single
    source of truth lives in tools/_chiptime.py — bench runs from the
    repo root, where `tools` is importable."""
    from tools._chiptime import fetch_rtt_s

    return fetch_rtt_s(force=True) * 1e3


def bench_detection(batch: int, batches: int, size: int, warmup: int,
                    model: str = "ssd_mobilenet") -> dict:
    """Config #2 names both SSD-MobileNet AND YOLOv5; ``model`` selects
    (all drive the same bounding_boxes decode, yolo via option1).
    ``yolov5s`` is the REAL-geometry CSP detector (~17 GF/frame @640,
    models/yolo.py apply_v5s) and runs at 640x640 / batch 32 by default;
    the plain ``yolov5`` name is the toy-backbone stand-in kept for cheap
    tests (its row is labeled _toy)."""
    if model == "yolov5s":
        if size is None:  # unset: real geometry means 640
            size = 640
        # 64 measured best (r5): MFU 0.199 model-only vs 0.172 at 32;
        # the [B,25200,96] f32 head transient bounds HBM above that
        batch = min(batch, 64)
    size = size or 224
    total = _source_total_frames(batch, batches, warmup)
    fmt = ("yolov5" if model in ("yolov5", "yolov5s")
           else model if model == "yolov8" else "ssd")
    # input convention per family: SSD-mobilenet [-1,1]; YOLO [0,1]
    norm = ("typecast:float32,div:255.0" if fmt != "ssd"
            else "typecast:float32,add:-127.5,div:127.5")
    desc = (
        f"videotestsrc device=true batch={batch} num-buffers={total} "
        f"width={size} height={size} pattern=ball name=src ! "
        f"tensor_transform mode=arithmetic option={norm} ! "
        f"tensor_filter framework=jax model={model} custom=size:{size},classes:91,batch:{batch} name=f ! "
        f"tensor_decoder mode=bounding_boxes option1={fmt} option3=0.5 "
        f"option4={size}:{size} option6=16 option7=device option9=tensors ! "
        f"tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
    )
    # option6=16: the synthetic scene holds <=2 objects; 16 kept rows
    # bound the per-frame D2H payload honestly (the [B,M,7] packed
    # payload is what the tunnel actually ships per batch)
    # option7=device fuses threshold + greedy NMS into the XLA program
    # (ops/nms.nms_jax); option9=tensors ships the final detections as
    # tensors with NO host canvas — the classification recipe (indices,
    # not payloads) applied to detection.  The overlay path stays golden-
    # tested; this measures the headless serving contract.
    label = model + ("_toy" if model in ("yolov5", "yolov8") else "")
    r = _source_driven_bench(
        desc, batch, batches, warmup,
        f"{label}_detection_fps_per_chip", 250.0, "videotestsrc",
    )
    r["decode_output"] = "tensors"
    r["input_size"] = size
    return r


def _bench_llm_continuous(p, rng, max_new: int, prompt_len: int,
                          streams: int, model: str, quant: str,
                          shared_prefix: int = 0, draft: str = "",
                          spec_k: int = 4,
                          temperature: float = 0.0) -> dict:
    """Continuous batching: stagger ``streams`` prompts into the RUNNING
    decode loop; report aggregate tokens/sec plus the late joiner's
    first-token latency (the metric continuous batching exists for —
    a static group would hold it until the whole running group ends).

    Token accounting uses the serve loop's per-token ``emit_t`` meta, not
    pull times: tokens queue at the sink while a pull blocks, so wall
    clocks around pulls would count tokens generated outside the window.
    The late joiner's first token is identified by stream identity (the
    SECOND buffer arriving with stream_index 0), not by pull order —
    stream 0's whole first chunk precedes the joiner's admission."""
    import numpy as np

    import nnstreamer_tpu as nt

    def tagged(base):  # distinguishes streams at the shared sink
        b = nt.Buffer([base])
        b.meta["bench_stream"] = tagged.n
        tagged.n += 1
        return b
    tagged.n = 0

    # prefix-sharing rows: every prompt = one shared preamble + its own
    # suffix (docs/SERVING.md §4b) — joiners after stream 0's prefill
    # hit the prefix cache, so their admission reservation and
    # first-token prefill collapse to ~the suffix
    pre = (rng.integers(1, 400, (shared_prefix,), dtype=np.int32)
           if shared_prefix else None)

    def prompt():
        suf = rng.integers(1, 400, (prompt_len,), dtype=np.int32)
        return suf if pre is None else np.concatenate([pre, suf])

    from nnstreamer_tpu.core.log import metrics as _metrics
    snap0 = _metrics.snapshot()

    with p:
        p.push("src", tagged(prompt()))
        first = p.pull("out", timeout=2100)  # stream 0 live (+compile)
        t_join = time.monotonic()
        p.push("src", tagged(prompt()))
        for _ in range(streams - 2):
            p.push("src", tagged(prompt()))
        total = streams * max_new - 1
        bufs = [p.pull("out", timeout=900) for _ in range(total)]
        p.eos()
        p.wait(timeout=120)
    join = next(b for b in bufs
                if b.meta["bench_stream"] == 1
                and b.meta["stream_index"] == 0)
    join_ms = (join.meta["emit_t"] - t_join) * 1e3
    # generation-window throughput: emission timestamps of every token
    # after stream 0's first (which carries compile + weight gen)
    emits = sorted(b.meta["emit_t"] for b in bufs)
    wall = emits[-1] - first.meta["emit_t"]
    tps = len(emits) / wall
    # Full-occupancy rate: the window where every slot is live (last
    # stream's first token -> first stream's last token).  The headline
    # window necessarily includes the stagger ramp (stream 0 decoding
    # alone until the joiners land), which is the SCENARIO's shape, not
    # the loop's ceiling — this field isolates the loop.
    firsts, lasts = {}, {}
    for b in [first] + bufs:
        s = b.meta["bench_stream"]
        t = b.meta["emit_t"]
        firsts[s] = min(firsts.get(s, t), t)
        lasts[s] = max(lasts.get(s, t), t)
    lo, hi = max(firsts.values()), min(lasts.values())
    occ = [b for b in [first] + bufs if lo <= b.meta["emit_t"] <= hi]
    occ_tps = (len(occ) - 1) / (hi - lo) if hi > lo and len(occ) > 1 else 0.0
    # Late-join decomposition: a joiner waits for the RUNNING chunk to
    # finish (admission is quantized to chunk boundaries), pays its own
    # bucketed prefill, and its first token crosses the link once — so
    # join_ms ~= chunk_ms + prefill + fetch RTT.  Carrying the session's
    # measured RTT and chunk time makes a slow-tunnel day's inflated
    # join latency self-evidencing (VERDICT r4 Next #3 honesty clause).
    chunk_ms = 0.0
    s0 = sorted(b.meta["emit_t"] for b in [first] + bufs
                if b.meta["bench_stream"] == 0)
    if len(s0) > 9:
        # stream 0's first two chunk boundaries (chunk tokens emit
        # together; the gap between bursts is one chunk's decode time)
        gaps = np.diff(np.asarray(s0[:17]))
        chunk_ms = float(np.max(gaps)) * 1e3
    row = {
        "metric": (f"{model}_{quant or 'bf16'}_continuous_tokens_per_sec"
                   f"_{streams}_streams"
                   + (f"_prefix{shared_prefix}" if shared_prefix else "")
                   + (f"_spec_k{spec_k}" if draft else "")
                   + ("_sampled" if temperature > 0.0 else "")),
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / 20.0, 3),
        "streams": streams,
        "max_new": max_new,
        "late_join_first_token_ms": round(join_ms, 1),
        "decode_chunk_ms": round(chunk_ms, 1),
        "fetch_rtt_ms": round(_fetch_rtt_ms(), 2),
        "full_occupancy_tokens_per_sec": round(occ_tps, 1),
        "wall_s": round(wall, 3),
    }
    if temperature > 0.0:
        row["temperature"] = temperature
    snap1 = _metrics.snapshot()

    def delta(name):
        return snap1.get(name, 0.0) - snap0.get(name, 0.0)

    if shared_prefix:
        row["shared_prefix"] = shared_prefix
        row["prefix_hits"] = int(delta("llm.serve.prefix_hits"))
        row["prefix_hit_blocks"] = int(delta("llm.serve.prefix_hit_blocks"))
        row["cow_forks"] = int(delta("llm.serve.cow_forks"))
    if draft:
        acc = delta("llm.serve.spec_accepted")
        rej = delta("llm.serve.spec_rejected")
        row["spec_draft"] = draft
        row["spec_k"] = spec_k
        row["spec_accept_rate"] = round(acc / (acc + rej), 3) \
            if acc + rej else 0.0
        row["spec_rounds"] = int(delta("llm.serve.spec_rounds"))
    return row


def bench_segmentation(batch: int, batches: int, size: int,
                       warmup: int, native: bool = False) -> dict:
    """Segmentation family: deeplab + fused image_segment decode (device
    argmax -> u8 class ids; 1 byte/pixel D2H, no host palette gather —
    the wav2vec2 decode-on-edge treatment; overlay compositing stays
    golden-tested and runs only where something displays it).

    The full-res row is D2H-BANDWIDTH-BOUND on the tunneled chip: the u8
    map is already the minimal full-resolution payload (H*W bytes/frame),
    so fps ~= link_bw / (H*W) regardless of compute — the per-stage
    breakdown in the row shows it.  ``native=True`` ships the class map
    at the model's output stride instead (custom=upsample:0, 256x smaller
    — full res is only a bilinear blow-up of this decision), which is the
    link-bound serving shape.
    """
    total = _source_total_frames(batch, batches, warmup)
    up = ",upsample:0" if native else ""
    desc = (
        f"videotestsrc device=true batch={batch} num-buffers={total} "
        f"width={size} height={size} pattern=smpte name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        f"tensor_filter framework=jax model=deeplab_mobilenet "
        f"custom=size:{size},batch:{batch}{up} name=f ! "
        f"tensor_decoder mode=image_segment option1=classmap ! "
        f"tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
    )
    metric = ("deeplab_segmentation_native_stride_fps_per_chip"
              if native else "deeplab_segmentation_fps_per_chip")
    r = _source_driven_bench(
        desc, batch, batches, warmup, metric, 250.0, "videotestsrc",
    )
    r["decode_output"] = "classmap" + ("_native_stride" if native else "")
    return r


def bench_pose(batch: int, batches: int, size: int, warmup: int) -> dict:
    total = _source_total_frames(batch, batches, warmup)
    desc = (
        f"videotestsrc device=true batch={batch} num-buffers={total} "
        f"width={size} height={size} pattern=ball name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        f"tensor_filter framework=jax model=posenet custom=size:{size},batch:{batch} name=f ! "
        f"tensor_decoder mode=pose_estimation option2={size}:{size} "
        f"option3=0.3 option4=tensors ! "
        f"tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
    )
    # option4=tensors: keypoint coordinates cross the sink edge (O(B*K)
    # floats), not skeleton canvases (O(B*H*W) pixels) — host-work
    # elimination per the classification recipe.
    r = _source_driven_bench(
        desc, batch, batches, warmup,
        "posenet_pipeline_fps_per_chip", 250.0, "videotestsrc",
    )
    r["decode_output"] = "tensors"
    return r


def bench_audio(batch: int, batches: int, warmup: int,
                source: str = "audiotestsrc",
                model: str = "speech_commands") -> dict:
    """Config #4 names both speech-command AND wav2vec2; ``model`` selects
    (wav2vec2 emits per-frame vocab logits via flexible output)."""
    import numpy as np

    samples = 16000  # 1s windows @16kHz
    mopts = f"dtype:float32,batch:{batch}"
    if model == "wav2vec2":
        mopts += f",samples:{samples}"
    # wav2vec2 decodes on-edge: mode=ctc fuses a device argmax into the
    # same XLA program, so D2H is [B,T] ids, not [B,T,vocab] logits
    # (which were the whole bottleneck on the tunneled chip: 405 win/s).
    dec = "tensor_decoder mode=ctc ! " if model == "wav2vec2" else ""
    if source == "audiotestsrc":
        # Device-generated windows (the audio analog of the videotestsrc
        # device source): zero H2D in the loop, measures the pipeline.
        total = _source_total_frames(batch, batches, warmup)
        desc = (
            f"audiotestsrc device=true batch={batch} num-buffers={total} "
            f"samplesperbuffer={samples} rate=16000 name=src ! "
            f"tensor_filter framework=jax model={model} "
            f"custom={mopts} name=f ! {dec}"
            f"tensor_sink name=out max-buffers={_SOURCE_QUEUE_CAPACITY}"
        )
        r = _source_driven_bench(
            desc, batch, batches, warmup,
            f"{model}_windows_per_sec_per_chip", 250.0, source,
        )
        r["unit"] = "windows/sec"
        return r
    rng = np.random.default_rng(0)
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={samples}:{batch},types=float32 ! "
        f"tensor_filter framework=jax model={model} custom={mopts} name=f ! "
        f"{dec}tensor_sink name=out"
    )
    r = _pipeline_bench(
        desc,
        lambda i: rng.standard_normal((batch, samples)).astype(np.float32),
        batch, batches, warmup,
        f"{model}_windows_per_sec_per_chip", 250.0,
        unit="windows/sec",
    )
    r["source"] = source
    return r


def _text_vocab_file(model: str) -> str:
    """Emit a .gguf carrying a SentencePiece vocab sized to ``model``'s
    embedding table (specials + byte fallback + ASCII chars + merge
    pieces, padded to the model vocab) — the text-path bench tokenizes
    through the same models/tokenizer.py machinery a real checkpoint's
    embedded vocab uses."""
    import os
    import tempfile

    import numpy as np

    from nnstreamer_tpu.models import gguf as _gguf
    from nnstreamer_tpu.models import llama as _llama
    from nnstreamer_tpu.models.tokenizer import toy_vocab

    vs = (_llama.PRESETS[model].vocab if model in _llama.PRESETS
          else 32000)
    merges = {"th": -1.0, "▁th": -0.9, "▁the": -0.4, "qu": -1.2,
              "ick": -1.1, "▁qu": -1.0, "▁quick": -0.5, "ox": -1.3,
              "▁f": -1.6, "▁fox": -0.6, "er": -0.9, "ov": -1.4,
              "▁ov": -1.2, "▁over": -0.7, "mp": -1.5, "ju": -1.4,
              "▁ju": -1.3, "▁jump": -0.8, "▁jumps": -0.7}
    tok = toy_vocab(merges)
    pad = vs - tok.n_vocab
    tok = toy_vocab(merges, n_normal_pad=max(0, pad))
    path = os.path.join(tempfile.gettempdir(),
                        f"nnstpu_bench_vocab_{model}.gguf")
    meta = {"general.architecture": "llama"}
    meta.update(tok.to_gguf_meta())
    _gguf.write(path, meta, {"pad": np.zeros((1,), np.float32)})
    return path


def bench_llm(batches: int, warmup: int, model: str = "llama_small",
              max_new: int | None = None, prompt_len: int = 32,
              quant: str = "", streams: int = 1,
              serve: str = "", text: bool = False,
              shared_prefix: int = 0, draft: str = "",
              spec_k: int = 4, temperature: float = 0.0) -> dict:
    """Config #5: tokens/sec through the llm filter (jitted prefill +
    lax.scan decode).  vs_baseline compares against the reference's
    llama.cpp CPU path order of magnitude (~20 tok/s).

    ``model=llama2_7b`` runs the REAL 7B shape: weights generated directly
    in bfloat16 on device (13.5 GB — fits one v5e chip; zero-egress stands
    in for a checkpoint upload), max_seq capped to bound the KV cache, and
    a wide stream chunk so the tunnel RTT amortizes over the lax.scan.
    """
    import numpy as np

    import nnstreamer_tpu as nt

    rng = np.random.default_rng(0)
    if (shared_prefix or draft or temperature > 0.0) \
            and serve != "continuous":
        # these rows only exist on the serve loop; silently dropping the
        # flags would record a mislabeled plain-decode artifact
        raise SystemExit("--llm-prefix/--llm-draft/--llm-temperature "
                         "require --llm-serve continuous")
    if max_new is None:
        # continuous default decodes longer so the steady full-occupancy
        # phase dominates the stagger ramp in the headline window (the
        # ramp is the scenario's shape; full_occupancy_tokens_per_sec
        # isolates it); an EXPLICIT max_new is always honored
        max_new = 128 if serve == "continuous" else 64
    custom = f"max_new:{max_new}"
    if model == "llama2_7b":
        # Multi-stream: the KV cache scales with streams (bf16 rows x
        # max_seq x B) AND XLA materializes layout-change copies of it,
        # so size it to the workload — 8 streams at max_seq:1024 blew a
        # 16 GB chip's HBM by 0.2 GB on the cache copies alone.
        max_seq = (1024 if streams == 1 and serve != "continuous"
                   else max(256, 1 << (shared_prefix + prompt_len
                                       + max_new).bit_length()))
        # continuous serving shortens the chunk: admission is quantized
        # to chunk boundaries, so 8 tokens (~150 ms at 7B int8) bounds a
        # late joiner's wait while the per-chunk roundtrip overhead stays
        # a few percent.  Static modes (r5 policy, BENCH_ALL_r5+) cover
        # max_new in ONE chunk — the decode is a single lax.scan
        # roundtrip, so a slow-tunnel day's fetch RTT (measured 15-107 ms
        # across sessions) is paid once, not per 32 tokens (the per-step
        # device profile, PROFILE_LLM_r5.json, shows the decode at its
        # HBM roofline — RTT is the only e2e lever left).  The r4 static
        # rows were measured with chunk 32 at the r4 commits recorded in
        # BENCH_ALL_r4.json; reproduce THOSE from that commit.
        chunk = 8 if serve == "continuous" else max(32, max_new)
        custom += (f",param_dtype:bfloat16,max_seq:{max_seq},"
                   f"stream_chunk:{chunk}")
    if quant:
        # weight-only int8: halves HBM bytes/token on the decode step
        custom += f",quant:{quant}"
    if text:
        # REAL tokenizer in the loop: SentencePiece encode on the prompt,
        # per-piece decode on every emitted token (stop_eos:0 keeps the
        # token count fixed — random weights sampling the eos id early
        # would shrink the measured window, not the per-token rate)
        custom += f",tokenizer:{_text_vocab_file(model)},stop_eos:0"
    n_streams = max(2, streams)
    if serve == "continuous":
        # admission granularity = one chunk; slots sized to the stream mix.
        # The paged-KV pool is sized to the WORKLOAD, not the worst case:
        # every stream reserves ceil((T + max_new) / block_size) blocks at
        # admission, so this pool admits all slots concurrently while a
        # max_seq-worst-case pool at x64 would hold ~1.6x the HBM for
        # rows no stream can ever write.
        block_size = 16
        full_len = shared_prefix + prompt_len
        need = -(-(full_len + max_new) // block_size)
        custom += (f",serve:continuous,slots:{n_streams}"
                   f",block_size:{block_size}"
                   f",kv_blocks:{n_streams * need}")
        if draft:
            # speculative decoding (docs/SERVING.md §4c): preset draft
            # priced beside the target.  temperature 0 = greedy accept
            # (bit-identical stream); >0 = rejection sampling (§4d) —
            # SAME fused verify program, accept math swaps in-body.
            custom += f",draft:{draft},spec_k:{spec_k}," \
                      f"temperature:{temperature}"
        elif temperature > 0.0:
            # sampled serve row (docs/SERVING.md §4d): per-slot seeded
            # PRNG rides the standing loop — same program census
            custom += f",temperature:{temperature}"
    # invoke-dynamic only for the continuous path: the committed static
    # rows were measured without it, and it must stay that way so this
    # commit reproduces the artifact's exact pipelines.  The '!' before
    # the sink stays OUTSIDE the conditional: interpolating it with the
    # option left the static pipelines with an UNLINKED sink (the parser
    # reads bare juxtaposition as a new gst-launch chain), which hung
    # every static llm row's first pull in the r4 sweeps until the
    # runtime learned to reject inputless non-sources at construction.
    dyn = "invoke-dynamic=true " if serve == "continuous" else ""
    desc = (
        "appsrc name=src ! "
        f"tensor_filter framework=llm model={model} custom={custom} "
        f"{dyn}! tensor_sink name=out"
    )
    p = nt.Pipeline(desc)
    if serve == "continuous":
        return _bench_llm_continuous(p, rng, max_new, prompt_len,
                                     n_streams, model, quant,
                                     shared_prefix=shared_prefix,
                                     draft=draft, spec_k=spec_k,
                                     temperature=temperature)
    toks = 0
    with p:
        # streams>1: N concurrent prompts decode in ONE lax.scan loop.
        # The decode step is weight-bandwidth-bound (the full parameter
        # set streams through the MXU once per step regardless of B), so
        # aggregate tokens/sec scales nearly linearly with streams —
        # the TPU-native serving win the per-request reference can't make.
        if text:
            if streams != 1:
                raise SystemExit("--llm-text measures the single-stream "
                                 "text contract (streams must be 1)")
            words = b"the quick brown fox jumps over the lazy dog "
            prompt = np.frombuffer(
                (words * (prompt_len // 8 + 1))[:prompt_len * 4], np.uint8)
        else:
            prompt = rng.integers(1, 400, (streams, prompt_len),
                                  dtype=np.int32)
        for _ in range(warmup):
            p.push("src", prompt)
            for _ in range(max_new):
                # generous: the FIRST pull carries device weight gen +
                # the scan-program compile, which a slow tunnel day can
                # stretch past 900 s (r4 sweep measured it)
                p.pull("out", timeout=2100)
        t0 = time.perf_counter()
        for _ in range(batches):
            p.push("src", prompt)
            for _ in range(max_new):
                p.pull("out", timeout=900)
                toks += 1
        wall = time.perf_counter() - t0
        p.eos()
        p.wait(timeout=60)
    tps = toks * streams / wall
    return {
        "metric": (f"{model}_{quant}_tokens_per_sec_per_chip" if quant
                   else f"{model}_tokens_per_sec_per_chip")
                  + (f"_x{streams}_streams" if streams > 1 else "")
                  + ("_text" if text else ""),
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / 20.0, 3),
        "max_new": max_new,
        "prompt_len": prompt_len,
        "wall_s": round(wall, 3),
    }


def bench_prefix_spec(batches: int, warmup: int,
                      model: str = "llama_small",
                      prefix_len: int = 512, suffix_len: int = 8,
                      spec_k: int = 4) -> dict:
    """ISSUE 15 A/B: prefix-sharing admission-to-first-token + the
    speculative-decoding round structure (docs/SERVING.md §4b/§4c).

    Arm 1 (prefix): serial shared-prefix streams against a warm
    continuous loop, ``prefix_cache:1`` vs ``prefix_cache:0`` — the
    cache-hit arm prefills only the non-shared suffix, so
    admission-to-first-token collapses (the ≥5x tentpole target; this
    IS visible on the CPU proxy, where prefill chunks are real compute).

    Arm 2 (speculation): decode tok/s with ``draft:<same preset>``
    (identical params → accept rate 1, the trained-draft agreement
    CEILING) vs plain decode.  The CPU proxy can NOT show the silicon
    win: the same-preset draft's propose steps cost exactly one
    target-step each here, while on silicon the row's real draft
    (llama_tiny vs 7B int8, llm7b_spec_k4) reads ~0.2% of the target's
    HBM bytes per step — the roofline projection
    ``(accept*k + 1) / (1 + k*draft_cost_ratio)`` rides the row
    (BENCH_LEARN_r01 precedent: proxy number + silicon rationale)."""
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.models import llama as _llama

    rng = np.random.default_rng(0)
    max_new = 16
    base = (f"max_new:{max_new},serve:continuous,slots:2,stream_chunk:2,"
            f"temperature:0.0,block_size:16,prefill_chunk:32,kv_blocks:0")
    pre = rng.integers(1, 400, (prefix_len,), dtype=np.int32)

    def admission_ms(prefix_cache: int) -> float:
        desc = ("appsrc name=src ! "
                f"tensor_filter framework=llm model={model} "
                f"custom={base},prefix_cache:{prefix_cache} "
                "invoke-dynamic=true ! tensor_sink name=out")
        lat = []
        with nt.Pipeline(desc) as p:
            # stream 0: compile warm-up + (hit arm) cache population
            p.push("src", np.concatenate(
                [pre, rng.integers(1, 400, (suffix_len,), np.int32)]))
            for _ in range(max_new):
                p.pull("out", timeout=2100)
            for i in range(warmup + batches):
                prompt = np.concatenate(
                    [pre, rng.integers(1, 400, (suffix_len,), np.int32)])
                t0 = time.monotonic()
                p.push("src", prompt)
                bufs = [p.pull("out", timeout=900)
                        for _ in range(max_new)]
                if i >= warmup:
                    first = next(b for b in bufs
                                 if b.meta["stream_index"] == 0)
                    lat.append((first.meta["emit_t"] - t0) * 1e3)
            p.eos()
            p.wait(timeout=60)
        lat.sort()
        return lat[len(lat) // 2]

    hit_ms = admission_ms(1)
    cold_ms = admission_ms(0)

    # -- arm 2: speculation round structure --------------------------------
    spec_new, streams, plen = 64, 2, 12

    def decode_tps(spec: bool) -> tuple:
        extra = f",draft:{model},spec_k:{spec_k}" if spec else ""
        desc = ("appsrc name=src ! "
                f"tensor_filter framework=llm model={model} "
                f"custom=max_new:{spec_new},serve:continuous,slots:"
                f"{streams},stream_chunk:4,temperature:0.0,block_size:16,"
                f"kv_blocks:0,prefix_cache:0{extra} "
                "invoke-dynamic=true ! tensor_sink name=out")
        a0 = _metrics.snapshot().get("llm.serve.spec_accepted", 0.0)
        r0 = _metrics.snapshot().get("llm.serve.spec_rejected", 0.0)
        with nt.Pipeline(desc) as p:
            p.push("src", rng.integers(1, 400, (plen,), np.int32))
            first = p.pull("out", timeout=2100)  # compile + stream 0 live
            for _ in range(streams - 1):
                p.push("src", rng.integers(1, 400, (plen,), np.int32))
            bufs = [p.pull("out", timeout=900)
                    for _ in range(streams * spec_new - 1)]
            p.eos()
            p.wait(timeout=60)
        emits = sorted(b.meta["emit_t"] for b in bufs)
        wall = emits[-1] - first.meta["emit_t"]
        snap = _metrics.snapshot()
        acc = snap.get("llm.serve.spec_accepted", 0.0) - a0
        rej = snap.get("llm.serve.spec_rejected", 0.0) - r0
        rate = acc / (acc + rej) if acc + rej else 0.0
        return len(emits) / wall, rate

    plain_tps, _ = decode_tps(False)
    spec_tps, accept_rate = decode_tps(True)

    # silicon roofline projection for the REAL row (llm7b_spec_k4:
    # llama_tiny draft against the int8 7B target): per decode step the
    # draft reads its own params, the target reads quantized params —
    # cost ratio c from the same estimates serving_plan() prices
    tiny = _llama.PRESETS["llama_tiny"]
    big = _llama.PRESETS["llama2_7b"]
    c = (_llama.param_bytes_estimate(tiny, param_dtype="float32")
         / _llama.param_bytes_estimate(big, quant="int8",
                                       param_dtype="bfloat16"))
    projected = {
        f"accept_{int(a * 100)}": round((a * spec_k + 1)
                                        / (1 + spec_k * c), 2)
        for a in (0.5, 0.7, 0.9)}

    speedup = cold_ms / hit_ms if hit_ms else 0.0
    return {
        "metric": f"{model}_prefix_hit_admission_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 5.0, 3),  # the ≥5x tentpole bar
        "prefix_len": prefix_len,
        "suffix_len": suffix_len,
        "admission_first_token_hit_ms": round(hit_ms, 1),
        "admission_first_token_cold_ms": round(cold_ms, 1),
        "spec_tokens_per_sec": round(spec_tps, 1),
        "plain_tokens_per_sec": round(plain_tps, 1),
        "spec_speedup_vs_plain": round(spec_tps / plain_tps, 3)
        if plain_tps else 0.0,
        "spec_k": spec_k,
        "spec_accept_rate": round(accept_rate, 3),
        "spec_draft_cost_ratio_7b_int8": round(c, 4),
        "spec_projected_speedup_7b": projected,
        "spec_proxy_caveat": (
            "same-preset draft on the CPU proxy: every propose step "
            "costs one full target step, so the measured ratio is the "
            "structural floor — the silicon row (llm7b_spec_k4, "
            "llama_tiny draft vs int8 7B) pays ~{:.2%} of the target's "
            "HBM bytes per draft step; projection = "
            "(accept*k+1)/(1+k*cost_ratio)".format(c)),
    }


def bench_gqa_sampling(batches: int, warmup: int,
                       model: str = "llama_tiny",
                       spec_k: int = 3) -> dict:
    """ISSUE 16 A/B: the three decode hot-loop changes in one row —
    grouped-GQA kernel traffic, the fused speculative verify's host
    transfer budget, and the sampled serve loop's overhead.

    Arm 1 (kernel): flash attention on the SAME [B,S,Hkv,D] K/V fed
    grouped vs pre-repeated to [B,S,H,D].  On the CPU proxy the Pallas
    kernel runs interpreted and per-call trace overhead dominates the
    wall (measured ratio ~1x despite the repeated layout running
    H/Hkv x the grid) — the A/B here only pins that grouped is never
    SLOWER; the silicon claim rides the projection below, which is pure
    ``serving_plan`` arithmetic (decode K/V bytes scale with n_kv_heads,
    tests/test_kernels_gqa.py pins the kernel's DMA structure).

    Arm 2 (sampling): continuous-serve tokens/sec at temperature 0.9 vs
    greedy on identical prompts — the per-slot seeded sampler
    (docs/SERVING.md §4d) compiles into the standing decode program, so
    its cost is a few fused element-wise ops per step, not a program
    swap.  The tiny CPU preset EXAGGERATES the sampler's share (its
    model step is microseconds; sort/cumsum over the vocab is
    comparable) — the silicon delta is llm7b_sampled_x32 vs
    llm7b_int8_continuous_x32, where the 7B step dwarfs it.

    Arm 3 (fused verify): sampled speculative serve (rejection
    sampling through the SAME fused [slots, k+1] verify program), plus
    the per-round host-transfer ledger the fusion buys: the loop now
    downloads exactly the emitted rows + accept counts, where the
    unfused round also shipped the proposals down and the tok/tok_prev
    state back up (tests/test_sampling.py pins proposals-never-leave).

    Silicon projection: llama2_7b at n_kv_heads 8 (the production 70B
    GQA geometry on the 7B shape) vs its stock 32 at int8 weights,
    32 streams x 1024 live context tokens — decode is HBM-roofline
    bound (PROFILE_LLM_r5 precedent), so projected tok/s scales with
    step bytes: (params + kv_mha) / (params + kv_gqa)."""
    import dataclasses

    import numpy as np

    import jax
    import jax.numpy as jnp

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.filters.llm import serving_plan
    from nnstreamer_tpu.models import llama as _llama
    from nnstreamer_tpu.ops import attention as _att

    rng = np.random.default_rng(0)
    on_cpu = jax.default_backend() == "cpu"

    # -- arm 1: grouped vs repeated kernel ---------------------------------
    b, s, h, hkv, d = 1, 256, 8, 2, 32
    kk = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kk[0], (b, s, h, d), jnp.float32)
    kt = jax.random.normal(kk[1], (b, s, hkv, d), jnp.float32)
    vt = jax.random.normal(kk[2], (b, s, hkv, d), jnp.float32)
    krep = jnp.repeat(kt, h // hkv, axis=2)
    vrep = jnp.repeat(vt, h // hkv, axis=2)

    def kernel_ms(kx, vx) -> float:
        def once():
            jax.block_until_ready(_att.flash_attention(
                q, kx, vx, causal=True, block_q=64, block_k=64,
                interpret=on_cpu or None))
        once()  # trace/compile warm-up
        reps = max(2, min(batches, 4 if on_cpu else 32))
        t0 = time.perf_counter()
        for _ in range(reps):
            once()
        return (time.perf_counter() - t0) / reps * 1e3

    grouped_ms = kernel_ms(kt, vt)
    repeated_ms = kernel_ms(krep, vrep)

    # -- arms 2+3: serve-loop tok/s (greedy / sampled / sampled spec) ------
    max_new, streams, plen = 32, 2, 12

    def serve_tps(temp: float, spec: bool) -> tuple:
        extra = f",draft:{model},spec_k:{spec_k}" if spec else ""
        desc = ("appsrc name=src ! "
                f"tensor_filter framework=llm model={model} "
                f"custom=max_new:{max_new},serve:continuous,slots:"
                f"{streams},stream_chunk:4,temperature:{temp},seed:3,"
                f"block_size:16,kv_blocks:0,prefix_cache:0{extra} "
                "invoke-dynamic=true ! tensor_sink name=out")
        a0 = _metrics.snapshot().get("llm.serve.spec_accepted", 0.0)
        r0 = _metrics.snapshot().get("llm.serve.spec_rejected", 0.0)
        with nt.Pipeline(desc) as p:
            p.push("src", rng.integers(1, 400, (plen,), np.int32))
            first = p.pull("out", timeout=2100)  # compile + stream 0 live
            for _ in range(streams - 1):
                p.push("src", rng.integers(1, 400, (plen,), np.int32))
            bufs = [p.pull("out", timeout=900)
                    for _ in range(streams * max_new - 1)]
            p.eos()
            p.wait(timeout=60)
        emits = sorted(bf.meta["emit_t"] for bf in bufs)
        wall = emits[-1] - first.meta["emit_t"]
        snap = _metrics.snapshot()
        acc = snap.get("llm.serve.spec_accepted", 0.0) - a0
        rej = snap.get("llm.serve.spec_rejected", 0.0) - r0
        rate = acc / (acc + rej) if acc + rej else 0.0
        return len(emits) / wall, rate

    greedy_tps, _ = serve_tps(0.0, False)
    sampled_tps, _ = serve_tps(0.9, False)
    spec_tps, accept_rate = serve_tps(0.9, True)

    # fused-verify host ledger, per round at [slots, k+1] (int32):
    # fused = emitted rows + accept counts; the unfused structure also
    # downloaded the k proposals and re-uploaded tok/tok_prev/positions
    fused_bytes = streams * (spec_k + 1) * 4 + streams * 4
    unfused_bytes = (fused_bytes + streams * spec_k * 4
                     + 3 * streams * 4)

    # -- silicon projection: 7B int8 decode step bytes, MHA vs GQA-8 -------
    big = _llama.PRESETS["llama2_7b"]
    gqa = dataclasses.replace(big, n_kv_heads=8)
    p_mha = serving_plan(big, slots=32, dtype="bfloat16")
    p_gqa = serving_plan(gqa, slots=32, dtype="bfloat16")
    param = _llama.param_bytes_estimate(big, quant="int8",
                                        param_dtype="bfloat16")
    live_ctx = 32 * 1024  # 32 streams x 1024 live context tokens
    step_mha = param + live_ctx * p_mha["decode_bytes_per_ctx_token"]
    step_gqa = param + live_ctx * p_gqa["decode_bytes_per_ctx_token"]
    proj = step_mha / step_gqa

    return {
        "metric": "gqa_grouped_decode_projected_speedup_7b",
        "value": round(proj, 2),
        "unit": "x",
        "vs_baseline": round(proj / 1.3, 3),  # the >=1.3x tentpole bar
        "kv_groups_7b_gqa8": p_gqa["kv_groups"],
        "decode_bytes_per_ctx_token_mha": p_mha[
            "decode_bytes_per_ctx_token"],
        "decode_bytes_per_ctx_token_gqa8": p_gqa[
            "decode_bytes_per_ctx_token"],
        "param_bytes_7b_int8": int(param),
        "projection_live_ctx_tokens": live_ctx,
        "flash_grouped_ms": round(grouped_ms, 1),
        "flash_repeated_ms": round(repeated_ms, 1),
        "kernel_ab_ratio": round(repeated_ms / grouped_ms, 2)
        if grouped_ms else 0.0,
        "kernel_proxy_caveat": (
            "interpreted Pallas on the CPU proxy: per-call trace "
            "overhead dominates the wall, so the A/B only pins that the "
            "grouped layout is never slower — on silicon the win is the "
            "K/V DMA traffic ratio (kv_groups), priced by serving_plan "
            "and pinned by tests/test_kernels_gqa.py"),
        "greedy_tokens_per_sec": round(greedy_tps, 1),
        "sampled_tokens_per_sec": round(sampled_tps, 1),
        "sampler_overhead_pct": round(
            (greedy_tps / sampled_tps - 1) * 100, 1)
        if sampled_tps else 0.0,
        "sampler_proxy_caveat": (
            "tiny-preset CPU proxy: the model step is microseconds, so "
            "the compiled-in sampler's vocab-length sort/cumsum reads "
            "as tens of percent — at 7B the same ops are noise against "
            "the HBM-bound step (llm7b_sampled_x32 vs "
            "llm7b_int8_continuous_x32 measures it)"),
        "spec_sampled_tokens_per_sec": round(spec_tps, 1),
        "spec_k": spec_k,
        "spec_accept_rate": round(accept_rate, 3),
        "fused_verify_host_bytes_per_round": fused_bytes,
        "unfused_verify_host_bytes_per_round": unfused_bytes,
        "verify_host_transfer_reduction": round(
            unfused_bytes / fused_bytes, 2),
    }


def bench_batching(batches: int, warmup: int, batch_max: int = 8,
                   dims: int = 256) -> dict:
    """Adaptive micro-batching row: a BACKLOGGED small-model pipeline
    (appsrc -> tensor_filter -> tensor_sink) where per-dispatch overhead
    dominates compute.  ``batch_max=8`` lets the filter stage drain the
    backlog into bucketed vmapped dispatches (one XLA call per <=8
    buffers); the row reports the throughput ratio vs the seed's
    one-dispatch-per-buffer path (``batch_max=1``) on identical input.
    ``vs_baseline`` is speedup/2.0: 1.0 = the >=2x acceptance bar.
    Backend-agnostic by design — dispatch overhead exists on every
    backend, so this row is meaningful on CPU too."""
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.utils.profiler import metrics_text

    n = max(384, 3 * batches)
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={dims},"
        "types=float32 ! "
        f"tensor_filter framework=jax model=scaler "
        f"custom=scale:1.5,dims:{dims} name=f ! "
        "tensor_sink name=out"
    )
    frames = [np.full((dims,), float(i % 7), np.float32) for i in range(8)]

    def run(bmax: int):
        _metrics.reset()
        # same queue capacity for both runs: the comparison isolates the
        # drain->one-dispatch mechanism, not queue depth
        p = nt.Pipeline(desc, queue_capacity=64, batch_max=bmax)
        walls = []
        with p:
            for i in range(max(64, 8 * warmup)):  # compile every bucket
                p.push("src", frames[i % len(frames)])
            for _ in range(max(64, 8 * warmup)):
                p.pull("out", timeout=120)

            # best-of-3 windows: scheduling noise on a shared host easily
            # costs 2x on a sub-second window, and the row's claim is the
            # MECHANISM's steady-state ratio, not the noise floor
            for _ in range(3):
                def pusher():
                    for i in range(n):
                        p.push("src", frames[i % len(frames)])

                t = threading.Thread(target=pusher, daemon=True)
                t0 = time.perf_counter()
                t.start()
                for _ in range(n):
                    p.pull("out", timeout=120)
                walls.append(time.perf_counter() - t0)
                t.join()
            p.eos()
            p.wait(timeout=60)
        snap = _metrics.snapshot()
        occ = {k.rsplit(".", 1)[1]: round(v, 2)
               for k, v in snap.items() if k.startswith("f.batch_occupancy.")}
        return n / min(walls), occ, "batch_occupancy" in metrics_text()

    fps_batched, occ, visible = run(batch_max)
    fps_single, _, _ = run(1)
    speedup = fps_batched / fps_single
    return {
        "metric": f"adaptive_batching_speedup_batch{batch_max}_vs_1",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 2.0, 3),
        "fps_batched": round(fps_batched, 1),
        "fps_unbatched": round(fps_single, 1),
        "batch_max": batch_max,
        "buffers": n,
        "dims": dims,
        "batch_occupancy": occ,
        "occupancy_in_metrics_text": visible,
    }


def bench_adaptive(batches: int, warmup: int, batch_max: int = 8,
                   burst: int = 6, dims: int = 1280,
                   layers: int = 32) -> dict:
    """Adaptive-ladder A/B (ISSUE 10 acceptance): a compute-bound MLP
    stage driven at a SKEWED steady occupancy — bursts of ``burst`` (6)
    same-spec buffers, two bursts pipelined so every drain catches a full
    burst without linger waits.  The static ladder pads every 6-drain to
    bucket 8 (+33% wasted rows of real matmul work); the adaptive ladder
    (``adaptive_buckets=True``) observes the skew and mints an exact
    6-bucket, so steady state dispatches exactly what arrived.  The row
    reports the throughput ratio (``vs_baseline`` = speedup/1.2: 1.0 =
    the >=1.2x acceptance bar), the measured pad-waste counters for both
    runs, and the refined ladder snapshot.  Backend-agnostic: pad rows
    cost real compute on CPU and TPU alike (CPU proxy acceptable per the
    acceptance)."""
    import jax.numpy as jnp
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    w = (np.random.default_rng(11).standard_normal((dims, dims))
         .astype(np.float32) * (0.9 / np.sqrt(dims)))

    def mlp(ins):
        x = ins[0]
        for _ in range(layers):
            x = jnp.tanh(x @ w)
        return [x]

    spec = TensorsSpec.from_string(str(dims), "float32")
    register_custom_easy("bench-adaptive-mlp", mlp, in_spec=spec,
                         out_spec=spec, jax_traceable=True)
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={dims},"
        "types=float32 ! "
        "tensor_filter framework=custom-easy model=bench-adaptive-mlp "
        "name=f ! tensor_sink name=out"
    )
    frames = [np.full((dims,), float(i % 7) * 0.1, np.float32)
              for i in range(8)]
    n_bursts = max(64, batches // 2)
    warm_bursts = max(40, 8 * warmup)  # past MINT_AFTER: the ladder is
    #                                    refined before the timed window

    def run(adaptive: bool):
        _metrics.reset()
        p = nt.Pipeline(desc, queue_capacity=64, batch_max=batch_max,
                        data_parallel=1, adaptive_buckets=adaptive)
        walls = []
        with p:
            def cycle(n):
                # two bursts pipelined: while burst k computes, burst k+1
                # is already queued, so each drain catches exactly
                # `burst` rows with NO linger wait
                k = 0
                for _ in range(2):
                    for _ in range(burst):
                        p.push("src", frames[k % 8]); k += 1
                for _ in range(n - 2):
                    for _ in range(burst):
                        p.pull("out", timeout=300)
                    for _ in range(burst):
                        p.push("src", frames[k % 8]); k += 1
                for _ in range(2 * burst):
                    p.pull("out", timeout=300)

            cycle(warm_bursts)
            for _ in range(3):  # best-of-3: the mechanism, not the noise
                t0 = time.perf_counter()
                cycle(n_bursts)
                walls.append(time.perf_counter() - t0)
            snap = _metrics.snapshot()
            ladders = p.ladder_snapshot()
            p.eos()
            p.wait(timeout=60)
        occ = {k.rsplit(".", 1)[1]: round(v, 2) for k, v in snap.items()
               if k.startswith("f.batch_occupancy.")}
        return (n_bursts * burst / min(walls),
                snap.get("f.batch_pad_waste", 0.0), occ, ladders)

    fps_adaptive, waste_adaptive, occ_a, ladders = run(True)
    fps_static, waste_static, occ_s, _ = run(False)
    speedup = fps_adaptive / fps_static
    return {
        "metric": f"adaptive_ladder_speedup_burst{burst}_vs_static",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 1.2, 3),
        "fps_adaptive": round(fps_adaptive, 1),
        "fps_static": round(fps_static, 1),
        "pad_waste_adaptive": waste_adaptive,
        "pad_waste_static": waste_static,
        "ladders": ladders,
        "batch_occupancy": occ_a,
        "batch_occupancy_static": occ_s,
        "burst": burst, "batch_max": batch_max,
        "dims": dims, "layers": layers,
    }


def bench_asr_stream(batches: int, warmup: int, chunk: int = 4000,
                     window: int = 16000) -> dict:
    """Windowed streaming-ASR A/B (ISSUE 10 acceptance): the
    examples/asr_streaming_window.py pipeline — device-generated audio
    chunks -> tensor_aggregator -> speech_commands — with the window
    carry HOST-side (np.concatenate per window, a full fetch round trip)
    vs DEVICE-RESIDENT (``device=true``: HBM ring, in-program appends,
    zero d2h between windows, 3-program census).  Reports windows/sec
    for the device ring and the host/device ratio.  On the tunneled chip
    the host path pays ``fetch_rtt_ms`` per chunk; the CPU proxy only
    shows the copy/dispatch savings — the row still pins the MECHANISM
    (ring windows bit-identical, resident edge counted)."""
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics

    stride = chunk
    n_windows = max(32, batches)
    chunks = (n_windows - 1) * stride // chunk + window // chunk
    desc = (
        f"audiotestsrc device=true num-buffers={{n}} "
        f"samplesperbuffer={chunk} rate=16000 freq=880 name=src ! "
        f"tensor_aggregator frames_in={chunk} frames_out={window} "
        f"frames_flush={stride} frames_dim=0 name=agg {{dev}}! "
        "tensor_filter framework=jax model=speech_commands "
        "custom=dtype:float32 name=f ! tensor_sink name=out"
    )

    def run(dev: str):
        _metrics.reset()
        warm = max(8, warmup * 4)
        total = chunks + warm
        p = nt.Pipeline(desc.format(n=total, dev=dev),
                        queue_capacity=_SOURCE_QUEUE_CAPACITY)
        with p:
            for _ in range(warm):  # compile + drain pre-buffered windows
                p.pull("out", timeout=300)
            t0 = time.perf_counter()
            outs = [p.pull("out", timeout=300) for _ in range(n_windows)]
            wall = time.perf_counter() - t0
            p.wait(timeout=120)
        head = np.asarray(outs[0].tensors[0])
        return n_windows / wall, head, p.residency.resident_edges

    fps_dev, head_dev, resident = run("device=true ")
    fps_host, head_host, _ = run("")
    return {
        "metric": "asr_streaming_window_windows_per_sec",
        "value": round(fps_dev, 1),
        "unit": "windows/sec",
        "vs_baseline": round(fps_dev / max(1e-9, fps_host), 3),
        "fps_host_aggregator": round(fps_host, 1),
        "speedup_device_vs_host": round(fps_dev / max(1e-9, fps_host), 3),
        "window": window, "chunk": chunk, "windows": n_windows,
        "resident_edges": resident,
        "first_window_scores_match": bool(
            np.array_equal(head_dev, head_host)),
    }


def bench_train_stream(batches: int, warmup: int, in_dim: int = 64,
                       hidden: int = 256, classes: int = 8,
                       bs: int = 32, epochs: int = 3) -> dict:
    """nns-learn A/B (ISSUE 14 acceptance, docs/TRAINING.md): the SAME
    jitted masked update step fed by (a) the device-resident streaming
    window (per-sample in-program appends, no host epoch accumulation)
    vs (b) the legacy host-accumulated epoch (stack + pad per
    minibatch).  Reports samples/sec for the device path and the ratio;
    the paths are bit-identical by test, so this is pure pipeline
    mechanics.  ``host_bytes_held`` contrasts the resident host memory:
    the host path keeps the WHOLE epoch as numpy, the streaming path one
    [batch-size] HBM window — on the tunneled chip the host path
    additionally pays an H2D per minibatch where the window is already
    resident.  The row also carries the checkpoint-resume contract:
    fsync'd write time and a save→load→train-one-epoch continuation
    checked BITWISE against the uninterrupted run."""
    import numpy as np

    from nnstreamer_tpu.trainer.subplugin import JaxTrainer

    n = max(256, batches * 8)
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, in_dim)).astype(np.float32)
    ys = rng.integers(0, classes, (n, 1)).astype(np.int32)
    model = f"mlp:{in_dim}:{hidden}:{hidden}:{classes}"
    props = {"model": model, "batch_size": bs, "learning_rate": 0.01}

    def epoch(tr):
        for i in range(n):
            tr.push_data([xs[i]], [ys[i]], False)
        return tr.train_epoch()

    def run(host: bool):
        tr = JaxTrainer()
        tr.open(dict(props, host_accumulate="true" if host else "false"))
        epoch(tr)  # warmup: compiles land here
        times = []
        for _ in range(epochs):
            t0 = time.perf_counter()
            epoch(tr)
            times.append(time.perf_counter() - t0)
        return tr, n * len(times) / sum(times)

    tr_dev, sps_dev = run(False)
    tr_host, sps_host = run(True)

    # checkpoint-resume row: fsync'd write, then a fresh trainer resumes
    # and must continue BITWISE where the uninterrupted twin lands
    import os
    import tempfile

    import jax

    ck = os.path.join(tempfile.mkdtemp(), "bench.ckpt")
    t0 = time.perf_counter()
    tr_dev.save(ck)
    ckpt_ms = (time.perf_counter() - t0) * 1e3
    resumed = JaxTrainer()
    resumed.open(dict(props, model_load_path=ck))
    epoch(resumed)
    epoch(tr_dev)
    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(resumed.params),
                        jax.tree_util.tree_leaves(tr_dev.params)))
    return {
        "metric": "train_stream_device_vs_host_speedup",
        "value": round(sps_dev / max(1e-9, sps_host), 3),
        "unit": "x",
        "vs_baseline": round(sps_dev / max(1e-9, sps_host), 3),
        "samples_per_sec_device": round(sps_dev, 1),
        "samples_per_sec_host": round(sps_host, 1),
        "samples": n, "batch_size": bs, "epochs": epochs,
        "model": model,
        "census": tr_dev.compile_counts(),
        "train_state_bytes": tr_dev.train_state_bytes(),
        "host_bytes_held_host_path": n * (xs[0].nbytes + ys[0].nbytes),
        "host_bytes_held_device_path": 0,
        "ckpt_write_ms": round(ckpt_ms, 2),
        "resume_bit_identical": bool(identical),
    }


def bench_sharded(batches: int, warmup: int, replicas: int = 4,
                  batch_max: int = 32, dims: int = 640,
                  layers: int = 40) -> dict:
    """Mesh-sharded micro-batching row (ISSUE 3 acceptance): a BACKLOGGED
    compute-bound pipeline (appsrc -> jax-traceable MLP filter ->
    tensor_sink) where per-dispatch compute, not overhead, bounds
    throughput.  ``data_parallel=4, dispatch_depth=2`` shards each
    bucketed micro-batch over a 4-chip ``data`` mesh and software-
    pipelines the drain; the row reports the throughput ratio vs the
    single-device lockstep path (``data_parallel=1, dispatch_depth=1``)
    on identical input, plus the per-replica placement counters from
    metrics_text().  ``vs_baseline`` is speedup/1.5: 1.0 = the >=1.5x
    acceptance bar.  On CPU the 8-virtual-device host platform is the
    mesh proxy (main() pins the XLA flag when JAX_PLATFORMS=cpu)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.filters.custom_easy import register_custom_easy
    from nnstreamer_tpu.utils.profiler import metrics_text

    if len(jax.devices()) < replicas:
        raise SystemExit(
            f"--config sharded needs {replicas} local devices, have "
            f"{len(jax.devices())} (CPU proxy: XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")

    w = (np.random.default_rng(3).standard_normal((dims, dims))
         .astype(np.float32) * (0.9 / np.sqrt(dims)))

    def mlp(ins):
        x = ins[0]
        for _ in range(layers):
            x = jnp.tanh(x @ w)
        return [x]

    spec = TensorsSpec.from_string(str(dims), "float32")
    register_custom_easy("bench-shard-mlp", mlp, in_spec=spec,
                         out_spec=spec, jax_traceable=True)
    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={dims},"
        "types=float32 ! "
        "tensor_filter framework=custom-easy model=bench-shard-mlp "
        "name=f ! tensor_sink name=out"
    )
    frames = [np.full((dims,), float(i % 7) * 0.1, np.float32)
              for i in range(8)]
    n = max(256, 2 * batches)

    def run(dp: int, depth: int):
        _metrics.reset()
        # same queue capacity + batch_max both runs: the comparison
        # isolates shard + window, not queue depth or drain size
        p = nt.Pipeline(desc, queue_capacity=64, batch_max=batch_max,
                        data_parallel=dp, dispatch_depth=depth)
        walls = []
        with p:
            for i in range(max(64, 8 * warmup)):  # compile every bucket
                p.push("src", frames[i % len(frames)])
            for _ in range(max(64, 8 * warmup)):
                p.pull("out", timeout=300)
            # best-of-3 windows, as the batching row: the claim is the
            # mechanism's steady-state ratio, not scheduler noise
            for _ in range(3):
                def pusher():
                    for i in range(n):
                        p.push("src", frames[i % len(frames)])

                t = threading.Thread(target=pusher, daemon=True)
                t0 = time.perf_counter()
                t.start()
                for _ in range(n):
                    p.pull("out", timeout=300)
                walls.append(time.perf_counter() - t0)
                t.join()
            p.eos()
            p.wait(timeout=60)
        snap = _metrics.snapshot()
        repl = {k.rsplit(".", 1)[1]: round(v, 1) for k, v in snap.items()
                if k.startswith("f.shard_rows.")}
        visible = "shard_rows" in metrics_text() if repl else False
        return (n / min(walls), repl, snap.get("f.shard_dispatch", 0.0),
                visible)

    fps_sharded, repl, dispatches, visible = run(replicas, 2)
    fps_single, _, _, _ = run(1, 1)
    speedup = fps_sharded / fps_single
    return {
        "metric": f"mesh_sharded_batching_speedup_dp{replicas}_vs_1",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup / 1.5, 3),
        "fps_sharded_dp_depth2": round(fps_sharded, 1),
        "fps_single_device_depth1": round(fps_single, 1),
        "data_parallel": replicas,
        "dispatch_depth": 2,
        "batch_max": batch_max,
        "buffers": n,
        "dims": dims,
        "mlp_layers": layers,
        "shard_dispatches": dispatches,
        "per_replica_rows": repl,
        "replica_counters_in_metrics_text": visible,
        "methodology": (
            "backlogged appsrc->filter->sink; best-of-3 steady-state "
            "windows after warmup; identical input + queue depth + "
            "batch_max both runs; CPU host-device proxy when "
            "JAX_PLATFORMS=cpu (xla_force_host_platform_device_count=8)"),
    }


def bench_tp(batches: int, warmup: int, model: str = "llama_small",
             ways: int = 2, max_new: int = 32, prompt_len: int = 16) -> dict:
    """2-D placement A/B row (ISSUE 9): tokens/sec of the llm decode
    under ``Pipeline(model_parallel=M)`` vs ``model_parallel=1`` on the
    SAME prompt — the filter rides the pipeline's shared ``(data x
    model)`` mesh, params + KV sharded per ``param_pspecs``.  On the CPU
    host-device proxy TP buys no wall-clock (the "chips" share one
    socket's caches), so like the fetch row this records the MECHANISM's
    ratio for the next chip sweep, where the decode's weight-bandwidth
    bound is what an M-way split actually divides.  The row decodes at
    the serving dtype (bf16): GSPMD's reduced collective order can flip
    a near-tie bf16 argmax, so ``greedy_ids_identical`` is informational
    here — the bitwise identity contract is pinned at f32 by
    tests/test_model_parallel.py (the mesh gate)."""
    import jax
    import numpy as np

    import nnstreamer_tpu as nt

    if len(jax.devices()) < ways:
        raise SystemExit(
            f"--config tp needs {ways} local devices, have "
            f"{len(jax.devices())} (CPU proxy: XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 400, (1, prompt_len), dtype=np.int32)
    desc = (
        "appsrc name=src ! "
        f"tensor_filter framework=llm model={model} "
        f"custom=max_new:{max_new},temperature:0.0,stream_chunk:8 "
        "invoke-dynamic=true ! tensor_sink name=out"
    )

    def run(mp: int):
        p = nt.Pipeline(desc, model_parallel=mp)
        ids = []
        toks = 0
        with p:
            for _ in range(max(1, warmup)):
                p.push("src", prompt)
                for _ in range(max_new):
                    p.pull("out", timeout=900)
            t0 = time.perf_counter()
            for _ in range(batches):
                p.push("src", prompt)
                for _ in range(max_new):
                    ids.append(int(p.pull("out", timeout=900)
                                   .tensors[0][0]))
                    toks += 1
            wall = time.perf_counter() - t0
            p.eos()
            p.wait(timeout=60)
        assert p.mesh_shape == (1, mp)
        return toks / wall, ids

    tps_tp, ids_tp = run(ways)
    tps_1, ids_1 = run(1)
    ratio = tps_tp / tps_1
    return {
        "metric": f"{model}_decode_tp{ways}_vs_tp1_tokens_per_sec",
        "value": round(tps_tp, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(ratio, 3),
        "speedup_vs_tp1": round(ratio, 3),
        "tokens_per_sec_tp1": round(tps_1, 1),
        "model_parallel": ways,
        "greedy_ids_identical_bf16": ids_tp == ids_1,
        "max_new": max_new,
        "prompt_len": prompt_len,
        "batches": batches,
        "methodology": (
            "same prompt/pipeline both runs at the serving dtype (bf16; "
            "near-tie argmax may flip under GSPMD reduction order — f32 "
            "bit-identity is pinned by tests/test_model_parallel.py); "
            "CPU host-device proxy when JAX_PLATFORMS=cpu "
            "(xla_force_host_platform_device_count=8); the chip sweep "
            "measures the real weight-bandwidth split"),
    }


def bench_tp_grid(batches: int, warmup: int, dp: int = 2, mp: int = 2,
                  dims: int = 512, layers: int = 12,
                  batch_max: int = 32) -> dict:
    """dp x tp grid row (ISSUE 9): the backlogged sharded-micro-batching
    pipeline of ``--config sharded``, but with a ``param_pspecs``-carrying
    MLP so the 2-D mesh places weights over ``model`` WHILE the batch dim
    shards over ``data`` — (dp=2, model=2) vs dp-only (dp=4) on the same
    4 chips.  The per-chip param bytes drop ~2x on the 2-D run (the
    placement counters prove it); fps ratio is the grid tradeoff the
    next chip sweep reads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics
    from nnstreamer_tpu.core.types import TensorsSpec
    from nnstreamer_tpu.models.zoo import ModelBundle, register_model

    need = dp * mp
    if len(jax.devices()) < need:
        raise SystemExit(
            f"--config tp_grid needs {need} local devices, have "
            f"{len(jax.devices())} (CPU proxy: XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")

    rng = np.random.default_rng(3)
    w1 = (rng.standard_normal((layers, dims, dims)).astype(np.float32)
          * (0.9 / np.sqrt(dims)))

    @register_model("bench-tp-grid-mlp")
    def _build(opts):
        from jax.sharding import PartitionSpec as P

        params = {"w": jnp.asarray(w1)}

        def apply_fn(p, x):
            def body(x, wl):
                return jnp.tanh(x @ wl), None
            x, _ = jax.lax.scan(body, x, p["w"])
            return x

        spec = TensorsSpec.from_string(str(dims), "float32")
        # layer-stacked mat: OUT dim shards over model (Megatron column
        # split; XLA re-gathers between layers — the grid row's point is
        # placement, not a tuned TP block)
        return ModelBundle(apply_fn, params, spec, spec,
                           param_pspecs={"w": P(None, None, "model")})

    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={dims},"
        "types=float32 ! "
        "tensor_filter framework=jax model=bench-tp-grid-mlp name=f ! "
        "tensor_sink name=out"
    )
    n = max(256, 2 * batches)
    frames = [np.full((dims,), float(i % 5) * 0.2, np.float32)
              for i in range(8)]

    def run(run_dp: int, run_mp: int):
        _metrics.reset()
        p = nt.Pipeline(desc, queue_capacity=64, batch_max=batch_max,
                        data_parallel=run_dp, model_parallel=run_mp,
                        dispatch_depth=2)
        walls = []
        with p:
            for i in range(max(64, 8 * warmup)):
                p.push("src", frames[i % len(frames)])
            for _ in range(max(64, 8 * warmup)):
                p.pull("out", timeout=300)
            for _ in range(3):
                def pusher():
                    for i in range(n):
                        p.push("src", frames[i % len(frames)])

                t = threading.Thread(target=pusher, daemon=True)
                t0 = time.perf_counter()
                t.start()
                for _ in range(n):
                    p.pull("out", timeout=300)
                walls.append(time.perf_counter() - t0)
                t.join()
            p.eos()
            p.wait(timeout=60)
        snap = _metrics.snapshot()
        return n / min(walls), {
            "shards": snap.get("f.param_shards", 0.0),
            "replicas": snap.get("f.param_replicas", 0.0),
            "rows": {k.rsplit(".", 1)[1]: round(v, 1)
                     for k, v in snap.items()
                     if k.startswith("f.shard_rows.")},
        }

    fps_grid, place_grid = run(dp, mp)
    fps_dp, place_dp = run(dp * mp, 1)
    ratio = fps_grid / fps_dp
    return {
        "metric": f"sharded_grid_dp{dp}xtp{mp}_vs_dp{dp * mp}_fps",
        "value": round(fps_grid, 1),
        "unit": "frames/sec",
        "vs_baseline": round(ratio, 3),
        "fps_dp_only": round(fps_dp, 1),
        "speedup_vs_dp_only": round(ratio, 3),
        "data_parallel": dp,
        "model_parallel": mp,
        "param_leaves_sharded": place_grid["shards"],
        "per_chip_rows_grid": place_grid["rows"],
        "per_chip_rows_dp_only": place_dp["rows"],
        "batch_max": batch_max,
        "dims": dims,
        "mlp_layers": layers,
        "buffers": n,
        "methodology": (
            "same 4 chips both runs: (data=2, model=2) with weights "
            "sharded over model vs (data=4) with weights replicated; "
            "identical input/queue/batch_max; CPU host-device proxy when "
            "JAX_PLATFORMS=cpu — per-chip weight HBM halves on the grid "
            "run, fps ratio is the tradeoff the chip sweep reads"),
    }


def bench_fetch(batches: int, warmup: int, dims: int = 1 << 16) -> dict:
    """Async-fetch-engine A/B row (ISSUE 7): a host-fed pipeline whose
    sink payload is LARGE (``dims`` float32 = 256 KB/buffer each way), so
    the pull path pays a real materialization per buffer.  A = the fetch
    engine on (``fetch_depth=2`` + ingress donation), B = the serial path
    (``fetch_depth=1``, no donation); identical input, queue depth, and
    admission bound both runs.  The row carries the h2d/d2h stall split,
    the overlapped-fetch milliseconds, and the window depth — on the
    tunneled chip the overlap hides the ~90 ms fetch RTT behind the next
    dispatch; on CPU (where D2H is a memcpy) the ratio is ~1.0 and the
    row documents the accounting, not a speedup.  ``vs_baseline`` is
    speedup/1.0."""
    import numpy as np

    import nnstreamer_tpu as nt
    from nnstreamer_tpu.core.log import metrics as _metrics

    desc = (
        f"appsrc name=src caps=other/tensors,dimensions={dims},"
        "types=float32 max-inflight=4 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,"
        "div:255.0 ! "
        f"tensor_filter framework=jax model=scaler "
        f"custom=scale:1.5,dims:{dims} name=f ! "
        "tensor_sink name=out"
    )
    frames = [np.full((dims,), float(i % 7), np.float32) for i in range(8)]
    n = max(128, batches)

    def run(depth: int, donate: bool):
        _metrics.reset()
        p = nt.Pipeline(desc, queue_capacity=16, fetch_depth=depth,
                        donate_ingress=donate)
        walls = []
        with p:
            for i in range(max(16, 4 * warmup)):
                p.push("src", frames[i % len(frames)])
                p.pull("out", timeout=120)
            for _ in range(3):  # best-of-3: the mechanism, not the noise
                def pusher():
                    for i in range(n):
                        p.push("src", frames[i % len(frames)])

                t = threading.Thread(target=pusher, daemon=True)
                t0 = time.perf_counter()
                t.start()
                for _ in range(n):
                    p.pull("out", timeout=120)
                walls.append(time.perf_counter() - t0)
                t.join()
            p.eos()
            p.wait(timeout=60)
        stats: dict = {}
        _attach_fetch_stats(stats)
        donated = any(getattr(s.element, "_ingress_put", False)
                      for s in p.stages)
        return n / min(walls), stats, donated

    fps_on, stats_on, donated = run(2, True)
    fps_off, stats_off, _ = run(1, False)
    speedup = fps_on / fps_off
    return {
        "metric": "async_fetch_speedup_depth2_donate_vs_serial",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "fps_fetch_engine": round(fps_on, 1),
        "fps_serial": round(fps_off, 1),
        "fetch_depth": 2,
        "donation_planned": donated,
        "payload_bytes": dims * 4,
        "buffers": n,
        "engine_stats": stats_on,
        "serial_stats": stats_off,
        "methodology": (
            "backlogged appsrc->transform+filter->sink, 256 KB payloads "
            "both ways; best-of-3 steady-state windows after warmup; "
            "identical input/queues/admission both runs; A = "
            "fetch_depth=2 + donate_ingress, B = fetch_depth=1 no "
            "donation"),
    }


def bench_link() -> dict:
    """Link-calibration row (VERDICT r4 Weak #4): raw H2D/D2H bandwidth
    and small-fetch RTT for THIS session, measured with the same sync
    discipline as the sweep rows — so every "link-bound" claim
    (segmentation full-res, appsrc, wav2vec2 history) is checkable
    against the same session's measured link instead of a remembered
    number.  ``vs_baseline`` compares D2H against the ~13 MB/s the r3/r4
    sessions saw.
    """
    import jax
    import numpy as np

    dev = jax.devices()[0]
    rtt_s = _fetch_rtt_ms() / 1e3

    mb = 32
    x = np.random.default_rng(0).integers(
        0, 255, mb << 20, dtype=np.uint8)
    n = 3
    # warm the tiny-slice gather program OUTSIDE the timed region at the
    # REAL payload shape (XLA caches programs per shape — a smaller warm
    # array would leave the 32 MB gather's compile inside the timing)
    warm = jax.device_put(x, dev)
    np.asarray(warm[:4])
    t0 = time.perf_counter()
    y = None
    for _ in range(n):
        y = jax.device_put(x, dev)
    np.asarray(y[:4])  # one roundtrip drains the transfer queue
    h2d_s = max(1e-9, (time.perf_counter() - t0 - rtt_s) / n)

    # jax caches the host copy of an array after its first fetch, so a
    # repeated np.asarray(z) measures the CACHE, not the link — pull n
    # DISTINCT device arrays, one fetch each
    plus1 = jax.jit(lambda a: a + 1)
    zs = [jax.block_until_ready(plus1(y)) for _ in range(n)]
    np.asarray(zs[0][:4])  # ensure all device work drained pre-t0
    t0 = time.perf_counter()
    for z in zs:
        np.asarray(z)
    d2h_s = max(1e-9, (time.perf_counter() - t0) / n - rtt_s)

    d2h_mbps = mb / d2h_s
    return {
        "metric": "link_calibration_d2h_mbps",
        "value": round(d2h_mbps, 1),
        "unit": "MB/s",
        "vs_baseline": round(d2h_mbps / 13.0, 3),
        "h2d_mbps": round(mb / h2d_s, 1),
        "d2h_mbps": round(d2h_mbps, 1),
        "fetch_rtt_ms": round(rtt_s * 1e3, 2),
        "payload_mb": mb,
    }


def _trace_off_guard_ns(iters: int = 200_000) -> float:
    """Measured cost of the tracing-off hot-path hook (one ``is not
    None`` pointer check per buffer per site — see utils/tracing.py):
    recorded in every bench row so the "off mode is free" claim stays a
    number, not an assertion.  Empty-loop baseline subtracted."""
    tr = None
    t0 = time.perf_counter()
    for _ in range(iters):
        if tr is not None:
            raise RuntimeError  # pragma: no cover - tr is None
    t1 = time.perf_counter()
    for _ in range(iters):
        pass
    t2 = time.perf_counter()
    return max(0.0, ((t1 - t0) - (t2 - t1)) / iters * 1e9)


def _backend_reachable(attempt_timeout_s: float = 60.0,
                       total_budget_s: float = 480.0,
                       retry_sleep_s: float = 20.0) -> bool:
    """Bounded, retried probe of the jax backend.  A dead device tunnel
    makes jax.devices() block forever; a bench run should fail with a
    clear reason rather than hang until the caller's timeout — but a
    transient tunnel flap should not zero the round either, so the probe
    retries with bounded backoff for up to ``total_budget_s`` before
    giving up."""
    from nnstreamer_tpu.utils.watchdog import call_with_watchdog

    def probe():
        import jax

        return jax.devices()

    deadline = time.monotonic() + total_budget_s
    attempt = 0
    while True:
        attempt += 1
        budget = min(attempt_timeout_s, max(1.0, deadline - time.monotonic()))
        try:
            call_with_watchdog(probe, budget, "jax.devices()")
            return True
        except TimeoutError:
            msg = (f"jax.devices() did not return within {budget:.0f}s "
                   "— tunnel down?")
        except Exception as e:  # noqa: BLE001 - reported to the caller
            # Deterministic init failures (bad platform value, missing
            # plugin, ImportError) won't heal with time: fail fast.
            print(f"bench: backend init failed (not retrying): {e}",
                  file=sys.stderr)
            return False
        remaining = deadline - time.monotonic()
        if remaining <= retry_sleep_s:
            print(f"bench: device backend unreachable after {attempt} "
                  f"probe(s) over {total_budget_s:.0f}s ({msg})",
                  file=sys.stderr)
            return False
        print(f"bench: probe {attempt} failed ({msg}); retrying in "
              f"{retry_sleep_s:.0f}s ({remaining:.0f}s budget left)",
              file=sys.stderr)
        time.sleep(retry_sleep_s)


def main() -> int:
    honor_jax_platforms()
    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="classification",
                    choices=["classification", "classification_quant",
                             "detection", "pose", "segmentation", "audio",
                             "llm", "llm7b", "link", "batching", "adaptive",
                             "asr_stream", "train_stream", "sharded",
                             "tp", "tp_grid", "fetch", "prefix_spec",
                             "gqa_sampling", "all"])
    # classification defaults to 256: the r3 on-chip session measured 2x
    # the fps AND 2x the MFU of batch 64 (30,137 fps / 0.175 MFU vs
    # 15,116 / 0.088) at a still-interactive 5.4 ms p50 — deeper batches
    # are the TPU-native lever.  Other configs keep 64 (detection/pose
    # host NMS+draw work scales with batch).
    ap.add_argument("--batch", type=int, default=None)
    # 128 batches ≈ 1.2s measured window: short runs (32) showed ±30%
    # run-to-run variance from scheduling spikes; 128 is ±2%.
    ap.add_argument("--batches", type=int, default=128)
    # None = per-config default (224; yolov5s detection 640) so an
    # EXPLICIT --size always wins
    ap.add_argument("--size", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--llm-model", default="llama_small")
    ap.add_argument("--llm-quant", default="", choices=["", "int8", "int4"],
                    help="weight-only quantization for llm/llm7b configs")
    ap.add_argument("--llm-streams", type=int, default=1,
                    help="concurrent prompts decoded in one batched scan "
                         "(aggregate tokens/sec reported)")
    ap.add_argument("--llm-prefix", type=int, default=0,
                    help="llm/llm7b continuous: every stream's prompt "
                         "shares an N-token prefix (prefix-sharing row; "
                         "0 = independent prompts)")
    ap.add_argument("--llm-draft", default="",
                    help="llm/llm7b continuous: speculative-decoding "
                         "draft preset (e.g. llama_tiny)")
    ap.add_argument("--llm-spec-k", type=int, default=4,
                    help="proposals per speculative round (with "
                         "--llm-draft)")
    ap.add_argument("--llm-temperature", type=float, default=0.0,
                    help="llm/llm7b continuous: sampled serving "
                         "(per-slot seeded temperature/top-k/top-p, "
                         "docs/SERVING.md §4d); 0 = greedy")
    ap.add_argument("--llm-serve", default="", choices=["", "continuous"],
                    help="continuous: staggered prompts join a RUNNING "
                         "decode loop (reports late-join latency too)")
    ap.add_argument("--llm-text", action="store_true",
                    help="text-in/text-out contract: SentencePiece encode "
                         "+ per-piece decode in the measured loop")
    ap.add_argument("--tp-ways", type=int, default=2,
                    help="tp config: model_parallel ways for the A/B "
                         "(vs model_parallel=1)")
    ap.add_argument("--source", default="videotestsrc",
                    choices=["videotestsrc", "appsrc"],
                    help="classification config: device-generated test "
                         "frames (default) or host-fed appsrc frames")
    ap.add_argument("--seg-native", action="store_true",
                    help="segmentation: ship the class map at the model's "
                         "native output stride (custom=upsample:0) instead "
                         "of full resolution")
    ap.add_argument("--audio-source", default="audiotestsrc",
                    choices=["audiotestsrc", "appsrc"],
                    help="audio config: device-generated windows (default) "
                         "or host-fed appsrc windows")
    ap.add_argument("--audio-model", default="speech_commands",
                    choices=["speech_commands", "wav2vec2"])
    ap.add_argument("--detection-model", default="ssd_mobilenet",
                    choices=["ssd_mobilenet", "yolov5", "yolov8",
                             "yolov5s"])
    ap.add_argument("--trace", default="", metavar="OUT.json",
                    help="wrap the measured phase in the flight recorder "
                         "(trace_mode=ring) and write the Chrome trace "
                         "artifact next to the BENCH json — load in "
                         "Perfetto (docs/OBSERVABILITY.md)")
    args = ap.parse_args()
    if (args.config in ("sharded", "tp", "tp_grid")
            and os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        # CPU proxy for the local mesh: 8 virtual host devices.  Must be
        # set before the backend initializes (the probe below does), and
        # only on CPU — a real TPU host keeps its real devices.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    if not _backend_reachable():
        # Emit parseable failure records with the SAME metric names and
        # units the success path would use (parsed must never be null in
        # the driver artifact, even when the device tunnel is down),
        # alongside the distinct exit code.
        fail_metrics = {
            "classification": ("mobilenet_v1_pipeline_fps_per_chip",
                               "frames/sec"),
            "classification_quant": (
                "mobilenet_v1_quant_pipeline_fps_per_chip", "frames/sec"),
            "detection": (f"{args.detection_model}_detection_fps_per_chip",
                          "frames/sec"),
            "pose": ("posenet_pipeline_fps_per_chip", "frames/sec"),
            "segmentation": ("deeplab_segmentation_fps_per_chip",
                             "frames/sec"),
            "audio": (f"{args.audio_model}_windows_per_sec_per_chip",
                      "windows/sec"),
            "llm": (f"{args.llm_model}_tokens_per_sec_per_chip",
                    "tokens/sec"),
            "llm7b": ("llama2_7b_tokens_per_sec_per_chip", "tokens/sec"),
            "link": ("link_calibration_d2h_mbps", "MB/s"),
            "batching": ("adaptive_batching_speedup_batch8_vs_1", "x"),
            "adaptive": ("adaptive_ladder_speedup_burst6_vs_static", "x"),
            "asr_stream": ("asr_streaming_window_windows_per_sec",
                           "windows/sec"),
            "train_stream": ("train_stream_device_vs_host_speedup", "x"),
            "sharded": ("mesh_sharded_batching_speedup_dp4_vs_1", "x"),
            "tp": (f"{args.llm_model}_decode_tp{args.tp_ways}_vs_tp1_"
                   "tokens_per_sec", "tokens/sec"),
            "tp_grid": ("sharded_grid_dp2xtp2_vs_dp4_fps", "frames/sec"),
            "fetch": ("async_fetch_speedup_depth2_donate_vs_serial", "x"),
            "prefix_spec": ("llama_small_prefix_hit_admission_speedup",
                            "x"),
            "gqa_sampling": ("gqa_grouped_decode_projected_speedup_7b",
                             "x"),
        }
        todo = (["classification", "detection", "pose", "segmentation",
                 "audio", "llm"]
                if args.config == "all" else [args.config])
        for name in todo:
            metric, unit = fail_metrics[name]
            print(json.dumps({
                "metric": metric,
                "value": 0.0,
                "unit": unit,
                "vs_baseline": 0.0,
                "error": "device backend unreachable (tunnel down?) after "
                         "bounded retry",
            }))
        return 3  # distinct from argparse's usage-error exit code 2

    # Batch 256 across the vision configs: the r3 on-chip sessions showed
    # 2x fps AND 2x MFU over batch 64 on classification once host work was
    # off the pull path; with tensors/classmap decode output the other
    # configs get the same treatment.  Segmentation stays shallower (the
    # u8 classmap is still H*W bytes/frame of D2H).
    batch = args.batch if args.batch is not None else 256
    cls_batch = args.batch if args.batch is not None else 256
    runners = {
        "classification": lambda: bench_classification(
            cls_batch, args.batches, args.size or 224, args.warmup,
            args.source),
        "classification_quant": lambda: bench_classification_quant(
            cls_batch, args.batches, args.size or 224, args.warmup),
        "detection": lambda: bench_detection(
            batch, args.batches, args.size, args.warmup,
            args.detection_model),
        "pose": lambda: bench_pose(
            batch, args.batches, args.size or 224, args.warmup),
        "segmentation": lambda: bench_segmentation(
            max(8, batch // 4), args.batches,
            min(args.size or 224, 224),
            args.warmup, native=args.seg_native),
        # audio DEFAULTS to 64: wav2vec2's attention tiles WORSE at 256
        # (measured 5.7k vs 15.4k windows/s), and speech_commands is
        # RTT-bound either way; an explicit --batch still wins
        "audio": lambda: bench_audio(
            args.batch if args.batch is not None else 64, args.batches,
            args.warmup, args.audio_source, args.audio_model),
        "llm": lambda: bench_llm(max(1, args.batches // 8), 1,
                                 model=args.llm_model,
                                 quant=args.llm_quant,
                                 streams=args.llm_streams,
                                 serve=args.llm_serve,
                                 text=args.llm_text,
                                 shared_prefix=args.llm_prefix,
                                 draft=args.llm_draft,
                                 spec_k=args.llm_spec_k,
                                 temperature=args.llm_temperature),
        "llm7b": lambda: bench_llm(2, 1, model="llama2_7b",
                                   quant=args.llm_quant,
                                   streams=args.llm_streams,
                                   serve=args.llm_serve,
                                   text=args.llm_text,
                                   shared_prefix=args.llm_prefix,
                                   draft=args.llm_draft,
                                   spec_k=args.llm_spec_k,
                                   temperature=args.llm_temperature),
        "link": bench_link,
        "batching": lambda: bench_batching(args.batches, args.warmup),
        "adaptive": lambda: bench_adaptive(args.batches, args.warmup),
        "asr_stream": lambda: bench_asr_stream(args.batches, args.warmup),
        "train_stream": lambda: bench_train_stream(args.batches,
                                                   args.warmup),
        "sharded": lambda: bench_sharded(args.batches, args.warmup),
        "tp": lambda: bench_tp(max(1, args.batches // 16), args.warmup,
                               model=args.llm_model, ways=args.tp_ways),
        "tp_grid": lambda: bench_tp_grid(args.batches, args.warmup),
        "fetch": lambda: bench_fetch(args.batches, args.warmup),
        "prefix_spec": lambda: bench_prefix_spec(
            max(4, args.batches // 16), args.warmup,
            model=args.llm_model, spec_k=args.llm_spec_k),
        "gqa_sampling": lambda: bench_gqa_sampling(
            max(2, args.batches // 32), args.warmup),
    }
    todo = list(runners) if args.config == "all" else [args.config]
    if args.config == "all":
        todo.remove("llm7b")  # 7B needs ~14 GB HBM free; run explicitly
        todo.remove("sharded")  # needs >=4 local devices; run explicitly
        todo.remove("tp")  # needs >=2 local devices; run explicitly
        todo.remove("tp_grid")  # needs >=4 local devices; run explicitly
    guard_ns = round(_trace_off_guard_ns(), 2)
    if args.trace:
        # Pipelines built inside the rows read the shared config, so the
        # flip covers the whole measured phase.
        from nnstreamer_tpu.core.config import get_config
        from nnstreamer_tpu.utils.tracing import recorder

        get_config().trace_mode = "ring"
    for name in todo:
        if args.trace:
            recorder.clear()
        row = runners[name]()
        if args.trace:
            from nnstreamer_tpu.utils.tracing import dump_chrome

            out = args.trace
            if len(todo) > 1:  # one artifact per row: prefix the BASENAME
                d, base = os.path.split(args.trace)
                out = os.path.join(d, f"{name}_{base}")
            row["trace"] = out
            row["trace_spans"] = dump_chrome(recorder.events(), out)
            row["trace_mode"] = "ring"
        # tracing-off overhead: one pointer check per hook site per
        # buffer; recorded so the row carries the claim as a number
        row["trace_off_guard_ns"] = guard_ns
        print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
